"""Vectorized create_accounts / create_transfers commit kernels (the fast path).

The reference executes a batch one event at a time with hash lookups
(state_machine.zig:1002-1088, the per-event create_transfer loop :1239-1368).
These kernels execute the whole 8190-event batch as data-parallel device code:

- every validation check becomes an independent vector mask;
- the final result code per event is the *minimum* over failing checks' codes —
  sound because the result enums are precedence-ordered to match the exact
  sequential check order (tigerbeetle.zig:122-124, and see types.py);
- intra-batch duplicate ids are resolved with a sort + segmented-min "winner"
  pass (the first standalone-ok occurrence inserts; later occurrences compare
  against it with the exists ladder), mirroring in-order execution;
- linked chains become a segmented first-failure propagation
  (state_machine.zig:1015-1082);
- balance updates become exact u128 segment-sums via 32-bit limbs (no carries
  are lost: limb partial sums of <= 8190 u32 terms fit u64), applied with one
  deterministic scatter per column.

Preconditions (enforced by the host dispatcher in machine.py, which otherwise
routes the batch to the fully-general sequential path):
  P1 no account in the table carries limit or history flags;
  P2 the batch has no balancing_debit/balancing_credit/post/void flags;
  P3 all amounts < 2**64 and every account balance is bounded away from
     2**128 overflow (host tracks a global bound), so the overflow ladder
     (state_machine.zig:1308-1320) cannot fire;
  P4 the batch does not combine linked chains with intra-batch duplicate ids.

Under P1-P4 these kernels are bit-identical to the reference semantics — the
differential tests against testing/model.py check exactly that.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from .. import u128
from ..obs.metrics import registry as _metrics
from ..u128 import U128
from . import hash_table as ht

MAX_PROBE = 1 << 12


def _obs_jit(impl, name: str, **jit_kwargs):
    """jit an entry-point kernel with a per-kernel dispatch counter.

    The counter lives OUTSIDE the traced function (incrementing a tracer-
    side Python int inside jit would either fail or bake in a constant);
    the wrapper costs one attribute load + branch per dispatch when the
    registry is disabled.  The raw jitted callable rides along as
    ``.jitted`` for callers that need jit-object APIs (lower/clear_cache)."""
    jitted = jax.jit(impl, **jit_kwargs)

    @functools.wraps(impl)
    def dispatch(*args, **kwargs):
        if _metrics.enabled:
            _metrics.counter("ops.kernel." + name).inc()
        return jitted(*args, **kwargs)

    dispatch.jitted = jitted
    return dispatch

# Account value columns (table stores everything but the id key; `reserved` is
# validated to zero and not stored).
ACCOUNT_COLS = {
    "debits_pending_lo": jnp.uint64,
    "debits_pending_hi": jnp.uint64,
    "debits_posted_lo": jnp.uint64,
    "debits_posted_hi": jnp.uint64,
    "credits_pending_lo": jnp.uint64,
    "credits_pending_hi": jnp.uint64,
    "credits_posted_lo": jnp.uint64,
    "credits_posted_hi": jnp.uint64,
    "user_data_128_lo": jnp.uint64,
    "user_data_128_hi": jnp.uint64,
    "user_data_64": jnp.uint64,
    "user_data_32": jnp.uint32,
    "ledger": jnp.uint32,
    "code": jnp.uint32,
    "flags": jnp.uint32,
    "timestamp": jnp.uint64,
}

TRANSFER_COLS = {
    "debit_account_id_lo": jnp.uint64,
    "debit_account_id_hi": jnp.uint64,
    "credit_account_id_lo": jnp.uint64,
    "credit_account_id_hi": jnp.uint64,
    "amount_lo": jnp.uint64,
    "amount_hi": jnp.uint64,
    "pending_id_lo": jnp.uint64,
    "pending_id_hi": jnp.uint64,
    "user_data_128_lo": jnp.uint64,
    "user_data_128_hi": jnp.uint64,
    "user_data_64": jnp.uint64,
    "user_data_32": jnp.uint32,
    "timeout": jnp.uint32,
    "ledger": jnp.uint32,
    "code": jnp.uint32,
    "flags": jnp.uint32,
    "timestamp": jnp.uint64,
}

# Posted groove: pending-transfer timestamp -> fulfillment (1 posted, 2 voided)
# (state_machine.zig:1471-1479).
POSTED_COLS = {"fulfillment": jnp.uint32}

# Account flag bits (tigerbeetle.zig:42-57).
AF_LINKED = 1
AF_DEBITS_MUST_NOT_EXCEED_CREDITS = 2
AF_CREDITS_MUST_NOT_EXCEED_DEBITS = 4
AF_HISTORY = 8
AF_PADDING = 0xFFF0

# Transfer flag bits (tigerbeetle.zig:107-120).
TF_LINKED = 1
TF_PENDING = 2
TF_POST = 4
TF_VOID = 8
TF_BALANCING_DEBIT = 16
TF_BALANCING_CREDIT = 32
TF_PADDING = 0xFFC0

NS_PER_S = 1_000_000_000


# History rows mirror the reference's AccountHistoryGrooveValue
# (state_machine.zig:275-294): post-update balances of the debit and credit
# accounts of one committed transfer (sides zeroed unless that account carries
# the HISTORY flag), keyed by the transfer's timestamp.
HISTORY_COLS = {
    name: jnp.uint64
    for name in (
        "dr_id_lo", "dr_id_hi",
        "dr_dp_lo", "dr_dp_hi", "dr_dpo_lo", "dr_dpo_hi",
        "dr_cp_lo", "dr_cp_hi", "dr_cpo_lo", "dr_cpo_hi",
        "cr_id_lo", "cr_id_hi",
        "cr_dp_lo", "cr_dp_hi", "cr_dpo_lo", "cr_dpo_hi",
        "cr_cp_lo", "cr_cp_hi", "cr_cpo_lo", "cr_cpo_hi",
        "timestamp",
    )
}


@struct.dataclass
class History:
    """Append-only device log of history rows (the account_history groove,
    state_machine.zig:108,275-294).  Slots [0, count) are live; appends write
    at ``count`` and linked-chain rollback pops by decrementing it.  The log
    never wraps: the host grows the arrays before a batch could overflow them
    (grow_history), the way the reference's LSM absorbs unbounded inserts."""

    cols: Dict[str, jax.Array]
    count: jax.Array  # uint64 scalar

    @property
    def capacity(self) -> int:
        return self.cols["timestamp"].shape[0]


def make_history(capacity: int) -> History:
    assert capacity & (capacity - 1) == 0
    return History(
        cols={name: jnp.zeros((capacity,), dt) for name, dt in HISTORY_COLS.items()},
        count=jnp.uint64(0),
    )


def grow_history(history: History, min_capacity: int) -> History:
    """Host-side capacity doubling (keeps power-of-two sizing)."""
    cap = history.capacity
    while cap < min_capacity:
        cap *= 2
    if cap == history.capacity:
        return history
    return History(
        cols={
            name: jnp.concatenate(
                [col, jnp.zeros((cap - history.capacity,), col.dtype)]
            )
            for name, col in history.cols.items()
        },
        count=history.count,
    )


@struct.dataclass
class Ledger:
    """The full device-resident ledger state."""

    accounts: ht.Table
    transfers: ht.Table
    posted: ht.Table
    history: History


def make_ledger(
    accounts_capacity: int,
    transfers_capacity: int,
    posted_capacity: int,
    history_capacity: int = 1 << 16,
) -> Ledger:
    return Ledger(
        accounts=ht.make_table(accounts_capacity, ACCOUNT_COLS),
        transfers=ht.make_table(transfers_capacity, TRANSFER_COLS),
        posted=ht.make_table(posted_capacity, POSTED_COLS),
        history=make_history(history_capacity),
    )


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------


def _min_code(n: int, *checks: Tuple[jax.Array, int]) -> jax.Array:
    """Combine (mask, code) checks into the minimum firing code (0 if none).

    Sound because result enums are precedence-ordered to match the sequential
    check order (tigerbeetle.zig:122-124)."""
    big = jnp.uint32(0xFFFFFFFF)
    acc = jnp.full((n,), big, jnp.uint32)
    for mask, code in checks:
        acc = jnp.minimum(acc, jnp.where(mask, jnp.uint32(code), big))
    return jnp.where(acc == big, jnp.uint32(0), acc)


def _merge_code(primary: jax.Array, secondary: jax.Array) -> jax.Array:
    """min(primary, secondary) treating 0 as 'ok' (no failure)."""
    big = jnp.uint32(0xFFFFFFFF)
    p = jnp.where(primary == 0, big, primary)
    s = jnp.where(secondary == 0, big, secondary)
    m = jnp.minimum(p, s)
    return jnp.where(m == big, jnp.uint32(0), m)


class DupInfo(NamedTuple):
    winner_lane: jax.Array  # int32[N]: first standalone-ok lane of the id group
    has_winner: jax.Array  # bool[N]
    after_winner: jax.Array  # bool[N]: lane strictly after its group's winner


def _resolve_duplicates(
    id_lo: jax.Array, id_hi: jax.Array, standalone_ok: jax.Array, valid: jax.Array
) -> DupInfo:
    """Intra-batch duplicate-id resolution.

    In-order execution means: among events sharing an id, the first that passes
    validation inserts; subsequent ones see it as existing. We recover that
    order-dependence vectorized: group lanes by id (stable lexsort keeps lane
    order), take the segmented-min ok lane as winner."""
    n = id_lo.shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    inf = jnp.int32(n)

    # Push invalid/padding lanes into a dedicated tail group via key munging
    # is unnecessary: their standalone_ok is False and ids may be 0; grouping
    # them together is harmless because winner selection requires ok.
    order = jnp.lexsort((lane, id_lo, id_hi))
    s_lo, s_hi, s_lane = id_lo[order], id_hi[order], lane[order]
    s_ok = standalone_ok[order] & valid[order]

    new_group = jnp.concatenate(
        [
            jnp.ones((1,), jnp.bool_),
            (s_lo[1:] != s_lo[:-1]) | (s_hi[1:] != s_hi[:-1]),
        ]
    )
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1

    winner_g = jax.ops.segment_min(
        jnp.where(s_ok, s_lane, inf), gid, num_segments=n
    )
    winner_sorted = winner_g[gid]
    winner_lane = jnp.zeros((n,), jnp.int32).at[order].set(winner_sorted)
    has_winner = winner_lane < inf
    after_winner = has_winner & (lane > winner_lane)
    return DupInfo(winner_lane, has_winner, after_winner)


def _chain_codes(
    linked: jax.Array, codes: jax.Array, count: jax.Array
) -> jax.Array:
    """Linked-chain failure propagation (state_machine.zig:1015-1082).

    A chain is a maximal run of linked events plus one terminator. The first
    failing member keeps its own code; members before it roll back to
    linked_event_failed(1); members after it get linked_event_failed, except a
    linked batch-final event which gets linked_event_chain_open(2) regardless
    (checked before chain_broken in execute, state_machine.zig:1022-1032)."""
    n = linked.shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    last_lane = count.astype(jnp.int32) - 1
    prev_linked = jnp.concatenate([jnp.zeros((1,), jnp.bool_), linked[:-1]])
    in_chain = linked | prev_linked
    start = linked & ~prev_linked
    chain_id = jnp.cumsum(start.astype(jnp.int32)) - 1

    # A linked batch-final event breaks its chain with chain_open.
    is_last = lane == last_lane
    codes_o = jnp.where(is_last & linked, jnp.uint32(2), codes)

    inf = jnp.int32(n)
    # Non-chain lanes route to a dummy segment (index n).
    seg = jnp.where(in_chain, chain_id, jnp.int32(n))
    fail_lane_g = jax.ops.segment_min(
        jnp.where(in_chain & (codes_o != 0), lane, inf), seg, num_segments=n + 1
    )
    f = fail_lane_g[seg]  # per-lane: first failing lane of my chain (inf if none)

    chain_failed = in_chain & (f < inf)
    out = jnp.where(
        chain_failed,
        jnp.where(
            lane < f,
            jnp.uint32(1),
            jnp.where(
                lane == f,
                codes_o,
                jnp.where(is_last & linked, jnp.uint32(2), jnp.uint32(1)),
            ),
        ),
        codes_o,
    )
    return out


def _u128_col(cols: Dict[str, jax.Array], name: str) -> U128:
    return U128(cols[name + "_lo"], cols[name + "_hi"])


def _timestamps(count: jax.Array, timestamp: jax.Array, n: int) -> jax.Array:
    # event.timestamp = batch_timestamp - len + index + 1 (state_machine.zig:1035)
    lane = jnp.arange(n, dtype=jnp.uint64)
    return timestamp - count + lane + jnp.uint64(1)


# ---------------------------------------------------------------------------
# create_accounts
# ---------------------------------------------------------------------------


def account_codes(
    batch: Dict[str, jax.Array],
    found: jax.Array,
    e: Dict[str, jax.Array],
    count: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Pure create_accounts validation (state_machine.zig:1198-1237): returns
    (codes, ok). ``found``/``e`` are the table-existence gather, however the
    table is sharded — replicated compute."""
    n = batch["id_lo"].shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    valid = lane < count.astype(jnp.int32)

    bid = _u128_col(batch, "id")
    flags = batch["flags"]
    linked = (flags & AF_LINKED).astype(jnp.bool_) & valid

    dp = _u128_col(batch, "debits_pending")
    dpo = _u128_col(batch, "debits_posted")
    cp = _u128_col(batch, "credits_pending")
    cpo = _u128_col(batch, "credits_posted")

    exists_code = _exists_ladder_accounts(batch, e, n)

    standalone = _min_code(
        n,
        ((batch["timestamp"] != 0), 3),  # execute(): timestamp_must_be_zero
        ((batch["reserved"] != 0), 4),
        ((flags & AF_PADDING) != 0, 5),
        (u128.is_zero(bid), 6),
        (u128.is_max(bid), 7),
        (
            ((flags & AF_DEBITS_MUST_NOT_EXCEED_CREDITS) != 0)
            & ((flags & AF_CREDITS_MUST_NOT_EXCEED_DEBITS) != 0),
            8,
        ),
        (~u128.is_zero(dp), 9),
        (~u128.is_zero(dpo), 10),
        (~u128.is_zero(cp), 11),
        (~u128.is_zero(cpo), 12),
        ((batch["ledger"] == 0), 13),
        ((batch["code"] == 0), 14),
    )
    standalone = _merge_code(standalone, jnp.where(found, exists_code, 0))

    # Intra-batch duplicates: later lanes compare against the winner's event.
    dup = _resolve_duplicates(bid.lo, bid.hi, standalone == 0, valid)
    intra = _exists_ladder_accounts(
        batch, {k: v[dup.winner_lane.clip(0, n - 1)] for k, v in batch.items()}, n
    )
    codes = jnp.where(
        dup.after_winner, jnp.where(standalone == 0, intra, standalone), standalone
    )

    codes = _chain_codes(linked, codes, count)
    ok = (codes == 0) & valid
    return codes, ok


def account_rows(
    batch: Dict[str, jax.Array], count: jax.Array, timestamp: jax.Array
) -> Dict[str, jax.Array]:
    """Rows to insert for accepted create_accounts events (assigned timestamps)."""
    n = batch["id_lo"].shape[0]
    ts = _timestamps(count, timestamp, n)
    return {
        name: (batch[name] if name != "timestamp" else ts).astype(dt)
        for name, dt in ACCOUNT_COLS.items()
    }


def create_accounts_impl(
    ledger: Ledger,
    batch: Dict[str, jax.Array],
    count: jax.Array,
    timestamp: jax.Array,
) -> Tuple[Ledger, jax.Array]:
    """Vectorized create_accounts (state_machine.zig:1198-1237).

    ``batch`` is the SoA of ACCOUNT_DTYPE columns padded to a fixed lane count;
    ``count`` is the true event count; ``timestamp`` the batch prepare
    timestamp. Returns (ledger, result codes uint32[N]) — 0 is ok, and lanes
    >= count are don't-care."""
    n = batch["id_lo"].shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    valid = lane < count.astype(jnp.int32)

    bid = _u128_col(batch, "id")

    # Table existence + exists ladder (state_machine.zig:1218-1237).
    look = ht.lookup(ledger.accounts, bid.lo, bid.hi, MAX_PROBE)
    found = look.found & valid
    e = ht.gather_cols(ledger.accounts, look.slot, found)

    codes, ok = account_codes(batch, found, e, count)
    rows = account_rows(batch, count, timestamp)
    accounts, _ = ht.insert(ledger.accounts, bid.lo, bid.hi, ok, rows, MAX_PROBE)
    return ledger.replace(accounts=accounts), codes


create_accounts = _obs_jit(
    create_accounts_impl, "create_accounts", donate_argnames=("ledger",)
)


def _exists_ladder_accounts(
    t: Dict[str, jax.Array], e: Dict[str, jax.Array], n: int
) -> jax.Array:
    """create_account_exists comparison ladder (state_machine.zig:1227-1237),
    evaluated in reverse so higher-precedence checks overwrite."""
    c = jnp.full((n,), 21, jnp.uint32)  # exists
    c = jnp.where(t["code"] != e["code"], jnp.uint32(20), c)
    c = jnp.where(t["ledger"] != e["ledger"], jnp.uint32(19), c)
    c = jnp.where(t["user_data_32"] != e["user_data_32"], jnp.uint32(18), c)
    c = jnp.where(t["user_data_64"] != e["user_data_64"], jnp.uint32(17), c)
    ud128_ne = (t["user_data_128_lo"] != e["user_data_128_lo"]) | (
        t["user_data_128_hi"] != e["user_data_128_hi"]
    )
    c = jnp.where(ud128_ne, jnp.uint32(16), c)
    c = jnp.where(t["flags"] != e["flags"], jnp.uint32(15), c)
    return c


# ---------------------------------------------------------------------------
# create_transfers (fast path)
# ---------------------------------------------------------------------------


class TransferCtx(NamedTuple):
    """Gathered context for transfer validation: everything the (replicated)
    validation pass needs, independent of how the tables are sharded."""

    dr_found: jax.Array
    cr_found: jax.Array
    dr_slot: jax.Array  # global slot ids (sharding-aware callers encode owner)
    cr_slot: jax.Array
    dr: Dict[str, jax.Array]
    cr: Dict[str, jax.Array]
    ex_found: jax.Array
    e: Dict[str, jax.Array]


def transfer_codes(
    batch: Dict[str, jax.Array],
    ctx: TransferCtx,
    count: jax.Array,
    timestamp: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Pure validation pass: (codes, ok, ts, pending). Identical whether the
    gathers came from a local table or a sharded one (replicated compute)."""
    n = batch["id_lo"].shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    valid = lane < count.astype(jnp.int32)

    tid = _u128_col(batch, "id")
    dr_id = _u128_col(batch, "debit_account_id")
    cr_id = _u128_col(batch, "credit_account_id")
    amt = _u128_col(batch, "amount")
    pend = _u128_col(batch, "pending_id")
    flags = batch["flags"]
    linked = (flags & TF_LINKED).astype(jnp.bool_) & valid
    pending = (flags & TF_PENDING).astype(jnp.bool_)

    ts = _timestamps(count, timestamp, n)
    both = ctx.dr_found & ctx.cr_found

    exists_code = _exists_ladder_transfers(batch, ctx.e, n)

    # overflows_timeout (state_machine.zig:1322): ts + timeout*1e9 > u64 max.
    timeout_ns = batch["timeout"].astype(jnp.uint64) * jnp.uint64(NS_PER_S)
    ts_sum = ts + timeout_ns
    timeout_overflow = ts_sum < ts

    standalone = _min_code(
        n,
        ((batch["timestamp"] != 0), 3),
        (((flags & TF_PADDING) != 0), 4),
        (u128.is_zero(tid), 5),
        (u128.is_max(tid), 6),
        (u128.is_zero(dr_id), 8),
        (u128.is_max(dr_id), 9),
        (u128.is_zero(cr_id), 10),
        (u128.is_max(cr_id), 11),
        (u128.eq(dr_id, cr_id), 12),
        (~u128.is_zero(pend), 13),
        (~pending & (batch["timeout"] != 0), 17),
        (u128.is_zero(amt), 18),
        ((batch["ledger"] == 0), 19),
        ((batch["code"] == 0), 20),
        (valid & ~ctx.dr_found, 21),
        (valid & ~ctx.cr_found, 22),
        (both & (ctx.dr["ledger"] != ctx.cr["ledger"]), 23),
        (both & (batch["ledger"] != ctx.dr["ledger"]), 24),
        (timeout_overflow, 53),
    )
    standalone = _merge_code(
        standalone, jnp.where(ctx.ex_found, exists_code, 0)
    )

    # Intra-batch duplicate ids.
    dup = _resolve_duplicates(tid.lo, tid.hi, standalone == 0, valid)
    w = dup.winner_lane.clip(0, n - 1)
    winner_event = {k: v[w] for k, v in batch.items()}
    intra = _exists_ladder_transfers(batch, winner_event, n)
    codes = jnp.where(
        dup.after_winner, jnp.where(standalone == 0, intra, standalone), standalone
    )

    codes = _chain_codes(linked, codes, count)
    ok = (codes == 0) & valid
    return codes, ok, ts, pending


class BalancePlan(NamedTuple):
    """Sorted, segment-summed balance deltas keyed by global account slot.

    ``s_slot[i]`` is the sorted global slot for sorted-lane i; ``head`` marks
    the first lane of each slot group; ``deltas[field] = (d_lo, d_hi)`` is the
    u128 total delta for the lane's group."""

    s_slot: jax.Array
    head: jax.Array
    deltas: Dict[str, Tuple[jax.Array, jax.Array]]


def balance_plan(
    dr_slot: jax.Array,
    cr_slot: jax.Array,
    ok: jax.Array,
    amt_lo: jax.Array,
    pending: jax.Array,
    sentinel,
) -> BalancePlan:
    """Exact u128 per-account balance deltas via 32-bit limb segment sums.

    Replaces the reference's two sequential balance updates per event
    (state_machine.zig:1330-1338) with sort + segment-sum: limb partial sums of
    <= 2*8190 u32 terms fit u64 exactly, so no carries are lost."""
    n = ok.shape[0]
    sent = jnp.uint64(sentinel)
    ok2 = jnp.concatenate([ok, ok])
    slots2 = jnp.concatenate([dr_slot, cr_slot])
    slots2 = jnp.where(ok2, slots2, sent)
    amt2 = jnp.concatenate([amt_lo, amt_lo])  # P3: amount_hi == 0
    pending2 = jnp.concatenate([pending, pending])
    is_dr2 = jnp.concatenate(
        [jnp.ones((n,), jnp.bool_), jnp.zeros((n,), jnp.bool_)]
    )

    order = jnp.argsort(slots2)
    s_slot = slots2[order]
    s_amt = amt2[order]
    s_pending = pending2[order]
    s_is_dr = is_dr2[order]
    s_live = s_slot < sent

    head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), s_slot[1:] != s_slot[:-1]]
    ) & s_live
    gid = jnp.cumsum(head.astype(jnp.int32)) - 1
    gid = jnp.where(s_live, gid, 2 * n)  # dead lanes -> dummy segment

    a0 = s_amt & jnp.uint64(0xFFFFFFFF)
    a1 = s_amt >> jnp.uint64(32)

    # ONE fused segment-sum over a (2N, 8) matrix — (field, limb) pairs as
    # columns — instead of eight independent passes over the leg arrays
    # (each limb column is a u64 sum of <= 2*8190 u32 terms: exact).
    fields = (
        ("debits_pending", s_is_dr & s_pending),
        ("debits_posted", s_is_dr & ~s_pending),
        ("credits_pending", ~s_is_dr & s_pending),
        ("credits_posted", ~s_is_dr & ~s_pending),
    )
    cols = []
    for _name, mask in fields:
        m = mask & s_live
        cols.append(jnp.where(m, a0, 0))
        cols.append(jnp.where(m, a1, 0))
    stacked = jnp.stack(cols, axis=1)  # (2N, 8)
    summed = jax.ops.segment_sum(stacked, gid, num_segments=2 * n + 1)
    per_leg = summed[gid]  # (2N, 8) gathered back to leg domain

    deltas = {}
    for i, (field, _mask) in enumerate(fields):
        sa0_l = per_leg[:, 2 * i]
        sa1_l = per_leg[:, 2 * i + 1]
        low_part = (sa1_l & jnp.uint64(0xFFFFFFFF)) << jnp.uint64(32)
        d_lo = sa0_l + low_part
        carry = (d_lo < low_part).astype(jnp.uint64)
        d_hi = (sa1_l >> jnp.uint64(32)) + carry
        deltas[field] = (d_lo, d_hi)
    return BalancePlan(s_slot=s_slot, head=head, deltas=deltas)


def apply_balance_plan(accounts: ht.Table, plan: BalancePlan) -> ht.Table:
    """Gather-old + add-delta + scatter at group heads (unique slots)."""
    sent = jnp.uint64(accounts.capacity)
    head_valid = plan.head & (plan.s_slot < sent)
    acc = ht.gather_cols(
        accounts, jnp.where(head_valid, plan.s_slot, 0), head_valid
    )
    updates = {}
    for field, (d_lo, d_hi) in plan.deltas.items():
        old = U128(acc[field + "_lo"], acc[field + "_hi"])
        new, _ = u128.add(old, U128(d_lo, d_hi))  # P3: cannot overflow
        updates[field + "_lo"] = new.lo
        updates[field + "_hi"] = new.hi
    return ht.scatter_cols(
        accounts, jnp.where(head_valid, plan.s_slot, sent), head_valid, updates
    )


def create_transfers_impl(
    ledger: Ledger,
    batch: Dict[str, jax.Array],
    count: jax.Array,
    timestamp: jax.Array,
) -> Tuple[Ledger, jax.Array]:
    """Vectorized create_transfers under preconditions P1-P4 (module docstring).

    Mirrors state_machine.zig:1239-1368 with the balancing/post-void/limit/
    overflow branches statically excluded."""
    tid = _u128_col(batch, "id")
    dr_id = _u128_col(batch, "debit_account_id")
    cr_id = _u128_col(batch, "credit_account_id")
    amt = _u128_col(batch, "amount")
    n = batch["id_lo"].shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    valid = lane < count.astype(jnp.int32)

    dr_look = ht.lookup(ledger.accounts, dr_id.lo, dr_id.hi, MAX_PROBE)
    cr_look = ht.lookup(ledger.accounts, cr_id.lo, cr_id.hi, MAX_PROBE)
    ex_look = ht.lookup(ledger.transfers, tid.lo, tid.hi, MAX_PROBE)
    dr_found = dr_look.found & valid
    cr_found = cr_look.found & valid
    ex_found = ex_look.found & valid
    ctx = TransferCtx(
        dr_found=dr_found,
        cr_found=cr_found,
        dr_slot=dr_look.slot,
        cr_slot=cr_look.slot,
        dr=ht.gather_cols(ledger.accounts, dr_look.slot, dr_found),
        cr=ht.gather_cols(ledger.accounts, cr_look.slot, cr_found),
        ex_found=ex_found,
        e=ht.gather_cols(ledger.transfers, ex_look.slot, ex_found),
    )

    codes, ok, ts, pending = transfer_codes(batch, ctx, count, timestamp)

    plan = balance_plan(
        ctx.dr_slot, ctx.cr_slot, ok, amt.lo, pending, ledger.accounts.capacity
    )
    accounts = apply_balance_plan(ledger.accounts, plan)

    # --- transfer inserts (timestamps recomputed in transfer_rows CSE under jit) ---
    rows = transfer_rows(batch, count, timestamp)
    transfers, _ = ht.insert(ledger.transfers, tid.lo, tid.hi, ok, rows, MAX_PROBE)

    return ledger.replace(accounts=accounts, transfers=transfers), codes


create_transfers_fast = _obs_jit(
    create_transfers_impl, "create_transfers_fast",
    donate_argnames=("ledger",),
)


def create_transfers_fast_probed_impl(
    ledger: Ledger,
    batch: Dict[str, jax.Array],
    count: jax.Array,
    timestamp: jax.Array,
) -> Tuple[Ledger, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fast kernel + the transfers probe_overflow flag as a third output.

    The overflow flag is widened to a FRESH uint32 buffer (never aliased
    into the returned ledger's pytree): a deferred readback handle
    (machine.DeviceCommitHandle) must still be able to fetch it after a
    LATER dispatch donates the ledger's buffers — reading
    ``ledger.transfers.probe_overflow`` at resolve time would trip the
    donation check.  Riding the commit dispatch, it costs zero extra syncs
    (the codes D2H carries it along).

    The BATCH is donated along with the ledger (its ~1 MB of pad-SoA
    columns become scratch/output space instead of live inputs pinned for
    the whole dispatch); the id columns the caller's index maintenance
    needs are passed through as outputs, which may alias the donated
    buffers.  Callers must hand this kernel a per-dispatch staged SoA
    (machine._pad_soa with count > 0, or an explicit copy) — never the
    cached zero-count template."""
    id_lo, id_hi = batch["id_lo"], batch["id_hi"]
    ledger, codes = create_transfers_impl(ledger, batch, count, timestamp)
    return (
        ledger, codes, ledger.transfers.probe_overflow.astype(jnp.uint32),
        id_lo, id_hi,
    )


create_transfers_fast_probed = _obs_jit(
    create_transfers_fast_probed_impl, "create_transfers_fast_probed",
    donate_argnames=("ledger", "batch"),
)


def transfer_rows(
    batch: Dict[str, jax.Array], count: jax.Array, timestamp: jax.Array
) -> Dict[str, jax.Array]:
    """Rows to insert for accepted create_transfers events."""
    n = batch["id_lo"].shape[0]
    ts = _timestamps(count, timestamp, n)
    return {
        name: (batch[name] if name != "timestamp" else ts).astype(dt)
        for name, dt in TRANSFER_COLS.items()
    }


def _exists_ladder_transfers(
    t: Dict[str, jax.Array], e: Dict[str, jax.Array], n: int
) -> jax.Array:
    """create_transfer_exists ladder (state_machine.zig:1370-1389), reverse
    evaluation order so higher-precedence comparisons overwrite."""

    def ne128(name):
        return (t[name + "_lo"] != e[name + "_lo"]) | (
            t[name + "_hi"] != e[name + "_hi"]
        )

    c = jnp.full((n,), 46, jnp.uint32)  # exists
    c = jnp.where(t["code"] != e["code"], jnp.uint32(45), c)
    c = jnp.where(t["timeout"] != e["timeout"], jnp.uint32(44), c)
    c = jnp.where(t["user_data_32"] != e["user_data_32"], jnp.uint32(43), c)
    c = jnp.where(t["user_data_64"] != e["user_data_64"], jnp.uint32(42), c)
    c = jnp.where(ne128("user_data_128"), jnp.uint32(41), c)
    c = jnp.where(ne128("pending_id"), jnp.uint32(40), c)
    c = jnp.where(ne128("amount"), jnp.uint32(39), c)
    c = jnp.where(ne128("credit_account_id"), jnp.uint32(38), c)
    c = jnp.where(ne128("debit_account_id"), jnp.uint32(37), c)
    c = jnp.where(t["flags"] != e["flags"], jnp.uint32(36), c)
    return c


# ---------------------------------------------------------------------------
# Lookups (state_machine.zig:1091-1126)
# ---------------------------------------------------------------------------


def lookup_accounts_impl(
    ledger: Ledger, id_lo: jax.Array, id_hi: jax.Array
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    look = ht.lookup(ledger.accounts, id_lo, id_hi, MAX_PROBE)
    cols = ht.gather_cols(ledger.accounts, look.slot, look.found)
    cols["id_lo"] = jnp.where(look.found, id_lo, 0)
    cols["id_hi"] = jnp.where(look.found, id_hi, 0)
    return look.found, cols


lookup_accounts = _obs_jit(lookup_accounts_impl, "lookup_accounts")


def lookup_transfers_impl(
    ledger: Ledger, id_lo: jax.Array, id_hi: jax.Array
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    look = ht.lookup(ledger.transfers, id_lo, id_hi, MAX_PROBE)
    cols = ht.gather_cols(ledger.transfers, look.slot, look.found)
    cols["id_lo"] = jnp.where(look.found, id_lo, 0)
    cols["id_hi"] = jnp.where(look.found, id_hi, 0)
    return look.found, cols


lookup_transfers = _obs_jit(lookup_transfers_impl, "lookup_transfers")


# ---------------------------------------------------------------------------
# Parity digest (the testing/hash_log analogue, testing/hash_log.zig:1-5)
# ---------------------------------------------------------------------------


@jax.jit
def ledger_digest(ledger: Ledger) -> jax.Array:
    """Order-independent deterministic digest of all account balances.

    Sum over live slots of mix64 over (id, balances, timestamp) — the on-device
    analogue of the reference's hash_log/StorageChecker parity oracles."""
    a = ledger.accounts
    live = (a.key_lo != 0) | (a.key_hi != 0)
    h = u128.mix64(a.key_lo, a.key_hi)
    for f in (
        "debits_pending",
        "debits_posted",
        "credits_pending",
        "credits_posted",
    ):
        h = u128.mix64(h ^ a.cols[f + "_lo"], h ^ a.cols[f + "_hi"])
    h = u128.mix64(h, a.cols["timestamp"])
    return jnp.sum(jnp.where(live, h, jnp.uint64(0)))
