"""Fully-vectorized create_transfers commit kernel (the round-2 fast path).

Covers the COMPLETE order-dependent semantics that round 1 delegated to the
sequential lax.scan path, in one data-parallel dispatch:

- two-phase pending / post_pending / void_pending transfers
  (state_machine.zig:1391-1498), including post/void of a pending transfer
  created EARLIER IN THE SAME BATCH, double-post/void detection within the
  batch (first ok fulfillment wins, later ones get already_posted/voided),
  and expiry (:1449-1453);
- per-event-exact overflow checks (:1308-1322) via segmented prefix sums of
  balance deltas — no host-side "amount bound" ratchet;
- history rows (:1342-1364) with exact post-event balances per transfer from
  the same prefix sums — history-flagged accounts no longer force the
  sequential path;
- intra-batch duplicate ids and linked chains as in the v1 kernel.

The cases whose acceptance is genuinely balance-order-dependent set a routing
flag instead of being computed wrong: balancing_debit/credit clamps
(:1286-1306), transfers touching balance-limit accounts (tigerbeetle.zig:31-39),
u128 amounts, an overflow check actually firing, linked chains interacting
with intra-batch references or post/void, and history snapshots whose
opposite-side balances a later event would poison.  When any flag bit is set
the kernel applies NOTHING (every scatter is masked off; the returned ledger
equals the input) and the host dispatcher (machine.py) re-routes the batch to
the sequential path or grows a table and retries.  The flags cost no extra
sync in the server path (result codes are pulled per batch anyway).

Intra-batch references are resolved by Jacobi iteration of a pure
"one sequential pass" operator: references only point to earlier lanes, so
pass k is exact for all lanes whose reference-chain depth is < k, and a
fixpoint (pass k == pass k-1) is THE sequential answer by induction over
lanes.  Three unrolled passes resolve depth <= 2 — which covers every
realistic two-phase batch (pending created + posted in one batch is depth 1,
a duplicate retry of that post is depth 2); deeper chains set FLAG_SEQ via
the stability check.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .. import u128
from ..u128 import U128
from . import hash_table as ht
from .state_machine import (
    AF_CREDITS_MUST_NOT_EXCEED_DEBITS,
    AF_DEBITS_MUST_NOT_EXCEED_CREDITS,
    AF_HISTORY,
    Ledger,
    MAX_PROBE,
    NS_PER_S,
    TF_BALANCING_CREDIT,
    TF_BALANCING_DEBIT,
    TF_LINKED,
    TF_PADDING,
    TF_PENDING,
    TF_POST,
    TF_VOID,
    TRANSFER_COLS,
    _chain_codes,
    _timestamps,
    _u128_col,
)

# Routing flag bits returned by the kernel (uint32). Nonzero => nothing was
# applied; the host must act and re-dispatch.
FLAG_SEQ = 1  # order-dependent semantics: run the sequential path
FLAG_GROW_ACCOUNTS = 2  # a probe hit MAX_PROBE: grow the table + retry
FLAG_GROW_TRANSFERS = 4
FLAG_GROW_POSTED = 8
FLAG_COLD = 16  # an id/pending_id may live in the cold spill: host resolves

_U32MASK = jnp.uint64(0xFFFFFFFF)


def _first_code(checks) -> jnp.ndarray:
    """Vector precedence ladder: the FIRST firing (mask, code) wins."""
    code = jnp.uint32(0)
    for cond, c in reversed(checks):
        val = c if isinstance(c, jnp.ndarray) else jnp.uint32(c)
        code = jnp.where(cond, val, code)
    return code


class IdIndex(NamedTuple):
    """Sorted view of the batch's transfer ids, shared by duplicate
    resolution and the pending-id join."""

    order: jax.Array  # int32[N]: lane at each sorted position
    s_lo: jax.Array
    s_hi: jax.Array
    gid: jax.Array  # int32[N]: group id at each sorted position
    group_of_lane: jax.Array  # int32[N]
    any_dup: jax.Array  # bool: some nonzero id occurs twice


def _build_id_index(id_lo, id_hi) -> IdIndex:
    n = id_lo.shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    order = jnp.lexsort((lane, id_lo, id_hi)).astype(jnp.int32)
    s_lo, s_hi = id_lo[order], id_hi[order]
    same = (s_lo[1:] == s_lo[:-1]) & (s_hi[1:] == s_hi[:-1])
    new_group = jnp.concatenate([jnp.ones((1,), jnp.bool_), ~same])
    gid = (jnp.cumsum(new_group.astype(jnp.int32)) - 1).astype(jnp.int32)
    group_of_lane = jnp.zeros((n,), jnp.int32).at[order].set(gid)
    any_dup = jnp.any(same & ((s_lo[1:] != 0) | (s_hi[1:] != 0)))
    return IdIndex(order, s_lo, s_hi, gid, group_of_lane, any_dup)


def _search128(s_hi, s_lo, q_hi, q_lo) -> jax.Array:
    """First sorted index with (s_hi,s_lo) >= (q_hi,q_lo) — batched binary
    search over 128-bit pairs (13 fixed steps for 8k lanes)."""
    n = s_hi.shape[0]
    lo = jnp.zeros(q_lo.shape, jnp.int32)
    hi = jnp.full(q_lo.shape, n, jnp.int32)
    for _ in range(int(n).bit_length()):
        mid = jnp.minimum((lo + hi) // 2, n - 1)
        m_hi, m_lo = s_hi[mid], s_lo[mid]
        less = (m_hi < q_hi) | ((m_hi == q_hi) & (m_lo < q_lo))
        active = lo < hi
        lo = jnp.where(active & less, mid + 1, lo)
        hi = jnp.where(active & ~less, mid, hi)
    return lo


def _group_winner(idx: IdIndex, ok: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(per-group, per-lane) first ok lane of each id group (n if none)."""
    n = ok.shape[0]
    inf = jnp.int32(n)
    s_ok = ok[idx.order]
    winner_g = jax.ops.segment_min(
        jnp.where(s_ok, idx.order, inf), idx.gid, num_segments=n
    )
    return winner_g, winner_g[idx.group_of_lane]


def _seg_prefix(values: jax.Array, head: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(exclusive, inclusive) prefix sums within runs delimited by ``head``."""
    c = jnp.cumsum(values)
    idx = jnp.arange(values.shape[0], dtype=jnp.int32)
    start_pos = jax.lax.cummax(jnp.where(head, idx, 0))
    base = c[start_pos] - values[start_pos]
    incl = c - base
    return incl - values, incl


def _limbs_to_u128(lo_limb: jax.Array, hi_limb: jax.Array) -> U128:
    """Recombine 32-bit limb sums (each < 2**46 for <=16k terms) into u128."""
    low = lo_limb + ((hi_limb & _U32MASK) << jnp.uint64(32))
    carry = (low < lo_limb).astype(jnp.uint64)
    return U128(low, (hi_limb >> jnp.uint64(32)) + carry)


def create_transfers_full_impl(
    ledger: Ledger,
    batch: Dict[str, jax.Array],
    count: jax.Array,
    timestamp: jax.Array,
    bloom: jax.Array = None,
    cold_checked: jax.Array = None,
) -> Tuple[Ledger, jax.Array, jax.Array]:
    """Returns (ledger', codes uint32[N], flags uint32 scalar).

    flags == 0: the batch was applied and ``codes`` are the final results.
    flags != 0: NOTHING was applied (ledger' == ledger value-wise); the host
    must grow the flagged tables, resolve cold ids (FLAG_COLD: ``bloom`` is
    the cold-id filter, ``cold_checked`` marks lanes the host already
    certified), and/or re-route to the sequential path.
    """
    n = batch["id_lo"].shape[0]
    assert n <= 1 << 14, "leg sort key packs (slot, legpos<2^15)"
    lane = jnp.arange(n, dtype=jnp.int32)
    valid = lane < count.astype(jnp.int32)
    ts = _timestamps(count, timestamp, n)

    tid = _u128_col(batch, "id")
    t_dr_id = _u128_col(batch, "debit_account_id")
    t_cr_id = _u128_col(batch, "credit_account_id")
    t_amt = _u128_col(batch, "amount")
    pend_id = _u128_col(batch, "pending_id")
    flags = batch["flags"]
    post = ((flags & TF_POST) != 0) & valid
    void = ((flags & TF_VOID) != 0) & valid
    postvoid = post | void
    pending_f = ((flags & TF_PENDING) != 0) & valid
    linked = ((flags & TF_LINKED) != 0) & valid
    balancing = ((flags & (TF_BALANCING_DEBIT | TF_BALANCING_CREDIT)) != 0) & valid

    # ---------------- table gathers (iteration-invariant) -----------------
    ex_look = ht.lookup(ledger.transfers, tid.lo, tid.hi, MAX_PROBE)
    ex_found = ex_look.found & valid
    e_tab = ht.gather_cols(ledger.transfers, ex_look.slot, ex_found)

    p_look = ht.lookup(ledger.transfers, pend_id.lo, pend_id.hi, MAX_PROBE)
    p_tab_found = p_look.found & postvoid
    p_tab = ht.gather_cols(ledger.transfers, p_look.slot, p_tab_found)

    drT_look = ht.lookup(ledger.accounts, t_dr_id.lo, t_dr_id.hi, MAX_PROBE)
    crT_look = ht.lookup(ledger.accounts, t_cr_id.lo, t_cr_id.hi, MAX_PROBE)
    drT_found = drT_look.found & valid
    crT_found = crT_look.found & valid
    drT = ht.gather_cols(ledger.accounts, drT_look.slot, drT_found)
    crT = ht.gather_cols(ledger.accounts, crT_look.slot, crT_found)

    # Accounts of a TABLE pending (post/void operates on the pending's
    # accounts, state_machine.zig:1420-1423).
    pdr_look = ht.lookup(
        ledger.accounts, p_tab["debit_account_id_lo"],
        p_tab["debit_account_id_hi"], MAX_PROBE,
    )
    pcr_look = ht.lookup(
        ledger.accounts, p_tab["credit_account_id_lo"],
        p_tab["credit_account_id_hi"], MAX_PROBE,
    )

    # Posted-groove fulfillment for a TABLE pending (key: its timestamp).
    postedT_look = ht.lookup(
        ledger.posted, p_tab["timestamp"], jnp.zeros_like(p_tab["timestamp"]),
        MAX_PROBE,
    )
    postedT_found = postedT_look.found & p_tab_found
    postedT_val = ht.gather_cols(
        ledger.posted, postedT_look.slot, postedT_found
    )["fulfillment"]

    probe_grow = (
        jnp.where(
            drT_look.overflow | crT_look.overflow | pdr_look.overflow
            | pcr_look.overflow,
            jnp.uint32(FLAG_GROW_ACCOUNTS), jnp.uint32(0),
        )
        | jnp.where(
            ex_look.overflow | p_look.overflow,
            jnp.uint32(FLAG_GROW_TRANSFERS), jnp.uint32(0),
        )
        | jnp.where(postedT_look.overflow, jnp.uint32(FLAG_GROW_POSTED), jnp.uint32(0))
    )

    # Cold-tier membership (ops/cold.py): an id or pending_id missing from
    # the HOT table but hitting the cold Bloom filter needs host resolution
    # (exact exists-precedence demands the cold row). cold_checked lanes were
    # already certified not-cold by the host, so false positives terminate.
    if bloom is not None:
        from .cold import bloom_check_impl

        checked = (
            cold_checked if cold_checked is not None
            else jnp.zeros((n,), jnp.bool_)
        )
        cold_ids = (
            valid & ~ex_look.found & ~checked
            & bloom_check_impl(bloom, tid.lo, tid.hi)
        )
        cold_pend = (
            postvoid & ~p_look.found & ~checked
            & bloom_check_impl(bloom, pend_id.lo, pend_id.hi)
        )
        probe_grow = probe_grow | jnp.where(
            jnp.any(cold_ids | cold_pend), jnp.uint32(FLAG_COLD), jnp.uint32(0)
        )

    idx = _build_id_index(tid.lo, tid.hi)

    # In-batch pending-create candidate group for each pv lane.
    pj = _search128(idx.s_hi, idx.s_lo, pend_id.hi, pend_id.lo)
    pj_c = jnp.minimum(pj, n - 1)
    pj_hit = (idx.s_hi[pj_c] == pend_id.hi) & (idx.s_lo[pj_c] == pend_id.lo) & (pj < n)
    pj_group = idx.gid[pj_c]

    # ------------------------------------------------------------------
    # One Jacobi pass of the sequential semantics.
    # ------------------------------------------------------------------

    def one_pass(ok_prev: jax.Array):
        inf = jnp.int32(n)
        winner_g, winner_of_lane = _group_winner(idx, ok_prev)

        # --- resolve each pv lane's pending row -------------------------
        pw = winner_g[pj_group]
        pwc = jnp.minimum(jnp.where(pj_hit, pw, inf), n - 1).astype(jnp.int32)
        # Any inserted transfer resolves the reference (a non-pending one
        # then fails the p_is_pending check with code 26, like the table
        # path — state_machine.zig:1417).
        in_batch_ref = (
            postvoid & pj_hit & (pw < inf) & (pw < lane) & ok_prev[pwc]
        )

        p_found = p_tab_found | in_batch_ref
        p = {}
        for name in TRANSFER_COLS:
            if name == "timestamp":
                p[name] = jnp.where(in_batch_ref, ts[pwc], p_tab[name])
            else:
                p[name] = jnp.where(in_batch_ref, batch[name][pwc], p_tab[name])
        p_is_pending = ((p["flags"] & TF_PENDING) != 0) & p_found
        p_amt = U128(p["amount_lo"], p["amount_hi"])
        p_dr_id = U128(p["debit_account_id_lo"], p["debit_account_id_hi"])
        p_cr_id = U128(p["credit_account_id_lo"], p["credit_account_id_hi"])

        # Effective account slots (regular: own; pv: the pending's).
        dr_slot = jnp.where(
            in_batch_ref, drT_look.slot[pwc],
            jnp.where(postvoid, pdr_look.slot, drT_look.slot),
        )
        cr_slot = jnp.where(
            in_batch_ref, crT_look.slot[pwc],
            jnp.where(postvoid, pcr_look.slot, crT_look.slot),
        )
        acc_flags_dr = ledger.accounts.cols["flags"][dr_slot]
        acc_flags_cr = ledger.accounts.cols["flags"][cr_slot]

        # --- composed insert rows (state_machine.zig:1326-1328, 1455-1469) -
        amount = u128.select(postvoid & u128.is_zero(t_amt), p_amt, t_amt)
        row = {name: batch[name] for name in TRANSFER_COLS}
        row["timestamp"] = ts
        row["amount_lo"] = amount.lo
        row["amount_hi"] = amount.hi
        for name in ("debit_account_id", "credit_account_id"):
            for l_ in ("_lo", "_hi"):
                row[name + l_] = jnp.where(postvoid, p[name + l_], batch[name + l_])
        ud128_nz = (batch["user_data_128_lo"] != 0) | (batch["user_data_128_hi"] != 0)
        for l_ in ("_lo", "_hi"):
            row["user_data_128" + l_] = jnp.where(
                postvoid & ~ud128_nz, p["user_data_128" + l_],
                batch["user_data_128" + l_],
            )
        for name in ("user_data_64", "user_data_32"):
            row[name] = jnp.where(postvoid & (batch[name] == 0), p[name], batch[name])
        row["ledger"] = jnp.where(postvoid, p["ledger"], batch["ledger"])
        row["code"] = jnp.where(postvoid, p["code"], batch["code"])
        row["timeout"] = jnp.where(postvoid, jnp.uint32(0), batch["timeout"])

        # --- regular-path ladder (through the exists check + ov_timeout;
        # the balance-dependent tail is handled by prefix sums / FLAG_SEQ) --
        timeout_ns = batch["timeout"].astype(jnp.uint64) * jnp.uint64(NS_PER_S)
        ov_timeout = (ts + timeout_ns) < ts
        exists_tab_reg = _exists_regular(batch, e_tab, amount, n)
        reg_code = _first_code([
            (((flags & TF_PADDING) != 0), 4),
            (u128.is_zero(tid), 5),
            (u128.is_max(tid), 6),
            (u128.is_zero(t_dr_id), 8),
            (u128.is_max(t_dr_id), 9),
            (u128.is_zero(t_cr_id), 10),
            (u128.is_max(t_cr_id), 11),
            (u128.eq(t_dr_id, t_cr_id), 12),
            (~u128.is_zero(pend_id), 13),
            (~pending_f & (batch["timeout"] != 0), 17),
            (~balancing & u128.is_zero(t_amt), 18),
            ((batch["ledger"] == 0), 19),
            ((batch["code"] == 0), 20),
            (~drT_found, 21),
            (~crT_found, 22),
            ((drT["ledger"] != crT["ledger"]), 23),
            ((batch["ledger"] != drT["ledger"]), 24),
            (ex_found, exists_tab_reg),
            (ov_timeout, 53),
        ])

        # --- post/void ladder (state_machine.zig:1391-1453) ----------------
        exists_tab_pv = _exists_postvoid(batch, e_tab, p, n)
        expiry_ns = p["timeout"].astype(jnp.uint64) * jnp.uint64(NS_PER_S)
        expired = (p["timeout"] != 0) & (ts >= p["timestamp"] + expiry_ns)
        pv_code = _first_code([
            (((flags & TF_PADDING) != 0), 4),
            (u128.is_zero(tid), 5),
            (u128.is_max(tid), 6),
            (post & void, 7),
            (pending_f, 7),
            (balancing, 7),
            (u128.is_zero(pend_id), 14),
            (u128.is_max(pend_id), 15),
            (u128.eq(pend_id, tid), 16),
            ((batch["timeout"] != 0), 17),
            (~p_found, 25),
            (~p_is_pending, 26),
            (~u128.is_zero(t_dr_id) & ~u128.eq(t_dr_id, p_dr_id), 27),
            (~u128.is_zero(t_cr_id) & ~u128.eq(t_cr_id, p_cr_id), 28),
            (((batch["ledger"] != 0) & (batch["ledger"] != p["ledger"])), 29),
            (((batch["code"] != 0) & (batch["code"] != p["code"])), 30),
            (u128.gt(amount, p_amt), 31),
            (void & u128.lt(amount, p_amt), 32),
            (ex_found, exists_tab_pv),
            (postedT_found & (postedT_val == 1), 33),
            (postedT_found & (postedT_val == 2), 34),
            (expired, 35),
        ])

        code = jnp.where(postvoid, pv_code, reg_code)
        code = jnp.where(batch["timestamp"] != 0, jnp.uint32(3), code)

        # --- intra-batch duplicate ids ------------------------------------
        # In sequential order the exists check sits BEFORE the fulfillment/
        # expiry checks (pv) and BEFORE ov_timeout (regular), so the in-batch
        # override replaces exactly those post-exists codes.
        after_winner = (winner_of_lane < inf) & (lane > winner_of_lane)
        wc = jnp.minimum(winner_of_lane, n - 1).astype(jnp.int32)
        w_row = {k: v[wc] for k, v in row.items()}
        intra_reg = _exists_regular(batch, w_row, amount, n)
        intra_pv = _exists_postvoid(batch, w_row, p, n)
        intra = jnp.where(postvoid, intra_pv, intra_reg)
        dup_overridable = jnp.where(
            postvoid,
            (code == 0) | (code == 33) | (code == 34) | (code == 35),
            (code == 0) | (code == 53),
        )
        code = jnp.where(after_winner & dup_overridable, intra, code)

        # --- intra-batch double post/void ---------------------------------
        # Group pv lanes by resolved pending timestamp; the first lane whose
        # pre-fulfillment checks pass records the fulfillment; later ones get
        # already_posted/voided. (Linked chains cannot interact: batches with
        # linked AND post/void route to the sequential path.)
        p_ts_key = jnp.where(postvoid & p_found, p["timestamp"], 0)
        f_order = jnp.lexsort((lane, p_ts_key)).astype(jnp.int32)
        f_ts = p_ts_key[f_order]
        f_head = jnp.concatenate([jnp.ones((1,), jnp.bool_), f_ts[1:] != f_ts[:-1]])
        f_gid = (jnp.cumsum(f_head.astype(jnp.int32)) - 1).astype(jnp.int32)
        f_ok = (code[f_order] == 0) & (f_ts != 0)
        f_winner_g = jax.ops.segment_min(
            jnp.where(f_ok, f_order, inf), f_gid, num_segments=n
        )
        f_winner = jnp.zeros((n,), jnp.int32).at[f_order].set(f_winner_g[f_gid])
        fulfil_after = (f_winner < inf) & (lane > f_winner) & (p_ts_key != 0)
        fwc = jnp.minimum(f_winner, n - 1).astype(jnp.int32)
        fulfil_code = jnp.where(post[fwc], jnp.uint32(33), jnp.uint32(34))
        code = jnp.where(
            fulfil_after & ((code == 0) | (code == 35)), fulfil_code, code
        )

        # --- linked chains -------------------------------------------------
        code = jnp.where(~valid, 0, code)
        code = _chain_codes(linked, code, count)
        ok = (code == 0) & valid
        aux = dict(
            in_batch_ref=in_batch_ref, p=p, p_found=p_found, p_amt=p_amt,
            dr_slot=dr_slot, cr_slot=cr_slot, row=row, amount=amount,
            acc_flags_dr=acc_flags_dr, acc_flags_cr=acc_flags_cr,
        )
        return ok, code, aux

    ok0 = jnp.zeros((n,), jnp.bool_)
    ok1, code1, _ = one_pass(ok0)
    ok2, code2, _ = one_pass(ok1)
    ok, codes, aux = one_pass(ok2)
    unconverged = jnp.any(code2 != codes)

    dr_slot, cr_slot = aux["dr_slot"], aux["cr_slot"]
    amount, p_amt = aux["amount"], aux["p_amt"]
    row = aux["row"]
    in_batch_ref = aux["in_batch_ref"]

    # ---------------- balance legs + exact prefix balances -----------------
    # Leg 2i = debit side of event i, 2i+1 = credit side. Sorted by
    # (account slot, SIDE, leg position): an account's debit-side fields are
    # only touched by debit legs, so per-(slot, side) prefixes in event order
    # reconstruct each field's exact running value.
    cap = ledger.accounts.capacity
    cap_sentinel = jnp.uint64(cap)
    leg_slot_raw = jnp.stack([dr_slot, cr_slot], axis=1).reshape(-1)
    leg_ok = jnp.repeat(ok, 2)
    leg_pos_id = jnp.arange(2 * n, dtype=jnp.uint64)
    leg_is_dr = (jnp.arange(2 * n, dtype=jnp.int32) & 1) == 0
    leg_slot = jnp.where(leg_ok, leg_slot_raw, cap_sentinel)

    amt_l = jnp.repeat(amount.lo, 2)
    pamt_l = jnp.repeat(p_amt.lo, 2)
    pend2 = jnp.repeat(pending_f, 2)
    post2 = jnp.repeat(post, 2)
    pv2 = jnp.repeat(postvoid, 2)

    # u64 per-leg deltas (u128 amounts route to FLAG_SEQ below).
    d_pending_add = jnp.where(leg_ok & pend2, amt_l, 0)
    d_pending_sub = jnp.where(leg_ok & pv2, pamt_l, 0)
    d_posted_add = jnp.where(leg_ok & ((~pend2 & ~pv2) | post2), amt_l, 0)

    side_bit = jnp.where(leg_is_dr, jnp.uint64(0), jnp.uint64(1))
    sort_key = (leg_slot << jnp.uint64(16)) | (side_bit << jnp.uint64(15)) | leg_pos_id
    leg_order = jnp.argsort(sort_key)
    s_key = sort_key[leg_order] >> jnp.uint64(15)  # (slot, side)
    s_slot = leg_slot[leg_order]
    s_live = s_slot < cap_sentinel
    s_head = jnp.concatenate([jnp.ones((1,), jnp.bool_), s_key[1:] != s_key[:-1]])

    def limb_prefix(vals):
        v = vals[leg_order]
        lo_e, lo_i = _seg_prefix(v & _U32MASK, s_head)
        hi_e, hi_i = _seg_prefix(v >> jnp.uint64(32), s_head)
        return (lo_e, hi_e), (lo_i, hi_i)

    pa_e, pa_i = limb_prefix(d_pending_add)
    ps_e, ps_i = limb_prefix(d_pending_sub)
    oa_e, oa_i = limb_prefix(d_posted_add)

    s_is_dr = leg_is_dr[leg_order]
    safe_slot = jnp.where(s_live, s_slot, 0)
    acols = ledger.accounts.cols

    def start_bal(field_dr, field_cr):
        lo = jnp.where(
            s_is_dr, acols[field_dr + "_lo"][safe_slot],
            acols[field_cr + "_lo"][safe_slot],
        )
        hi = jnp.where(
            s_is_dr, acols[field_dr + "_hi"][safe_slot],
            acols[field_cr + "_hi"][safe_slot],
        )
        return U128(lo, hi)

    start_pend = start_bal("debits_pending", "credits_pending")
    start_post = start_bal("debits_posted", "credits_posted")

    def bal_at(start, add_limbs, sub_limbs):
        added, ov1 = u128.add(start, _limbs_to_u128(*add_limbs))
        val, neg = u128.sub(added, _limbs_to_u128(*sub_limbs))
        return val, ov1, neg

    zero2 = (jnp.zeros((2 * n,), jnp.uint64), jnp.zeros((2 * n,), jnp.uint64))
    pend_pre, ovA, negA = bal_at(start_pend, pa_e, ps_e)
    pend_post_, ovB, negB = bal_at(start_pend, pa_i, ps_i)
    post_pre, ovC, _ = bal_at(start_post, oa_e, zero2)
    post_post_, ovD, _ = bal_at(start_post, oa_i, zero2)
    arith_broken = jnp.any(s_live & (ovA | ovB | ovC | ovD | negA | negB))

    # Exact per-event overflow ladder (state_machine.zig:1308-1320): any
    # firing means sequential execution would reject an event we accepted,
    # changing later balances -> route the batch.
    s_okleg = leg_ok[leg_order] & s_live
    s_amt128 = U128(amt_l[leg_order], jnp.zeros((2 * n,), jnp.uint64))
    s_pend2 = pend2[leg_order]
    s_pv2 = pv2[leg_order]
    _, ov_p = u128.add(s_amt128, pend_pre)
    _, ov_o = u128.add(s_amt128, post_pre)
    tot, ov_t1 = u128.add(pend_pre, post_pre)
    _, ov_t2 = u128.add(s_amt128, tot)
    overflow_fires = jnp.any(
        s_okleg & ~s_pv2
        & ((s_pend2 & ov_p) | ov_o | ov_t1 | ov_t2)
    )

    # ---------------- history (state_machine.zig:1342-1364) ----------------
    dr_hist = ((aux["acc_flags_dr"] & AF_HISTORY) != 0) & ok
    cr_hist = ((aux["acc_flags_cr"] & AF_HISTORY) != 0) & ok
    do_hist = (dr_hist | cr_hist) & ~postvoid
    # The same-side balances per event are exact (prefix sums above); the
    # OPPOSITE side of a recorded account is gathered from the post-batch
    # table, which is only the correct per-event snapshot if no LATER ok
    # event touches that account's opposite side.
    hist_alias = jnp.any(do_hist) & _hist_cross_side_alias(
        dr_slot, cr_slot, ok, do_hist & dr_hist, do_hist & cr_hist, cap
    )

    # ---------------- routing flags ---------------------------------------
    limit_flags = AF_DEBITS_MUST_NOT_EXCEED_CREDITS | AF_CREDITS_MUST_NOT_EXCEED_DEBITS
    any_limit = jnp.any(
        valid & (
            (((drT["flags"] & limit_flags) != 0) & drT_found)
            | (((crT["flags"] & limit_flags) != 0) & crT_found)
            | (((aux["acc_flags_dr"] & limit_flags) != 0) & postvoid & aux["p_found"])
            | (((aux["acc_flags_cr"] & limit_flags) != 0) & postvoid & aux["p_found"])
        )
    )
    any_u128_amount = jnp.any(
        valid & ((batch["amount_hi"] != 0) | (postvoid & (aux["p"]["amount_hi"] != 0)))
    )
    any_linked = jnp.any(linked)
    linked_x_intra = any_linked & (
        idx.any_dup | jnp.any(in_batch_ref) | jnp.any(postvoid)
    )

    # Insert slots are claimed (no writes) BEFORE the flags are finalized so
    # an insert-probe overflow also routes the batch with nothing applied.
    t_claim, t_ovf = ht.claim_slots(ledger.transfers, tid.lo, tid.hi, ok, MAX_PROBE)
    pv_ok_pre = ok & postvoid
    posted_key = jnp.where(pv_ok_pre, aux["p"]["timestamp"], 0)
    p_claim, p_ovf = ht.claim_slots(
        ledger.posted, posted_key, jnp.zeros((n,), jnp.uint64), pv_ok_pre, MAX_PROBE
    )
    probe_grow = (
        probe_grow
        | jnp.where(t_ovf, jnp.uint32(FLAG_GROW_TRANSFERS), jnp.uint32(0))
        | jnp.where(p_ovf, jnp.uint32(FLAG_GROW_POSTED), jnp.uint32(0))
    )

    kflags = probe_grow | jnp.where(
        unconverged | any_limit | jnp.any(balancing) | any_u128_amount
        | linked_x_intra | arith_broken | overflow_fires | hist_alias,
        jnp.uint32(FLAG_SEQ), jnp.uint32(0),
    )
    commit = kflags == jnp.uint32(0)

    # ---------------- apply: balances (two scatters, one per side) ---------
    is_last = jnp.concatenate([s_key[1:] != s_key[:-1], jnp.ones((1,), jnp.bool_)])
    scat = is_last & s_live & commit
    dr_scat = scat & s_is_dr
    cr_scat = scat & ~s_is_dr
    accounts = ht.scatter_cols(
        ledger.accounts, jnp.where(dr_scat, s_slot, cap_sentinel), dr_scat,
        {
            "debits_pending_lo": pend_post_.lo, "debits_pending_hi": pend_post_.hi,
            "debits_posted_lo": post_post_.lo, "debits_posted_hi": post_post_.hi,
        },
    )
    accounts = ht.scatter_cols(
        accounts, jnp.where(cr_scat, s_slot, cap_sentinel), cr_scat,
        {
            "credits_pending_lo": pend_post_.lo, "credits_pending_hi": pend_post_.hi,
            "credits_posted_lo": post_post_.lo, "credits_posted_hi": post_post_.hi,
        },
    )

    # ---------------- apply: transfer + posted inserts ---------------------
    ins_rows = {name: row[name].astype(dt) for name, dt in TRANSFER_COLS.items()}
    transfers = ht.write_rows(
        ledger.transfers, tid.lo, tid.hi, t_claim, ok & commit, ins_rows
    )
    posted = ht.write_rows(
        ledger.posted,
        posted_key,
        jnp.zeros((n,), jnp.uint64),
        p_claim,
        pv_ok_pre & commit,
        {"fulfillment": jnp.where(post, jnp.uint32(1), jnp.uint32(2))},
    )

    # ---------------- apply: history rows ---------------------------------
    leg_pos = jnp.zeros((2 * n,), jnp.int32).at[leg_order].set(
        jnp.arange(2 * n, dtype=jnp.int32)
    )

    def lane_bal(leg_index):
        pos = leg_pos[leg_index]
        return (
            pend_post_.lo[pos], pend_post_.hi[pos],
            post_post_.lo[pos], post_post_.hi[pos],
        )

    do_hist_c = do_hist & commit
    h = ledger.history
    h_off = jnp.cumsum(do_hist_c.astype(jnp.uint64)) - do_hist_c.astype(jnp.uint64)
    h_idx = jnp.where(do_hist_c, h.count + h_off, jnp.uint64(h.capacity))

    dr_dp_lo, dr_dp_hi, dr_dpo_lo, dr_dpo_hi = lane_bal(2 * lane)
    cr_cp_lo, cr_cp_hi, cr_cpo_lo, cr_cpo_hi = lane_bal(2 * lane + 1)
    hist_row = {
        "timestamp": ts,
        "dr_id_lo": jnp.where(dr_hist, row["debit_account_id_lo"], 0),
        "dr_id_hi": jnp.where(dr_hist, row["debit_account_id_hi"], 0),
        "dr_dp_lo": jnp.where(dr_hist, dr_dp_lo, 0),
        "dr_dp_hi": jnp.where(dr_hist, dr_dp_hi, 0),
        "dr_dpo_lo": jnp.where(dr_hist, dr_dpo_lo, 0),
        "dr_dpo_hi": jnp.where(dr_hist, dr_dpo_hi, 0),
        "dr_cp_lo": jnp.where(dr_hist, accounts.cols["credits_pending_lo"][dr_slot], 0),
        "dr_cp_hi": jnp.where(dr_hist, accounts.cols["credits_pending_hi"][dr_slot], 0),
        "dr_cpo_lo": jnp.where(dr_hist, accounts.cols["credits_posted_lo"][dr_slot], 0),
        "dr_cpo_hi": jnp.where(dr_hist, accounts.cols["credits_posted_hi"][dr_slot], 0),
        "cr_id_lo": jnp.where(cr_hist, row["credit_account_id_lo"], 0),
        "cr_id_hi": jnp.where(cr_hist, row["credit_account_id_hi"], 0),
        "cr_cp_lo": jnp.where(cr_hist, cr_cp_lo, 0),
        "cr_cp_hi": jnp.where(cr_hist, cr_cp_hi, 0),
        "cr_cpo_lo": jnp.where(cr_hist, cr_cpo_lo, 0),
        "cr_cpo_hi": jnp.where(cr_hist, cr_cpo_hi, 0),
        "cr_dp_lo": jnp.where(cr_hist, accounts.cols["debits_pending_lo"][cr_slot], 0),
        "cr_dp_hi": jnp.where(cr_hist, accounts.cols["debits_pending_hi"][cr_slot], 0),
        "cr_dpo_lo": jnp.where(cr_hist, accounts.cols["debits_posted_lo"][cr_slot], 0),
        "cr_dpo_hi": jnp.where(cr_hist, accounts.cols["debits_posted_hi"][cr_slot], 0),
    }
    history = h.replace(
        cols={
            name: h.cols[name].at[h_idx].set(hist_row[name], mode="drop")
            for name in h.cols
        },
        count=h.count + jnp.sum(do_hist_c.astype(jnp.uint64)),
    )

    out = Ledger(
        accounts=accounts, transfers=transfers, posted=posted, history=history
    )
    return out, codes, kflags


def _hist_cross_side_alias(dr_slot, cr_slot, ok, rec_dr, rec_cr, cap):
    """True if a history-recorded account is touched on its OPPOSITE side by
    a LATER ok event (poisoning the gathered post-batch snapshot)."""
    n = ok.shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    sent = jnp.uint64(cap)

    def violated(rec_slot, rec_mask, opp_slot, opp_mask):
        key_all = jnp.concatenate([
            jnp.where(rec_mask, rec_slot, sent),
            jnp.where(opp_mask, opp_slot, sent),
        ])
        lane2 = jnp.concatenate([lane, lane])
        is_opp = jnp.concatenate(
            [jnp.zeros((n,), jnp.bool_), jnp.ones((n,), jnp.bool_)]
        )
        order = jnp.argsort(key_all)
        s = key_all[order]
        head = jnp.concatenate([jnp.ones((1,), jnp.bool_), s[1:] != s[:-1]])
        gid = jnp.cumsum(head.astype(jnp.int32)) - 1
        live = s < sent
        opp_max = jax.ops.segment_max(
            jnp.where(is_opp[order] & live, lane2[order], -1),
            gid, num_segments=2 * n,
        )
        rec_is = ~is_opp[order] & live
        return jnp.any(rec_is & (opp_max[gid] > lane2[order]))

    # dr-account records: poisoned by later events using it as credit side.
    v1 = violated(dr_slot, rec_dr, cr_slot, ok)
    v2 = violated(cr_slot, rec_cr, dr_slot, ok)
    return v1 | v2


def _exists_regular(t, e, t_amount: U128, n) -> jax.Array:
    """create_transfer_exists (state_machine.zig:1370-1389): ``t`` the raw
    event, ``e`` the stored/winner row, ``t_amount`` the event amount."""

    def ne128(name):
        return (t[name + "_lo"] != e[name + "_lo"]) | (
            t[name + "_hi"] != e[name + "_hi"]
        )

    c = jnp.full((n,), 46, jnp.uint32)
    c = jnp.where(t["code"] != e["code"], jnp.uint32(45), c)
    c = jnp.where(t["timeout"] != e["timeout"], jnp.uint32(44), c)
    c = jnp.where(t["user_data_32"] != e["user_data_32"], jnp.uint32(43), c)
    c = jnp.where(t["user_data_64"] != e["user_data_64"], jnp.uint32(42), c)
    c = jnp.where(ne128("user_data_128"), jnp.uint32(41), c)
    amount_ne = (t_amount.lo != e["amount_lo"]) | (t_amount.hi != e["amount_hi"])
    c = jnp.where(ne128("pending_id"), jnp.uint32(40), c)
    c = jnp.where(amount_ne, jnp.uint32(39), c)
    c = jnp.where(ne128("credit_account_id"), jnp.uint32(38), c)
    c = jnp.where(ne128("debit_account_id"), jnp.uint32(37), c)
    c = jnp.where(t["flags"] != e["flags"], jnp.uint32(36), c)
    return c


def _exists_postvoid(t, e, p, n) -> jax.Array:
    """post_or_void_pending_transfer_exists (state_machine.zig:1500-1561)."""

    def pair_ne(a, b, name):
        return (a[name + "_lo"] != b[name + "_lo"]) | (
            a[name + "_hi"] != b[name + "_hi"]
        )

    t_amount_zero = (t["amount_lo"] == 0) & (t["amount_hi"] == 0)
    amount_ne = jnp.where(
        t_amount_zero, pair_ne(e, p, "amount"), pair_ne(t, e, "amount")
    )
    ud128_zero = (t["user_data_128_lo"] == 0) & (t["user_data_128_hi"] == 0)
    ud128_ne = jnp.where(
        ud128_zero, pair_ne(e, p, "user_data_128"), pair_ne(t, e, "user_data_128")
    )
    ud64_ne = jnp.where(
        t["user_data_64"] == 0, e["user_data_64"] != p["user_data_64"],
        t["user_data_64"] != e["user_data_64"],
    )
    ud32_ne = jnp.where(
        t["user_data_32"] == 0, e["user_data_32"] != p["user_data_32"],
        t["user_data_32"] != e["user_data_32"],
    )
    c = jnp.full((n,), 46, jnp.uint32)
    c = jnp.where(ud32_ne, jnp.uint32(43), c)
    c = jnp.where(ud64_ne, jnp.uint32(42), c)
    c = jnp.where(ud128_ne, jnp.uint32(41), c)
    c = jnp.where(pair_ne(t, e, "pending_id"), jnp.uint32(40), c)
    c = jnp.where(amount_ne, jnp.uint32(39), c)
    c = jnp.where(t["flags"] != e["flags"], jnp.uint32(36), c)
    return c


create_transfers_full = jax.jit(
    create_transfers_full_impl, donate_argnames=("ledger",)
)
