"""Fully-vectorized create_transfers commit kernel (the round-2/3 fast path).

Covers the COMPLETE order-dependent semantics that round 1 delegated to the
sequential lax.scan path, in one data-parallel dispatch:

- two-phase pending / post_pending / void_pending transfers
  (state_machine.zig:1391-1498), including post/void of a pending transfer
  created EARLIER IN THE SAME BATCH, double-post/void detection within the
  batch (first ok fulfillment wins, later ones get already_posted/voided),
  and expiry (:1449-1453);
- balancing_debit / balancing_credit clamps (state_machine.zig:1286-1306)
  evaluated per event against that event's EXACT running pre-balances;
- balance-limit accounts (tigerbeetle.zig:31-39): exceeds_credits /
  exceeds_debits evaluated per event, exactly;
- per-event-exact overflow checks (:1308-1322) as first-class result codes
  (47..52) — not a host re-route;
- history rows (:1342-1364) with exact post-event balances of BOTH sides of
  every recorded account, from the same running balances;
- intra-batch duplicate ids and linked chains as in the v1 kernel.

Running balances are reconstructed per event without a sequential scan: each
event contributes a debit leg (2i) and a credit leg (2i+1); legs are sorted
by (account slot, leg position) and segmented prefix sums over the slot runs
of all four balance fields (debits_pending/posted, credits_pending/posted)
yield every leg's exact pre- and post-event account state — leg position
order IS event order, so the exclusive prefix at a leg includes precisely
the effects of earlier accepted events, both sides.

Because acceptance (and balancing-clamped amounts) feed back into later
events' balances, the balance machinery lives INSIDE the Jacobi fixpoint
iteration: pass k computes balances from pass k-1's (accepted, amount)
vector, then re-evaluates every ladder.  References only point to earlier
lanes and a stable pass (codes AND amounts unchanged) is a fixpoint of the
exact "evaluate lane i given outcomes of lanes j<i" operator, whose fixpoint
is unique and equal to the sequential answer (induction over lanes).  The
pass runs under a lax.while_loop with an early-exit stability check: pass
k+1 resolves every batch whose outcome-change cascade depth is <= k
(uncontended batches stabilize in 2 passes; each clamp/rejection cascade
adds 1), up to _MAX_PASSES; deeper cascades set FLAG_SEQ and run
sequentially.

The remaining FLAG_SEQ routes are genuinely order-chaotic or out-of-scope
for the u64-limb delta machinery: unconverged fixpoints, u128 amounts,
linked chains interacting with intra-batch references/post-void, failed
linked chains whose members' codes are balance-dependent (the sequential
path sees the chain's transient effects; the fixpoint sees the rollback),
and balance reconstructions that overflow u128.  When any flag bit is set
the kernel applies NOTHING (every scatter is masked off; the returned ledger
equals the input) and the host dispatcher (machine.py) re-routes the batch
to the sequential path or grows a table and retries.

Structure (round-3 refactor for the sharded path, parallel/sharded.py):

    GatherCtx       every table-derived input, assembled either by local
                    ht.lookup (single chip) or masked-probe + psum combine
                    over a device mesh (every shard then holds the full,
                    replicated context);
    _kernel_core    the PURE batch semantics: Jacobi loop, ladders, balance
                    legs — identical replicated math on every shard, no
                    table access;
    apply           claims + scatters, owner-local on a mesh.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import u128
from ..u128 import U128
from . import hash_table as ht
from .state_machine import (
    AF_CREDITS_MUST_NOT_EXCEED_DEBITS,
    AF_DEBITS_MUST_NOT_EXCEED_CREDITS,
    AF_HISTORY,
    Ledger,
    MAX_PROBE,
    NS_PER_S,
    TF_BALANCING_CREDIT,
    TF_BALANCING_DEBIT,
    TF_LINKED,
    TF_PADDING,
    TF_PENDING,
    TF_POST,
    TF_VOID,
    TRANSFER_COLS,
    _chain_codes,
    _timestamps,
    _u128_col,
)

# Routing flag bits returned by the kernel (uint32). Nonzero => nothing was
# applied; the host must act and re-dispatch.
FLAG_SEQ = 1  # order-dependent semantics: run the sequential path
FLAG_GROW_ACCOUNTS = 2  # a probe hit MAX_PROBE: grow the table + retry
FLAG_GROW_TRANSFERS = 4
FLAG_GROW_POSTED = 8
FLAG_COLD = 16  # an id/pending_id may live in the cold spill: host resolves

_U32MASK = jnp.uint64(0xFFFFFFFF)
_U64MAX = jnp.uint64(0xFFFF_FFFF_FFFF_FFFF)

# Result codes whose value depends on account balances (clamps, overflow
# ladder, limits). Used for the failed-linked-chain hazard route.
_BALANCE_CODES = (47, 48, 49, 50, 51, 52, 54, 55)

# Jacobi pass budget: pass k is exact for outcome-cascade depth < k, and a
# stable pass is THE answer, so this bounds only how deep accept/reject
# cascades may go before the batch routes to the sequential path.
_MAX_PASSES = 8

# Account balance fields carried through GatherCtx (limb pairs).
_BAL_FIELDS = (
    "debits_pending", "debits_posted", "credits_pending", "credits_posted",
)


class AccountView(NamedTuple):
    """The slice of an account row the kernel core needs."""

    found: jax.Array  # bool[N]
    slot: jax.Array  # uint64[N] — GLOBAL slot id (mesh: owner-offset)
    flags: jax.Array  # uint32[N]
    ledger: jax.Array  # uint32[N]
    bal: Dict[str, jax.Array]  # {field_lo/_hi: uint64[N]}


class GatherCtx(NamedTuple):
    """Every table-derived input of the pure kernel core.

    Single-chip: built by local probes (build_gather_ctx). Mesh: every
    shard probes its partition and psums the masked results, after which
    the ctx is replicated (parallel/sharded.py)."""

    ex_found: jax.Array
    e_tab: Dict[str, jax.Array]
    p_tab_found: jax.Array
    p_tab: Dict[str, jax.Array]
    drT: AccountView  # the event's own debit account
    crT: AccountView
    pdr: AccountView  # the TABLE pending's debit account
    pcr: AccountView
    postedT_found: jax.Array
    postedT_val: jax.Array
    probe_grow: jax.Array  # uint32 scalar: FLAG_GROW_*/FLAG_COLD bits
    accounts_capacity: jax.Array  # uint64 scalar: GLOBAL slot-space bound


class ApplyPlan(NamedTuple):
    """Everything the (single-chip or owner-local) apply phase needs."""

    codes: jax.Array  # uint32[N] final result codes
    route: jax.Array  # uint32 scalar: FLAG_SEQ bit (pure routing only)
    ok: jax.Array  # bool[N]
    row: Dict[str, jax.Array]  # composed transfer rows to insert
    post: jax.Array  # bool[N]
    posted_key: jax.Array  # uint64[N] pending timestamps (0 = none)
    pv_ok: jax.Array  # bool[N]
    # Balance scatter set (sorted leg domain, 2N):
    s_slot: jax.Array  # uint64[2N] global slots (capacity = sentinel)
    scat: jax.Array  # bool[2N] last live leg of each slot run
    bal_incl: Dict[str, jax.Array]  # {field_lo/_hi: uint64[2N]} final values
    # History (single-chip only; sharded mode excludes history accounts):
    do_hist: jax.Array  # bool[N]
    hist_row: Dict[str, jax.Array]
    # Jacobi iterations the fixpoint actually took (instrumentation).
    passes: jax.Array  # int32 scalar
    # Wave scheduler instrumentation (use_waves; zeros when off):
    # wave_bound: proved pass bound (depth_max + 1) when the conflict index
    # certified the batch, else 0.  wave_hist: per-lane wave-depth histogram
    # (buckets 0..7, 8 = deeper), valid lanes only.
    wave_bound: jax.Array  # int32 scalar
    wave_hist: jax.Array  # int32[9]


def _first_code(checks) -> jnp.ndarray:
    """Vector precedence ladder: the FIRST firing (mask, code) wins."""
    code = jnp.uint32(0)
    for cond, c in reversed(checks):
        val = c if isinstance(c, jnp.ndarray) else jnp.uint32(c)
        code = jnp.where(cond, val, code)
    return code


class IdIndex(NamedTuple):
    """Sorted view of the batch's transfer ids, shared by duplicate
    resolution and the pending-id join."""

    order: jax.Array  # int32[N]: lane at each sorted position
    s_lo: jax.Array
    s_hi: jax.Array
    gid: jax.Array  # int32[N]: group id at each sorted position
    group_of_lane: jax.Array  # int32[N]
    any_dup: jax.Array  # bool: some nonzero id occurs twice


def _build_id_index(id_lo, id_hi) -> IdIndex:
    n = id_lo.shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    order = jnp.lexsort((lane, id_lo, id_hi)).astype(jnp.int32)
    s_lo, s_hi = id_lo[order], id_hi[order]
    same = (s_lo[1:] == s_lo[:-1]) & (s_hi[1:] == s_hi[:-1])
    new_group = jnp.concatenate([jnp.ones((1,), jnp.bool_), ~same])
    gid = (jnp.cumsum(new_group.astype(jnp.int32)) - 1).astype(jnp.int32)
    group_of_lane = jnp.zeros((n,), jnp.int32).at[order].set(gid)
    any_dup = jnp.any(same & ((s_lo[1:] != 0) | (s_hi[1:] != 0)))
    return IdIndex(order, s_lo, s_hi, gid, group_of_lane, any_dup)


def _search128(s_hi, s_lo, q_hi, q_lo) -> jax.Array:
    """First sorted index with (s_hi,s_lo) >= (q_hi,q_lo) — batched binary
    search over 128-bit pairs (13 fixed steps for 8k lanes)."""
    n = s_hi.shape[0]
    lo = jnp.zeros(q_lo.shape, jnp.int32)
    hi = jnp.full(q_lo.shape, n, jnp.int32)
    for _ in range(int(n).bit_length()):
        mid = jnp.minimum((lo + hi) // 2, n - 1)
        m_hi, m_lo = s_hi[mid], s_lo[mid]
        less = (m_hi < q_hi) | ((m_hi == q_hi) & (m_lo < q_lo))
        active = lo < hi
        lo = jnp.where(active & less, mid + 1, lo)
        hi = jnp.where(active & ~less, mid, hi)
    return lo


def _group_winner(idx: IdIndex, ok: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(per-group, per-lane) first ok lane of each id group (n if none)."""
    n = ok.shape[0]
    inf = jnp.int32(n)
    s_ok = ok[idx.order]
    winner_g = jax.ops.segment_min(
        jnp.where(s_ok, idx.order, inf), idx.gid, num_segments=n
    )
    return winner_g, winner_g[idx.group_of_lane]


def _limbs_to_u128(lo_limb: jax.Array, hi_limb: jax.Array) -> U128:
    """Recombine 32-bit limb sums (each < 2**47 for <=32k terms) into u128."""
    low = lo_limb + ((hi_limb & _U32MASK) << jnp.uint64(32))
    carry = (low < lo_limb).astype(jnp.uint64)
    return U128(low, (hi_limb >> jnp.uint64(32)) + carry)


class _LegBalances(NamedTuple):
    """Per-leg exact account state around each event (sorted leg domain),
    plus the scatter set (final value of every touched slot)."""

    leg_pos: jax.Array  # int32[2N]: leg index -> sorted position
    # exclusive (pre-event) / inclusive (post-event) per field, U128 each:
    dp_pre: U128
    dp_incl: U128
    dpo_pre: U128
    dpo_incl: U128
    cp_pre: U128
    cp_incl: U128
    cpo_pre: U128
    cpo_incl: U128
    s_slot: jax.Array  # uint64[2N] sorted slot (capacity = sentinel)
    s_live: jax.Array  # bool[2N]
    is_last: jax.Array  # bool[2N]: last leg of its slot run
    arith_broken: jax.Array  # bool scalar: reconstruction over/underflowed


def _leg_balances(
    start_bal: Dict[str, jax.Array],
    cap_sentinel: jax.Array,
    ok_lanes: jax.Array,
    amt_lo: jax.Array,
    pamt_lo: jax.Array,
    dr_slot: jax.Array,
    cr_slot: jax.Array,
    dr_live: jax.Array,
    cr_live: jax.Array,
    pending_f: jax.Array,
    post: jax.Array,
    postvoid: jax.Array,
    has_postvoid: bool = True,
) -> _LegBalances:
    """Exact running balances of all four account fields at every leg.

    Legs 2i (debit side) / 2i+1 (credit side) sorted by (slot, leg position);
    leg position order is event order, so segmented prefix sums within slot
    runs reconstruct each account's exact field values before/after every
    event.  Deltas are gated by ``ok_lanes`` (the previous Jacobi iterate);
    ``amt_lo``/``pamt_lo`` are the previous iterate's effective / pending
    amounts (u64 — u128 amounts route to FLAG_SEQ).  ``start_bal`` carries
    each LEG's account start balances ({field_lo/_hi: uint64[2N]}, leg
    domain, pre-sort), composed from the GatherCtx account views — every
    leg of a slot run belongs to the same account, so each leg's own value
    is its run's start."""
    n = ok_lanes.shape[0]

    leg_slot_raw = jnp.stack([dr_slot, cr_slot], axis=1).reshape(-1)
    leg_live_raw = jnp.stack([dr_live, cr_live], axis=1).reshape(-1)
    leg_ok = jnp.repeat(ok_lanes, 2)
    leg_is_dr = (jnp.arange(2 * n, dtype=jnp.int32) & 1) == 0
    leg_slot = jnp.where(leg_live_raw, leg_slot_raw, cap_sentinel)

    amt2 = jnp.repeat(amt_lo, 2)
    pamt2 = jnp.repeat(pamt_lo, 2)
    pend2 = jnp.repeat(pending_f, 2)
    post2 = jnp.repeat(post, 2)
    pv2 = jnp.repeat(postvoid, 2)
    reg2 = ~pend2 & ~pv2

    on = leg_ok  # delta gate
    zero = jnp.uint64(0)
    dp_add = jnp.where(on & leg_is_dr & pend2, amt2, zero)
    dp_sub = jnp.where(on & leg_is_dr & pv2, pamt2, zero)
    dpo_add = jnp.where(on & leg_is_dr & (reg2 | post2), amt2, zero)
    cp_add = jnp.where(on & ~leg_is_dr & pend2, amt2, zero)
    cp_sub = jnp.where(on & ~leg_is_dr & pv2, pamt2, zero)
    cpo_add = jnp.where(on & ~leg_is_dr & (reg2 | post2), amt2, zero)

    # (slot, legpos) sort: n <= 2^14 so legpos < 2^15 fits under the slot.
    leg_pos_id = jnp.arange(2 * n, dtype=jnp.uint64)
    sort_key = (leg_slot << jnp.uint64(15)) | leg_pos_id
    leg_order = jnp.argsort(sort_key)
    s_slot = leg_slot[leg_order]
    s_live = s_slot < cap_sentinel
    s_head = jnp.concatenate([jnp.ones((1,), jnp.bool_), s_slot[1:] != s_slot[:-1]])
    is_last = jnp.concatenate([s_slot[1:] != s_slot[:-1], jnp.ones((1,), jnp.bool_)])
    leg_pos = jnp.zeros((2 * n,), jnp.int32).at[leg_order].set(
        jnp.arange(2 * n, dtype=jnp.int32)
    )

    # ONE stacked segmented prefix sum for all six delta streams, in pure
    # u32: TPU emulates u64 scans as u32-pair reduce-windows whose scoped
    # VMEM scratch blows the 16M budget inside the while_loop body (measured:
    # 64M at 8192 lanes). Instead each u64 delta is split into four 16-bit
    # parts — part sums over <= 2^15 legs stay < 2^31, so a single native
    # (2N, 24) u32 cumsum + one shared run-start cummax computes everything,
    # and the u64 limb sums are recombined per gathered leg afterwards.
    # Streams are permuted 1D BEFORE stacking (2D row gathers lower to
    # per-row DMAs on TPU); run bases come from a columnwise cummax —
    # exclusive sums at run heads are nondecreasing down the array, so
    # max-carry propagates each run's base with no gather.
    m16 = jnp.uint64(0xFFFF)

    def parts(d):
        return [
            (d & m16).astype(jnp.uint32),
            ((d >> jnp.uint64(16)) & m16).astype(jnp.uint32),
            ((d >> jnp.uint64(32)) & m16).astype(jnp.uint32),
            (d >> jnp.uint64(48)).astype(jnp.uint32),
        ]

    # The pv subtraction streams (void/post releasing a pending) exist only
    # when the batch can carry post/void lanes: a static has_postvoid=False
    # shrinks the stacked scan from 24 to 16 columns (1/3 less cumsum +
    # cummax work on the hot plain/limits shapes).
    streams = [parts(dp_add[leg_order])]
    if has_postvoid:
        streams.append(parts(dp_sub[leg_order]))
    streams.append(parts(dpo_add[leg_order]))
    streams.append(parts(cp_add[leg_order]))
    if has_postvoid:
        streams.append(parts(cp_sub[leg_order]))
    streams.append(parts(cpo_add[leg_order]))
    if has_postvoid:
        col_dp, col_dpo, col_cp, col_cpo = 0, 8, 12, 20
    else:
        col_dp, col_dpo, col_cp, col_cpo = 0, 4, 8, 12
    # Streams stack on AXIS 0 — (streams, 2N) with the scans along the
    # MINOR dimension.  The axis-1 layout made XLA flip layouts around
    # every cumsum/cummax: copyhound counted 52-74 MB-scale copies of
    # these very temporaries per compiled kernel (one set per Jacobi
    # pass), all gone in this orientation.
    v = jnp.stack(sum(streams, []), axis=0)
    c = jnp.cumsum(v, axis=1)
    base = jax.lax.cummax(jnp.where(s_head[None, :], c - v, 0), axis=1)
    incl_all = c - base
    excl_all = incl_all - v

    zeros2n = jnp.zeros((2 * n,), jnp.uint64)

    def recombine(limbs, col):
        """u64 limb sum from two adjacent 16-bit part-sum rows."""
        return limbs[col].astype(jnp.uint64) + (
            limbs[col + 1].astype(jnp.uint64) << jnp.uint64(16)
        )

    def field_vals(field, col, has_sub):
        start = U128(
            start_bal[field + "_lo"][leg_order],
            start_bal[field + "_hi"][leg_order],
        )

        def at(limbs):
            add = _limbs_to_u128(recombine(limbs, col), recombine(limbs, col + 2))
            sub = (
                _limbs_to_u128(recombine(limbs, col + 4), recombine(limbs, col + 6))
                if has_sub else U128(zeros2n, zeros2n)
            )
            added, ov = u128.add(start, add)
            val, neg = u128.sub(added, sub)
            return val, ov | neg

        pre, bad_e = at(excl_all)
        incl, bad_i = at(incl_all)
        return pre, incl, bad_e | bad_i

    dp_pre, dp_incl, bad1 = field_vals("debits_pending", col_dp, has_postvoid)
    dpo_pre, dpo_incl, bad2 = field_vals("debits_posted", col_dpo, False)
    cp_pre, cp_incl, bad3 = field_vals("credits_pending", col_cp, has_postvoid)
    cpo_pre, cpo_incl, bad4 = field_vals("credits_posted", col_cpo, False)
    arith_broken = jnp.any(s_live & (bad1 | bad2 | bad3 | bad4))

    return _LegBalances(
        leg_pos=leg_pos,
        dp_pre=dp_pre, dp_incl=dp_incl,
        dpo_pre=dpo_pre, dpo_incl=dpo_incl,
        cp_pre=cp_pre, cp_incl=cp_incl,
        cpo_pre=cpo_pre, cpo_incl=cpo_incl,
        s_slot=s_slot, s_live=s_live, is_last=is_last,
        arith_broken=arith_broken,
    )


def _wave_schedule(
    hazard: jax.Array,
    unschedulable: jax.Array,
    wdr_slot: jax.Array,
    wdr_live: jax.Array,
    wcr_slot: jax.Array,
    wcr_live: jax.Array,
    valid: jax.Array,
    cap_sentinel: jax.Array,
    max_rounds: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Vectorized conflict-index wave scheduler (docs/waves.md).

    Assigns every lane a WAVE DEPTH: 0 for lanes whose outcome is provably
    independent of every other lane's outcome (non-hazard: fixed amount, no
    clamp/limit/overflow/fulfillment/dup/chain sensitivity), and for hazard
    lanes 1 + the maximum depth of any EARLIER hazard lane sharing one of
    its accounts — the index-based schedule of 1911.11329, restricted to
    the lanes whose outcomes can actually change across Jacobi iterates.
    Outcome changes propagate only through shared account balances, and
    only hazard lanes ever change outcome, so pass d+1 of the Jacobi
    fixpoint is exact for every lane of depth <= d (induction over depth;
    non-hazard lanes are exact at pass 1).  max depth + 1 is therefore a
    PROVED pass bound: the loop may commit after that many passes without
    observing stability, skipping the verification pass entirely — wave-0
    batches (no conflicts) commit in one evaluation pass plus the single
    balance-update (aux) pass.

    Depth is the longest chain in a DAG, computed by at most ``max_rounds``
    cheap relaxation rounds over ONE (slot, leg-position) sort — each round
    is a segmented exclusive running-max, ~20x cheaper than a semantic
    Jacobi pass.  A batch whose depth has not stabilized within
    ``max_rounds`` rounds would need more passes than the Jacobi budget
    anyway, so it simply falls back to the stability exit (today's path).

    Returns (proved bool scalar, passes_needed int32 scalar, depth int32[N],
    hist int32[9]).
    """
    n = hazard.shape[0]
    leg_slot = jnp.stack([wdr_slot, wcr_slot], axis=1).reshape(-1)
    leg_live = jnp.stack([wdr_live, wcr_live], axis=1).reshape(-1)
    leg_slot = jnp.where(leg_live, leg_slot, cap_sentinel)
    # (slot, legpos) sort: leg position order IS event order within a slot
    # run (the _leg_balances invariant), so "earlier leg in my run" is
    # exactly "earlier conflicting lane".
    leg_pos_id = jnp.arange(2 * n, dtype=jnp.uint64)
    leg_order = jnp.argsort((leg_slot << jnp.uint64(15)) | leg_pos_id)
    s_slot = leg_slot[leg_order]
    s_head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), s_slot[1:] != s_slot[:-1]]
    )
    s_lane = (leg_order >> 1).astype(jnp.int32)
    s_live = s_slot < cap_sentinel
    run_id = jnp.cumsum(s_head.astype(jnp.uint64)) - 1

    def relax_round(carry):
        depth, _, rounds = carry
        # Segmented EXCLUSIVE running max of hazard depths within slot
        # runs, via (run_id << 32 | depth) key packing: run_id is
        # nondecreasing down the sorted array, so a plain cummax never
        # leaks a value across runs (an earlier run's key always packs
        # smaller than the current run's zero).  Dead legs (sentinel
        # slot) share one tail run and are masked out of both sides.
        leg_depth = jnp.where(
            s_live, depth[s_lane], jnp.int32(0)
        ).astype(jnp.uint64)
        packed = (run_id << jnp.uint64(32)) | leg_depth
        incl = jax.lax.cummax(packed)
        excl = jnp.concatenate([jnp.zeros((1,), jnp.uint64), incl[:-1]])
        excl_val = jnp.where(
            s_live & ((excl >> jnp.uint64(32)) == run_id),
            (excl & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32),
            jnp.int32(0),
        )
        prior = jnp.zeros((n,), jnp.int32).at[s_lane].max(excl_val)
        new_depth = jnp.where(
            hazard, jnp.maximum(depth, jnp.int32(1) + prior), jnp.int32(0)
        )
        return new_depth, jnp.any(new_depth != depth), rounds + 1

    depth, changed, _ = jax.lax.while_loop(
        lambda c: c[1] & (c[2] < max_rounds),
        relax_round,
        (
            jnp.where(hazard, jnp.int32(1), jnp.int32(0)),
            jnp.bool_(True),
            jnp.int32(0),
        ),
    )
    proved = ~unschedulable & ~changed
    passes_needed = jnp.max(jnp.where(valid, depth, 0)) + jnp.int32(1)
    hist = jnp.zeros((9,), jnp.int32).at[
        jnp.where(valid, jnp.clip(depth, 0, 8), 9)
    ].add(1, mode="drop")
    return proved, passes_needed, depth, hist


def _at(val: U128, pos: jax.Array) -> U128:
    return U128(val.lo[pos], val.hi[pos])


def _account_view(table, look, found, rows=None) -> AccountView:
    rows = rows if rows is not None else ht.gather_cols(table, look.slot, found)
    return AccountView(
        found=found,
        slot=look.slot,
        flags=rows["flags"],
        ledger=rows["ledger"],
        bal={
            f + l: rows[f + l] for f in _BAL_FIELDS for l in ("_lo", "_hi")
        },
    )


def build_gather_ctx(
    ledger: Ledger,
    batch: Dict[str, jax.Array],
    valid: jax.Array,
    postvoid: jax.Array,
    bloom: jax.Array = None,
    cold_checked: jax.Array = None,
    has_postvoid: bool = True,
) -> GatherCtx:
    """Single-chip GatherCtx: local probes of the ledger tables.

    ``has_postvoid`` is a STATIC host hint: False means the host proved the
    batch carries no post/void flags, so the four pending-side probe loops
    and gathers (pending row, its two accounts, its fulfillment) compile
    away entirely — the flagship plain-batch shape pays only its own three
    probes."""
    n = batch["id_lo"].shape[0]
    tid = _u128_col(batch, "id")
    pend_id = _u128_col(batch, "pending_id")
    t_dr_id = _u128_col(batch, "debit_account_id")
    t_cr_id = _u128_col(batch, "credit_account_id")

    ex_look = ht.lookup(ledger.transfers, tid.lo, tid.hi, MAX_PROBE)
    ex_found = ex_look.found & valid
    e_tab = ht.gather_cols(ledger.transfers, ex_look.slot, ex_found)

    drT_look = ht.lookup(ledger.accounts, t_dr_id.lo, t_dr_id.hi, MAX_PROBE)
    crT_look = ht.lookup(ledger.accounts, t_cr_id.lo, t_cr_id.hi, MAX_PROBE)
    drT = _account_view(ledger.accounts, drT_look, drT_look.found & valid)
    crT = _account_view(ledger.accounts, crT_look, crT_look.found & valid)

    if has_postvoid:
        p_look = ht.lookup(ledger.transfers, pend_id.lo, pend_id.hi, MAX_PROBE)
        p_tab_found = p_look.found & postvoid
        p_tab = ht.gather_cols(ledger.transfers, p_look.slot, p_tab_found)

        # Accounts of a TABLE pending (post/void operates on the pending's
        # accounts, state_machine.zig:1420-1423).
        pdr_look = ht.lookup(
            ledger.accounts, p_tab["debit_account_id_lo"],
            p_tab["debit_account_id_hi"], MAX_PROBE,
        )
        pcr_look = ht.lookup(
            ledger.accounts, p_tab["credit_account_id_lo"],
            p_tab["credit_account_id_hi"], MAX_PROBE,
        )
        pdr = _account_view(
            ledger.accounts, pdr_look, pdr_look.found & p_tab_found
        )
        pcr = _account_view(
            ledger.accounts, pcr_look, pcr_look.found & p_tab_found
        )

        # Posted-groove fulfillment for a TABLE pending (key: its timestamp).
        postedT_look = ht.lookup(
            ledger.posted, p_tab["timestamp"],
            jnp.zeros_like(p_tab["timestamp"]), MAX_PROBE,
        )
        postedT_found = postedT_look.found & p_tab_found
        postedT_val = ht.gather_cols(
            ledger.posted, postedT_look.slot, postedT_found
        )["fulfillment"]
        pv_overflow = (
            jnp.where(
                pdr_look.overflow | pcr_look.overflow,
                jnp.uint32(FLAG_GROW_ACCOUNTS), jnp.uint32(0),
            )
            | jnp.where(p_look.overflow, jnp.uint32(FLAG_GROW_TRANSFERS),
                        jnp.uint32(0))
            | jnp.where(postedT_look.overflow, jnp.uint32(FLAG_GROW_POSTED),
                        jnp.uint32(0))
        )
        p_found_for_cold = p_look.found
    else:
        zero64 = jnp.zeros((n,), jnp.uint64)
        p_tab_found = jnp.zeros((n,), jnp.bool_)
        p_tab = {
            name: jnp.zeros((n,), dt) for name, dt in TRANSFER_COLS.items()
        }
        pdr = pcr = AccountView(
            found=p_tab_found, slot=zero64,
            flags=jnp.zeros((n,), jnp.uint32),
            ledger=jnp.zeros((n,), jnp.uint32),
            bal={f + l: zero64 for f in _BAL_FIELDS for l in ("_lo", "_hi")},
        )
        postedT_found = p_tab_found
        postedT_val = jnp.zeros((n,), jnp.uint32)
        pv_overflow = jnp.uint32(0)
        p_found_for_cold = p_tab_found

    probe_grow = (
        jnp.where(
            drT_look.overflow | crT_look.overflow,
            jnp.uint32(FLAG_GROW_ACCOUNTS), jnp.uint32(0),
        )
        | jnp.where(
            ex_look.overflow,
            jnp.uint32(FLAG_GROW_TRANSFERS), jnp.uint32(0),
        )
        | pv_overflow
    )

    # Cold-tier membership (ops/cold.py): an id or pending_id missing from
    # the HOT table but hitting the cold Bloom filter needs host resolution
    # (exact exists-precedence demands the cold row). cold_checked lanes were
    # already certified not-cold by the host, so false positives terminate.
    if bloom is not None:
        from .cold import bloom_check_impl

        checked = (
            cold_checked if cold_checked is not None
            else jnp.zeros((n,), jnp.bool_)
        )
        cold_ids = (
            valid & ~ex_look.found & ~checked
            & bloom_check_impl(bloom, tid.lo, tid.hi)
        )
        cold_pend = (
            postvoid & ~p_found_for_cold & ~checked
            & bloom_check_impl(bloom, pend_id.lo, pend_id.hi)
        )
        probe_grow = probe_grow | jnp.where(
            jnp.any(cold_ids | cold_pend), jnp.uint32(FLAG_COLD), jnp.uint32(0)
        )

    return GatherCtx(
        ex_found=ex_found, e_tab=e_tab,
        p_tab_found=p_tab_found, p_tab=p_tab,
        drT=drT, crT=crT, pdr=pdr, pcr=pcr,
        postedT_found=postedT_found, postedT_val=postedT_val,
        probe_grow=probe_grow,
        accounts_capacity=jnp.uint64(ledger.accounts.capacity),
    )


def _kernel_core(
    ctx: GatherCtx,
    batch: Dict[str, jax.Array],
    count: jax.Array,
    timestamp: jax.Array,
    max_passes: int = _MAX_PASSES,
    static_trip: Optional[bool] = None,
    has_postvoid: bool = True,
    use_waves: bool = False,
) -> ApplyPlan:
    """The pure batch semantics: no table access, replicable on a mesh.

    ``has_postvoid`` (STATIC host hint, mirroring build_gather_ctx's): False
    means the batch provably carries no post/void lanes, so the per-pass
    two-phase machinery — the in-batch pending join, the 20-column pending
    row composition, the pv result ladder, and the fulfillment-winner sort —
    compiles away, and _leg_balances drops its pv subtraction streams
    (24 -> 16 scan columns).  The flagship plain and --limits shapes pay
    only the regular ladder per pass."""
    n = batch["id_lo"].shape[0]
    assert n <= 1 << 14, "leg sort key packs (slot, legpos<2^15)"
    lane = jnp.arange(n, dtype=jnp.int32)
    valid = lane < count.astype(jnp.int32)
    ts = _timestamps(count, timestamp, n)

    tid = _u128_col(batch, "id")
    t_dr_id = _u128_col(batch, "debit_account_id")
    t_cr_id = _u128_col(batch, "credit_account_id")
    t_amt = _u128_col(batch, "amount")
    pend_id = _u128_col(batch, "pending_id")
    flags = batch["flags"]
    false_n = jnp.zeros((n,), jnp.bool_)
    if has_postvoid:
        post = ((flags & TF_POST) != 0) & valid
        void = ((flags & TF_VOID) != 0) & valid
        postvoid = post | void
    else:
        # Host-proved: no pv lanes.  Static False gates fold the pv paths.
        post = void = postvoid = false_n
    pending_f = ((flags & TF_PENDING) != 0) & valid
    linked = ((flags & TF_LINKED) != 0) & valid
    bal_dr = ((flags & TF_BALANCING_DEBIT) != 0) & valid
    bal_cr = ((flags & TF_BALANCING_CREDIT) != 0) & valid
    balancing = bal_dr | bal_cr

    ex_found, e_tab = ctx.ex_found, ctx.e_tab
    p_tab_found, p_tab = ctx.p_tab_found, ctx.p_tab
    drT, crT, pdr, pcr = ctx.drT, ctx.crT, ctx.pdr, ctx.pcr
    cap_sentinel = ctx.accounts_capacity

    idx = _build_id_index(tid.lo, tid.hi)

    if has_postvoid:
        # In-batch pending-create candidate group for each pv lane.
        pj = _search128(idx.s_hi, idx.s_lo, pend_id.hi, pend_id.lo)
        pj_c = jnp.minimum(pj, n - 1)
        pj_hit = (
            (idx.s_hi[pj_c] == pend_id.hi)
            & (idx.s_lo[pj_c] == pend_id.lo) & (pj < n)
        )
        pj_group = idx.gid[pj_c]

    timeout_ns = batch["timeout"].astype(jnp.uint64) * jnp.uint64(NS_PER_S)
    ov_timeout = (ts + timeout_ns) < ts
    dr_limf = ((drT.flags & AF_DEBITS_MUST_NOT_EXCEED_CREDITS) != 0) & drT.found
    cr_limf = ((crT.flags & AF_CREDITS_MUST_NOT_EXCEED_DEBITS) != 0) & crT.found

    if use_waves:
        # --- conflict-index wave schedule (TB_WAVES; docs/waves.md) -------
        # HAZARD lanes are the only ones whose (code, amount) can change
        # across Jacobi iterates: balancing clamps, balance-limit
        # accounts, and start balances within one batch's delta margin of
        # u128 overflow (the near_ov threshold the failed-chain hazard
        # route already uses).  Everything else has a fixed outcome from
        # pass 1, whatever its account conflicts — including a post/void
        # of a TABLE pending: its whole ladder compares fixed table/batch
        # values (the reference's post_or_void path has no balance
        # checks), so even the fulfillment winner race resolves from codes
        # that never change across iterates.  A post/void whose pending
        # may resolve IN BATCH is the exception (it reads another lane's
        # composed row) and is excluded batch-wide below.
        #
        # The margin is stricter than near_ov's: any start field >=
        # 2^127 - 2^80 is hazard, so for non-hazard lanes every overflow
        # operand (single fields AND the dp+dpo / cp+cpo pair sums, whose
        # u128 wrap boundary the ladder is sensitive to) sits further from
        # 2^128 than one batch's total delta (< n * 2^64 <= 2^77) can
        # move it — no overflow code can change across iterates.
        near_w = jnp.uint64(0x7FFF_FFFF_FFFF_0000)

        def _near_start(v: AccountView):
            return v.found & (
                (v.bal["debits_pending_hi"] >= near_w)
                | (v.bal["debits_posted_hi"] >= near_w)
                | (v.bal["credits_pending_hi"] >= near_w)
                | (v.bal["credits_posted_hi"] >= near_w)
            )

        hazard = valid & (
            balancing | dr_limf | cr_limf
            | _near_start(drT) | _near_start(crT)
        )
        # Unschedulable couplings fall back to the stability exit (today's
        # behavior, bit-for-bit): linked chains propagate failure BACKWARD
        # (a cycle in the dependency DAG), duplicate ids couple through
        # winner selection rather than accounts, and the in-batch pending
        # reference above.
        unschedulable = jnp.any(linked) | idx.any_dup
        if has_postvoid:
            hazard = hazard | (
                postvoid & (_near_start(pdr) | _near_start(pcr))
            )
            unschedulable = unschedulable | jnp.any(postvoid & pj_hit)
            wdr_slot = jnp.where(postvoid, pdr.slot, drT.slot)
            wdr_live = jnp.where(postvoid, pdr.found, drT.found & valid)
            wcr_slot = jnp.where(postvoid, pcr.slot, crT.slot)
            wcr_live = jnp.where(postvoid, pcr.found, crT.found & valid)
        else:
            wdr_slot, wdr_live = drT.slot, drT.found & valid
            wcr_slot, wcr_live = crT.slot, crT.found & valid
        sched_proved, passes_needed, _wave_depth, wave_hist = _wave_schedule(
            hazard, unschedulable, wdr_slot, wdr_live, wcr_slot, wcr_live,
            valid, cap_sentinel, max_passes,
        )
        wave_bound = jnp.where(sched_proved, passes_needed, jnp.int32(0))
    else:
        sched_proved = jnp.bool_(False)
        passes_needed = jnp.int32(_MAX_PASSES + 1)
        wave_bound = jnp.int32(0)
        wave_hist = jnp.zeros((9,), jnp.int32)

    # ------------------------------------------------------------------
    # One Jacobi pass of the sequential semantics.
    # ------------------------------------------------------------------

    def one_pass(ok_prev: jax.Array, amt_prev: U128):
        inf = jnp.int32(n)
        winner_g, winner_of_lane = _group_winner(idx, ok_prev)

        if has_postvoid:
            # --- resolve each pv lane's pending row ----------------------
            pw = winner_g[pj_group]
            pwc = jnp.minimum(
                jnp.where(pj_hit, pw, inf), n - 1
            ).astype(jnp.int32)
            # Any inserted transfer resolves the reference (a non-pending
            # one then fails the p_is_pending check with code 26, like the
            # table path — state_machine.zig:1417).
            in_batch_ref = (
                postvoid & pj_hit & (pw < inf) & (pw < lane) & ok_prev[pwc]
            )

            p_found = p_tab_found | in_batch_ref
            p = {}
            for name in TRANSFER_COLS:
                if name == "timestamp":
                    p[name] = jnp.where(in_batch_ref, ts[pwc], p_tab[name])
                elif name == "amount_lo":
                    # The stored amount of an in-batch pending is its
                    # CLAMPED amount (balancing pending): the previous
                    # iterate's effective amount — exact at the fixpoint.
                    p[name] = jnp.where(
                        in_batch_ref, amt_prev.lo[pwc], p_tab[name]
                    )
                elif name == "amount_hi":
                    p[name] = jnp.where(
                        in_batch_ref, amt_prev.hi[pwc], p_tab[name]
                    )
                else:
                    p[name] = jnp.where(
                        in_batch_ref, batch[name][pwc], p_tab[name]
                    )
            p_is_pending = ((p["flags"] & TF_PENDING) != 0) & p_found
            p_amt = U128(p["amount_lo"], p["amount_hi"])
            p_dr_id = U128(
                p["debit_account_id_lo"], p["debit_account_id_hi"]
            )
            p_cr_id = U128(
                p["credit_account_id_lo"], p["credit_account_id_hi"]
            )

            # Effective accounts (regular: own; pv: the pending's),
            # composed from the gathered views — no table access.
            def compose(own: AccountView, pend_side: AccountView):
                def pick(o, pv_):
                    return jnp.where(
                        in_batch_ref, o[pwc], jnp.where(postvoid, pv_, o)
                    )

                return (
                    pick(own.slot, pend_side.slot),
                    pick(own.found, pend_side.found) & valid,
                    pick(own.flags, pend_side.flags),
                    {k: pick(own.bal[k], pend_side.bal[k]) for k in own.bal},
                )

            dr_slot, dr_live, acc_flags_dr, dr_bal = compose(drT, pdr)
            cr_slot, cr_live, acc_flags_cr, cr_bal = compose(crT, pcr)
        else:
            in_batch_ref = false_n
            p_found = p_tab_found
            p = p_tab
            p_amt = U128(p["amount_lo"], p["amount_hi"])
            dr_slot, dr_live = drT.slot, drT.found & valid
            cr_slot, cr_live = crT.slot, crT.found & valid
            acc_flags_dr, acc_flags_cr = drT.flags, crT.flags
            dr_bal, cr_bal = drT.bal, crT.bal

        # --- exact running balances from the previous iterate -------------
        start_bal = {
            k: jnp.stack([dr_bal[k], cr_bal[k]], axis=1).reshape(-1)
            for k in dr_bal
        }
        legs = _leg_balances(
            start_bal, cap_sentinel, ok_prev, amt_prev.lo, p_amt.lo,
            dr_slot, cr_slot, dr_live, cr_live, pending_f, post, postvoid,
            has_postvoid=has_postvoid,
        )
        dpos = legs.leg_pos[2 * lane]
        cpos = legs.leg_pos[2 * lane + 1]
        a_dp = _at(legs.dp_pre, dpos)      # dr account, pre-event
        a_dpo = _at(legs.dpo_pre, dpos)
        a_cpo = _at(legs.cpo_pre, dpos)
        b_cp = _at(legs.cp_pre, cpos)      # cr account, pre-event
        b_cpo = _at(legs.cpo_pre, cpos)
        b_dpo = _at(legs.dpo_pre, cpos)

        # --- balancing clamps (state_machine.zig:1286-1306) ----------------
        zero = jnp.uint64(0)
        amount0 = u128.select(
            balancing & u128.is_zero(t_amt), U128(_U64MAX, zero), t_amt
        )
        dr_balance = u128.add_wrap(a_dpo, a_dp)
        avail_dr = u128.sub_saturate(a_cpo, dr_balance)
        amount1 = u128.select(bal_dr, u128.min_(amount0, avail_dr), amount0)
        exceeds_credits_bal = bal_dr & u128.is_zero(amount1)
        cr_balance = u128.add_wrap(b_cpo, b_cp)
        avail_cr = u128.sub_saturate(b_dpo, cr_balance)
        amount2 = u128.select(bal_cr, u128.min_(amount1, avail_cr), amount1)
        exceeds_debits_bal = bal_cr & ~exceeds_credits_bal & u128.is_zero(amount2)
        reg_amount = amount2

        # --- overflow ladder (:1308-1322) ----------------------------------
        _, ov_dp = u128.add(reg_amount, a_dp)
        _, ov_cp = u128.add(reg_amount, b_cp)
        _, ov_dpo = u128.add(reg_amount, a_dpo)
        _, ov_cpo = u128.add(reg_amount, b_cpo)
        dr_total, _ = u128.add(a_dp, a_dpo)
        _, ov_d = u128.add(reg_amount, dr_total)
        cr_total, _ = u128.add(b_cp, b_cpo)
        _, ov_c = u128.add(reg_amount, cr_total)

        # --- balance limits (tigerbeetle.zig:31-39) ------------------------
        new_dr_tot, _ = u128.add(dr_total, reg_amount)
        exceeds_credits_lim = dr_limf & u128.gt(new_dr_tot, a_cpo)
        new_cr_tot, _ = u128.add(cr_total, reg_amount)
        exceeds_debits_lim = cr_limf & u128.gt(new_cr_tot, b_dpo)

        # --- effective amount + composed insert rows -----------------------
        # (state_machine.zig:1326-1328, 1431, 1455-1469)
        row = {name: batch[name] for name in TRANSFER_COLS}
        row["timestamp"] = ts
        if has_postvoid:
            pv_amount = u128.select(u128.is_zero(t_amt), p_amt, t_amt)
            amount = u128.select(postvoid, pv_amount, reg_amount)
            for name in ("debit_account_id", "credit_account_id"):
                for l_ in ("_lo", "_hi"):
                    row[name + l_] = jnp.where(
                        postvoid, p[name + l_], batch[name + l_]
                    )
            ud128_nz = (
                (batch["user_data_128_lo"] != 0)
                | (batch["user_data_128_hi"] != 0)
            )
            for l_ in ("_lo", "_hi"):
                row["user_data_128" + l_] = jnp.where(
                    postvoid & ~ud128_nz, p["user_data_128" + l_],
                    batch["user_data_128" + l_],
                )
            for name in ("user_data_64", "user_data_32"):
                row[name] = jnp.where(
                    postvoid & (batch[name] == 0), p[name], batch[name]
                )
            row["ledger"] = jnp.where(postvoid, p["ledger"], batch["ledger"])
            row["code"] = jnp.where(postvoid, p["code"], batch["code"])
            row["timeout"] = jnp.where(
                postvoid, jnp.uint32(0), batch["timeout"]
            )
        else:
            amount = reg_amount
        row["amount_lo"] = amount.lo
        row["amount_hi"] = amount.hi

        # --- regular-path ladder (state_machine.zig:1239-1368) -------------
        # The exists check compares the RAW event amount against the stored
        # (possibly clamped) amount (:1379).
        exists_tab_reg = _exists_regular(batch, e_tab, t_amt, n)
        reg_code = _first_code([
            (((flags & TF_PADDING) != 0), 4),
            (u128.is_zero(tid), 5),
            (u128.is_max(tid), 6),
            (u128.is_zero(t_dr_id), 8),
            (u128.is_max(t_dr_id), 9),
            (u128.is_zero(t_cr_id), 10),
            (u128.is_max(t_cr_id), 11),
            (u128.eq(t_dr_id, t_cr_id), 12),
            (~u128.is_zero(pend_id), 13),
            (~pending_f & (batch["timeout"] != 0), 17),
            (~balancing & u128.is_zero(t_amt), 18),
            ((batch["ledger"] == 0), 19),
            ((batch["code"] == 0), 20),
            (~drT.found, 21),
            (~crT.found, 22),
            ((drT.ledger != crT.ledger), 23),
            ((batch["ledger"] != drT.ledger), 24),
            (ex_found, exists_tab_reg),
            (exceeds_credits_bal, 54),
            (exceeds_debits_bal, 55),
            (pending_f & ov_dp, 47),
            (pending_f & ov_cp, 48),
            (ov_dpo, 49),
            (ov_cpo, 50),
            (ov_d, 51),
            (ov_c, 52),
            (ov_timeout, 53),
            (exceeds_credits_lim, 54),
            (exceeds_debits_lim, 55),
        ])

        if has_postvoid:
            # --- post/void ladder (state_machine.zig:1391-1453) ------------
            exists_tab_pv = _exists_postvoid(batch, e_tab, p, n)
            expiry_ns = p["timeout"].astype(jnp.uint64) * jnp.uint64(NS_PER_S)
            expired = (p["timeout"] != 0) & (
                ts >= p["timestamp"] + expiry_ns
            )
            pv_code = _first_code([
                (((flags & TF_PADDING) != 0), 4),
                (u128.is_zero(tid), 5),
                (u128.is_max(tid), 6),
                (post & void, 7),
                (pending_f, 7),
                (balancing, 7),
                (u128.is_zero(pend_id), 14),
                (u128.is_max(pend_id), 15),
                (u128.eq(pend_id, tid), 16),
                ((batch["timeout"] != 0), 17),
                (~p_found, 25),
                (~p_is_pending, 26),
                (~u128.is_zero(t_dr_id) & ~u128.eq(t_dr_id, p_dr_id), 27),
                (~u128.is_zero(t_cr_id) & ~u128.eq(t_cr_id, p_cr_id), 28),
                (((batch["ledger"] != 0) & (batch["ledger"] != p["ledger"])),
                 29),
                (((batch["code"] != 0) & (batch["code"] != p["code"])), 30),
                (u128.gt(amount, p_amt), 31),
                (void & u128.lt(amount, p_amt), 32),
                (ex_found, exists_tab_pv),
                (ctx.postedT_found & (ctx.postedT_val == 1), 33),
                (ctx.postedT_found & (ctx.postedT_val == 2), 34),
                (expired, 35),
            ])
            code = jnp.where(postvoid, pv_code, reg_code)
        else:
            code = reg_code
        code = jnp.where(batch["timestamp"] != 0, jnp.uint32(3), code)

        # --- intra-batch duplicate ids ------------------------------------
        # In sequential order the exists check sits BEFORE the balance-
        # dependent tail (clamps/overflows/limits, pv fulfillment/expiry),
        # so the in-batch override replaces exactly those post-exists codes.
        after_winner = (winner_of_lane < inf) & (lane > winner_of_lane)
        wc = jnp.minimum(winner_of_lane, n - 1).astype(jnp.int32)
        w_row = {k: v[wc] for k, v in row.items()}
        intra_reg = _exists_regular(batch, w_row, t_amt, n)
        balance_code = jnp.zeros((n,), jnp.bool_)
        for bc in _BALANCE_CODES:
            balance_code = balance_code | (code == bc)
        if has_postvoid:
            intra_pv = _exists_postvoid(batch, w_row, p, n)
            intra = jnp.where(postvoid, intra_pv, intra_reg)
            dup_overridable = jnp.where(
                postvoid,
                (code == 0) | (code == 33) | (code == 34) | (code == 35),
                (code == 0) | (code == 53) | balance_code,
            )
        else:
            intra = intra_reg
            dup_overridable = (code == 0) | (code == 53) | balance_code
        code = jnp.where(after_winner & dup_overridable, intra, code)

        if has_postvoid:
            # --- intra-batch double post/void -----------------------------
            # Group pv lanes by resolved pending timestamp; the first lane
            # whose pre-fulfillment checks pass records the fulfillment;
            # later ones get already_posted/voided. (Linked chains cannot
            # interact: batches with linked AND post/void route to the
            # sequential path.)
            p_ts_key = jnp.where(postvoid & p_found, p["timestamp"], 0)
            f_order = jnp.lexsort((lane, p_ts_key)).astype(jnp.int32)
            f_ts = p_ts_key[f_order]
            f_head = jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), f_ts[1:] != f_ts[:-1]]
            )
            f_gid = (jnp.cumsum(f_head.astype(jnp.int32)) - 1).astype(
                jnp.int32
            )
            f_ok = (code[f_order] == 0) & (f_ts != 0)
            f_winner_g = jax.ops.segment_min(
                jnp.where(f_ok, f_order, inf), f_gid, num_segments=n
            )
            f_winner = jnp.zeros((n,), jnp.int32).at[f_order].set(
                f_winner_g[f_gid]
            )
            fulfil_after = (
                (f_winner < inf) & (lane > f_winner) & (p_ts_key != 0)
            )
            fwc = jnp.minimum(f_winner, n - 1).astype(jnp.int32)
            fulfil_code = jnp.where(
                post[fwc], jnp.uint32(33), jnp.uint32(34)
            )
            code = jnp.where(
                fulfil_after & ((code == 0) | (code == 35)), fulfil_code,
                code
            )

        # --- linked chains -------------------------------------------------
        code = jnp.where(~valid, 0, code)
        pre_chain_code = code
        code = _chain_codes(linked, code, count)
        ok = (code == 0) & valid

        # Overflow checks of a lane inside a FAILED chain may depend on the
        # chain's transient sibling effects (< n * 2^64 total): if any such
        # lane's balances sit within that margin of 2^128, the sequential
        # path could fire an overflow code the rolled-back fixpoint cannot
        # see. Flag "near overflow" = any involved hi limb in the top 2^15
        # values (margin 2^79 >= n * 2^64 for n <= 2^14).
        near = jnp.uint64(0xFFFF_FFFF_FFFF_0000)
        near_ov = (
            (a_dp.hi >= near) | (a_dpo.hi >= near)
            | (b_cp.hi >= near) | (b_cpo.hi >= near)
        )
        aux = dict(
            in_batch_ref=in_batch_ref, p=p, p_found=p_found, p_amt=p_amt,
            dr_slot=dr_slot, cr_slot=cr_slot, row=row,
            acc_flags_dr=acc_flags_dr, acc_flags_cr=acc_flags_cr,
            legs=legs, pre_chain_code=pre_chain_code, near_ov=near_ov,
        )
        return ok, code, amount, aux

    # Jacobi iteration: a pass whose codes and accepted amounts equal the
    # previous pass's is a fixpoint => THE sequential answer (induction
    # over lanes).  Two loop forms, identical results:
    #
    # - STATIC trip (lax.scan, length=max_passes) on TPU.  The fixpoint is
    #   absorbing (a pass from a stable state reproduces it bit-for-bit),
    #   so running all max_passes passes returns exactly what the early-
    #   exit loop returns; `converged` tracks whether stability was EVER
    #   observed (unconverged batches set FLAG_SEQ, as before).  The trip
    #   count being data-INdependent lets XLA:TPU schedule the passes as
    #   one straight-line program — the round-4 window-4 phase bisect
    #   measured the while-based core at +47 ms/batch on v5e-1 with every
    #   primitive in the body at 1-3 us (the dynamic-condition lowering
    #   was the overhead, not the pass body).
    # - EARLY EXIT (lax.while_loop) elsewhere: on XLA-CPU the dynamic
    #   lowering is cheap and cascade-free batches stop after 2 of the
    #   max_passes=8 passes — always paying all 8 would be a ~4x
    #   regression for the CPU engine/fallback paths.
    # The carry holds ONLY the iterate (k, stable, ok, code, amount) — aux
    # (legs, composed rows, pending views: ~6 MB at 8k lanes) stays OUT of
    # the loop state and is recomputed ONCE from the fixpoint afterwards.
    # At a fixpoint the recompute reproduces the stable pass bit-for-bit
    # (the absorbing property), so every downstream consumer sees exactly
    # the converged pass's values; unconverged batches route FLAG_SEQ and
    # apply nothing, so their aux values are never observable.
    ok0 = jnp.zeros((n,), jnp.bool_)
    code_sentinel = jnp.full((n,), 0xFFFFFFFF, jnp.uint32)
    carry0 = (jnp.int32(0), jnp.bool_(False), ok0, code_sentinel, t_amt)

    def step_pass(carry):
        k, ever_stable, ok_p, code_p, amt_p = carry
        ok_n, code_n, amt_n, _aux = one_pass(ok_p, amt_p)
        # The pass consumed (ok_p, amt_p); equality of codes and of accepted
        # amounts makes the next pass a no-op. Amounts of rejected lanes are
        # irrelevant downstream.
        stable = ~(
            jnp.any(code_n != code_p)
            | jnp.any(ok_n & ((amt_n.lo != amt_p.lo) | (amt_n.hi != amt_p.hi)))
        )
        # k counts passes up to and including the stabilizing one (the
        # bench's jacobi_passes diagnostic).
        k = k + jnp.where(ever_stable, jnp.int32(0), jnp.int32(1))
        return (k, ever_stable | stable, ok_n, code_n, amt_n)

    use_scan = (
        static_trip if static_trip is not None
        else jax.default_backend() == "tpu"
    )
    if use_scan:
        def chunk(c, length):
            c, _ = jax.lax.scan(
                lambda c_, _: (step_pass(c_), None), c, None, length=length
            )
            return c

        # Two static chunks with a convergence gate between them: chunk 1
        # covers every measured workload's cascade depth (plain: 2,
        # two-phase in-batch: 3, balancing chain: 3 — run_kernel_profile's
        # jacobi_passes), so the lax.cond skips the second chunk's passes
        # for the common shapes while deep cascades still get max_passes.
        # The carry is ~170 KB post-aux-removal, so the cond is cheap.
        head = min(max_passes, 4)
        c = chunk(carry0, head)
        if max_passes > head:
            # The wave bound joins the stability flag in the chunk gate: a
            # certified batch whose proved pass count fits in the head
            # chunk skips the tail even when stability was never observed.
            c = jax.lax.cond(
                c[1] | (sched_proved & (c[0] >= passes_needed)),
                lambda c_: c_,
                lambda c_: chunk(c_, max_passes - head), c,
            )
        k_passes, converged, ok_f, code_f, amt_f = c
    else:
        # Wave-bound early exit: once the certified pass count has run,
        # the iterate IS the fixpoint (docs/waves.md) — stop without the
        # verification pass.  With use_waves off, sched_proved is a False
        # constant and this folds to the pre-waves condition.
        k_passes, converged, ok_f, code_f, amt_f = jax.lax.while_loop(
            lambda c: (
                ~c[1] & (c[0] < max_passes)
                & ~(sched_proved & (c[0] >= passes_needed))
            ),
            step_pass, carry0,
        )
    proved_done = sched_proved & (k_passes >= passes_needed)
    unconverged = ~converged & ~proved_done

    # The single aux-bearing pass from the fixpoint (see the carry note).
    ok, codes, amount, aux = one_pass(ok_f, amt_f)

    row = aux["row"]
    in_batch_ref = aux["in_batch_ref"]
    legs = aux["legs"]

    # ---------------- history (state_machine.zig:1342-1364) ----------------
    dr_hist = ((aux["acc_flags_dr"] & AF_HISTORY) != 0) & ok
    cr_hist = ((aux["acc_flags_cr"] & AF_HISTORY) != 0) & ok
    do_hist = (dr_hist | cr_hist) & ~postvoid

    # ---------------- routing flags ---------------------------------------
    any_u128_amount = jnp.any(
        valid & ((batch["amount_hi"] != 0) | (postvoid & (aux["p"]["amount_hi"] != 0)))
    )
    any_linked = jnp.any(linked)
    linked_x_intra = any_linked & (
        idx.any_dup | jnp.any(in_batch_ref) | jnp.any(postvoid)
    )
    # A FAILED linked chain rolls back members whose transient effects the
    # sequential path's balance checks DID see; if any member of a failed
    # chain carries a balance-dependent code (or the chain contains
    # balancing/limit-sensitive members), the fixpoint's codes may differ
    # from the sequential ones — route for exactness. Successful chains are
    # exact (all members' contributions present at the fixpoint). Chain
    # membership includes the terminator (linked flag false, previous lane
    # linked) — mirroring _chain_codes.
    prev_linked = jnp.concatenate([jnp.zeros((1,), jnp.bool_), linked[:-1]])
    in_chain = linked | prev_linked
    chain_failed = in_chain & (codes != 0)
    failed_member_balance = jnp.zeros((n,), jnp.bool_)
    for bc in _BALANCE_CODES:
        failed_member_balance = failed_member_balance | (
            chain_failed & (aux["pre_chain_code"] == bc)
        )
    chain_hazard = jnp.any(
        chain_failed & (balancing | dr_limf | cr_limf | aux["near_ov"])
    ) | jnp.any(failed_member_balance)

    route = jnp.where(
        unconverged | any_u128_amount | linked_x_intra | chain_hazard
        | legs.arith_broken,
        jnp.uint32(FLAG_SEQ), jnp.uint32(0),
    )

    # ---------------- history rows (values; apply decides placement) -------
    # Each recorded account's post-event snapshot of ALL FOUR fields is the
    # inclusive value at that event's leg (leg order = event order within the
    # slot run, and cross-side legs of the same account share the run).
    dpos = legs.leg_pos[2 * lane]
    cpos = legs.leg_pos[2 * lane + 1]

    def hv(val: U128, pos, mask):
        return (
            jnp.where(mask, val.lo[pos], 0),
            jnp.where(mask, val.hi[pos], 0),
        )

    dr_dp_lo, dr_dp_hi = hv(legs.dp_incl, dpos, dr_hist)
    dr_dpo_lo, dr_dpo_hi = hv(legs.dpo_incl, dpos, dr_hist)
    dr_cp_lo, dr_cp_hi = hv(legs.cp_incl, dpos, dr_hist)
    dr_cpo_lo, dr_cpo_hi = hv(legs.cpo_incl, dpos, dr_hist)
    cr_cp_lo, cr_cp_hi = hv(legs.cp_incl, cpos, cr_hist)
    cr_cpo_lo, cr_cpo_hi = hv(legs.cpo_incl, cpos, cr_hist)
    cr_dp_lo, cr_dp_hi = hv(legs.dp_incl, cpos, cr_hist)
    cr_dpo_lo, cr_dpo_hi = hv(legs.dpo_incl, cpos, cr_hist)
    hist_row = {
        "timestamp": ts,
        "dr_id_lo": jnp.where(dr_hist, row["debit_account_id_lo"], 0),
        "dr_id_hi": jnp.where(dr_hist, row["debit_account_id_hi"], 0),
        "dr_dp_lo": dr_dp_lo, "dr_dp_hi": dr_dp_hi,
        "dr_dpo_lo": dr_dpo_lo, "dr_dpo_hi": dr_dpo_hi,
        "dr_cp_lo": dr_cp_lo, "dr_cp_hi": dr_cp_hi,
        "dr_cpo_lo": dr_cpo_lo, "dr_cpo_hi": dr_cpo_hi,
        "cr_id_lo": jnp.where(cr_hist, row["credit_account_id_lo"], 0),
        "cr_id_hi": jnp.where(cr_hist, row["credit_account_id_hi"], 0),
        "cr_cp_lo": cr_cp_lo, "cr_cp_hi": cr_cp_hi,
        "cr_cpo_lo": cr_cpo_lo, "cr_cpo_hi": cr_cpo_hi,
        "cr_dp_lo": cr_dp_lo, "cr_dp_hi": cr_dp_hi,
        "cr_dpo_lo": cr_dpo_lo, "cr_dpo_hi": cr_dpo_hi,
    }

    pv_ok = ok & postvoid
    posted_key = jnp.where(pv_ok, aux["p"]["timestamp"], 0)
    bal_incl = {
        "debits_pending_lo": legs.dp_incl.lo, "debits_pending_hi": legs.dp_incl.hi,
        "debits_posted_lo": legs.dpo_incl.lo, "debits_posted_hi": legs.dpo_incl.hi,
        "credits_pending_lo": legs.cp_incl.lo, "credits_pending_hi": legs.cp_incl.hi,
        "credits_posted_lo": legs.cpo_incl.lo, "credits_posted_hi": legs.cpo_incl.hi,
    }
    return ApplyPlan(
        codes=codes, route=route, ok=ok, row=row, post=post,
        posted_key=posted_key, pv_ok=pv_ok,
        s_slot=legs.s_slot, scat=legs.is_last & legs.s_live,
        bal_incl=bal_incl, do_hist=do_hist, hist_row=hist_row,
        passes=k_passes,
        wave_bound=wave_bound, wave_hist=wave_hist,
    )


def create_transfers_full_impl(
    ledger: Ledger,
    batch: Dict[str, jax.Array],
    count: jax.Array,
    timestamp: jax.Array,
    bloom: jax.Array = None,
    cold_checked: jax.Array = None,
    max_passes: int = _MAX_PASSES,
    has_postvoid: bool = True,
    has_history: bool = True,
    static_trip: Optional[bool] = None,
    use_waves: bool = False,
) -> Tuple[jax.Array, ...]:
    """Returns (ledger', codes uint32[N], flags uint32 scalar), plus a
    fourth wave-profile vector when ``use_waves`` (see below).

    flags == 0: the batch was applied and ``codes`` are the final results.
    flags != 0: NOTHING was applied (ledger' == ledger value-wise); the host
    must grow the flagged tables, resolve cold ids (FLAG_COLD: ``bloom`` is
    the cold-id filter, ``cold_checked`` marks lanes the host already
    certified), and/or re-route to the sequential path.

    ``use_waves`` (STATIC; TB_WAVES at the machine level) arms the
    conflict-index wave scheduler: bit-identical codes/ledger, fewer
    Jacobi passes on batches the conflict index certifies, and a FOURTH
    return — int32[11] = (passes, wave_bound, hist[9 wave-depth buckets])
    — for the bench/metrics surface.  Off compiles exactly the pre-waves
    program with the three-tuple return.
    """
    n = batch["id_lo"].shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    valid = lane < count.astype(jnp.int32)
    flags = batch["flags"]
    postvoid = (((flags & TF_POST) != 0) | ((flags & TF_VOID) != 0)) & valid
    tid = _u128_col(batch, "id")

    ctx = build_gather_ctx(
        ledger, batch, valid, postvoid, bloom, cold_checked,
        has_postvoid=has_postvoid,
    )
    plan = _kernel_core(ctx, batch, count, timestamp, max_passes, static_trip,
                        has_postvoid=has_postvoid, use_waves=use_waves)

    # Insert slots are claimed (no writes) BEFORE the flags are finalized so
    # an insert-probe overflow also routes the batch with nothing applied.
    t_claim, t_ovf = ht.claim_slots(
        ledger.transfers, tid.lo, tid.hi, plan.ok, MAX_PROBE
    )
    if has_postvoid:
        p_claim, p_ovf = ht.claim_slots(
            ledger.posted, plan.posted_key, jnp.zeros((n,), jnp.uint64),
            plan.pv_ok, MAX_PROBE,
        )
    else:
        # Host proved no post/void lanes: plan.pv_ok is all-False, so the
        # probe loop and the fulfillment write below compile away.
        p_claim = jnp.zeros((n,), jnp.uint64)
        p_ovf = jnp.bool_(False)
    kflags = (
        ctx.probe_grow
        | plan.route
        | jnp.where(t_ovf, jnp.uint32(FLAG_GROW_TRANSFERS), jnp.uint32(0))
        | jnp.where(p_ovf, jnp.uint32(FLAG_GROW_POSTED), jnp.uint32(0))
    )
    commit = kflags == jnp.uint32(0)

    # ---------------- apply: balances (one scatter over slot runs) ---------
    # The final pass's inclusive values were computed from the second-to-
    # last iterate, which equals the final (ok, amount) whenever the batch
    # commits (stability), so the last leg of each slot run carries the
    # slot's exact final field values.
    scat = plan.scat & commit
    cap_sentinel = jnp.uint64(ledger.accounts.capacity)
    accounts = ht.scatter_cols(
        ledger.accounts, jnp.where(scat, plan.s_slot, cap_sentinel), scat,
        plan.bal_incl,
    )

    # ---------------- apply: transfer + posted inserts ---------------------
    ins_rows = {
        name: plan.row[name].astype(dt) for name, dt in TRANSFER_COLS.items()
    }
    transfers = ht.write_rows(
        ledger.transfers, tid.lo, tid.hi, t_claim, plan.ok & commit, ins_rows
    )
    if has_postvoid:
        posted = ht.write_rows(
            ledger.posted,
            plan.posted_key,
            jnp.zeros((n,), jnp.uint64),
            p_claim,
            plan.pv_ok & commit,
            {"fulfillment": jnp.where(plan.post, jnp.uint32(1), jnp.uint32(2))},
        )
    else:
        posted = ledger.posted

    # ---------------- apply: history rows ---------------------------------
    if has_history:
        do_hist_c = plan.do_hist & commit
        h = ledger.history
        h_off = (
            jnp.cumsum(do_hist_c.astype(jnp.uint64))
            - do_hist_c.astype(jnp.uint64)
        )
        h_idx = jnp.where(do_hist_c, h.count + h_off, jnp.uint64(h.capacity))
        history = h.replace(
            cols={
                name: h.cols[name].at[h_idx].set(
                    plan.hist_row[name], mode="drop"
                )
                for name in h.cols
            },
            count=h.count + jnp.sum(do_hist_c.astype(jnp.uint64)),
        )
    else:
        # Host proved no account carries the HISTORY flag: the 21-column
        # append scatter compiles away.
        history = ledger.history

    out = Ledger(
        accounts=accounts, transfers=transfers, posted=posted, history=history
    )
    if use_waves:
        wave_vec = jnp.concatenate([
            plan.passes.reshape(1), plan.wave_bound.reshape(1),
            plan.wave_hist,
        ])
        return out, plan.codes, kflags, wave_vec
    return out, plan.codes, kflags


def _exists_regular(t, e, t_amount: U128, n) -> jax.Array:
    """create_transfer_exists (state_machine.zig:1370-1389): ``t`` the raw
    event, ``e`` the stored/winner row, ``t_amount`` the RAW event amount
    (the stored side may be clamped; the reference compares t.amount)."""

    def ne128(name):
        return (t[name + "_lo"] != e[name + "_lo"]) | (
            t[name + "_hi"] != e[name + "_hi"]
        )

    c = jnp.full((n,), 46, jnp.uint32)
    c = jnp.where(t["code"] != e["code"], jnp.uint32(45), c)
    c = jnp.where(t["timeout"] != e["timeout"], jnp.uint32(44), c)
    c = jnp.where(t["user_data_32"] != e["user_data_32"], jnp.uint32(43), c)
    c = jnp.where(t["user_data_64"] != e["user_data_64"], jnp.uint32(42), c)
    c = jnp.where(ne128("user_data_128"), jnp.uint32(41), c)
    amount_ne = (t_amount.lo != e["amount_lo"]) | (t_amount.hi != e["amount_hi"])
    c = jnp.where(ne128("pending_id"), jnp.uint32(40), c)
    c = jnp.where(amount_ne, jnp.uint32(39), c)
    c = jnp.where(ne128("credit_account_id"), jnp.uint32(38), c)
    c = jnp.where(ne128("debit_account_id"), jnp.uint32(37), c)
    c = jnp.where(t["flags"] != e["flags"], jnp.uint32(36), c)
    return c


def _exists_postvoid(t, e, p, n) -> jax.Array:
    """post_or_void_pending_transfer_exists (state_machine.zig:1500-1561)."""

    def pair_ne(a, b, name):
        return (a[name + "_lo"] != b[name + "_lo"]) | (
            a[name + "_hi"] != b[name + "_hi"]
        )

    t_amount_zero = (t["amount_lo"] == 0) & (t["amount_hi"] == 0)
    amount_ne = jnp.where(
        t_amount_zero, pair_ne(e, p, "amount"), pair_ne(t, e, "amount")
    )
    ud128_zero = (t["user_data_128_lo"] == 0) & (t["user_data_128_hi"] == 0)
    ud128_ne = jnp.where(
        ud128_zero, pair_ne(e, p, "user_data_128"), pair_ne(t, e, "user_data_128")
    )
    ud64_ne = jnp.where(
        t["user_data_64"] == 0, e["user_data_64"] != p["user_data_64"],
        t["user_data_64"] != e["user_data_64"],
    )
    ud32_ne = jnp.where(
        t["user_data_32"] == 0, e["user_data_32"] != p["user_data_32"],
        t["user_data_32"] != e["user_data_32"],
    )
    c = jnp.full((n,), 46, jnp.uint32)
    c = jnp.where(ud32_ne, jnp.uint32(43), c)
    c = jnp.where(ud64_ne, jnp.uint32(42), c)
    c = jnp.where(ud128_ne, jnp.uint32(41), c)
    c = jnp.where(pair_ne(t, e, "pending_id"), jnp.uint32(40), c)
    c = jnp.where(amount_ne, jnp.uint32(39), c)
    c = jnp.where(t["flags"] != e["flags"], jnp.uint32(36), c)
    return c


create_transfers_full = jax.jit(
    create_transfers_full_impl, donate_argnames=("ledger",),
    static_argnames=(
        "max_passes", "has_postvoid", "has_history", "static_trip",
        "use_waves",
    ),
)
