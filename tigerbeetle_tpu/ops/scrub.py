"""Device fault domain: SDC scrub kernel, host mirror, re-materialization.

Every other fault domain in this system is adversarially exercised —
sim/storage.py injects torn writes and latent sector faults under a
repairability atlas, sim/network.py partitions and drops — but the
device-resident ledger was implicitly trusted: a bit flip in HBM, a failed
XLA dispatch, or a device loss mid-pipeline silently corrupted balances
with no detection and no recovery path.  This module is the detection and
recovery substrate (machine.py wires it into the commit paths):

- ``scrub_digest``: an on-device incremental checksum kernel — a parallel
  mix64 fold over each ledger pad's live columns (accounts, transfers,
  posted), returning a uint64[3] vector so the whole scrub costs ONE
  device->host readback (it rides the existing commit-barrier funnel,
  machine._d2h_codes).  The accounts fold is bit-identical to
  ops.state_machine.ledger_digest, so scrub digests remain comparable with
  the superblock's checkpoint digest.
- ``mirror_digests``: the host-side expected digests, computed in numpy
  from the authoritative mirror — a ``testing.model.ReferenceStateMachine``
  seeded from a VERIFIED ledger snapshot (``model_from_ledger``) and
  advanced by every committed batch.  The model is the same scalar oracle
  every device kernel is differentially tested against (its stored rows
  are byte-exact vs the device's: the sim auditor compares lookup replies
  bit-for-bit), so device-vs-mirror divergence IS silent data corruption.
- ``materialize_ledger``: re-materialize a fresh device ledger from the
  mirror (recovery after a scrub mismatch or dispatch failure).  Content-
  identical, layout-rebuilt: slot assignment may differ from the
  incrementally-built table, which is invisible to semantics and to the
  order-independent digests.
- ``build_host_ledger``: the same re-materialization targeting the native
  host engine's numpy ledger (the degrade-to-host_engine path after N
  consecutive device failures).

Coverage note: the folds cover the accounts pad (id, all four balances,
timestamp), the transfers pad (id, amount, timestamp) and the posted pad
(pending timestamp, fulfillment).  History rows and non-digested columns
(user_data, codes) are NOT scrubbed — corruption there is caught by the
per-commit differential oracles in the sim, not by the production scrub.
The transfers fold is only comparable while the cold tier is empty (evicted
rows leave the hot table but stay in the mirror); machine.scrub_check
skips it once spill runs exist.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..u128 import mix64
from . import hash_table as ht
from . import state_machine as sm

U64_MASK = (1 << 64) - 1

_BALANCE_FIELDS = (
    "debits_pending", "debits_posted", "credits_pending", "credits_posted",
)


class SimulatedDeviceFault(RuntimeError):
    """Injected device-dispatch failure (tests / VOPR fault schedules).

    Raised from the dispatch funnels when machine.inject_device_faults
    armed one — stands in for the XlaRuntimeError family a real failed
    dispatch, lost device, or dead tunnel raises."""


class DeviceStateUnrecoverable(RuntimeError):
    """The device state is corrupt/failing AND the in-process mirror
    recovery cannot apply (mirror suspect, cold tier active, native engine
    unavailable at the degrade point).  The replica layer answers this
    with the last-resort path: checkpoint + WAL replay
    (vsr.replica.Replica.recover_device_state)."""


def _device_fault_types() -> tuple:
    kinds: List[type] = [SimulatedDeviceFault]
    try:  # jax >= 0.4: the public alias
        from jax.errors import JaxRuntimeError

        kinds.append(JaxRuntimeError)
    except ImportError:
        pass
    try:  # the concrete XLA error type (subclasses RuntimeError)
        from jaxlib.xla_extension import XlaRuntimeError

        kinds.append(XlaRuntimeError)
    except ImportError:
        pass
    # Dedupe aliases while preserving order.
    return tuple(dict.fromkeys(kinds))


# The exception family the dispatch funnels treat as "the device failed"
# (never bare RuntimeError: the machine's own integrity errors — probe
# overflow, digest mismatch — must not route into dispatch retry).
DEVICE_FAULT_TYPES = _device_fault_types()


# ---------------------------------------------------------------------------
# On-device fold kernel (ONE scalar-vector readback)
# ---------------------------------------------------------------------------


def row_hash_accounts(key_lo, key_hi, cols) -> jax.Array:
    """Per-row account fold (the scrub fold's per-slot term, and the
    Merkle leaf value — ops/merkle.py).  ``cols`` may be full columns or
    already-gathered lanes; shapes follow the inputs."""
    h = mix64(key_lo, key_hi)
    for f in _BALANCE_FIELDS:
        h = mix64(h ^ cols[f + "_lo"], h ^ cols[f + "_hi"])
    return mix64(h, cols["timestamp"])


def row_hash_transfers(key_lo, key_hi, cols) -> jax.Array:
    h = mix64(key_lo, key_hi)
    h = mix64(h ^ cols["amount_lo"], h ^ cols["amount_hi"])
    return mix64(h, cols["timestamp"])


def row_hash_posted(key_lo, key_hi, cols) -> jax.Array:
    h = mix64(key_lo, key_hi)
    return mix64(h, cols["fulfillment"].astype(jnp.uint64))


def leaf_hashes(table: ht.Table, row_hash) -> jax.Array:
    """uint64[capacity] per-slot live-masked row folds: the scrub fold's
    addends, and the Merkle tree's leaf level (ops/merkle.py)."""
    live = (table.key_lo != 0) | (table.key_hi != 0)
    h = row_hash(table.key_lo, table.key_hi, table.cols)
    return jnp.where(live, h, jnp.uint64(0))


def _fold_accounts(a: ht.Table) -> jax.Array:
    """Bit-identical to ops.state_machine.ledger_digest (docstring)."""
    return jnp.sum(leaf_hashes(a, row_hash_accounts))


def _fold_transfers(t: ht.Table) -> jax.Array:
    return jnp.sum(leaf_hashes(t, row_hash_transfers))


def _fold_posted(p: ht.Table) -> jax.Array:
    return jnp.sum(leaf_hashes(p, row_hash_posted))


@jax.jit  # deliberately NOT donated: the scrub must never consume the ledger
def scrub_digest(ledger: sm.Ledger) -> jax.Array:
    """uint64[3] = (accounts, transfers, posted) live-column folds."""
    return jnp.stack([
        _fold_accounts(ledger.accounts),
        _fold_transfers(ledger.transfers),
        _fold_posted(ledger.posted),
    ])


# ---------------------------------------------------------------------------
# Host-side numpy twins (the expected digests, from the mirror model)
# ---------------------------------------------------------------------------

_K1 = np.uint64(0x9E3779B97F4A7C15)
_K2 = np.uint64(0xBF58476D1CE4E5B9)
_K3 = np.uint64(0x94D049BB133111EB)


def mix64_np(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """numpy twin of u128.mix64 (same splitmix64 finalizer, uint64 wrap)."""
    with np.errstate(over="ignore"):
        x = lo ^ (hi * _K1)
        x = (x ^ (x >> np.uint64(30))) * _K2
        x = (x ^ (x >> np.uint64(27))) * _K3
        return x ^ (x >> np.uint64(31))


def _limbs(values: List[int]) -> Tuple[np.ndarray, np.ndarray]:
    lo = np.fromiter(
        (v & U64_MASK for v in values), dtype=np.uint64, count=len(values)
    )
    hi = np.fromiter(
        ((v >> 64) & U64_MASK for v in values),
        dtype=np.uint64, count=len(values),
    )
    return lo, hi


def _wrap_sum(h: np.ndarray) -> int:
    with np.errstate(over="ignore"):
        return int(np.sum(h, dtype=np.uint64)) if len(h) else 0


def mirror_digests(model) -> Tuple[int, int, int]:
    """(accounts, transfers, posted) expected digests from the mirror
    model, matching scrub_digest's device folds value-for-value."""
    accounts = list(model.accounts.values())
    if accounts:
        id_lo, id_hi = _limbs([a.id for a in accounts])
        h = mix64_np(id_lo, id_hi)
        for f in _BALANCE_FIELDS:
            lo, hi = _limbs([getattr(a, f) for a in accounts])
            h = mix64_np(h ^ lo, h ^ hi)
        ts = np.fromiter(
            (a.timestamp for a in accounts), np.uint64, count=len(accounts)
        )
        acc = _wrap_sum(mix64_np(h, ts))
    else:
        acc = 0
    transfers = list(model.transfers.values())
    if transfers:
        id_lo, id_hi = _limbs([t.id for t in transfers])
        h = mix64_np(id_lo, id_hi)
        lo, hi = _limbs([t.amount for t in transfers])
        h = mix64_np(h ^ lo, h ^ hi)
        ts = np.fromiter(
            (t.timestamp for t in transfers), np.uint64, count=len(transfers)
        )
        tr = _wrap_sum(mix64_np(h, ts))
    else:
        tr = 0
    posted = list(model.posted.items())
    if posted:
        key = np.fromiter((ts for ts, _ in posted), np.uint64, count=len(posted))
        ful = np.fromiter(
            ((1 if kind == "posted" else 2) for _, kind in posted),
            np.uint64, count=len(posted),
        )
        po = _wrap_sum(mix64_np(mix64_np(key, np.zeros_like(key)), ful))
    else:
        po = 0
    return acc, tr, po


# ---------------------------------------------------------------------------
# Mirror seeding: ReferenceStateMachine from a verified ledger snapshot
# ---------------------------------------------------------------------------

# model history dict key -> device HISTORY_COLS (lo, hi) column names.
_HIST_U128 = {
    "dr_account_id": ("dr_id_lo", "dr_id_hi"),
    "dr_debits_pending": ("dr_dp_lo", "dr_dp_hi"),
    "dr_debits_posted": ("dr_dpo_lo", "dr_dpo_hi"),
    "dr_credits_pending": ("dr_cp_lo", "dr_cp_hi"),
    "dr_credits_posted": ("dr_cpo_lo", "dr_cpo_hi"),
    "cr_account_id": ("cr_id_lo", "cr_id_hi"),
    "cr_debits_pending": ("cr_dp_lo", "cr_dp_hi"),
    "cr_debits_posted": ("cr_dpo_lo", "cr_dpo_hi"),
    "cr_credits_pending": ("cr_cp_lo", "cr_cp_hi"),
    "cr_credits_posted": ("cr_cpo_lo", "cr_cpo_hi"),
}


def _join(lo, hi) -> int:
    return int(lo) | (int(hi) << 64)


def model_from_ledger(
    ledger: sm.Ledger,
    cold_rows: Iterable[np.ndarray] = (),
    prepare_timestamp: int = 0,
    commit_timestamp: int = 0,
):
    """Seed a ReferenceStateMachine mirror from a VERIFIED device ledger
    (genesis, a digest-checked checkpoint restore, or a just-recovered
    state).  ``cold_rows``: the cold store's spilled TRANSFER_DTYPE runs —
    the mirror must know every transfer, hot or cold, for exists/post
    semantics to stay exact."""
    from ..testing import model as M

    m = M.ReferenceStateMachine()

    a = ledger.accounts
    key_lo, key_hi = np.asarray(a.key_lo), np.asarray(a.key_hi)
    cols = {name: np.asarray(col) for name, col in a.cols.items()}
    for slot in np.flatnonzero((key_lo != 0) | (key_hi != 0)):
        acct = M.Account(
            id=_join(key_lo[slot], key_hi[slot]),
            timestamp=int(cols["timestamp"][slot]),
            ledger=int(cols["ledger"][slot]),
            code=int(cols["code"][slot]),
            flags=int(cols["flags"][slot]),
            user_data_128=_join(
                cols["user_data_128_lo"][slot], cols["user_data_128_hi"][slot]
            ),
            user_data_64=int(cols["user_data_64"][slot]),
            user_data_32=int(cols["user_data_32"][slot]),
        )
        for f in _BALANCE_FIELDS:
            setattr(acct, f, _join(cols[f + "_lo"][slot], cols[f + "_hi"][slot]))
        m.accounts[acct.id] = acct

    t = ledger.transfers
    key_lo, key_hi = np.asarray(t.key_lo), np.asarray(t.key_hi)
    cols = {name: np.asarray(col) for name, col in t.cols.items()}
    for slot in np.flatnonzero((key_lo != 0) | (key_hi != 0)):
        tr = M.Transfer(
            id=_join(key_lo[slot], key_hi[slot]),
            debit_account_id=_join(
                cols["debit_account_id_lo"][slot],
                cols["debit_account_id_hi"][slot],
            ),
            credit_account_id=_join(
                cols["credit_account_id_lo"][slot],
                cols["credit_account_id_hi"][slot],
            ),
            amount=_join(cols["amount_lo"][slot], cols["amount_hi"][slot]),
            pending_id=_join(
                cols["pending_id_lo"][slot], cols["pending_id_hi"][slot]
            ),
            user_data_128=_join(
                cols["user_data_128_lo"][slot], cols["user_data_128_hi"][slot]
            ),
            user_data_64=int(cols["user_data_64"][slot]),
            user_data_32=int(cols["user_data_32"][slot]),
            timeout=int(cols["timeout"][slot]),
            ledger=int(cols["ledger"][slot]),
            code=int(cols["code"][slot]),
            flags=int(cols["flags"][slot]),
            timestamp=int(cols["timestamp"][slot]),
        )
        m.transfers[tr.id] = tr
    for run in cold_rows:
        for row in np.asarray(run):
            tr = M.transfer_from_row(row)
            m.transfers.setdefault(tr.id, tr)

    p = ledger.posted
    key_lo, key_hi = np.asarray(p.key_lo), np.asarray(p.key_hi)
    ful = np.asarray(p.cols["fulfillment"])
    for slot in np.flatnonzero((key_lo != 0) | (key_hi != 0)):
        m.posted[int(key_lo[slot])] = (
            "posted" if int(ful[slot]) == 1 else "voided"
        )

    hist = ledger.history
    n_hist = int(hist.count)
    if n_hist:
        hcols = {name: np.asarray(col) for name, col in hist.cols.items()}
        for i in range(n_hist):
            row = {
                key: _join(hcols[lo][i], hcols[hi][i])
                for key, (lo, hi) in _HIST_U128.items()
            }
            row["timestamp"] = int(hcols["timestamp"][i])
            m.history[row["timestamp"]] = row

    m.prepare_timestamp = int(prepare_timestamp)
    m.commit_timestamp = int(commit_timestamp)
    return m


# ---------------------------------------------------------------------------
# Re-materialization: device ledger / host ledger from the mirror
# ---------------------------------------------------------------------------


def _grown(capacity: int, rows: int) -> int:
    while rows * 2 > capacity:
        capacity *= 2
    return capacity


def _pad_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length()) if n else 1


def _insert_all(table: ht.Table, id_lo, id_hi, rows: Dict[str, np.ndarray]):
    """One padded batched insert of distinct keys (probe-overflow-checked)."""
    n = len(id_lo)
    if n == 0:
        return table
    lanes = _pad_pow2(n)
    pad_lo = np.zeros(lanes, np.uint64)
    pad_hi = np.zeros(lanes, np.uint64)
    pad_lo[:n], pad_hi[:n] = id_lo, id_hi
    mask = np.zeros(lanes, bool)
    mask[:n] = True
    padded_rows = {}
    for name, col in rows.items():
        buf = np.zeros(lanes, col.dtype)
        buf[:n] = col
        padded_rows[name] = jnp.asarray(buf)
    table, _ = ht.insert(
        table, jnp.asarray(pad_lo), jnp.asarray(pad_hi), jnp.asarray(mask),
        padded_rows, max_probe=table.capacity,
    )
    if bool(np.asarray(table.probe_overflow)):
        raise DeviceStateUnrecoverable(
            "re-materialization probe overflow (capacity planning violated)"
        )
    return table


def _account_arrays(model):
    items = sorted(model.accounts.values(), key=lambda a: a.id)
    id_lo, id_hi = _limbs([a.id for a in items])
    rows: Dict[str, np.ndarray] = {}
    for f in _BALANCE_FIELDS + ("user_data_128",):
        lo, hi = _limbs([getattr(a, f) for a in items])
        rows[f + "_lo"], rows[f + "_hi"] = lo, hi
    rows["user_data_64"] = np.fromiter(
        (a.user_data_64 for a in items), np.uint64, count=len(items))
    rows["user_data_32"] = np.fromiter(
        (a.user_data_32 for a in items), np.uint32, count=len(items))
    rows["ledger"] = np.fromiter(
        (a.ledger for a in items), np.uint32, count=len(items))
    rows["code"] = np.fromiter(
        (a.code for a in items), np.uint32, count=len(items))
    rows["flags"] = np.fromiter(
        (a.flags for a in items), np.uint32, count=len(items))
    rows["timestamp"] = np.fromiter(
        (a.timestamp for a in items), np.uint64, count=len(items))
    return id_lo, id_hi, rows


def _transfer_arrays(model):
    items = sorted(model.transfers.values(), key=lambda t: t.id)
    id_lo, id_hi = _limbs([t.id for t in items])
    rows: Dict[str, np.ndarray] = {}
    for f in ("debit_account_id", "credit_account_id", "amount",
              "pending_id", "user_data_128"):
        lo, hi = _limbs([getattr(t, f) for t in items])
        rows[f + "_lo"], rows[f + "_hi"] = lo, hi
    rows["user_data_64"] = np.fromiter(
        (t.user_data_64 for t in items), np.uint64, count=len(items))
    rows["user_data_32"] = np.fromiter(
        (t.user_data_32 for t in items), np.uint32, count=len(items))
    rows["timeout"] = np.fromiter(
        (t.timeout for t in items), np.uint32, count=len(items))
    rows["ledger"] = np.fromiter(
        (t.ledger for t in items), np.uint32, count=len(items))
    rows["code"] = np.fromiter(
        (t.code for t in items), np.uint32, count=len(items))
    rows["flags"] = np.fromiter(
        (t.flags for t in items), np.uint32, count=len(items))
    rows["timestamp"] = np.fromiter(
        (t.timestamp for t in items), np.uint64, count=len(items))
    return id_lo, id_hi, rows


def _posted_arrays(model):
    items = sorted(model.posted.items())
    key = np.fromiter((ts for ts, _ in items), np.uint64, count=len(items))
    ful = np.fromiter(
        ((1 if kind == "posted" else 2) for _, kind in items),
        np.uint32, count=len(items),
    )
    return key, np.zeros_like(key), {"fulfillment": ful}


def _history_arrays(model) -> Tuple[Dict[str, np.ndarray], int]:
    items = [model.history[ts] for ts in sorted(model.history)]
    n = len(items)
    cols: Dict[str, np.ndarray] = {}
    for key, (lo_name, hi_name) in _HIST_U128.items():
        lo, hi = _limbs([h[key] for h in items])
        cols[lo_name], cols[hi_name] = lo, hi
    cols["timestamp"] = np.fromiter(
        (h["timestamp"] for h in items), np.uint64, count=n)
    return cols, n


def materialize_ledger(model, ledger_config) -> sm.Ledger:
    """Fresh device ledger with the mirror's exact content (recovery).

    Capacities derive from the config floor grown to the mirror's row
    counts (load factor <= 0.5, the host growth policy) — they may differ
    from the corrupted ledger's, which only affects layout, never content
    or the order-independent digests."""
    cfg = ledger_config
    acc_lo, acc_hi, acc_rows = _account_arrays(model)
    tr_lo, tr_hi, tr_rows = _transfer_arrays(model)
    po_lo, po_hi, po_rows = _posted_arrays(model)
    hist_cols, hist_n = _history_arrays(model)

    accounts = _insert_all(
        ht.make_table(
            _grown(cfg.accounts_capacity, len(acc_lo)), sm.ACCOUNT_COLS
        ),
        acc_lo, acc_hi, acc_rows,
    )
    transfers = _insert_all(
        ht.make_table(
            _grown(cfg.transfers_capacity, len(tr_lo)), sm.TRANSFER_COLS
        ),
        tr_lo, tr_hi, tr_rows,
    )
    posted = _insert_all(
        ht.make_table(_grown(cfg.posted_capacity, len(po_lo)), sm.POSTED_COLS),
        po_lo, po_hi, po_rows,
    )
    hist_cap = cfg.history_capacity
    while hist_cap < hist_n:
        hist_cap *= 2
    hcols = {}
    for name in sm.HISTORY_COLS:
        buf = np.zeros(hist_cap, np.uint64)
        if hist_n:
            buf[:hist_n] = hist_cols[name]
        hcols[name] = jnp.asarray(buf)
    history = sm.History(cols=hcols, count=jnp.uint64(hist_n))
    return sm.Ledger(
        accounts=accounts, transfers=transfers, posted=posted, history=history
    )


def build_host_ledger(model, ledger_config):
    """HostLedger (native engine numpy ledger) with the mirror's content —
    the degrade-to-host_engine target.  Pure host-side: the probe-insert
    runs in numpy/python (mix64 home slot + linear probe, the exact
    hash_table.py discipline), so a failing device is never touched."""
    from ..host_engine import HostLedger

    cfg = ledger_config
    acc_lo, acc_hi, acc_rows = _account_arrays(model)
    tr_lo, tr_hi, tr_rows = _transfer_arrays(model)
    po_lo, po_hi, po_rows = _posted_arrays(model)
    hist_cols, hist_n = _history_arrays(model)

    hist_cap = cfg.history_capacity
    while hist_cap < hist_n:
        hist_cap *= 2
    led = HostLedger(
        _grown(cfg.accounts_capacity, len(acc_lo)),
        _grown(cfg.transfers_capacity, len(tr_lo)),
        _grown(cfg.posted_capacity, len(po_lo)),
        history_capacity=hist_cap,
    )

    def fill(table, key_lo, key_hi, rows):
        cap = table.capacity
        mask = np.uint64(cap - 1)
        occupied = np.zeros(cap, bool)
        home = mix64_np(key_lo, key_hi) & mask
        cols = table.cols  # device-column-name views into the AoS rows
        for i in range(len(key_lo)):
            slot = int(home[i])
            while occupied[slot]:
                slot = (slot + 1) & int(mask)
            occupied[slot] = True
            table.rows["key_lo"][slot] = key_lo[i]
            table.rows["key_hi"][slot] = key_hi[i]
            for name, col in rows.items():
                cols[name][slot] = col[i]
        table.count = len(key_lo)

    fill(led.accounts, acc_lo, acc_hi, acc_rows)
    fill(led.transfers, tr_lo, tr_hi, tr_rows)
    fill(led.posted, po_lo, po_hi, po_rows)
    for name in led.history:
        if hist_n:
            led.history[name][:hist_n] = hist_cols[name]
    led.history_count = hist_n
    return led
