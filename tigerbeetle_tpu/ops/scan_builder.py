"""General scan composition over per-field secondary indexes.

Reference: ``lsm/scan_builder.zig`` (``scan_prefix`` conditions composed by
``merge_union``; ``merge_intersection``/``merge_difference`` are declared at
scan_builder.zig:184-205 but stubbed ``unimplemented``) and
``lsm/scan_merge.zig`` (k-way merge streams over index scans).  This module
is the TPU-native generalization the round-3 verdict asked for: prefix scans
over ANY groove field, composed by union / intersection / difference to any
nesting depth, exact results in timestamp order — strictly more than the
reference's implemented surface (2-condition union).

Design.  Each scanned field gets a :class:`FieldIndex` — the same
Bentley–Saxe sorted-runs pyramid as ``ops/index.py`` (per committed batch one
sorted run; binary-counter carries; query = binary search + bounded candidate
window per level, FLAT in table capacity) keyed by ``(field value,
timestamp)``.  Indexes are DERIVED state, materialized lazily on the first
scan that names the field (one full-table sort), then maintained per batch.
Leaves run on device (the jitted multi-level window gather shared with
``ops/index.py``); the set algebra runs on host over <=K candidates per leg —
mirroring the reference, whose ScanMerge* k-way merges also run replica-side
on the CPU, outside the LSM.

Exactness with bounded windows.  A leaf's candidate list is its complete
match prefix in rank order (rank = ts ascending, ~ts descending) up to its
*frontier*: the rank of its last candidate if any level's window filled, else
+inf (leaf exhausted — every match enumerated).  A merge node's membership is
decidable only up to the min frontier of its children, so the evaluator
truncates there and propagates the frontier upward; the top-level loop
doubles K until the root yields ``limit`` rows or is exhausted.  Timestamps
are unique per groove object (strictly-increasing assignment), so rank
equality IS object identity — which is what makes the host-side multiplicity
count an exact intersection.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import hash_table as ht
from . import index as ix
from . import state_machine as sm

U64M = (1 << 64) - 1

# Scannable fields per groove: name -> (lo_col, hi_col | None).  Mirrors the
# reference's per-groove index trees (state_machine.zig TransfersGroove /
# AccountsGroove IndexTrees); u128 fields split into limb columns, narrower
# fields widen to u64 with hi = 0.
TRANSFER_FIELDS: Dict[str, Tuple[str, Optional[str]]] = {
    "debit_account_id": ("debit_account_id_lo", "debit_account_id_hi"),
    "credit_account_id": ("credit_account_id_lo", "credit_account_id_hi"),
    "pending_id": ("pending_id_lo", "pending_id_hi"),
    "user_data_128": ("user_data_128_lo", "user_data_128_hi"),
    "user_data_64": ("user_data_64", None),
    "user_data_32": ("user_data_32", None),
    "ledger": ("ledger", None),
    "code": ("code", None),
}
ACCOUNT_FIELDS: Dict[str, Tuple[str, Optional[str]]] = {
    "user_data_128": ("user_data_128_lo", "user_data_128_hi"),
    "user_data_64": ("user_data_64", None),
    "user_data_32": ("user_data_32", None),
    "ledger": ("ledger", None),
    "code": ("code", None),
}


# -- expression algebra ------------------------------------------------------


class Scan:
    """Base of the scan expression tree."""

    def fields(self) -> List[str]:
        raise NotImplementedError


@dataclass(frozen=True)
class Prefix(Scan):
    """All objects whose ``field`` equals ``value`` (scan_builder.zig
    scan_prefix)."""

    field: str
    value: int

    def fields(self) -> List[str]:
        return [self.field]


@dataclass(frozen=True)
class Union(Scan):
    children: Tuple[Scan, ...]

    def fields(self) -> List[str]:
        return [f for c in self.children for f in c.fields()]


@dataclass(frozen=True)
class Intersection(Scan):
    children: Tuple[Scan, ...]

    def fields(self) -> List[str]:
        return [f for c in self.children for f in c.fields()]


@dataclass(frozen=True)
class Difference(Scan):
    include: Scan
    exclude: Scan

    def fields(self) -> List[str]:
        return self.include.fields() + self.exclude.fields()


def scan_prefix(field: str, value: int) -> Scan:
    return Prefix(field, int(value))


def merge_union(*scans: Scan) -> Scan:
    assert scans, "union of zero scans"
    return scans[0] if len(scans) == 1 else Union(tuple(scans))


def merge_intersection(*scans: Scan) -> Scan:
    assert scans, "intersection of zero scans"
    return scans[0] if len(scans) == 1 else Intersection(tuple(scans))


def merge_difference(include: Scan, exclude: Scan) -> Scan:
    return Difference(include, exclude)


# -- device builders (generic-field twins of ops/index.py's) -----------------


@functools.partial(
    jax.jit, static_argnames=("table_name", "lo_col", "hi_col")
)
def _build_field_run(
    ledger: sm.Ledger,
    id_lo: jax.Array,
    id_hi: jax.Array,
    ok: jax.Array,
    table_name: str,
    lo_col: str,
    hi_col: Optional[str],
) -> Dict[str, jax.Array]:
    """Sorted level-0 run for a just-committed batch, keyed by one field."""
    table = getattr(ledger, table_name)
    look = ht.lookup(table, id_lo, id_hi, sm.MAX_PROBE)
    use = ok & look.found
    rows = ht.gather_cols(table, look.slot, use)
    key_lo = rows[lo_col].astype(jnp.uint64)
    key_hi = (
        rows[hi_col].astype(jnp.uint64) if hi_col else jnp.zeros_like(key_lo)
    )
    big = jnp.uint64(U64M)
    lvl = {
        "acct_lo": jnp.where(use, key_lo, big),
        "acct_hi": jnp.where(use, key_hi, big),
        "ts": jnp.where(use, rows["timestamp"], big),
        "tid_lo": jnp.where(use, id_lo, big),
        "tid_hi": jnp.where(use, id_hi, big),
    }
    return ix._sort_level(lvl)


@functools.partial(
    jax.jit, static_argnames=("table_name", "lo_col", "hi_col", "capacity")
)
def _full_build_field(
    ledger: sm.Ledger,
    table_name: str,
    lo_col: str,
    hi_col: Optional[str],
    capacity: int,
) -> Dict[str, jax.Array]:
    """One sorted run over every live object (lazy materialization)."""
    t = getattr(ledger, table_name)
    live = ((t.key_lo != 0) | (t.key_hi != 0)) & ~t.tombstone
    n = t.capacity
    assert capacity >= n
    pad = capacity - n

    def col(vals):
        v = jnp.where(live, vals.astype(jnp.uint64), jnp.uint64(U64M))
        return jnp.concatenate([v, jnp.full((pad,), U64M, jnp.uint64)])

    lvl = {
        "acct_lo": col(t.cols[lo_col]),
        "acct_hi": col(t.cols[hi_col]) if hi_col
        else jnp.where(
            jnp.concatenate([live, jnp.zeros((pad,), jnp.bool_)]),
            jnp.uint64(0), jnp.uint64(U64M),
        ),
        "ts": col(t.cols["timestamp"]),
        "tid_lo": col(t.key_lo),
        "tid_hi": col(t.key_hi),
    }
    return ix._sort_level(lvl)


@functools.partial(jax.jit, static_argnames=("k", "descending"))
def _leaf_window(
    levels: Tuple[Dict[str, jax.Array], ...],
    key_lo: jax.Array,
    key_hi: jax.Array,
    ts_min: jax.Array,
    ts_max: jax.Array,
    k: int,
    descending: bool,
):
    return ix._query_side(
        list(levels), key_lo, key_hi, ts_min, ts_max, k, descending
    )


class FieldIndex:
    """Single-field sorted-runs pyramid (one side of ops/index.py's
    TransferIndex, generalized to any key column pair).

    NOTE: the carry-chain/rebuild/host-rows machinery here is the
    single-side twin of TransferIndex's (ops/index.py) — a fix to either
    pyramid's level logic almost certainly applies to both."""

    def __init__(
        self, base: int, table_name: str, lo_col: str, hi_col: Optional[str]
    ) -> None:
        assert base & (base - 1) == 0
        self.base = base
        self.table_name = table_name
        self.lo_col = lo_col
        self.hi_col = hi_col
        self.levels: List[Dict[str, jax.Array]] = []
        self.occupied: List[bool] = []
        # Born stale: materialization happens against a table that may
        # already hold objects.
        self.stale = True
        # New-level allocations (power-of-two shape classes whose first
        # merge jit-compiles) — the TransferIndex twin's counter; the
        # machine's TB_SANITIZE tripwire forgives exactly these.
        self.shape_class_events = 0

    def reset(self) -> None:
        self.levels, self.occupied = [], []
        self.stale = True

    def _ensure_level(self, k: int) -> None:
        while len(self.occupied) <= k:
            cap = self.base << len(self.occupied)
            self.levels.append(ix._sentinel_level(cap))
            self.occupied.append(False)
            self.shape_class_events += 1  # new size class: first-use jits

    def capacity(self) -> int:
        return sum(self.base << j for j in range(len(self.occupied))) or self.base

    def append_batch(self, ledger, id_lo, id_hi, ok) -> None:
        if self.stale:
            return  # rebuilt wholesale on next scan
        run = _build_field_run(
            ledger, id_lo, id_hi, ok,
            self.table_name, self.lo_col, self.hi_col,
        )
        k = 0
        while k < len(self.occupied) and self.occupied[k]:
            k += 1
        self._ensure_level(k)
        if k == 0:
            self.levels[0] = run
        else:
            self.levels[k] = ix._merge_jit([run] + self.levels[:k])
            for j in range(k):
                self.levels[j] = ix._sentinel_level(self.base << j)
                self.occupied[j] = False
        self.occupied[k] = True

    def rebuild(self, ledger, extra_rows: Sequence[np.ndarray] = ()) -> None:
        cap = max(self.base, getattr(ledger, self.table_name).capacity)
        k = (cap // self.base - 1).bit_length()
        self.levels, self.occupied = [], []
        self._ensure_level(k)
        self.levels[k] = _full_build_field(
            ledger, self.table_name, self.lo_col, self.hi_col, self.base << k
        )
        self.occupied[k] = True
        for rows in extra_rows:
            self._add_host_rows(rows)
        self.stale = False

    def _add_host_rows(self, rows: np.ndarray) -> None:
        """Occupy a free level with host rows (cold-tier runs at rebuild)."""
        rows = np.asarray(rows)
        n = len(rows)
        if n == 0:
            return
        j = max(0, ((n + self.base - 1) // self.base - 1).bit_length())
        self._ensure_level(j)
        while self.occupied[j]:
            j += 1
            self._ensure_level(j)
        cap = self.base << j

        def col(vals):
            out = np.full((cap,), U64M, np.uint64)
            out[:n] = vals
            return jnp.asarray(out)

        self.levels[j] = ix._sort_level_jit({
            "acct_lo": col(rows[self.lo_col].astype(np.uint64)),
            "acct_hi": col(
                rows[self.hi_col].astype(np.uint64) if self.hi_col
                else np.zeros((n,), np.uint64)
            ),
            "ts": col(rows["timestamp"]),
            "tid_lo": col(rows["id_lo"]),
            "tid_hi": col(rows["id_hi"]),
        })
        self.occupied[j] = True


# -- evaluation --------------------------------------------------------------


@dataclass
class _Res:
    """One node's decided candidates, rank-ascending (= result order)."""

    rank: np.ndarray     # uint64, sorted ascending, unique
    tid_lo: np.ndarray
    tid_hi: np.ndarray
    frontier: int        # membership decided for rank <= frontier
    exhausted: bool      # True: candidates are the node's COMPLETE result set


def _eval_leaf(
    idx: FieldIndex, value: int, ts_min: int, ts_max: int,
    k: int, descending: bool,
) -> _Res:
    if not idx.levels:
        return _Res(*_empty3(), frontier=U64M, exhausted=True)
    levels = tuple(idx.levels)
    ts_d, lo_d, hi_d = _leaf_window(
        levels,
        jnp.uint64(value & U64M), jnp.uint64(value >> 64),
        jnp.uint64(ts_min), jnp.uint64(ts_max), k, descending,
    )
    n_lvl = len(levels)
    ts = np.asarray(ts_d).reshape(n_lvl, k)
    lo = np.asarray(lo_d).reshape(n_lvl, k)
    hi = np.asarray(hi_d).reshape(n_lvl, k)
    valid = ts != np.uint64(U64M)
    rank = np.invert(ts) if descending else ts
    counts = valid.sum(axis=1)
    full = counts == k
    if full.any():
        # Window positions walk away from the range boundary, so each
        # level's valid candidates are a rank-ascending prefix; a full
        # window means the level may hold more matches beyond its last
        # candidate's rank.
        frontier = int(
            min(int(rank[l, counts[l] - 1]) for l in np.nonzero(full)[0])
        )
        exhausted = False
    else:
        frontier = U64M
        exhausted = True
    keep = valid & (rank <= np.uint64(frontier))
    r, l_, h_ = rank[keep], lo[keep], hi[keep]
    # A rebuild can index one object twice (hot table + its cold run):
    # dedup by rank so intersection multiplicity counting stays exact.
    r, first = np.unique(r, return_index=True)
    return _Res(r, l_[first], h_[first], frontier, exhausted)


def _empty3():
    z = np.zeros(0, np.uint64)
    return z, z.copy(), z.copy()


def _truncate(res: _Res, frontier: int) -> _Res:
    keep = res.rank <= np.uint64(frontier)
    # An exhausted node's candidate set is COMPLETE, so its membership is
    # decided at every rank: propagate an infinite frontier (a finite one
    # would make a parent Union truncate its siblings' decided results).
    return _Res(
        res.rank[keep], res.tid_lo[keep], res.tid_hi[keep],
        U64M if res.exhausted else frontier, res.exhausted,
    )


def _fully_decided(res: _Res, frontier: int) -> bool:
    """An exhausted child whose every candidate ranks <= frontier has its
    node-level contribution fully decided by the other legs' windows."""
    return res.exhausted and (
        len(res.rank) == 0 or int(res.rank[-1]) <= frontier
    )


def _eval(node: Scan, leaf: Callable[[Prefix], _Res]) -> _Res:
    if isinstance(node, Prefix):
        return leaf(node)
    if isinstance(node, (Union, Intersection)):
        rs = [_eval(c, leaf) for c in node.children]
        frontier = min(r.frontier for r in rs)
        rank = np.concatenate([r.rank for r in rs])
        lo = np.concatenate([r.tid_lo for r in rs])
        hi = np.concatenate([r.tid_hi for r in rs])
        order = np.argsort(rank, kind="stable")
        rank, lo, hi = rank[order], lo[order], hi[order]
        uniq, first, counts = np.unique(
            rank, return_index=True, return_counts=True
        )
        if isinstance(node, Union):
            res = _Res(
                uniq, lo[first], hi[first], frontier,
                all(r.exhausted for r in rs),
            )
        else:
            hit = counts == len(rs)
            exhausted = all(r.exhausted for r in rs) or any(
                _fully_decided(r, frontier) for r in rs
            )
            res = _Res(
                uniq[hit], lo[first][hit], hi[first][hit], frontier, exhausted
            )
        return _truncate(res, frontier)
    if isinstance(node, Difference):
        a = _eval(node.include, leaf)
        b = _eval(node.exclude, leaf)
        frontier = min(a.frontier, b.frontier)
        keep = ~np.isin(a.rank, b.rank)
        exhausted = (a.exhausted and b.exhausted) or _fully_decided(
            a, frontier
        )
        return _truncate(
            _Res(a.rank[keep], a.tid_lo[keep], a.tid_hi[keep],
                 frontier, exhausted),
            frontier,
        )
    raise TypeError(f"unknown scan node {node!r}")


class ScanSet:
    """Per-groove registry of lazily-materialized field indexes plus the
    scan evaluator (the ScanBuilder/ScanMerge role, scan_builder.zig:23)."""

    def __init__(
        self,
        table_name: str,
        field_specs: Dict[str, Tuple[str, Optional[str]]],
        base: int,
    ) -> None:
        self.table_name = table_name
        self.field_specs = field_specs
        self.base = base
        self.indexes: Dict[str, FieldIndex] = {}
        # Supplies host TRANSFER_DTYPE rows to index at rebuild (the machine
        # wires its cold-tier runs here, like TransferIndex).
        self.extra_rows_provider: Optional[Callable[[], Sequence]] = None

    def reset(self) -> None:
        for idx in self.indexes.values():
            idx.reset()

    def append_batch(self, ledger, id_lo, id_hi, ok) -> None:
        for idx in self.indexes.values():
            idx.append_batch(ledger, id_lo, id_hi, ok)

    def _ensure(self, fields: Sequence[str], ledger) -> None:
        for f in fields:
            if f not in self.field_specs:
                raise KeyError(
                    f"{self.table_name} has no scannable field {f!r} "
                    f"(choose from {sorted(self.field_specs)})"
                )
            idx = self.indexes.get(f)
            if idx is None:
                lo_col, hi_col = self.field_specs[f]
                idx = FieldIndex(self.base, self.table_name, lo_col, hi_col)
                self.indexes[f] = idx
            if idx.stale:
                extra = (
                    self.extra_rows_provider()
                    if self.extra_rows_provider else ()
                )
                idx.rebuild(ledger, extra)

    def evaluate(
        self,
        expr: Scan,
        ledger,
        ts_min: int,
        ts_max: int,
        limit: int,
        descending: bool,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(tid_lo, tid_hi) of up to ``limit`` matching objects in result
        order (ascending / descending timestamp)."""
        fields = expr.fields()
        self._ensure(fields, ledger)
        cap = max(self.indexes[f].capacity() for f in fields)
        k = max(16, 1 << max(0, limit - 1).bit_length())
        while True:
            res = _eval(
                expr,
                lambda p: _eval_leaf(
                    self.indexes[p.field], p.value, ts_min, ts_max,
                    k, descending,
                ),
            )
            if len(res.rank) >= limit or res.exhausted or k >= cap:
                break
            k *= 2
        return res.tid_lo[:limit], res.tid_hi[:limit]
