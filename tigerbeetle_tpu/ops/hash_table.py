"""On-HBM open-addressing SoA hash table — the device analogue of an LSM Groove.

The reference resolves object lookups through an LSM tree hierarchy with a
set-associative cache in front (src/lsm/groove.zig:138+, cache_map.zig:10-25).
On TPU the working set lives resident in HBM as one struct-of-arrays
open-addressing table: lookups are a batched vectorized linear probe (a few
gathers over 8k lanes), and inserts are a batched claim protocol — both O(1)
expected per key at load factor < 0.5, fully inside jit, no host round trips.

Design:
- Capacity is a static power of two; slot = splitmix64(key) & (C-1).
- Empty slot: key == 0 (valid ids are nonzero: id_must_not_be_zero).
- Tombstones (from linked-chain rollback of inserts) keep ``tombstone=True``
  with key cleared; probes continue past them, inserts may not reuse them
  (wastes a slot per rolled-back insert; rollbacks are rare).
- Batched insert resolves intra-batch slot collisions by lane order: among
  unplaced lanes probing the same slot, the lowest batch index wins; losers
  advance their probe. Deterministic (a pure function of the batch).

All entry points are shape-stable and jit-traceable.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..u128 import mix64


@struct.dataclass
class Table:
    """SoA open-addressing table. ``cols`` holds the value columns."""

    key_lo: jax.Array  # uint64[C]; 0 = empty/tombstone
    key_hi: jax.Array  # uint64[C]
    tombstone: jax.Array  # bool[C]
    cols: Dict[str, jax.Array]
    count: jax.Array  # uint64 scalar: live entries
    probe_overflow: jax.Array  # bool scalar: a probe exceeded max_probe (host must grow)

    @property
    def capacity(self) -> int:
        return self.key_lo.shape[0]


def make_table(capacity: int, col_specs: Dict[str, jnp.dtype]) -> Table:
    assert capacity & (capacity - 1) == 0, "capacity must be a power of two"
    return Table(
        key_lo=jnp.zeros((capacity,), jnp.uint64),
        key_hi=jnp.zeros((capacity,), jnp.uint64),
        tombstone=jnp.zeros((capacity,), jnp.bool_),
        cols={name: jnp.zeros((capacity,), dt) for name, dt in col_specs.items()},
        count=jnp.uint64(0),
        probe_overflow=jnp.bool_(False),
    )


class LookupResult(NamedTuple):
    found: jax.Array  # bool[N]
    slot: jax.Array  # uint64[N] — valid where found
    overflow: jax.Array  # bool scalar — some lane exhausted max_probe


@functools.partial(jax.jit, static_argnames=("max_probe", "hash_shift"))
def lookup(
    table: Table,
    key_lo: jax.Array,
    key_hi: jax.Array,
    max_probe: int,
    hash_shift: int = 0,
) -> LookupResult:
    """Batched linear probe: for each key, find its slot or prove absence.

    ``hash_shift`` discards low hash bits before slotting — sharded tables use
    the low bits as the owner-shard index (parallel/sharded.py) and the rest
    for the local slot, so shard-local probes never cross devices."""
    capacity = table.capacity
    mask = jnp.uint64(capacity - 1)
    home = (mix64(key_lo, key_hi) >> jnp.uint64(hash_shift)) & mask

    # Lanes probing key 0 (invalid id / padding lanes) resolve immediately.
    is_null = (key_lo == 0) & (key_hi == 0)

    def cond(state):
        i, done, _, _ = state
        return jnp.any(~done) & (i < max_probe)

    def body(state):
        i, done, found, slot = state
        cur = (home + jnp.uint64(i)) & mask
        t_lo = table.key_lo[cur]
        t_hi = table.key_hi[cur]
        tomb = table.tombstone[cur]
        match = ~done & (t_lo == key_lo) & (t_hi == key_hi) & ~tomb
        empty = ~done & (t_lo == 0) & (t_hi == 0) & ~tomb
        found = found | match
        slot = jnp.where(match, cur, slot)
        done = done | match | empty
        return i + 1, done, found, slot

    i0 = jnp.int32(0)
    done0 = is_null
    found0 = jnp.zeros_like(is_null)
    slot0 = jnp.zeros_like(home)
    i, done, found, slot = jax.lax.while_loop(cond, body, (i0, done0, found0, slot0))
    return LookupResult(found=found, slot=slot, overflow=jnp.any(~done))


def claim_slots(
    table: Table,
    key_lo: jax.Array,
    key_hi: jax.Array,
    insert_mask: jax.Array,
    max_probe: int,
    hash_shift: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Compute the insert slot for each masked key WITHOUT writing.

    Returns (claimed_slot[N], overflow).  Lets callers detect probe overflow
    BEFORE committing any state (the transfer kernel folds it into its
    routing flags so 'flags != 0 => nothing applied' holds exactly), then
    apply via write_rows.

    Placement protocol (unchanged since v1; this is a cost rewrite):
    every still-unplaced lane probes home+i at iteration i, and among
    unplaced lanes sharing a slot the lowest batch index wins.  Because ALL
    unplaced lanes advance together, two lanes can only collide when they
    share the same HOME slot — group membership is static.  So the winner
    of any iteration is simply the group's next lane in batch order: ONE
    upfront sort assigns each lane its rank within its home group, and the
    loop body just compares rank against a per-group placed counter.  The
    previous per-iteration argsort (an XLA comparator sort of all N lanes,
    the dominant term of the commit hot path at realistic table fills —
    BENCH_r08 vs_baseline) is gone; occupancy rides a 1-bit-per-slot packed
    bitmap so the loop carry is capacity/32 words, not a capacity-wide
    bool column.  Claimed slots are bit-identical to the sort-based
    protocol — tests/test_hash_table.py keeps that protocol as an inline
    numpy oracle and pins claim parity against it (random fills, masked
    lanes, forced same-home collisions).

    WINDOWED probing (the remaining PR 7 hot-path term): the loop trips
    to the MAX cluster depth over the batch, but after the first few
    probes only a geometric tail of lanes is still unplaced — paying
    N-lane gathers/scatters per trip for that tail is the per-iteration
    floor BENCH_r08 left on the table.  The loop therefore runs in two
    phases over the SAME protocol state: a wide phase (all N lanes) only
    while more than ``window`` lanes remain unplaced, then ONE compaction
    (jnp.nonzero at a static size) gathers exactly the surviving lanes
    and a narrow phase finishes them at window-width cost.  No placed
    lane ever rejoins and all unplaced lanes still advance together, so
    the iteration-by-iteration evolution — and every claimed slot — is
    bit-identical to the single-loop protocol (the same parity tests pin
    it).
    """
    capacity = table.capacity
    n = key_lo.shape[0]
    mask = jnp.uint64(capacity - 1)
    home = (mix64(key_lo, key_hi) >> jnp.uint64(hash_shift)) & mask
    sentinel = jnp.uint64(capacity)  # out-of-range: dropped by scatters
    lane = jnp.arange(n, dtype=jnp.uint32)

    # Home-group ranks (one sort per call, outside the probe loop): masked
    # lanes key to a shared tail group and never win, so their ranks are
    # inert.  rank = position within the group in batch-lane order.
    gkey = jnp.where(insert_mask, home, sentinel)
    order = jnp.lexsort((lane, gkey))
    s_home = gkey[order]
    s_head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), s_home[1:] != s_home[:-1]]
    )
    gid_sorted = (jnp.cumsum(s_head.astype(jnp.int32)) - 1).astype(jnp.int32)
    pos = jnp.arange(n, dtype=jnp.int32)
    gstart = jax.lax.cummax(jnp.where(s_head, pos, 0))
    rank = jnp.zeros((n,), jnp.int32).at[order].set(pos - gstart)
    gid = jnp.zeros((n,), jnp.int32).at[order].set(gid_sorted)

    # Packed occupancy bitmap (1 bit/slot).  Tiny test tables may be
    # narrower than one word; pad with zero bits the probe mask never
    # addresses.
    occ_bool = (table.key_lo != 0) | (table.key_hi != 0) | table.tombstone
    pad = (-capacity) % 32
    if pad:
        occ_bool = jnp.concatenate(
            [occ_bool, jnp.zeros((pad,), jnp.bool_)]
        )
    occ0 = jnp.sum(
        occ_bool.reshape(-1, 32).astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, :],
        axis=1, dtype=jnp.uint32,
    )
    nwords = jnp.uint64(occ0.shape[0])

    # Static compaction width: small enough that the narrow phase is ~an
    # order cheaper per trip, large enough that the wide phase exits after
    # the first few probes at load <= 0.5 (the unplaced count decays
    # geometrically with probe depth).
    window = min(n, max(64, n // 8))

    def wide_cond(state):
        _, _, unplaced, _, overflow, _ = state
        return (jnp.sum(unplaced) > window) & ~overflow

    def wide_body(state):
        occ, offset, unplaced, claimed, _, next_rank = state
        cur = (home + offset) & mask
        word = cur >> jnp.uint64(5)
        bit = (cur & jnp.uint64(31)).astype(jnp.uint32)
        occupied = ((occ[word] >> bit) & jnp.uint32(1)).astype(jnp.bool_)

        # The group's next unclaimed lane in batch order is THE winner
        # (lanes sharing a slot always share a home — see docstring).
        is_winner = rank == next_rank[gid]
        win = unplaced & ~occupied & is_winner
        claimed = jnp.where(win, cur, claimed)
        # Winners' slots are unique, but two winners may share a WORD:
        # distinct bits make the add an OR with no carries.
        occ = occ.at[jnp.where(win, word, nwords)].add(
            jnp.uint32(1) << bit, mode="drop"
        )
        next_rank = next_rank.at[jnp.where(win, gid, n)].add(1, mode="drop")

        unplaced = unplaced & ~win
        offset = jnp.where(unplaced, offset + jnp.uint64(1), offset)
        overflow = jnp.any(offset >= jnp.uint64(max_probe))
        return occ, offset, unplaced, claimed, overflow, next_rank

    offset0 = jnp.zeros((n,), jnp.uint64)
    unplaced0 = insert_mask
    claimed0 = jnp.full((n,), sentinel, jnp.uint64)
    overflow0 = jnp.bool_(False)
    next_rank0 = jnp.zeros((n,), jnp.int32)

    occ, offset, unplaced, claimed, overflow, next_rank = jax.lax.while_loop(
        wide_cond, wide_body,
        (occ0, offset0, unplaced0, claimed0, overflow0, next_rank0),
    )

    # Compaction: exactly the surviving unplaced lanes (<= window unless
    # the wide phase exited on overflow, in which case the narrow cond is
    # already false and the truncation is inert).  Fill lanes carry index
    # n: inactive in the narrow body, dropped by its scatters.
    idx = jnp.nonzero(unplaced, size=window, fill_value=n)[0]
    active = idx < n
    idx_safe = jnp.where(active, idx, 0)
    home_w = home[idx_safe]
    rank_w = rank[idx_safe]
    gid_w = gid[idx_safe]

    def narrow_cond(state):
        _, _, unplaced_w, _, overflow, _ = state
        return jnp.any(unplaced_w) & ~overflow

    def narrow_body(state):
        occ, off_w, unplaced_w, claimed, _, next_rank = state
        cur = (home_w + off_w) & mask
        word = cur >> jnp.uint64(5)
        bit = (cur & jnp.uint64(31)).astype(jnp.uint32)
        occupied = ((occ[word] >> bit) & jnp.uint32(1)).astype(jnp.bool_)
        is_winner = rank_w == next_rank[gid_w]
        win = unplaced_w & ~occupied & is_winner
        claimed = claimed.at[jnp.where(win, idx, n)].set(cur, mode="drop")
        occ = occ.at[jnp.where(win, word, nwords)].add(
            jnp.uint32(1) << bit, mode="drop"
        )
        next_rank = next_rank.at[jnp.where(win, gid_w, n)].add(
            1, mode="drop"
        )
        unplaced_w = unplaced_w & ~win
        off_w = jnp.where(unplaced_w, off_w + jnp.uint64(1), off_w)
        overflow = jnp.any(off_w >= jnp.uint64(max_probe))
        return occ, off_w, unplaced_w, claimed, overflow, next_rank

    _, _, _, claimed, overflow, _ = jax.lax.while_loop(
        narrow_cond, narrow_body,
        (occ, offset[idx_safe], unplaced[idx_safe] & active,
         claimed, overflow, next_rank),
    )
    return claimed, overflow


def write_rows(
    table: Table,
    key_lo: jax.Array,
    key_hi: jax.Array,
    claimed: jax.Array,
    write_mask: jax.Array,
    rows: Dict[str, jax.Array],
) -> Table:
    """Write keys + value columns at slots from claim_slots (unique across
    the batch by construction); ``write_mask`` may be narrower than the
    claim mask (e.g. a commit flag zeroed it)."""
    sentinel = jnp.uint64(table.capacity)
    scatter_idx = jnp.where(write_mask & (claimed < sentinel), claimed, sentinel)
    key_lo_new = table.key_lo.at[scatter_idx].set(key_lo, mode="drop")
    key_hi_new = table.key_hi.at[scatter_idx].set(key_hi, mode="drop")
    tomb_new = table.tombstone.at[scatter_idx].set(False, mode="drop")
    cols_new = {
        name: table.cols[name].at[scatter_idx].set(rows[name], mode="drop")
        for name in table.cols
    }
    inserted = jnp.sum((scatter_idx < sentinel).astype(jnp.uint64))
    return table.replace(
        key_lo=key_lo_new,
        key_hi=key_hi_new,
        tombstone=tomb_new,
        cols=cols_new,
        count=table.count + inserted,
    )


@functools.partial(jax.jit, static_argnames=("max_probe", "hash_shift"))
def insert(
    table: Table,
    key_lo: jax.Array,
    key_hi: jax.Array,
    insert_mask: jax.Array,
    rows: Dict[str, jax.Array],
    max_probe: int,
    hash_shift: int = 0,
) -> Tuple[Table, jax.Array]:
    """Batched insert of *new, distinct* keys where ``insert_mask`` is set
    (claim_slots + write_rows; probe overflow is recorded on the table)."""
    claimed, overflow = claim_slots(
        table, key_lo, key_hi, insert_mask, max_probe, hash_shift
    )
    table = write_rows(table, key_lo, key_hi, claimed, insert_mask, rows)
    return table.replace(probe_overflow=table.probe_overflow | overflow), claimed


def gather_cols(table: Table, slot: jax.Array, valid: jax.Array) -> Dict[str, jax.Array]:
    """Gather value columns at ``slot``, zeroed where ``valid`` is False."""
    safe = jnp.where(valid, slot, jnp.uint64(0))
    return {
        name: jnp.where(valid, col[safe], jnp.zeros((), col.dtype))
        for name, col in table.cols.items()
    }


def scatter_cols(
    table: Table, slot: jax.Array, valid: jax.Array, updates: Dict[str, jax.Array]
) -> Table:
    """Scatter updated value columns back at ``slot`` where ``valid``.

    Slots must be unique among valid lanes (callers pre-combine per-slot
    updates — see the segment reduction in the commit kernel)."""
    sentinel = jnp.uint64(table.capacity)
    idx = jnp.where(valid, slot, sentinel)
    cols = dict(table.cols)
    for name, val in updates.items():
        cols[name] = cols[name].at[idx].set(val, mode="drop")
    return table.replace(cols=cols)


def grow(table: Table, new_capacity: int, hash_shift: int = 0) -> Table:
    """Rehash every live entry into a table of ``new_capacity`` slots.

    The reference absorbs unbounded growth in the LSM tree (lsm/tree.zig:87);
    the device-table analogue is an explicit stop-the-world rehash, run by the
    host between batches when the load factor approaches 0.5 or a probe
    overflows (VERDICT.md round-1 Weak #5).  One batched insert call with all
    old slots as lanes; tombstones are dropped in the process.
    """
    assert new_capacity & (new_capacity - 1) == 0
    assert new_capacity >= table.capacity
    live = (table.key_lo != 0) | (table.key_hi != 0)
    fresh = make_table(new_capacity, {k: v.dtype for k, v in table.cols.items()})
    grown, _ = insert(
        fresh, table.key_lo, table.key_hi, live, table.cols,
        max_probe=new_capacity, hash_shift=hash_shift,
    )
    return grown


def remove_to_tombstone(table: Table, slot: jax.Array, valid: jax.Array) -> Table:
    """Clear keys at ``slot`` (rollback of inserts), leaving tombstones."""
    sentinel = jnp.uint64(table.capacity)
    idx = jnp.where(valid, slot, sentinel)
    removed = jnp.sum(valid.astype(jnp.uint64))
    return table.replace(
        key_lo=table.key_lo.at[idx].set(jnp.uint64(0), mode="drop"),
        key_hi=table.key_hi.at[idx].set(jnp.uint64(0), mode="drop"),
        tombstone=table.tombstone.at[idx].set(True, mode="drop"),
        count=table.count - removed,
    )
