"""Scalar reference model of the accounting state machine (the parity oracle).

A deliberately straightforward, event-at-a-time Python implementation of the
reference semantics, used as the differential-testing oracle for the device
kernels (the reference's own strategy: a second state-machine implementation
exists precisely for tests, src/testing/state_machine.zig).

Semantics transcribed from (reference, src/state_machine.zig):
- ``execute``                    :1002-1088  (linked chains, scopes, rollback)
- ``create_account``             :1198-1225
- ``create_account_exists``      :1227-1237
- ``create_transfer``            :1239-1368
- ``create_transfer_exists``     :1370-1389
- ``post_or_void_pending_transfer``         :1391-1498
- ``post_or_void_pending_transfer_exists``  :1500-1561
- timestamp assignment           :1035  (timestamp - len + index + 1)
- ``prepare`` timestamp advance  :503-512
- ``sum_overflows``              :1645-1650

All integers are Python ints; u128/u64/u32 wrap/overflow behavior is made
explicit where the reference checks it.  This model is not performance-relevant
— it exists so that every device path can be checked for *byte-identical*
results and balances.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import NS_PER_S
from ..types import (
    ACCOUNT_DTYPE,
    TRANSFER_DTYPE,
    AccountFlags,
    CreateAccountResult,
    CreateTransferResult,
    TransferFlags,
    u128_join,
)

U64_MAX = (1 << 64) - 1
U128_MAX = (1 << 128) - 1


@dataclasses.dataclass
class Account:
    id: int
    debits_pending: int = 0
    debits_posted: int = 0
    credits_pending: int = 0
    credits_posted: int = 0
    user_data_128: int = 0
    user_data_64: int = 0
    user_data_32: int = 0
    reserved: int = 0
    ledger: int = 0
    code: int = 0
    flags: int = 0
    timestamp: int = 0

    def copy(self) -> "Account":
        return dataclasses.replace(self)


@dataclasses.dataclass
class Transfer:
    id: int
    debit_account_id: int = 0
    credit_account_id: int = 0
    amount: int = 0
    pending_id: int = 0
    user_data_128: int = 0
    user_data_64: int = 0
    user_data_32: int = 0
    timeout: int = 0
    ledger: int = 0
    code: int = 0
    flags: int = 0
    timestamp: int = 0

    def copy(self) -> "Transfer":
        return dataclasses.replace(self)


def account_from_row(row: np.void) -> Account:
    return Account(
        id=u128_join(row["id_lo"], row["id_hi"]),
        debits_pending=u128_join(row["debits_pending_lo"], row["debits_pending_hi"]),
        debits_posted=u128_join(row["debits_posted_lo"], row["debits_posted_hi"]),
        credits_pending=u128_join(row["credits_pending_lo"], row["credits_pending_hi"]),
        credits_posted=u128_join(row["credits_posted_lo"], row["credits_posted_hi"]),
        user_data_128=u128_join(row["user_data_128_lo"], row["user_data_128_hi"]),
        user_data_64=int(row["user_data_64"]),
        user_data_32=int(row["user_data_32"]),
        reserved=int(row["reserved"]),
        ledger=int(row["ledger"]),
        code=int(row["code"]),
        flags=int(row["flags"]),
        timestamp=int(row["timestamp"]),
    )


def transfer_from_row(row: np.void) -> Transfer:
    return Transfer(
        id=u128_join(row["id_lo"], row["id_hi"]),
        debit_account_id=u128_join(row["debit_account_id_lo"], row["debit_account_id_hi"]),
        credit_account_id=u128_join(row["credit_account_id_lo"], row["credit_account_id_hi"]),
        amount=u128_join(row["amount_lo"], row["amount_hi"]),
        pending_id=u128_join(row["pending_id_lo"], row["pending_id_hi"]),
        user_data_128=u128_join(row["user_data_128_lo"], row["user_data_128_hi"]),
        user_data_64=int(row["user_data_64"]),
        user_data_32=int(row["user_data_32"]),
        timeout=int(row["timeout"]),
        ledger=int(row["ledger"]),
        code=int(row["code"]),
        flags=int(row["flags"]),
        timestamp=int(row["timestamp"]),
    )


def _u128_lists(batch: np.ndarray, name: str) -> List[int]:
    lo = batch[name + "_lo"].astype(np.uint64).tolist()
    hi = batch[name + "_hi"].astype(np.uint64).tolist()
    return [lo_ | (hi_ << 64) for lo_, hi_ in zip(lo, hi)]


def accounts_from_batch(batch: np.ndarray) -> List[Account]:
    """Column-wise batch -> Account conversion (one C pass per column).

    Value-identical to [account_from_row(r) for r in batch]; the per-row
    form pays ~17 numpy scalar extractions per event, which made the scrub
    mirror's per-commit advance the dominant scrub tax (BENCH_r05's ~1.6x
    overhead_vs_off) — machine._mirror_apply uses this batched form."""
    return [
        Account(
            id=i, debits_pending=dp, debits_posted=dpo,
            credits_pending=cp, credits_posted=cpo,
            user_data_128=u128, user_data_64=u64, user_data_32=u32,
            reserved=res, ledger=led, code=code, flags=flags, timestamp=ts,
        )
        for i, dp, dpo, cp, cpo, u128, u64, u32, res, led, code, flags, ts
        in zip(
            _u128_lists(batch, "id"),
            _u128_lists(batch, "debits_pending"),
            _u128_lists(batch, "debits_posted"),
            _u128_lists(batch, "credits_pending"),
            _u128_lists(batch, "credits_posted"),
            _u128_lists(batch, "user_data_128"),
            batch["user_data_64"].tolist(),
            batch["user_data_32"].tolist(),
            batch["reserved"].tolist(),
            batch["ledger"].tolist(),
            batch["code"].tolist(),
            batch["flags"].tolist(),
            batch["timestamp"].tolist(),
        )
    ]


def transfers_from_batch(batch: np.ndarray) -> List[Transfer]:
    """Column-wise batch -> Transfer conversion (see accounts_from_batch)."""
    return [
        Transfer(
            id=i, debit_account_id=dr, credit_account_id=cr, amount=amt,
            pending_id=pend, user_data_128=u128, user_data_64=u64,
            user_data_32=u32, timeout=to, ledger=led, code=code,
            flags=flags, timestamp=ts,
        )
        for i, dr, cr, amt, pend, u128, u64, u32, to, led, code, flags, ts
        in zip(
            _u128_lists(batch, "id"),
            _u128_lists(batch, "debit_account_id"),
            _u128_lists(batch, "credit_account_id"),
            _u128_lists(batch, "amount"),
            _u128_lists(batch, "pending_id"),
            _u128_lists(batch, "user_data_128"),
            batch["user_data_64"].tolist(),
            batch["user_data_32"].tolist(),
            batch["timeout"].tolist(),
            batch["ledger"].tolist(),
            batch["code"].tolist(),
            batch["flags"].tolist(),
            batch["timestamp"].tolist(),
        )
    ]


def sum_overflows(a: int, b: int, bits: int) -> bool:
    return a + b > (1 << bits) - 1


_MISSING = object()


class ReferenceStateMachine:
    """Event-at-a-time oracle with undo-log scopes for linked-chain rollback."""

    def __init__(self) -> None:
        self.accounts: Dict[int, Account] = {}
        self.transfers: Dict[int, Transfer] = {}
        # pending transfer timestamp -> "posted" | "voided" (PostedGroove).
        self.posted: Dict[int, str] = {}
        # timestamp -> history groove value (dict of dr_/cr_ snapshot fields).
        self.history: Dict[int, dict] = {}
        self.prepare_timestamp = 0
        self.commit_timestamp = 0
        # Undo log for the open scope (state_machine.zig:972-1000 scope_open/close).
        self._scope: Optional[List[Tuple[dict, int, object]]] = None

    # -- scopes (groove.zig scope_open/scope_close via undo log) -----------

    def _scope_open(self) -> None:
        assert self._scope is None
        self._scope = []

    def _scope_close(self, persist: bool) -> None:
        assert self._scope is not None
        if not persist:
            for store, key, old in reversed(self._scope):
                if old is _MISSING:
                    del store[key]
                else:
                    store[key] = old
        self._scope = None

    def _record(self, store: dict, key: int) -> None:
        if self._scope is not None:
            old = store.get(key, _MISSING)
            if old is not _MISSING and not isinstance(old, str):
                old = old.copy()
            self._scope.append((store, key, old))

    def _put(self, store: dict, key: int, value) -> None:
        self._record(store, key)
        store[key] = value

    # -- prepare (state_machine.zig:503-512) --------------------------------

    def prepare(self, operation: str, count: int, wall_clock_ns: int = 0) -> int:
        """Advance prepare_timestamp by the event count and return the batch
        timestamp (the highest timestamp of the batch).  The replica bumps
        prepare_timestamp to wall clock first (replica.zig on_request path);
        callers can pass wall_clock_ns to model that."""
        if wall_clock_ns > self.prepare_timestamp:
            self.prepare_timestamp = wall_clock_ns
        if operation in ("create_accounts", "create_transfers"):
            self.prepare_timestamp += count
        return self.prepare_timestamp

    # -- execute (state_machine.zig:1002-1088) -------------------------------

    def execute(
        self, operation: str, timestamp: int, events: List
    ) -> List[Tuple[int, int]]:
        assert operation in ("create_accounts", "create_transfers")
        results: List[Tuple[int, int]] = []
        chain: Optional[int] = None
        chain_broken = False

        for index, event_ in enumerate(events):
            event = event_.copy()
            linked = bool(event.flags & 1)

            result = None
            if linked:
                if chain is None:
                    chain = index
                    assert not chain_broken
                    self._scope_open()
                if index == len(events) - 1:
                    result = 2  # linked_event_chain_open
            if result is None and chain_broken:
                result = 1  # linked_event_failed
            if result is None and event.timestamp != 0:
                result = 3  # timestamp_must_be_zero
            if result is None:
                event.timestamp = timestamp - len(events) + index + 1
                if operation == "create_accounts":
                    result = int(self.create_account(event))
                else:
                    result = int(self.create_transfer(event))

            if result != 0:
                if chain is not None:
                    if not chain_broken:
                        chain_broken = True
                        self._scope_close(persist=False)
                        for chain_index in range(chain, index):
                            results.append((chain_index, 1))
                results.append((index, result))

            if chain is not None and (not linked or result == 2):
                if not chain_broken:
                    self._scope_close(persist=True)
                chain = None
                chain_broken = False

        assert chain is None
        assert not chain_broken
        return results

    # -- create_account (state_machine.zig:1198-1225) ------------------------

    def create_account(self, a: Account) -> CreateAccountResult:
        R = CreateAccountResult
        assert a.timestamp > self.commit_timestamp

        if a.reserved != 0:
            return R.reserved_field
        if a.flags & AccountFlags.PADDING_MASK:
            return R.reserved_flag
        if a.id == 0:
            return R.id_must_not_be_zero
        if a.id == U128_MAX:
            return R.id_must_not_be_int_max
        if (a.flags & AccountFlags.DEBITS_MUST_NOT_EXCEED_CREDITS) and (
            a.flags & AccountFlags.CREDITS_MUST_NOT_EXCEED_DEBITS
        ):
            return R.flags_are_mutually_exclusive
        if a.debits_pending != 0:
            return R.debits_pending_must_be_zero
        if a.debits_posted != 0:
            return R.debits_posted_must_be_zero
        if a.credits_pending != 0:
            return R.credits_pending_must_be_zero
        if a.credits_posted != 0:
            return R.credits_posted_must_be_zero
        if a.ledger == 0:
            return R.ledger_must_not_be_zero
        if a.code == 0:
            return R.code_must_not_be_zero

        e = self.accounts.get(a.id)
        if e is not None:
            return self._create_account_exists(a, e)

        self._put(self.accounts, a.id, a.copy())
        self.commit_timestamp = a.timestamp
        return R.ok

    @staticmethod
    def _create_account_exists(a: Account, e: Account) -> CreateAccountResult:
        # state_machine.zig:1227-1237
        R = CreateAccountResult
        assert a.id == e.id
        if a.flags != e.flags:
            return R.exists_with_different_flags
        if a.user_data_128 != e.user_data_128:
            return R.exists_with_different_user_data_128
        if a.user_data_64 != e.user_data_64:
            return R.exists_with_different_user_data_64
        if a.user_data_32 != e.user_data_32:
            return R.exists_with_different_user_data_32
        if a.ledger != e.ledger:
            return R.exists_with_different_ledger
        if a.code != e.code:
            return R.exists_with_different_code
        return R.exists

    # -- create_transfer (state_machine.zig:1239-1368) -----------------------

    def create_transfer(self, t: Transfer) -> CreateTransferResult:
        R = CreateTransferResult
        F = TransferFlags
        assert t.timestamp > self.commit_timestamp

        if t.flags & F.PADDING_MASK:
            return R.reserved_flag
        if t.id == 0:
            return R.id_must_not_be_zero
        if t.id == U128_MAX:
            return R.id_must_not_be_int_max

        if t.flags & (F.POST_PENDING_TRANSFER | F.VOID_PENDING_TRANSFER):
            return self._post_or_void_pending_transfer(t)

        if t.debit_account_id == 0:
            return R.debit_account_id_must_not_be_zero
        if t.debit_account_id == U128_MAX:
            return R.debit_account_id_must_not_be_int_max
        if t.credit_account_id == 0:
            return R.credit_account_id_must_not_be_zero
        if t.credit_account_id == U128_MAX:
            return R.credit_account_id_must_not_be_int_max
        if t.credit_account_id == t.debit_account_id:
            return R.accounts_must_be_different
        if t.pending_id != 0:
            return R.pending_id_must_be_zero
        if not (t.flags & F.PENDING):
            if t.timeout != 0:
                return R.timeout_reserved_for_pending_transfer
        if not (t.flags & (F.BALANCING_DEBIT | F.BALANCING_CREDIT)):
            if t.amount == 0:
                return R.amount_must_not_be_zero
        if t.ledger == 0:
            return R.ledger_must_not_be_zero
        if t.code == 0:
            return R.code_must_not_be_zero

        dr = self.accounts.get(t.debit_account_id)
        if dr is None:
            return R.debit_account_not_found
        cr = self.accounts.get(t.credit_account_id)
        if cr is None:
            return R.credit_account_not_found

        if dr.ledger != cr.ledger:
            return R.accounts_must_have_the_same_ledger
        if t.ledger != dr.ledger:
            return R.transfer_must_have_the_same_ledger_as_accounts

        e = self.transfers.get(t.id)
        if e is not None:
            return self._create_transfer_exists(t, e)

        # Balancing amount clamp (state_machine.zig:1286-1306).
        amount = t.amount
        if t.flags & (F.BALANCING_DEBIT | F.BALANCING_CREDIT):
            if amount == 0:
                amount = U64_MAX
        if t.flags & F.BALANCING_DEBIT:
            dr_balance = dr.debits_posted + dr.debits_pending
            amount = min(amount, max(0, dr.credits_posted - dr_balance))
            if amount == 0:
                return R.exceeds_credits
        if t.flags & F.BALANCING_CREDIT:
            cr_balance = cr.credits_posted + cr.credits_pending
            amount = min(amount, max(0, cr.debits_posted - cr_balance))
            if amount == 0:
                return R.exceeds_debits

        # Overflow checks (state_machine.zig:1308-1322).
        if t.flags & F.PENDING:
            if sum_overflows(amount, dr.debits_pending, 128):
                return R.overflows_debits_pending
            if sum_overflows(amount, cr.credits_pending, 128):
                return R.overflows_credits_pending
        if sum_overflows(amount, dr.debits_posted, 128):
            return R.overflows_debits_posted
        if sum_overflows(amount, cr.credits_posted, 128):
            return R.overflows_credits_posted
        if sum_overflows(amount, dr.debits_pending + dr.debits_posted, 128):
            return R.overflows_debits
        if sum_overflows(amount, cr.credits_pending + cr.credits_posted, 128):
            return R.overflows_credits
        if sum_overflows(t.timestamp, t.timeout * NS_PER_S, 64):
            return R.overflows_timeout

        # Balance limits (tigerbeetle.zig:31-39, state_machine.zig:1323-1324).
        if (dr.flags & AccountFlags.DEBITS_MUST_NOT_EXCEED_CREDITS) and (
            dr.debits_pending + dr.debits_posted + amount > dr.credits_posted
        ):
            return R.exceeds_credits
        if (cr.flags & AccountFlags.CREDITS_MUST_NOT_EXCEED_DEBITS) and (
            cr.credits_pending + cr.credits_posted + amount > cr.debits_posted
        ):
            return R.exceeds_debits

        # Insert + balance updates (state_machine.zig:1326-1367).
        t2 = t.copy()
        t2.amount = amount
        self._put(self.transfers, t2.id, t2)

        self._record(self.accounts, dr.id)
        self._record(self.accounts, cr.id)
        dr = self.accounts[dr.id]
        cr = self.accounts[cr.id]
        if t.flags & F.PENDING:
            dr.debits_pending += amount
            cr.credits_pending += amount
        else:
            dr.debits_posted += amount
            cr.credits_posted += amount

        if (dr.flags & AccountFlags.HISTORY) or (cr.flags & AccountFlags.HISTORY):
            self._insert_history(t2.timestamp, dr, cr)

        self.commit_timestamp = t.timestamp
        return R.ok

    def _insert_history(self, timestamp: int, dr: Account, cr: Account) -> None:
        # state_machine.zig:1342-1364
        h = dict(
            timestamp=timestamp,
            dr_account_id=0, dr_debits_pending=0, dr_debits_posted=0,
            dr_credits_pending=0, dr_credits_posted=0,
            cr_account_id=0, cr_debits_pending=0, cr_debits_posted=0,
            cr_credits_pending=0, cr_credits_posted=0,
        )
        if dr.flags & AccountFlags.HISTORY:
            h.update(
                dr_account_id=dr.id,
                dr_debits_pending=dr.debits_pending,
                dr_debits_posted=dr.debits_posted,
                dr_credits_pending=dr.credits_pending,
                dr_credits_posted=dr.credits_posted,
            )
        if cr.flags & AccountFlags.HISTORY:
            h.update(
                cr_account_id=cr.id,
                cr_debits_pending=cr.debits_pending,
                cr_debits_posted=cr.debits_posted,
                cr_credits_pending=cr.credits_pending,
                cr_credits_posted=cr.credits_posted,
            )
        self._put(self.history, timestamp, h)

    @staticmethod
    def _create_transfer_exists(t: Transfer, e: Transfer) -> CreateTransferResult:
        # state_machine.zig:1370-1389
        R = CreateTransferResult
        assert t.id == e.id
        if t.flags != e.flags:
            return R.exists_with_different_flags
        if t.debit_account_id != e.debit_account_id:
            return R.exists_with_different_debit_account_id
        if t.credit_account_id != e.credit_account_id:
            return R.exists_with_different_credit_account_id
        if t.amount != e.amount:
            return R.exists_with_different_amount
        if t.user_data_128 != e.user_data_128:
            return R.exists_with_different_user_data_128
        if t.user_data_64 != e.user_data_64:
            return R.exists_with_different_user_data_64
        if t.user_data_32 != e.user_data_32:
            return R.exists_with_different_user_data_32
        if t.timeout != e.timeout:
            return R.exists_with_different_timeout
        if t.code != e.code:
            return R.exists_with_different_code
        return R.exists

    # -- post/void (state_machine.zig:1391-1498) -----------------------------

    def _post_or_void_pending_transfer(self, t: Transfer) -> CreateTransferResult:
        R = CreateTransferResult
        F = TransferFlags
        post = bool(t.flags & F.POST_PENDING_TRANSFER)
        void = bool(t.flags & F.VOID_PENDING_TRANSFER)
        assert post or void

        if post and void:
            return R.flags_are_mutually_exclusive
        if t.flags & F.PENDING:
            return R.flags_are_mutually_exclusive
        if t.flags & F.BALANCING_DEBIT:
            return R.flags_are_mutually_exclusive
        if t.flags & F.BALANCING_CREDIT:
            return R.flags_are_mutually_exclusive

        if t.pending_id == 0:
            return R.pending_id_must_not_be_zero
        if t.pending_id == U128_MAX:
            return R.pending_id_must_not_be_int_max
        if t.pending_id == t.id:
            return R.pending_id_must_be_different
        if t.timeout != 0:
            return R.timeout_reserved_for_pending_transfer

        p = self.transfers.get(t.pending_id)
        if p is None:
            return R.pending_transfer_not_found
        if not (p.flags & F.PENDING):
            return R.pending_transfer_not_pending

        dr = self.accounts[p.debit_account_id]
        cr = self.accounts[p.credit_account_id]

        if t.debit_account_id > 0 and t.debit_account_id != p.debit_account_id:
            return R.pending_transfer_has_different_debit_account_id
        if t.credit_account_id > 0 and t.credit_account_id != p.credit_account_id:
            return R.pending_transfer_has_different_credit_account_id
        if t.ledger > 0 and t.ledger != p.ledger:
            return R.pending_transfer_has_different_ledger
        if t.code > 0 and t.code != p.code:
            return R.pending_transfer_has_different_code

        amount = t.amount if t.amount > 0 else p.amount
        if amount > p.amount:
            return R.exceeds_pending_transfer_amount
        if void and amount < p.amount:
            return R.pending_transfer_has_different_amount

        e = self.transfers.get(t.id)
        if e is not None:
            return self._post_or_void_pending_transfer_exists(t, e, p)

        fulfillment = self.posted.get(p.timestamp)
        if fulfillment == "posted":
            return R.pending_transfer_already_posted
        if fulfillment == "voided":
            return R.pending_transfer_already_voided

        assert p.timestamp < t.timestamp
        if p.timeout > 0:
            if t.timestamp >= p.timestamp + p.timeout * NS_PER_S:
                return R.pending_transfer_expired

        # Insert the posting/voiding transfer (state_machine.zig:1455-1469).
        t2 = Transfer(
            id=t.id,
            debit_account_id=p.debit_account_id,
            credit_account_id=p.credit_account_id,
            amount=amount,
            pending_id=t.pending_id,
            user_data_128=t.user_data_128 if t.user_data_128 > 0 else p.user_data_128,
            user_data_64=t.user_data_64 if t.user_data_64 > 0 else p.user_data_64,
            user_data_32=t.user_data_32 if t.user_data_32 > 0 else p.user_data_32,
            timeout=0,
            ledger=p.ledger,
            code=p.code,
            flags=t.flags,
            timestamp=t.timestamp,
        )
        self._put(self.transfers, t2.id, t2)
        self._put(self.posted, p.timestamp, "posted" if post else "voided")

        self._record(self.accounts, dr.id)
        self._record(self.accounts, cr.id)
        dr = self.accounts[dr.id]
        cr = self.accounts[cr.id]
        dr.debits_pending -= p.amount
        cr.credits_pending -= p.amount
        if post:
            dr.debits_posted += amount
            cr.credits_posted += amount

        self.commit_timestamp = t.timestamp
        return R.ok

    @staticmethod
    def _post_or_void_pending_transfer_exists(
        t: Transfer, e: Transfer, p: Transfer
    ) -> CreateTransferResult:
        # state_machine.zig:1500-1561
        R = CreateTransferResult
        if t.flags != e.flags:
            return R.exists_with_different_flags
        if t.amount == 0:
            if e.amount != p.amount:
                return R.exists_with_different_amount
        else:
            if t.amount != e.amount:
                return R.exists_with_different_amount
        if t.pending_id != e.pending_id:
            return R.exists_with_different_pending_id
        if t.user_data_128 == 0:
            if e.user_data_128 != p.user_data_128:
                return R.exists_with_different_user_data_128
        else:
            if t.user_data_128 != e.user_data_128:
                return R.exists_with_different_user_data_128
        if t.user_data_64 == 0:
            if e.user_data_64 != p.user_data_64:
                return R.exists_with_different_user_data_64
        else:
            if t.user_data_64 != e.user_data_64:
                return R.exists_with_different_user_data_64
        if t.user_data_32 == 0:
            if e.user_data_32 != p.user_data_32:
                return R.exists_with_different_user_data_32
        else:
            if t.user_data_32 != e.user_data_32:
                return R.exists_with_different_user_data_32
        return R.exists

    # -- lookups (state_machine.zig:1091-1126) -------------------------------

    def lookup_accounts(self, ids: List[int]) -> List[Account]:
        return [self.accounts[i].copy() for i in ids if i in self.accounts]

    def lookup_transfers(self, ids: List[int]) -> List[Transfer]:
        return [self.transfers[i].copy() for i in ids if i in self.transfers]

    # -- queries (state_machine.zig:693-892, 1128-1195) ----------------------

    @staticmethod
    def _filter_window(
        account_id: int, ts_min: int, ts_max: int, limit: int, flags: int
    ) -> Optional[Tuple[int, int, bool]]:
        """get_scan_from_filter validity + effective window
        (state_machine.zig:823-837)."""
        valid = (
            account_id not in (0, U128_MAX)
            and ts_min != U64_MAX
            and ts_max != U64_MAX
            and (ts_max == 0 or ts_min <= ts_max)
            and limit != 0
            and flags & 0x3
            and flags & ~0x7 == 0
        )
        if not valid:
            return None
        return (ts_min or 1, ts_max or U64_MAX - 1, bool(flags & 0x4))

    def get_account_transfers(
        self, account_id: int, ts_min: int, ts_max: int, limit: int, flags: int
    ) -> List[Transfer]:
        window = self._filter_window(account_id, ts_min, ts_max, limit, flags)
        if window is None:
            return []
        lo, hi, descending = window
        matches = [
            t.copy()
            for t in self.transfers.values()
            if lo <= t.timestamp <= hi
            and (
                (flags & 0x1 and t.debit_account_id == account_id)
                or (flags & 0x2 and t.credit_account_id == account_id)
            )
        ]
        matches.sort(key=lambda t: t.timestamp, reverse=descending)
        return matches[:limit]

    def get_account_history(
        self, account_id: int, ts_min: int, ts_max: int, limit: int, flags: int
    ) -> List[Tuple[int, int, int, int, int]]:
        """(timestamp, dp, dpo, cp, cpo) rows, side-selected
        (execute_get_account_history, state_machine.zig:1149-1195)."""
        window = self._filter_window(account_id, ts_min, ts_max, limit, flags)
        if window is None:
            return []
        acct = self.accounts.get(account_id)
        if acct is None or not (acct.flags & AccountFlags.HISTORY):
            return []
        lo, hi, descending = window
        rows = []
        for ts in sorted(self.history, reverse=descending):
            if not lo <= ts <= hi:
                continue
            h = self.history[ts]
            # Side selection honors the DEBITS/CREDITS flags: the reference
            # resolves history rows through the transfers debit/credit index
            # scans (get_scan_from_filter, state_machine.zig:823-892).
            if flags & 0x1 and h["dr_account_id"] == account_id:
                rows.append((
                    ts, h["dr_debits_pending"], h["dr_debits_posted"],
                    h["dr_credits_pending"], h["dr_credits_posted"],
                ))
            elif flags & 0x2 and h["cr_account_id"] == account_id:
                rows.append((
                    ts, h["cr_debits_pending"], h["cr_debits_posted"],
                    h["cr_credits_pending"], h["cr_credits_posted"],
                ))
        return rows[:limit]

    # -- convenience entry points -------------------------------------------

    def create_accounts(self, events: List[Account], wall_clock_ns: int = 0):
        ts = self.prepare("create_accounts", len(events), wall_clock_ns)
        return self.execute("create_accounts", ts, events)

    def create_transfers(self, events: List[Transfer], wall_clock_ns: int = 0):
        ts = self.prepare("create_transfers", len(events), wall_clock_ns)
        return self.execute("create_transfers", ts, events)

    # -- parity digest -------------------------------------------------------

    def balances_snapshot(self) -> List[Tuple[int, int, int, int, int, int]]:
        """(id, dp, dposted, cp, cposted, ts) sorted by id — the parity check
        surface (the north star's 'byte-identical balances')."""
        return sorted(
            (
                a.id,
                a.debits_pending,
                a.debits_posted,
                a.credits_pending,
                a.credits_posted,
                a.timestamp,
            )
            for a in self.accounts.values()
        )
