"""Op-ordered write-path auditor for the cluster simulator.

The role of the reference's Workload/Auditor pair
(src/testing/state_machine/auditor.zig:1-4, workload.zig:1-19): the
reference auditor tracks in-flight requests, a pending-expiry mirror, and
per-event ALLOWED-result sets, because its clients observe replies with no
global order and must tolerate every legal interleaving.

This auditor is stricter, because it can be: the VSR reply/prepare headers
carry the assigned op and batch timestamp, so total commit order is
observable.  Hooked into every replica's commit path (production code —
``Replica._commit_prepare``), it:

- stages each committed ``(op, operation, timestamp, body, results)``;
- asserts every replica (and every crash-replay of the same replica)
  commits byte-identical results for the same op — a content-level
  divergence oracle that pinpoints the op (hash_log pinpoints only the
  ledger digest);
- replays the ops in contiguous commit order through the scalar oracle
  model (testing/model.py) and asserts the produced result codes match
  EXACTLY — wrong-but-conserving results that digest checks cannot see
  (e.g. a transfer applied with a wrong result code, an expiry missed)
  fail here.  The pending-expiry mirror is the model itself: it applies
  pending timeouts from the committed batch timestamps.

Read-only operations (lookups/queries) occupy ops in the total order but
do not advance the model; their correctness is covered by the differential
query tests.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from .. import types
from . import model as M

WRITE_OPS = ("create_accounts", "create_transfers")


class AuditError(AssertionError):
    """A committed result diverged — across replicas, across a replay, or
    from the oracle model."""


def _encode_results(results) -> bytes:
    """Mirror of vsr.replica._encode_results (kept independent so a bug
    there cannot hide itself from the audit)."""
    arr = np.zeros(len(results), dtype=types.EVENT_RESULT_DTYPE)
    for i, (index, code) in enumerate(results):
        arr[i]["index"] = index
        arr[i]["result"] = code
    return arr.tobytes()


class Auditor:
    def __init__(self) -> None:
        self.model = M.ReferenceStateMachine()
        # op -> (operation, timestamp, body, result_body): every commit of
        # an op must match the first observation bit-for-bit.
        self.records: Dict[int, Tuple[str, int, bytes, bytes]] = {}
        self.next_op = 1      # lowest op not yet replayed through the model
        self.audited = 0      # write ops validated against the model

    def observe_commit(
        self,
        op: int,
        operation: str,
        timestamp: int,
        body: bytes,
        result_body: bytes,
        replica: int,
        replay: bool,
    ) -> None:
        rec = (operation, timestamp, bytes(body), bytes(result_body))
        prev = self.records.get(op)
        if prev is not None:
            if prev != rec:
                diffs = [
                    name
                    for name, a, b in zip(
                        ("operation", "timestamp", "body", "results"),
                        prev, rec,
                    )
                    if a != b
                ]
                raise AuditError(
                    f"op {op}: replica {replica} (replay={replay}) committed "
                    f"{operation} with diverging body/results vs the first "
                    f"commit of this op (diverging: {', '.join(diffs)}; "
                    f"first ts={prev[1]} vs ts={rec[1]}, "
                    f"first results={prev[3][:64]!r} vs {rec[3][:64]!r})"
                )
            return
        self.records[op] = rec
        self._drain()

    def observe_reply(
        self,
        op: int,
        operation: str,
        result_body: bytes,
        client: int = 0,
        request: int = 0,
    ) -> None:
        """Cross-check a client-ACCEPTED reply against committed state — the
        byzantine fault domain's lying-reply oracle (docs/fault_domains.md).

        A reply exists only because some replica committed the op and
        answered, and every replica's commit of that op was already staged
        through ``observe_commit`` (the primary commits before it replies,
        and network delivery happens strictly later on the sim's virtual
        time).  So a reply naming an op with NO record is fabricated, and a
        reply whose result bytes differ from the committed record is a lie
        about state the honest quorum agreed on — both are safety
        violations regardless of which replica sent the frame."""
        rec = self.records.get(op)
        if rec is None:
            raise AuditError(
                f"client {client:#x} accepted a reply claiming op {op} "
                f"({operation}, request {request}) but no replica ever "
                f"committed that op — fabricated reply"
            )
        rec_operation, _ts, _body, rec_results = rec
        if rec_operation != operation:
            raise AuditError(
                f"client {client:#x} accepted a reply for op {op} claiming "
                f"{operation}, but the committed op is {rec_operation}"
            )
        if bytes(result_body) != rec_results:
            raise AuditError(
                f"client {client:#x} accepted a lying reply for op {op} "
                f"({operation}, request {request}): result bytes diverge "
                f"from the committed record "
                f"(got {bytes(result_body)[:48]!r} "
                f"want {rec_results[:48]!r})"
            )

    def _drain(self) -> None:
        while self.next_op in self.records:
            operation, timestamp, body, result_body = self.records[self.next_op]
            if operation == "create_accounts":
                events = [
                    M.account_from_row(r)
                    for r in np.frombuffer(body, dtype=types.ACCOUNT_DTYPE)
                ]
                expected = _encode_results(
                    self.model.execute(operation, timestamp, events)
                )
            elif operation == "create_transfers":
                events = [
                    M.transfer_from_row(r)
                    for r in np.frombuffer(body, dtype=types.TRANSFER_DTYPE)
                ]
                expected = _encode_results(
                    self.model.execute(operation, timestamp, events)
                )
            elif operation in ("lookup_accounts", "lookup_transfers"):
                self._audit_lookup(operation, body, result_body)
                expected = None
            else:
                expected = None  # register / query ops: order-occupying
            if expected is not None:
                if expected != result_body:
                    got = np.frombuffer(
                        result_body, dtype=types.EVENT_RESULT_DTYPE
                    )
                    want = np.frombuffer(
                        expected, dtype=types.EVENT_RESULT_DTYPE
                    )
                    raise AuditError(
                        f"op {self.next_op} ({operation}, ts={timestamp}): "
                        f"cluster results diverge from the oracle model: "
                        f"got {got.tolist()[:8]} want {want.tolist()[:8]}"
                    )
                self.audited += 1
            self.next_op += 1

    def _audit_lookup(self, operation, body, result_body) -> None:
        """Reads occupy the commit order too: the committed reply rows must
        match the model EXACTLY — the model's rows are re-encoded to the
        wire dtypes and compared byte-for-byte, covering every field
        (digests can't see a wrong lookup reply)."""
        import dataclasses as _dc

        ids_arr = np.frombuffer(body, dtype="<u8").reshape(-1, 2)
        ids = [int(lo) | (int(hi) << 64) for lo, hi in ids_arr]
        if operation == "lookup_accounts":
            objs = self.model.lookup_accounts(ids)
            want = types.accounts_array(
                [types.account(**_dc.asdict(o)) for o in objs]
            ).tobytes() if objs else b""
        else:
            objs = self.model.lookup_transfers(ids)
            want = types.transfers_array(
                [types.transfer(**_dc.asdict(o)) for o in objs]
            ).tobytes() if objs else b""
        if want != result_body:
            raise AuditError(
                f"op {self.next_op} ({operation}): committed reply "
                f"({len(result_body) // 128} rows) diverges byte-wise from "
                f"the model ({len(objs)} rows)"
            )
