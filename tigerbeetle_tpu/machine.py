"""TpuStateMachine: the host-side seam mirroring the reference's StateMachine.

The reference makes the application state machine pluggable behind
``StateMachineType(comptime Storage, comptime config)`` (state_machine.zig:34),
with the contract prepare()/prefetch()/commit() driven by the replica
(replica.zig:3102-3173 commit dispatch).  This class is the TPU-native
implementation of that seam: it owns the device-resident ledger, assigns batch
timestamps like prepare() does (state_machine.zig:503-512), dispatches each
batch to the widest safe device kernel, and compresses dense device result
codes into the wire's (index, result) pairs (only failures are emitted —
state_machine.zig:1051-1073).

Dispatch policy (round 2):
- create_accounts: vectorized kernel, unless the batch combines linked chains
  with intra-batch duplicate ids -> sequential path.
- create_transfers: ALWAYS dispatched to the full vectorized kernel
  (ops/transfer_full.py), which covers pending/post/void two-phase flows,
  intra-batch references, history, and exact overflow checks.  The kernel
  itself decides routing: it returns a flags word, nonzero meaning "nothing
  applied" — either a table must grow (host grows + retries) or the batch is
  genuinely order-dependent (balancing flags, balance-limit accounts, u128
  amounts, deep intra-batch chains) and re-routes to the sequential path.
  There is NO host-side global precondition state: one history/limit account
  in the ledger no longer affects batches that do not reference it
  (VERDICT.md round-1 Weak #3).

The sequential path (ops/scan_path.py) runs the full semantics on device as a
lax.scan and is bit-identical but latency-bound.
"""

from __future__ import annotations

import random as _random
import time as _time
import warnings

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import sanitize as _san
from . import types
from .config import LedgerConfig
from .obs.metrics import registry as _obs
from .obs.txtrace import txtrace
from .ops import merkle as merkle_ops
from .ops import scrub as scrub_ops
from .ops import state_machine as sm
from .ops.scrub import (  # re-exported: the replica's fault-domain surface
    DEVICE_FAULT_TYPES, DeviceStateUnrecoverable, SimulatedDeviceFault,
)

_LIMIT_FLAGS = (
    types.AccountFlags.DEBITS_MUST_NOT_EXCEED_CREDITS
    | types.AccountFlags.CREDITS_MUST_NOT_EXCEED_DEBITS
)
# Balance-bound saturation point: past this the fast path stays off and
# further tracking is pointless (and giant Python ints are avoided).
_BOUND_CLAMP = 1 << 127
# Transfer flags that exclude the plain fast-path kernel (P2/P4: two-phase,
# balancing, and linked chains run the fully-general kernel).
_SLOW_TRANSFER_FLAGS = (
    types.TransferFlags.POST_PENDING_TRANSFER
    | types.TransferFlags.VOID_PENDING_TRANSFER
    | types.TransferFlags.BALANCING_DEBIT
    | types.TransferFlags.BALANCING_CREDIT
    | types.TransferFlags.LINKED
)

U64_MAX = (1 << 64) - 1
# Reply rows are 128 B; one 1 MiB message body holds at most this many
# (constants.zig:203-204, state_machine.zig:70-75).
QUERY_ROWS_MAX = ((1 << 20) - 256) // 128


def _group_fast_dispatch_impl(ledger, stacked, counts, timestamps):
    """Scan the fast commit kernel over GROUP_K stacked batches: one device
    dispatch, batch order preserved, ledger threaded through the carry
    (see TpuStateMachine.commit_group_fast).

    Besides (ledger, codes) it returns the transfers probe_overflow flag
    widened into a FRESH uint32 buffer (the deferred readback handle must
    be able to fetch it after a later dispatch donates the ledger; riding
    the commit dispatch it costs zero extra syncs) and the stacked id
    columns, so the dispatch closure's index maintenance never holds the
    whole 17-column stacked SoA alive past the kernel call.  ``stacked``
    itself is deliberately NOT donated: on XLA-CPU jax.device_put may
    alias the numpy staging buffers straight into these device arrays
    (the _stage_group zero-copy note), and a donated alias would let XLA
    scribble scratch into the pooled staging set behind the dirty-row
    tracking's back."""

    def step(led, xs):
        soa, cnt, ts = xs
        led, codes = sm.create_transfers_impl(led, soa, cnt, ts)
        return led, codes

    ledger, codes = jax.lax.scan(step, ledger, (stacked, counts, timestamps))
    return (
        ledger, codes, ledger.transfers.probe_overflow.astype(jnp.uint32),
        stacked["id_lo"], stacked["id_hi"],
    )


_group_fast_dispatch = jax.jit(
    _group_fast_dispatch_impl, donate_argnames=("ledger",)
)


def _overflow_any(overflow) -> bool:
    """True if any probe-overflow flag fired.  Accepts the scalar the
    single-device kernels return, the per-shard uint32 lane vector the
    sharded probed step returns, or a tuple of either (one per batch of a
    sharded grouped run)."""
    if isinstance(overflow, (list, tuple)):
        return any(_overflow_any(o) for o in overflow)
    return bool(np.any(np.asarray(overflow)))


def pipeline_depth_default() -> int:
    """Commit-pipeline depth (TB_PIPELINE env; default 2).  Depth 1 (and
    TB_PIPELINE=0, "off") disables deferral entirely — the serving path is
    then bit-for-bit the pre-pipeline blocking path.  Depth >= 2 runs the
    pipelined engine with ONE commit group in flight; deeper values are
    reserved (currently equivalent to 2)."""
    import os

    env = os.environ.get("TB_PIPELINE", "")
    if env.isdigit():
        return max(1, int(env))  # 0 == off == depth 1
    return 2


class DeviceCommitHandle:
    """An in-flight fast-path device commit (one batch or a grouped run).

    ``result`` is either the dispatch's (codes, overflow, id_lo, id_hi)
    device tuple (the dispatch already executed on the calling thread) or
    a Future of one — deferred dispatches run on the machine's single
    dispatch-lane thread, which restores the async-dispatch property on
    backends whose execute blocks the calling thread (XLA-CPU): the
    serving thread stages uploads, journals, and builds replies while the
    lane thread sits in the (GIL-free) device execute.

    ``resolve()`` joins the dispatch, performs the ONE deferred
    device->host readback (result codes + the probe-overflow flag ride
    together), and runs the host bookkeeping that needs the codes —
    result compression and commit-timestamp advance — returning per-batch
    (index, result) lists.  ``join_wait_s`` records how long the join
    blocked (queue wait, not commit work — callers keep it out of the
    commit-stage latency series).

    Handles must be resolved in dispatch order (the commit timestamp and
    index appends are op-ordered); the replica's pipelined commit engine
    enforces that with a FIFO in-flight queue (at most one commit group's
    runs deep).
    """

    __slots__ = ("_machine", "_result", "_stacked", "_counts",
                 "_timestamps", "_stage", "_resolved", "join_wait_s",
                 "_batches", "_recovered", "_deferred")

    def __init__(self, machine, result, counts, timestamps,
                 stacked: bool, stage=None, batches=None,
                 deferred: bool = False) -> None:
        self._machine = machine
        self._result = result        # (codes, overflow) | Future of one
        self._stacked = stacked      # True: leading per-batch dim
        self._counts = counts
        self._timestamps = timestamps
        self._stage = stage          # staging buffer set to release on resolve
        self._resolved = False
        self._deferred = deferred    # counted in the machine's in-flight depth
        self.join_wait_s = 0.0
        # Host-side copies of the dispatched batches: the device fault
        # domain re-dispatches a quarantined run from these after a failed
        # dispatch (machine._recover_inflight); None when the fault domain
        # is off (no retention cost).
        self._batches = batches
        # Per-batch results computed by a recovery re-dispatch; resolve()
        # returns them instead of touching the dead device future.
        self._recovered = None

    def __len__(self) -> int:
        return len(self._counts)

    def discard(self) -> None:
        """Abort path: QUIESCE the dispatch (join it, swallow its error)
        and release the staging set — an orphaned closure left running on
        the lane would keep mutating machine.ledger concurrently with the
        serving thread after the caller dropped this handle."""
        if self._resolved:
            return
        self._resolved = True
        self._machine._deferred_done(self)
        self._machine._inflight_untrack(self)
        if hasattr(self._result, "result"):
            try:
                # The group's failure already propagated via the engine;
                # this join only quiesces the lane.
                self._result.result()
            except BaseException:  # tblint: ignore[swallow] abort quiesce
                pass
        if self._stage is not None:
            self._machine._stage_release(self._stage)
            self._stage = None

    def resolve(self) -> List[List[Tuple[int, int]]]:
        assert not self._resolved, "commit handle resolved twice"
        self._resolved = True
        m = self._machine
        m._deferred_done(self)
        if self._recovered is not None:
            # A device-fault recovery already re-committed this run through
            # the blocking path (machine._recover_inflight): bookkeeping,
            # index appends and mirror application all happened there.
            return self._recovered
        try:
            if hasattr(self._result, "result"):
                t0 = _time.perf_counter()
                self._result = self._result.result()
                self.join_wait_s = _time.perf_counter() - t0
                if txtrace.active:
                    # FIFO lane queue time — pipeline idle, not commit work.
                    txtrace.stage_observe(
                        "dispatch_wait", self.join_wait_s * 1e6
                    )
                if _obs.enabled:
                    _obs.histogram(
                        "pipeline.resolve_wait_us", "us"
                    ).observe(self.join_wait_s * 1e6)
                    if m.shards:
                        _obs.histogram(
                            "pipeline.shard.resolve_wait_us", "us"
                        ).observe(self.join_wait_s * 1e6)
            codes_dev, overflow_dev = self._result
            codes, overflow = m._d2h_codes(codes_dev, overflow_dev,
                                           stage="readback")
        except DEVICE_FAULT_TYPES as err:
            # Dispatch-lane funnel: the dispatch (or its readback) failed —
            # quarantine the in-flight pipeline and re-dispatch every
            # pending run from the authoritative mirror (docs/
            # fault_domains.md).  Raises the original error when the fault
            # domain is disarmed (pre-fault-domain behavior).
            m._device_fault_at_resolve(err)
            assert self._recovered is not None
            return self._recovered
        finally:
            m._inflight_untrack(self)
            if self._stage is not None:
                # The dispatch completed (or failed terminally): either
                # way its H2D reads are over — the staging set must go
                # back on the free-list, not leak with the handle.
                m._stage_release(self._stage)
                self._stage = None
        if _overflow_any(overflow):
            # Load-factor management keeps this unreachable; losing inserts
            # silently is the one unacceptable outcome, so fail loud (the
            # deferred check fires one resolve later than the blocking
            # path's, but always before any reply is released).
            raise RuntimeError("transfers probe overflow during fast insert")
        if _obs.enabled:
            _obs.counter("pipeline.resolves").inc()
            if m.shards:
                _obs.counter("pipeline.shard.resolves").inc()
        # NOTE: index maintenance already happened inside the dispatch
        # closure (machine._index_append_device) — it is device work that
        # must ride the ledger chain; reading self.ledger HERE could see
        # buffers a later in-flight dispatch already donated.
        out = []
        for j, (count, ts) in enumerate(zip(self._counts, self._timestamps)):
            row = codes[j] if self._stacked else codes
            out.append(m._compress(row, count))
            m._update_commit_timestamp(row, count, ts)
        m._device_fault_streak = 0
        if m.scrub_armed:
            # Advance the scrub cadence in resolve (== op) order.  The
            # merkle forest already advanced INSIDE the dispatch closure
            # (device work must ride the ledger chain); only the mirror
            # replay belongs here.
            m._scrub_commits += len(self._counts)
        if m._scrub_mirror is not None and self._batches is not None:
            # Advance the authoritative mirror in resolve (== op) order;
            # the digest folds at the next scrub point compare against it.
            for b, ts in zip(self._batches, self._timestamps):
                m._mirror_apply("create_transfers", b, ts)
        return out


class TpuStateMachine:
    def __init__(
        self,
        ledger_config: Optional[LedgerConfig] = None,
        batch_lanes: int = 8192,
        force_sequential: bool = False,
        spill_dir: Optional[str] = None,
        hot_transfers_capacity_max: Optional[int] = None,
        host_engine: bool = False,
        shards: Optional[int] = None,
    ) -> None:
        cfg = ledger_config or LedgerConfig()
        self.config = cfg
        self.batch_lanes = batch_lanes
        self.force_sequential = force_sequential
        # Sharded execution mode (docs/sharding.md): the pad SoA lives
        # under a Mesh + NamedSharding(PartitionSpec('shard')) over the
        # account axis and commits dispatch through shard_map
        # (parallel/sharded.py).  ``shards`` None defers to TB_SHARDS; 0 is
        # today's single-device path, bit-identical by construction (not
        # one sharded branch is taken).
        if shards is None:
            import os

            env = os.environ.get("TB_SHARDS", "")
            shards = int(env) if env.isdigit() else 0
        self.shards = 0
        self._shard_mesh = None
        self._shard_steps = None
        self._canon = None            # cached canonical (single-layout) view
        self._ledger_is_sharded = False
        self.shard_lanes_total = 0    # plain-int counters (tests/bench)
        self.shard_lanes_cross = 0
        self.shard_seq_fallbacks = 0
        # Per-shard attempted-insert bounds (accounts/transfers): the
        # global load<=0.5 policy no longer bounds a SHARD's load — hash
        # skew can overfill one cap/n local region while the global count
        # sits under cap/2, and a fast-path probe overflow there is fatal
        # (rows already dropped).  Owners are host-computable (one mix64
        # pass per batch), so growth sizes off the peak shard too.
        self._shard_insert_bounds: dict = {}
        # Online shard split (docs/reconfiguration.md): volatile migration
        # state (None = no split in flight).  Deliberately NOT part of any
        # checkpoint — a crash mid-migration rolls back to serving the old
        # layout and the operator (or VOPR's reconfig fault kind) re-arms.
        self._reshard: Optional[dict] = None
        self.reshard_stats = {
            "splits_started": 0, "splits_completed": 0, "abandons": 0,
            "restarts": 0, "catchup_rounds": 0, "chunks": 0,
            "chunk_retries": 0, "bytes_migrated": 0, "bytes_full": 0,
        }
        if shards >= 2 and host_engine:
            # Sharding runs on the device path.  A process-wide TB_SHARDS
            # env must not take down a host-engine solo server: degrade to
            # the proven single-device path loudly (the
            # DEGRADED_DEVICE_COUNT discipline).  Cold tiering now
            # COMPOSES with sharding (PR 20): the mesh kernels still have
            # no bloom, so tiered transfer commits route through the
            # sequential fallback's canonical window, where the existing
            # host-exact cold resolution applies unchanged.
            warnings.warn(
                f"TB_SHARDS={shards} ignored: "
                "the host engine is the commit authority here",
                RuntimeWarning, stacklevel=2,
            )
            shards = 0
        if shards >= 2:
            assert shards & (shards - 1) == 0, "TB_SHARDS must be a power of 2"
            devs = jax.devices()
            if len(devs) < shards:
                # The DEGRADED_DEVICE_COUNT discipline (jaxenv.py): degrade
                # to the proven single-device path rather than wedge.
                warnings.warn(
                    f"TB_SHARDS={shards} but only {len(devs)} device(s) "
                    "visible; running single-device",
                    RuntimeWarning, stacklevel=2,
                )
            else:
                from .parallel import sharded as shard_mod
                from jax.sharding import Mesh

                for cap in (cfg.accounts_capacity, cfg.transfers_capacity,
                            cfg.posted_capacity):
                    assert cap % shards == 0, "capacity not shard-divisible"
                self.shards = shards
                self._shard_mesh = Mesh(
                    np.array(devs[:shards]), (shard_mod.AXIS,)
                )
                self._shard_steps = shard_mod.machine_steps(
                    self._shard_mesh, cfg.jacobi_max_passes
                )
                self._shard_insert_bounds = {
                    "accounts": np.zeros(shards, np.int64),
                    "transfers": np.zeros(shards, np.int64),
                }
                if _obs.enabled:
                    _obs.gauge("sharding.shards").set(shards)
        # Grouped device commit (commit_group_fast).  None = auto: enabled
        # on the TPU backend, where an empty scan step is us-scale; on
        # XLA-CPU each step pays table-sized temporaries, so per-batch
        # dispatch is cheaper there.  Tests force True to pin the path.
        self._group_device_commit: Optional[bool] = None
        # Host data-plane mode (host_engine.py): commits run in the native
        # engine over a numpy mirror; the device ledger is materialized
        # lazily for queries/checkpoints/digests.  The mirror is the
        # authority between materializations.
        self._engine = None
        self._host_led = None
        self._device_stale = False
        self._index_stale = False
        if host_engine:
            from .host_engine import HostEngine, HostLedger

            assert not force_sequential, (
                "host engine is already sequential-exact"
            )
            assert hot_transfers_capacity_max is None, (
                "tiering runs on the device path"
            )
            self._host_led = HostLedger(
                cfg.accounts_capacity, cfg.transfers_capacity,
                cfg.posted_capacity, cfg.history_capacity,
            )
            self._engine = HostEngine(self._host_led, cfg.max_probe)
            self._device_stale = True
            self._ledger = None
        elif self._shard_mesh is not None:
            from .parallel import sharded as shard_mod

            self._ledger = shard_mod.make_sharded_ledger(
                self._shard_mesh,
                cfg.accounts_capacity,
                cfg.transfers_capacity,
                cfg.posted_capacity,
                history_capacity=cfg.history_capacity,
            )
            self._ledger_is_sharded = True
        else:
            self._ledger = sm.make_ledger(
                cfg.accounts_capacity,
                cfg.transfers_capacity,
                cfg.posted_capacity,
                cfg.history_capacity,
            )
        self.prepare_timestamp = 0
        self.commit_timestamp = 0
        # Host-side upper bounds on live rows (for growth decisions without
        # device syncs): counts only grow, so bounding by attempted inserts
        # is safe.
        self._accounts_bound = 0
        self._transfers_bound = 0
        self._posted_bound = 0
        self._history_bound = 0
        # Growth hint only (NOT a dispatch precondition): history rows can
        # only ever append if some create_accounts batch requested the flag.
        self._history_accounts_possible = False
        # Fast-path preconditions (ops/state_machine.py P1/P3): once any
        # account carries limit flags, plain batches must run the full
        # kernel; _balance_bound over-approximates every balance field so
        # the overflow ladder provably cannot fire on the fast path.
        self._limit_accounts_possible = False
        self._balance_bound = 0
        # Secondary index for get_account_transfers (ops/index.py): derived
        # state, rebuilt from the table after restore/state-sync.
        from .ops.index import TransferIndex

        self.index = TransferIndex(base=batch_lanes)
        # Every index rebuild (incl. the stale fallback inside query) must
        # also cover the cold-tier runs, or restarts drop evicted
        # transfers from query results.
        self.index.extra_rows_provider = (
            lambda: [np.asarray(r) for r in self.cold.runs]
        )
        # General scan composition (ops/scan_builder.py): lazily-built
        # per-field indexes serving union/intersection/difference scans
        # (scan_builder.zig / scan_merge.zig generality).
        from .ops import scan_builder as sb

        self.scans_transfers = sb.ScanSet(
            "transfers", sb.TRANSFER_FIELDS, base=batch_lanes
        )
        self.scans_transfers.extra_rows_provider = (
            lambda: [np.asarray(r) for r in self.cold.runs]
        )
        self.scans_accounts = sb.ScanSet(
            "accounts", sb.ACCOUNT_FIELDS, base=batch_lanes
        )
        # Tiered transfers store (ops/cold.py): hot device window + cold
        # host spill; None spill_dir with no cap = tiering off (everything
        # stays hot).
        from .ops.cold import ColdStore, make_bloom

        self.cold = ColdStore(spill_dir)
        self.hot_transfers_capacity_max = hot_transfers_capacity_max
        # Tiering is driven by the hot-window cap (evictions never trigger
        # without one); a spill_dir alone is just where cold state WOULD
        # live — restore_host_state turns tiering on when a checkpoint's
        # cold_manifest says evictions already happened.
        self._tiering = hot_transfers_capacity_max is not None
        self._bloom_log2 = cfg.bloom_bits_log2
        self._bloom_np = None
        self._bloom_dev = None
        self._evictions = 0
        # Device-dispatch accounting (bench.py e2e decomposition, VERDICT r5
        # ask #6): every blocking codes D2H counts one dispatch + its wait.
        self.disp_count = 0
        self.disp_wait_s = 0.0
        # Commit pipeline (docs/commit_pipeline.md): bounded deferred-
        # readback depth (TB_PIPELINE; resolved lazily so tests can set the
        # env per-instance), plus the cached host staging buffers for the
        # grouped H2D upload and the zero-count pad-SoA template.
        self._pipeline_depth: Optional[int] = None
        # Wave scheduler (TB_WAVES; docs/waves.md), lazy like the depth.
        self._waves_enabled: Optional[bool] = None
        self._stage_pool: List[tuple] = []  # free staging sets (_stage_acquire)
        self._pad_soa_zero: dict = {}
        self._lane = None  # FIFO dispatch-lane executor (see _dispatch_lane)
        # TB_SANITIZE=1 (sanitize.py, test/CI-only): poison released
        # staging sets, guard the cached zero templates, and trip on
        # post-warmup recompiles in the serving path.  One bool read at
        # init; sanitize-off runs take none of the branches.
        self._sanitize = _san.enabled()
        # jaxenv.compile_count() as of the last known-legitimate compile
        # point (warmup / growth); None until warmup() arms it.
        self._sanitize_compile_base: Optional[int] = None
        # One-readback grace window after a growth rehash: the grown
        # capacity is a new shape class, so the next dispatch's compiles
        # are legitimate — the tripwire re-baselines instead of tripping.
        self._sanitize_grace = False
        # Set alongside: once ANY capacity changed post-warmup, kernel
        # variants not yet exercised at the new capacity may legitimately
        # first-compile much later (e.g. the first two-phase batch after
        # a growth), so strict raising downgrades to warn-until-re-arm.
        self._sanitize_soft = False
        # Device fault domain (ops/scrub.py; docs/fault_domains.md).  Armed
        # by scrub_arm() when scrub_interval > 0: the mirror is the
        # authoritative host twin (ReferenceStateMachine) every committed
        # batch also applies to; scrub points compare its expected digests
        # against the on-device fold, and recovery re-materializes the
        # device ledger from it.  All None/zero by default: scrub-off runs
        # take none of these branches.
        self._scrub_interval: Optional[int] = None  # lazy (TB_SCRUB_INTERVAL)
        self._scrub_mirror = None
        self._scrub_suspect = False
        self._scrub_commits = 0        # create_* commits since the last check
        self._inflight_handles: List[DeviceCommitHandle] = []
        # Deferred dispatches currently in flight on the FIFO lane
        # (submit/resolve both happen on the serving thread): the
        # commit-lane occupancy the pipeline.shard.* series report.
        self._deferred_inflight = 0
        self._injected_device_faults = 0
        self._device_fault_streak = 0  # consecutive failed dispatches
        self.device_fault_limit = 3    # streak that triggers the degrade
        # Jittered exponential re-dispatch backoff (vsr/timeout.py): one
        # tick of backoff sleeps retry_tick_s seconds; the sim pins it to 0
        # (virtual time).  The prng feeds ONLY sleep jitter, never state.
        self.retry_tick_s = 0.01
        self._retry_prng = _random.Random(0x5C12)  # jitter only, never state
        self._retry_timeout = None
        # Merkle commitment tree (ops/merkle.py; docs/commitments.md).
        # TB_MERKLE=1 replaces the scrub check substrate with the on-device
        # incremental forest: per-commit touched-path updates, root-compare
        # checks, client-verifiable proofs; the authoritative mirror is
        # kept only at the TB_SCRUB_INTERVAL=1 paranoid cadence.  All
        # None/False by default: merkle-off runs take none of these
        # branches (bit-identical to pre-merkle behavior).
        self._merkle_enabled: Optional[bool] = None  # lazy (TB_MERKLE)
        self._scrub_paranoid: Optional[bool] = None  # lazy (TB_SCRUB_PARANOID)
        self._merkle_forest = None
        self._merkle_dirty = False
        self._merkle_steps_cache = None
        self._canon_tree = None  # (canon ledger ref, {pad name: np heap})
        # Deferred commitment lane (TB_MERKLE_ASYNC; docs/commitments.md):
        # touched-row records of committed batches whose leaf->root path
        # refresh has not run yet.  Drained by merkle_settle() at every
        # point a maintained root is observed; leaves recompute from
        # CURRENT table content, so one fused settle is bit-identical to
        # the per-commit update sequence.  Empty unless the knob is on.
        self._merkle_async: Optional[bool] = None  # lazy (TB_MERKLE_ASYNC)
        self._merkle_pending: List[Tuple[str, np.ndarray]] = []
        # Cross-batch conflict fusion (TB_FUSE; vsr/overload.py): read by
        # the replica's dispatch lane, lazy like the knobs above.
        self._fuse_batches: Optional[bool] = None  # lazy (TB_FUSE)
        # Plain-int event counters (read by obs/vopr_viz and tests without
        # the global metrics registry).
        self.scrub_checks = 0
        self.scrub_mismatches = 0
        self.merkle_updates = 0
        self.merkle_rebuilds = 0
        self.merkle_mismatches = 0
        self.merkle_settles = 0  # commitment-lane drains (TB_MERKLE_ASYNC)
        self.device_recoveries = 0
        self.degraded_to_host_engine = False
        if self._tiering:
            self._bloom_np = np.zeros(((1 << self._bloom_log2) // 32,), np.uint32)
            self._bloom_dev = make_bloom(self._bloom_log2)

    def _d2h_codes(self, codes, overflow=None, stage=None):
        """The blocking device->host read of a commit's result codes: the
        ONE point every device dispatch funnels through.  Timed so the e2e
        bench can decompose wall time into device-wait vs host work (and
        project a zero-tunnel-RTT deployment).

        ``overflow`` (the table's probe_overflow flag) rides the SAME
        device_get, so the per-batch/per-group overflow check costs zero
        extra syncs; when passed, returns (codes, overflow) instead of
        codes alone.

        host-sync: commit barrier — this is the deliberate readback point
        of the deferred commit pipeline (docs/commit_pipeline.md; the
        bench's RTT-emulation sweep wraps exactly this method)."""
        self._injected_fault_check()
        t0 = _time.perf_counter()
        if overflow is None:
            out = jax.device_get(codes)
        else:
            out, overflow = jax.device_get((codes, overflow))
        wait = _time.perf_counter() - t0
        self.disp_wait_s += wait
        self.disp_count += 1
        if stage is not None and txtrace.active:
            # Attribution ledger: only EXPLICITLY staged readbacks bill
            # (the deferred resolve passes stage="readback").  The default
            # funnel is already inside a device_execute stage block
            # (commit_batch / the lane closures) — billing its wait again
            # would double-count the barrier.
            txtrace.stage_observe(stage, wait * 1e6)
        if _obs.enabled:
            _obs.counter("ops.dispatch").inc()
            _obs.histogram("ops.dispatch_wait_us", "us").observe(wait * 1e6)
        if (self._sanitize and self._sanitize_compile_base is not None
                and self._deferred_inflight == 0):
            # Recompile tripwire: every commit funnels through this
            # readback, so a post-warmup compile (PR 10's size-class bug)
            # is caught one dispatch after it happened, with the count.
            # Checked ONLY at pipeline-quiescent readbacks: a still-
            # running lane closure may be mid-growth, with its compile
            # already counted but its grace flag not yet visible — every
            # closure's flags ARE visible here via its resolve() join.
            # (_deferred_inflight is serving-thread-only: submit and
            # resolve both happen there.)
            self._sanitize_recompile_check("serving commit path")
        return out if overflow is None else (out, overflow)

    # -- device fault domain (ops/scrub.py, docs/fault_domains.md) -----------

    @property
    def scrub_interval(self) -> int:
        """Scrub cadence in commit batches (TB_SCRUB_INTERVAL env; the CLI's
        --scrub-interval overrides).  0 = the device fault domain is off —
        no mirror, no checks, no retry: byte-identical to pre-fault-domain
        behavior."""
        if self._scrub_interval is None:
            import os

            env = os.environ.get("TB_SCRUB_INTERVAL", "")
            self._scrub_interval = int(env) if env.isdigit() else 0
        return self._scrub_interval

    @scrub_interval.setter
    def scrub_interval(self, value: int) -> None:
        self._scrub_interval = max(0, int(value))

    @property
    def merkle_enabled(self) -> bool:
        """Merkle commitment mode (TB_MERKLE env; docs/commitments.md).
        Off (the default) is bit-identical pre-merkle behavior: the scrub
        fault domain runs the PR 4 host-mirror discipline unchanged."""
        if self._merkle_enabled is None:
            import os

            self._merkle_enabled = os.environ.get("TB_MERKLE", "") == "1"
        return self._merkle_enabled

    @merkle_enabled.setter
    def merkle_enabled(self, value: bool) -> None:
        self._merkle_enabled = bool(value)

    @property
    def scrub_paranoid(self) -> bool:
        """Merkle mode's mirror retention: keep the authoritative host
        mirror ALONGSIDE the commitment forest (in-process
        re-materialization recovery + semantic authority — the PR 4
        discipline and its ~1.6x replay tax).  Default: exactly at the
        TB_SCRUB_INTERVAL=1 paranoid cadence; TB_SCRUB_PARANOID=0/1 (or
        the setter) overrides — 0 at interval 1 gives the cheapest
        check-ahead-of-every-commit config: root compare only, recovery
        via checkpoint + WAL replay."""
        if self._scrub_paranoid is None:
            import os

            env = os.environ.get("TB_SCRUB_PARANOID", "")
            if env in ("0", "1"):
                return env == "1"
            return self.scrub_interval == 1
        return self._scrub_paranoid

    @scrub_paranoid.setter
    def scrub_paranoid(self, value: Optional[bool]) -> None:
        self._scrub_paranoid = value if value is None else bool(value)

    @property
    def merkle_armed(self) -> bool:
        return self._merkle_forest is not None

    @property
    def scrub_armed(self) -> bool:
        return self._scrub_mirror is not None or self._merkle_forest is not None

    @property
    def scrub_due(self) -> bool:
        # +1: a check runs BEFORE the commit that would complete the
        # window, so interval 1 verifies the at-rest state ahead of EVERY
        # commit (a flip injected between commits is caught before any
        # commit reads it), interval N ahead of every Nth.
        armed = self._merkle_forest is not None or (
            self._scrub_mirror is not None and not self._scrub_suspect
        )
        return armed and self._scrub_commits + 1 >= self.scrub_interval

    def scrub_arm(self) -> bool:
        """Enable the device fault domain from the CURRENT ledger state.
        Callers arm only at VERIFIED points: genesis, a digest-checked
        checkpoint restore + WAL replay, or the end of a recovery.  No-op
        (returns False) in host-engine mode — there the numpy ledger
        already IS the authority — or when scrub_interval is 0.

        Mirror mode (default): seed the authoritative host mirror — every
        committed batch replays into it, checks compare digest folds.
        Merkle mode (TB_MERKLE=1, docs/commitments.md): build the
        on-device commitment forest — commits update touched leaf->root
        paths, checks compare maintained vs recomputed roots, and the
        full mirror is kept ONLY at the TB_SCRUB_INTERVAL=1 paranoid
        cadence (check-ahead-of-every-commit closes the read-before-check
        window the self-referential tree cannot)."""
        if self._engine is not None or self.scrub_interval <= 0:
            self._scrub_mirror = None
            self._merkle_forest = None
            return False
        if self.merkle_enabled:
            self._merkle_rebuild()
            keep_mirror = self.scrub_paranoid
        else:
            self._merkle_forest = None
            keep_mirror = True
        self._scrub_mirror = scrub_ops.model_from_ledger(
            self.ledger,
            cold_rows=[np.asarray(r) for r in self.cold.runs],
            prepare_timestamp=self.prepare_timestamp,
            commit_timestamp=self.commit_timestamp,
        ) if keep_mirror else None
        self._scrub_suspect = False
        self._scrub_commits = 0
        return True

    def scrub_disarm(self) -> None:
        self._scrub_mirror = None
        self._merkle_forest = None
        self._merkle_dirty = False
        self._scrub_suspect = False

    def inject_device_faults(self, n: int = 1) -> None:
        """Arm ``n`` simulated dispatch failures (tests / VOPR schedules):
        the next n device readbacks raise SimulatedDeviceFault through the
        same funnel a real XlaRuntimeError would."""
        self._injected_device_faults += int(n)

    def _injected_fault_check(self) -> None:
        if self._injected_device_faults > 0:
            self._injected_device_faults -= 1
            raise SimulatedDeviceFault("injected device dispatch fault")

    _SDC_COLS = (
        "debits_pending_lo", "debits_posted_lo",
        "credits_pending_lo", "credits_posted_lo",
        "debits_pending_hi", "debits_posted_hi",
        "credits_pending_hi", "credits_posted_hi",
    )

    def inject_sdc_bitflip(self, rng) -> bool:
        """Flip one seeded bit in a live account balance column on device —
        the VOPR's device-SDC fault (tests / sim only).  Returns False when
        no live account exists yet (nothing to corrupt)."""
        if self._engine is not None or self._ledger is None:
            return False
        a = self._ledger.accounts
        live = np.flatnonzero(
            (np.asarray(a.key_lo) != 0) | (np.asarray(a.key_hi) != 0)
        )
        if live.size == 0:
            return False
        slot = int(live[rng.randrange(live.size)])
        col = self._SDC_COLS[rng.randrange(len(self._SDC_COLS))]
        bit = rng.randrange(64)
        arr = a.cols[col]
        cols = dict(a.cols)
        cols[col] = arr.at[slot].set(arr[slot] ^ jnp.uint64(1 << bit))
        self._ledger = self._ledger.replace(accounts=a.replace(cols=cols))
        self._canon = None  # the corruption must be visible to queries too
        return True

    def _inflight_untrack(self, handle) -> None:
        try:
            self._inflight_handles.remove(handle)
        except ValueError:
            pass  # never tracked (fault domain off) or already recovered

    def _deferred_done(self, handle) -> None:
        if handle._deferred:
            handle._deferred = False
            self._deferred_inflight = max(0, self._deferred_inflight - 1)

    def _deferred_submitted(self, lanes: int, owners=None) -> None:
        """Commit-lane occupancy accounting for one deferred dispatch
        (serving thread, at submit).  Under TB_SHARDS the pipeline.shard.*
        series record per-shard lane occupancy: every shard executes every
        deferred batch (replicated dispatch), so ``inflight`` IS the
        per-shard commit-lane depth, and the per-shard lane counters
        (from the host-side owner bincount) expose insert skew."""
        self._deferred_inflight += 1
        if not _obs.enabled:
            return
        if self.shards:
            _obs.counter("pipeline.shard.dispatches").inc()
            _obs.histogram("pipeline.shard.inflight", "handles").observe(
                self._deferred_inflight
            )
            _obs.counter("pipeline.shard.lanes").inc(lanes)
            if owners is not None:
                for s, c in enumerate(owners.tolist()):
                    if c:
                        _obs.counter(f"pipeline.shard.lanes.{s}").inc(c)

    def _mirror_apply(self, operation: str, batch: np.ndarray,
                      timestamp: int) -> None:
        """Advance the authoritative mirror by one committed batch (strict
        commit order — callers are the post-success blocking commit paths
        and FIFO handle resolves).  A mirror application failure marks it
        SUSPECT: scrub checks stand down and any later recovery escalates
        to checkpoint + WAL replay (the replica's recover_device_state)."""
        model = self._scrub_mirror
        if model is None or self._scrub_suspect:
            return
        from .testing import model as M

        try:
            # Batched column-wise conversion (testing/model.py): one C pass
            # per column instead of ~17 numpy scalar reads per event — the
            # dominant term of the scrub mirror tax (BENCH_r05 ~1.6x
            # overhead_vs_off; re-measured in BENCH_r08).
            if operation == "create_accounts":
                events = M.accounts_from_batch(batch)
            else:
                events = M.transfers_from_batch(batch)
            model.execute(operation, int(timestamp), events)
        except Exception:  # noqa: BLE001 — a broken mirror must stand down
            self._scrub_suspect = True
            if _obs.enabled:
                _obs.counter("scrub.mirror_suspect").inc()

    def _guarded_commit(self, operation, batch, timestamp, impl):
        """The dispatch-lane funnel for blocking commits: scrub cadence
        check BEFORE the commit reads device state, dispatch retry with
        jittered exponential backoff on device faults, and the commitment
        substrate (mirror and/or merkle forest) advanced after success.
        Pass-through (zero new branches beyond one armed check) when the
        fault domain is off."""
        if not self.scrub_armed or self._engine is not None or (
            len(batch) == 0
        ):
            return impl(batch, timestamp)
        while True:
            try:
                self._scrub_maybe_check()
                results = impl(batch, timestamp)
                self._device_fault_streak = 0
                break
            except DEVICE_FAULT_TYPES as err:
                recovered = self._on_blocking_device_fault(
                    operation, batch, timestamp, err
                )
                if recovered is not None:
                    return recovered  # degraded: the host engine committed
        self._scrub_commits += 1
        self._mirror_apply(operation, batch, timestamp)
        self._merkle_apply(operation, batch)
        return results

    def _on_blocking_device_fault(self, operation, batch, timestamp, err):
        """One failed blocking dispatch: quarantine + re-materialize + back
        off (caller retries), or — at device_fault_limit consecutive
        failures — degrade to the host engine and commit there.  Returns
        the results when degraded, None when the caller should retry."""
        if _obs.enabled:
            _obs.counter("device_recovery.dispatch_faults").inc()
        self._device_fault_streak += 1
        if self._device_fault_streak >= self.device_fault_limit:
            self._degrade_to_host_engine(err)
            results = self._engine_commit(operation, batch, timestamp)
            self._device_fault_streak = 0
            return results
        self.quarantine()
        self._rematerialize_from_mirror()
        self._retry_backoff()
        self.device_recoveries += 1
        if _obs.enabled:
            _obs.counter("device_recovery.recoveries").inc()
            _obs.counter("device_recovery.redispatches").inc()
        return None

    def _device_fault_at_resolve(self, err) -> None:
        """Deferred-path funnel: the oldest in-flight handle's dispatch (or
        readback) failed.  Quarantine the whole FIFO lane and re-dispatch
        EVERY pending run from the mirror via the blocking path (which owns
        retry/backoff/degrade), storing per-handle results for resolve()."""
        if _obs.enabled:
            _obs.counter("device_recovery.dispatch_faults").inc()
        if self._scrub_mirror is None:
            if self._merkle_forest is not None:
                # Merkle-only mode: no in-process authority to re-dispatch
                # from — escalate to the durable-state rebuild
                # (replica._settle_or_recover aborts the failed group and
                # runs checkpoint + WAL replay) instead of leaking the raw
                # device error into the serving path.
                self._merkle_dirty = True
                raise DeviceStateUnrecoverable(
                    "deferred dispatch failed with no mirror armed "
                    "(merkle mode recovers via checkpoint + WAL replay)"
                ) from err
            raise err
        self._device_fault_streak += 1
        if self._device_fault_streak >= self.device_fault_limit:
            # Let the re-dispatch below run on the host engine directly.
            self._degrade_to_host_engine(err)
        self._retry_backoff()
        self._recover_inflight()

    def _recover_inflight(self) -> None:
        """Quarantine + rebuild from the mirror, then re-commit every
        pending deferred run's batches in FIFO (== op) order through the
        guarded blocking path."""
        pending = list(self._inflight_handles)
        self._inflight_handles = []
        self.quarantine()
        try:
            if self._engine is None:
                self._rematerialize_from_mirror()
            for handle in pending:
                if hasattr(handle._result, "result"):
                    try:
                        handle._result.result()  # quiesce the dead future
                    except BaseException:  # tblint: ignore[swallow] quiesced fault
                        pass
                assert handle._batches is not None, (
                    "deferred handle tracked without batch retention"
                )
                results = [
                    self._commit_create_transfers(b, ts)
                    for b, ts in zip(handle._batches, handle._timestamps)
                ]
                handle._recovered = results
                if handle._stage is not None:
                    self._stage_release(handle._stage)
                    handle._stage = None
        except BaseException:
            # Recovery itself failed (e.g. escalating to the durable-state
            # rebuild): the not-yet-recovered handles are already
            # untracked — quiesce them and release their staging sets so
            # nothing leaks; the caller's pipeline abort (or the direct
            # caller) sees the escalation, never a dangling handle.
            for handle in pending:
                if handle._recovered is not None:
                    continue
                if hasattr(handle._result, "result"):
                    try:
                        handle._result.result()
                    except BaseException:  # tblint: ignore[swallow] quiesced fault
                        pass
                if handle._stage is not None:
                    self._stage_release(handle._stage)
                    handle._stage = None
            raise
        self.device_recoveries += 1
        if _obs.enabled:
            _obs.counter("device_recovery.recoveries").inc()

    def _scrub_maybe_check(self) -> None:
        if not self.scrub_due or self._inflight_handles:
            return
        self.scrub_check()

    def scrub_check(self, boundary: bool = False) -> bool:
        """Integrity check of the at-rest device state.  Mirror mode:
        compare the on-device fold digests (ops/scrub.scrub_digest — ONE
        readback through the commit-barrier funnel) against the mirror's
        expectation.  Merkle mode: compare the maintained commitment
        roots against roots recomputed from the pads (ONE (2, 3) — or
        per-shard (n, 2, 3) — readback; no mirror, no replay).  On
        mismatch: quarantine, re-materialize the device ledger from the
        mirror, and verify the rebuild took; without a mirror (merkle
        cadence > 1) the mismatch escalates directly to the durable-state
        rebuild (DeviceStateUnrecoverable -> replica checkpoint + WAL
        replay).  Returns True when the state was already clean.
        ``boundary`` marks a checkpoint-boundary check (a divergence there
        is a hard integrity violation the capture must never bake in —
        counted separately)."""
        model = self._scrub_mirror
        mirror_armed = model is not None and not self._scrub_suspect
        if self._merkle_forest is None and not mirror_armed:
            return True
        assert not self._inflight_handles, (
            "scrub requires a settled pipeline"
        )
        self._scrub_commits = 0
        self.scrub_checks += 1
        if _obs.enabled:
            _obs.counter("scrub.checks").inc()
        ok = True
        if self._merkle_forest is not None:
            try:
                ok = self._merkle_verify()
            except DEVICE_FAULT_TYPES as err:
                # The verify dispatch itself failed: without a mirror the
                # only recovery substrate is durable state — escalate
                # instead of leaking a raw device error to the serving
                # path (the mirror path below retries via quarantine).
                if _obs.enabled:
                    _obs.counter("device_recovery.dispatch_faults").inc()
                if not mirror_armed:
                    self._merkle_dirty = True
                    raise DeviceStateUnrecoverable(
                        "device fault during merkle verification "
                        "(no mirror armed)"
                    ) from err
                ok = False
        want = scrub_ops.mirror_digests(model) if mirror_armed else None
        if mirror_armed:
            try:
                got = self._scrub_fold_digests()
                ok = ok and (
                    int(got[0]) == want[0] and int(got[2]) == want[2] and (
                        self.cold.count != 0 or int(got[1]) == want[1]
                    )
                )
            except DEVICE_FAULT_TYPES:
                # The scrub dispatch itself failed: same quarantine/rebuild
                # path as a mismatch (the re-digest below is the retry).
                if _obs.enabled:
                    _obs.counter("device_recovery.dispatch_faults").inc()
                ok = False
        if ok:
            return True
        self.scrub_mismatches += 1
        if _obs.enabled:
            _obs.counter("scrub.mismatches").inc()
            if boundary:
                _obs.counter("scrub.boundary_mismatches").inc()
        if not mirror_armed:
            # Merkle-only detection: there is no in-process authority to
            # re-materialize from — route to the fault domain's last
            # resort (replica.recover_device_state: checkpoint + WAL
            # replay, then scrub_arm rebuilds the forest from the
            # recovered state).
            self._merkle_dirty = True
            raise DeviceStateUnrecoverable(
                "merkle root mismatch: device state corrupt and no "
                "authoritative mirror armed (TB_SCRUB_INTERVAL=1 keeps one)"
            )
        self.quarantine()
        self._rematerialize_from_mirror()
        if self._merkle_forest is not None:
            # The re-materialized ledger is a fresh layout: rebuild the
            # forest from it before re-verifying.
            self._merkle_rebuild()
        try:
            got = self._scrub_fold_digests()
        except DEVICE_FAULT_TYPES as err:
            # A second fault during the verification re-digest: escalate
            # to the durable-state rebuild rather than crash the serving
            # path with a raw device error.
            self._scrub_suspect = True
            raise DeviceStateUnrecoverable(
                "device fault during post-recovery scrub verification"
            ) from err
        if int(got[0]) != want[0] or int(got[2]) != want[2] or (
            self.cold.count == 0 and int(got[1]) != want[1]
        ):
            self._scrub_suspect = True
            raise DeviceStateUnrecoverable(
                "scrub mismatch survived re-materialization: mirror suspect"
            )
        self.device_recoveries += 1
        if _obs.enabled:
            _obs.counter("device_recovery.recoveries").inc()
            _obs.counter("device_recovery.scrub").inc()
        return False

    def _scrub_fold_digests(self) -> np.ndarray:
        """The on-device (accounts, transfers, posted) fold triple through
        the commit-barrier funnel (ONE readback).  Under TB_SHARDS the
        readback is the per-shard uint64 lane matrix (n_shards, 3) from
        parallel/sharded.sharded_scrub_digest, summed mod 2^64 into the
        global digests — the folds are wrap-adds over disjoint owner
        partitions, so the sum equals the single-device fold bit for bit
        (and the lanes localize a mismatch to one shard)."""
        if self._ledger_is_sharded:
            lanes = np.asarray(
                self._d2h_codes(self._shard_steps["scrub"](self.ledger))
            )
            if _obs.enabled:
                _obs.counter("sharding.scrub_lane_checks").inc()
            with np.errstate(over="ignore"):
                return lanes.sum(axis=0, dtype=np.uint64)
        return np.asarray(
            self._d2h_codes(scrub_ops.scrub_digest(self.ledger))
        )

    # -- merkle commitment tree (ops/merkle.py, docs/commitments.md) ---------

    def _merkle_steps(self) -> dict:
        """Jitted sharded merkle steps for this mesh (process-wide cache,
        like the commit steps)."""
        if self._merkle_steps_cache is None:
            from .parallel import sharded as shard_mod

            self._merkle_steps_cache = shard_mod.merkle_steps(
                self._shard_mesh
            )
        return self._merkle_steps_cache

    def _merkle_rebuild(self) -> None:
        """Full forest rebuild from the current ledger — O(capacity), paid
        only at arm points and after non-incremental mutations (growth
        rehash, sequential fallback, tier moves, recovery installs).  A
        rebuild resets the detection window: corruption already present in
        the pads is baked into the fresh tree (same semantics as reseeding
        the mirror — arm/rebuild only at verified or just-checked points)."""
        if self._ledger_is_sharded:
            self._merkle_forest = self._merkle_steps()["build"](self._ledger)
        else:
            self._merkle_forest = merkle_ops.build_forest(self.ledger)
        self._merkle_dirty = False
        # A rebuild reads the whole ledger, so it subsumes every queued
        # deferred-lane touch (TB_MERKLE_ASYNC); stale records would only
        # re-touch rows idempotently, but dropping them keeps lag honest.
        self._merkle_pending.clear()
        self.merkle_rebuilds += 1
        if _obs.enabled:
            _obs.counter("merkle.rebuilds").inc()

    def _merkle_rebuild_if_dirty(self) -> bool:
        if self._merkle_forest is None or not self._merkle_dirty:
            return False
        self._merkle_rebuild()
        return True

    def _merkle_mark_dirty(self) -> None:
        if self._merkle_forest is not None:
            self._merkle_dirty = True

    def _merkle_verify(self) -> bool:
        """Maintained roots vs roots recomputed from the pads: ONE
        readback through the commit-barrier funnel ((2, 3) single-device;
        per-shard (n, 2, 3) lanes under TB_SHARDS, which also localize a
        mismatch to one shard)."""
        self.merkle_settle()  # the scrub oracle observes settled roots only
        self._merkle_rebuild_if_dirty()
        if self._ledger_is_sharded:
            lanes = np.asarray(self._d2h_codes(
                self._merkle_steps()["verify"](
                    self._merkle_forest, self._ledger
                )
            ))
            ok = bool((lanes[:, 0, :] == lanes[:, 1, :]).all())
        else:
            lanes = np.asarray(self._d2h_codes(
                merkle_ops.verify_roots(self._merkle_forest, self.ledger)
            ))
            ok = bool((lanes[0] == lanes[1]).all())
        if _obs.enabled:
            _obs.counter("merkle.checks").inc()
        if not ok:
            self.merkle_mismatches += 1
            if _obs.enabled:
                _obs.counter("merkle.mismatches").inc()
        return ok

    _MERKLE_MIN_LANES = 256

    @staticmethod
    def _merkle_pad(lo: np.ndarray, hi: np.ndarray, min_lanes: int):
        """Pad key arrays to power-of-two lane classes (bounded jit
        variants; zero keys resolve as instant probe misses)."""
        n = len(lo)
        lanes = max(min_lanes, 1 << (n - 1).bit_length()) if n else min_lanes
        p_lo = np.zeros(lanes, np.uint64)
        p_hi = np.zeros(lanes, np.uint64)
        p_lo[:n] = lo
        p_hi[:n] = hi
        return jnp.asarray(p_lo), jnp.asarray(p_hi)

    def _merkle_apply(self, operation: str, batch: np.ndarray) -> None:
        """Advance the commitment forest by one committed batch (the
        blocking paths' post-success hook; deferred dispatches call
        _merkle_update_transfers_batches INSIDE their lane closure so the
        device update rides the ledger chain)."""
        if self._merkle_forest is None or len(batch) == 0:
            return
        if self.merkle_async:
            # Deferred commitment lane: record the touched rows and let a
            # settle barrier pay the leaf->root refresh (merkle_settle).
            self._merkle_lane_enqueue(operation, batch)
            return
        if self._merkle_rebuild_if_dirty():
            return  # the rebuild already reflects this batch
        if operation == "create_accounts":
            self._merkle_apply_accounts(batch)
        else:
            self._merkle_update_transfers_batches([batch])

    def _merkle_apply_accounts(self, batch: np.ndarray) -> None:
        with txtrace.stage("merkle_refresh"):
            lo, hi = self._merkle_pad(
                batch["id_lo"].astype(np.uint64),
                batch["id_hi"].astype(np.uint64),
                self._MERKLE_MIN_LANES,
            )
            if self._ledger_is_sharded:
                self._merkle_forest = (
                    self._merkle_steps()["update_accounts"](
                        self._merkle_forest, self._ledger, lo, hi
                    )
                )
            else:
                self._merkle_forest = merkle_ops.update_accounts(
                    self._merkle_forest, self.ledger, lo, hi,
                    max_probe=sm.MAX_PROBE,
                )
        self.merkle_updates += 1
        if _obs.enabled:
            _obs.counter("merkle.updates").inc()

    def _merkle_lane_enqueue(self, operation: str, batch: np.ndarray) -> None:
        """Queue one committed batch's touched-row record on the deferred
        commitment lane (TB_MERKLE_ASYNC).  Batches are immutable after
        commit, so holding the reference is safe; the queue is
        serving-thread-only, like _deferred_inflight."""
        self._merkle_pending.append((operation, batch))
        if _obs.enabled:
            _obs.counter("merkle.lane.deferred_updates").inc()

    def merkle_settle(self) -> None:
        """Settle barrier for the deferred commitment lane: replay every
        queued touched-row record into the maintained forest, restoring
        exactly the per-batch refresh sequence the synchronous path would
        have produced (leaves recompute from current table content, so
        one coalesced update == the batch-at-a-time sequence).  Runs at
        every point a maintained root is observed — scrub check,
        get_proof, reply-root stamping, merkle_roots, checkpoint capture
        (docs/commitments.md) — and MUST run with the dispatch lane idle:
        the touched-path update reads self.ledger, which in-flight lane
        closures swap and donate."""
        if not self._merkle_pending:
            return
        assert self._deferred_inflight == 0, (
            "merkle_settle with the dispatch lane busy — settle barriers "
            "run only at drained points"
        )
        pending, self._merkle_pending = self._merkle_pending, []
        if self._merkle_forest is None:
            return  # disarmed while records were queued: nothing to anchor
        if _obs.enabled:
            _obs.counter("merkle.lane.settle_waits").inc()
            _obs.histogram("merkle.lane.lag_batches", "batches").observe(
                len(pending)
            )
        self.merkle_settles += 1
        if self._merkle_rebuild_if_dirty():
            return  # the O(capacity) rebuild subsumes every queued touch
        for op, batches in merkle_ops.coalesce_touch_records(
            pending, max_rows=self.batch_lanes
        ):
            if op == "create_accounts":
                self._merkle_apply_accounts(batches[0])
            else:
                self._merkle_update_transfers_batches(batches)

    def _merkle_update_transfers_batches(self, batches) -> None:
        """ONE touched-path update covering a run of committed
        create_transfers batches: inserted ids, deduped account sides,
        pending refs (their posted keys and account sides resolve on
        device).  Over-approximation is safe — recomputing an untouched
        leaf writes the identical value."""
        if self._merkle_forest is None:
            return
        if self._merkle_rebuild_if_dirty():
            return
        with txtrace.stage("merkle_refresh"):
            self._merkle_update_transfers_apply(batches)

    def _merkle_update_transfers_apply(self, batches) -> None:
        ids_lo = np.concatenate([b["id_lo"] for b in batches])
        ids_hi = np.concatenate([b["id_hi"] for b in batches])
        dr_lo = np.concatenate([b["debit_account_id_lo"] for b in batches])
        dr_hi = np.concatenate([b["debit_account_id_hi"] for b in batches])
        cr_lo = np.concatenate([b["credit_account_id_lo"] for b in batches])
        cr_hi = np.concatenate([b["credit_account_id_hi"] for b in batches])
        flags = np.concatenate([b["flags"] for b in batches])
        pv = (
            flags & (types.TransferFlags.POST_PENDING_TRANSFER
                     | types.TransferFlags.VOID_PENDING_TRANSFER)
        ) != 0
        # Dedupe the account side (hot accounts repeat heavily under
        # zipfian batches; np.unique is sorted => deterministic).
        acc = np.unique(np.stack([
            np.concatenate([dr_hi, cr_hi]).astype(np.uint64),
            np.concatenate([dr_lo, cr_lo]).astype(np.uint64),
        ], axis=1), axis=0)
        id_lo, id_hi = self._merkle_pad(
            ids_lo.astype(np.uint64), ids_hi.astype(np.uint64),
            self._MERKLE_MIN_LANES,
        )
        acc_lo, acc_hi = self._merkle_pad(
            acc[:, 1], acc[:, 0], self._MERKLE_MIN_LANES
        )
        has_pv = bool(pv.any())
        pend = (
            np.concatenate([b["pending_id_lo"] for b in batches])[pv],
            np.concatenate([b["pending_id_hi"] for b in batches])[pv],
        ) if has_pv else (np.zeros(0, np.uint64), np.zeros(0, np.uint64))
        pend_lo, pend_hi = self._merkle_pad(
            pend[0].astype(np.uint64), pend[1].astype(np.uint64),
            self._MERKLE_MIN_LANES,
        )
        if self._ledger_is_sharded:
            step = self._merkle_steps()[
                "update_transfers_pv" if has_pv else "update_transfers"
            ]
            self._merkle_forest = step(
                self._merkle_forest, self._ledger, id_lo, id_hi,
                acc_lo, acc_hi, pend_lo, pend_hi,
            )
        else:
            self._merkle_forest = merkle_ops.update_transfers(
                self._merkle_forest, self.ledger, id_lo, id_hi,
                acc_lo, acc_hi, pend_lo, pend_hi,
                max_probe=sm.MAX_PROBE, has_postvoid=has_pv,
            )
        self.merkle_updates += 1
        if _obs.enabled:
            _obs.counter("merkle.updates").inc()

    def merkle_roots(self) -> Optional[Tuple[int, int, int]]:
        """The LIVE maintained commitment roots (accounts, transfers,
        posted) — under TB_SHARDS the wrap-sum fold of the per-shard
        subtree roots through the per-shard uint64 readback lanes.  None
        when merkle mode is not armed.  Callers need a settled pipeline
        (the replica settles before checks/checkpoints/queries)."""
        if self._merkle_forest is None:
            return None
        self.merkle_settle()
        self._merkle_rebuild_if_dirty()
        if self._ledger_is_sharded:
            lanes = np.asarray(self._d2h_codes(
                self._merkle_steps()["roots"](self._merkle_forest)
            ))
            with np.errstate(over="ignore"):
                triple = lanes.sum(axis=0, dtype=np.uint64)
        else:
            triple = np.asarray(self._d2h_codes(
                merkle_ops.forest_roots(self._merkle_forest)
            ))
        return (int(triple[0]), int(triple[1]), int(triple[2]))

    def merkle_canonical_roots(self) -> Optional[Tuple[int, int, int]]:
        """Roots over the CANONICAL single-device layout — the
        shard-config-independent commitment checkpoints serialize and
        proofs anchor to (== merkle_roots() when sharding is off and the
        forest is clean)."""
        if self._merkle_forest is None:
            return None
        # Canonical roots derive from the LEDGER, not the maintained
        # forest, so deferred-lane staleness cannot skew them — but
        # checkpoint capture is a root-observation point, so settle the
        # lane here too (when idle) to bound commitment-lane lag.
        if self._merkle_pending and self._deferred_inflight == 0:
            self.merkle_settle()
        return merkle_ops.np_ledger_roots(self._query_ledger())

    def commitment_root(self) -> int:
        """The canonical ACCOUNTS-pad commitment root of the current
        committed state — the audit anchor the replica stamps into every
        reply header (wire.REPLY_DTYPE ``root``; docs/commitments.md) and
        the root client-held account proofs fold to.  0 when commitments
        are not armed (merkle off / host engine), which is also what
        legacy frames decode, so the field is skippable end to end.

        Single-device mode reads the maintained forest root (one scalar
        readback — the single-device layout IS the canonical one).
        Under TB_SHARDS the canonical root lives in the host tree cache
        get_proof maintains; REBUILDING it costs a full unshard plus an
        O(capacity) hash pass, which must never ride the per-reply hot
        path — so sharded replies stamp the root only when the cache is
        already fresh (a get_proof just built it — exactly the reply the
        client cross-checks) and 0 otherwise, which clients skip by
        contract.  Under grouped/pipelined commit the value may reflect
        a commit point slightly AFTER the op being replied to (the lane
        holds the whole wave): the contract is at-or-after, which a
        get_proof reply — always a group boundary, served from settled
        state — meets exactly.

        Under TB_MERKLE_ASYNC the same skippable-0 contract covers a
        backlogged commitment lane: when deferred touch records are
        queued the reply stamps 0 (clients skip it) rather than a stale
        root — per-reply stamping must never pull the lane's work onto
        the serving thread (that would serialize exactly the refresh the
        deferred lane exists to move off the commit stream).  The HARD
        settle barriers — scrub check, checkpoint capture, get_proof,
        state-sync summary — bound the lag and are the points real roots
        are certified; a get_proof reply (the one clients cross-check)
        is always served from settled state."""
        if self._merkle_forest is None or self._engine is not None:
            return 0
        if self._merkle_pending:
            return 0  # lane backlogged: stamp the skippable sentinel
        self._merkle_rebuild_if_dirty()
        if self._ledger_is_sharded:
            # Cache-fresh check WITHOUT touching _query_ledger() (that
            # would itself trigger the O(capacity) unshard per commit).
            canon = self._canon
            cached = self._canon_tree
            if (
                canon is None or cached is None
                or cached[0] is not canon
                or "accounts" not in cached[1]
            ):
                return 0
            return int(cached[1]["accounts"][1])
        # The forest object is swapped wholesale by commit closures (an
        # immutable pytree per batch), so this read sees SOME committed
        # forest, never a torn one.
        return int(np.asarray(self._merkle_forest.accounts[1]))

    def _canon_tree_nodes(self, pad_name: str) -> np.ndarray:
        """The cached canonical host-side tree heap for ``pad_name``
        (shared by sharded get_proof paths and commitment_root),
        invalidated with the canonical view itself."""
        canon = self._query_ledger()
        cached = self._canon_tree
        if cached is None or cached[0] is not canon:
            self._canon_tree = cached = (canon, {})
        nodes = cached[1].get(pad_name)
        if nodes is None:
            nodes = merkle_ops.np_tree(
                merkle_ops.np_table_leaves(getattr(canon, pad_name), pad_name)
            )
            cached[1][pad_name] = nodes
        return nodes

    def get_proof(self, ident: int, kind: str = "accounts") -> Optional[bytes]:
        """Root-anchored Merkle inclusion proof for one row
        (docs/commitments.md proof format), client-verifiable via
        ops.merkle.check_proof.  Kinds:

        - ``accounts``: the account row + sibling path to the canonical
          accounts root (the PR 10 surface, wire-compatible).
        - ``transfers``: the transfer row + path to the transfers root.
          Only hot-pad rows have leaves — a cold-evicted transfer yields
          None (the tree commits to the pads, not the spill).
        - ``posted``: the fulfillment record of PENDING transfer
          ``ident``: the posted pad is keyed by the pending transfer's
          timestamp, which the proof row carries so a client can bind it
          to that transfer's own proof (its row holds id + timestamp).

        None when the row does not exist in the pad or merkle is off."""
        if self._merkle_forest is None or self._engine is not None:
            return None
        if kind not in merkle_ops.PROOF_KINDS:
            raise ValueError(f"unknown proof kind {kind!r}")
        self.merkle_settle()  # proofs anchor to settled roots only
        lo = np.uint64(ident & U64_MAX)
        hi = np.uint64(ident >> 64)
        row_bytes = None
        if kind == "accounts":
            rows = self.lookup_accounts([ident])
            if len(rows) == 0:
                return None
            row_bytes = rows[0].tobytes()
        elif kind == "transfers":
            rows = self.lookup_transfers([ident])
            if len(rows) == 0:
                return None
            row_bytes = rows[0].tobytes()
        else:  # posted: resolve the pending id to its pad key (timestamp)
            rows = self.lookup_transfers([ident])
            if len(rows) == 0:
                return None
            lo = np.uint64(int(rows[0]["timestamp"]))
            hi = np.uint64(0)
        self._merkle_rebuild_if_dirty()
        if self._ledger_is_sharded:
            path = self._canon_proof_path(lo, hi, kind)
            if path is None:
                return None
            slot, siblings, root = path
            table = getattr(self._query_ledger(), kind)
        else:
            from .ops import hash_table as ht

            table = getattr(self.ledger, kind)
            pad = 8  # one size class for the point lookup
            k_lo = np.zeros(pad, np.uint64)
            k_hi = np.zeros(pad, np.uint64)
            k_lo[0], k_hi[0] = lo, hi
            look = ht.lookup(
                table, jnp.asarray(k_lo), jnp.asarray(k_hi), sm.MAX_PROBE
            )
            if not bool(np.asarray(look.found)[0]):
                return None
            slot = int(np.asarray(look.slot)[0])
            levels = max(0, table.capacity.bit_length() - 1)
            _leaf, sib_dev, root_dev = merkle_ops.gather_path(
                self._merkle_forest.pad(kind), jnp.uint64(slot), levels
            )
            siblings = np.asarray(sib_dev)
            root = int(np.asarray(root_dev))
        if kind == "posted":
            prow = np.zeros((), merkle_ops.PROOF_POSTED_DTYPE)
            prow["pending_timestamp"] = lo
            # One-element readback of the pad's value column at the slot.
            prow["fulfillment"] = int(np.asarray(
                table.cols["fulfillment"][slot]
            ))
            row_bytes = prow.tobytes()
        if _obs.enabled:
            _obs.counter("merkle.proofs").inc()
        return merkle_ops.encode_proof(
            row_bytes, slot, siblings, root, kind=kind
        )

    def _canon_proof_path(self, lo: np.uint64, hi: np.uint64,
                          pad_name: str = "accounts"):
        """Proof path from a cached host-side tree over the canonical
        layout of ``pad_name`` (sharded mode: the live per-shard subtrees
        commit to the sharded layout; proofs and checkpoints anchor to
        the canonical one).  The cached heaps — one per pad, built
        lazily — are invalidated with the canonical view itself.
        Returns (slot, siblings, root), or None when the key is absent."""
        nodes = self._canon_tree_nodes(pad_name)
        table = getattr(self._query_ledger(), pad_name)
        cap = len(nodes) // 2
        key_lo = np.asarray(table.key_lo)
        key_hi = np.asarray(table.key_hi)
        tomb = np.asarray(table.tombstone)
        slot = int(scrub_ops.mix64_np(
            np.asarray([lo]), np.asarray([hi])
        )[0]) & (cap - 1)
        probes = 0
        while not (key_lo[slot] == lo and key_hi[slot] == hi):
            if key_lo[slot] == 0 and key_hi[slot] == 0 and not bool(
                tomb[slot]
            ):
                return None  # absent from the canonical pad
            slot = (slot + 1) & (cap - 1)
            probes += 1
            if probes > cap:
                return None
        idx = cap + slot
        siblings = np.empty(max(0, cap.bit_length() - 1), np.uint64)
        for level in range(len(siblings)):
            siblings[level] = nodes[idx ^ 1]
            idx >>= 1
        return slot, siblings, int(nodes[1])

    def quarantine(self) -> None:
        """Quarantine the in-flight device pipeline: drain the FIFO dispatch
        lane (joining any running closure) and invalidate the cached staging
        buffers and the zero-count pad-SoA template — after a failed or
        corrupted dispatch chain, every cached device buffer is suspect."""
        lane, self._lane = self._lane, None
        if lane is not None:
            lane.shutdown(wait=True)
        self._stage_pool.clear()
        self._pad_soa_zero.clear()

    def _rematerialize_from_mirror(self) -> None:
        """Rebuild the device ledger (fresh buffers) from the authoritative
        mirror and resynchronize the host-side derived state.  Content-
        exact; table layout is rebuilt (invisible to semantics and to the
        order-independent digests)."""
        model = self._scrub_mirror
        if model is None or self._scrub_suspect:
            raise DeviceStateUnrecoverable("mirror unavailable or suspect")
        if self._tiering or self.cold.count:
            # The mirror holds every transfer but cannot reproduce the
            # hot/cold split the bloom filter and spill manifest encode.
            raise DeviceStateUnrecoverable(
                "cold tier active: mirror re-materialization unsupported"
            )
        # Property assignment: under TB_SHARDS the setter re-places the
        # single-layout materialization onto the mesh.
        self.ledger = scrub_ops.materialize_ledger(model, self.config)
        self._merkle_mark_dirty()  # fresh layout: forest rebuilds from it
        self._resync_host_state_from_mirror(model)

    def _resync_host_state_from_mirror(self, model) -> None:
        self._accounts_bound = len(model.accounts)
        self._transfers_bound = len(model.transfers)
        self._posted_bound = len(model.posted)
        self._history_bound = len(model.history)
        self._history_accounts_possible = any(
            a.flags & types.AccountFlags.HISTORY
            for a in model.accounts.values()
        )
        self._limit_accounts_possible = any(
            a.flags & _LIMIT_FLAGS for a in model.accounts.values()
        )
        bound = 0
        for a in model.accounts.values():
            bound = max(a.debits_pending, a.debits_posted,
                        a.credits_pending, a.credits_posted, bound)
        self._balance_bound = min(bound, _BOUND_CLAMP)
        self.commit_timestamp = max(
            self.commit_timestamp, model.commit_timestamp
        )
        self.index.reset()
        self.scans_transfers.reset()
        self.scans_accounts.reset()

    def reset_device_state(self) -> None:
        """Genesis reset (the replica's checkpoint-free recovery path):
        fresh empty ledger, derived state cleared.  The prepare clock is
        PRESERVED — already-issued prepare timestamps must stay monotone."""
        cfg = self.config
        if self._shard_mesh is not None:
            from .parallel import sharded as shard_mod

            self._ledger = shard_mod.make_sharded_ledger(
                self._shard_mesh, cfg.accounts_capacity,
                cfg.transfers_capacity, cfg.posted_capacity,
                history_capacity=cfg.history_capacity,
            )
            self._ledger_is_sharded = True
            self._shard_insert_bounds = {
                "accounts": np.zeros(self.shards, np.int64),
                "transfers": np.zeros(self.shards, np.int64),
            }
        else:
            self._ledger = sm.make_ledger(
                cfg.accounts_capacity, cfg.transfers_capacity,
                cfg.posted_capacity, cfg.history_capacity,
            )
        self._canon = None
        self._merkle_mark_dirty()
        self.commit_timestamp = 0
        self._accounts_bound = self._transfers_bound = 0
        self._posted_bound = self._history_bound = 0
        self._history_accounts_possible = False
        self._limit_accounts_possible = False
        self._balance_bound = 0
        self.index.reset()
        self.scans_transfers.reset()
        self.scans_accounts.reset()

    def _retry_backoff(self) -> None:
        """Jittered exponential backoff between re-dispatch attempts
        (vsr/timeout.py Timeout — the same discipline replica retries use).
        Sleeps retry_tick_s per tick; 0 (the sim) skips the sleep, keeping
        virtual-time replay deterministic (the jitter prng feeds only the
        sleep duration, never state)."""
        if self._retry_timeout is None:
            from .vsr.timeout import Timeout

            self._retry_timeout = Timeout(
                self._retry_prng, base_ticks=1, max_ticks=64
            )
        ticks = self._retry_timeout.next_backoff()
        if _obs.enabled:
            _obs.counter("device_recovery.retries").inc()
        if self.retry_tick_s > 0:
            _time.sleep(ticks * self.retry_tick_s)  # backoff sleep, not state

    def _degrade_to_host_engine(self, err) -> None:
        """After device_fault_limit consecutive dispatch failures: stop
        trusting the device entirely and serve from the native host engine
        over a ledger rebuilt from the mirror — a RuntimeWarning, not a
        wedge (the DEGRADED_DEVICE_COUNT discipline in jaxenv.py)."""
        from .host_engine import HostEngine, HostLedger, engine_available

        model = self._scrub_mirror
        if model is None or self._scrub_suspect:
            raise DeviceStateUnrecoverable(
                "device failing and mirror unavailable"
            ) from err
        if self._tiering or self.cold.count or (
            self.hot_transfers_capacity_max is not None
        ):
            raise DeviceStateUnrecoverable(
                "device failing under tiering: host engine cannot take over"
            ) from err
        if not engine_available():
            raise DeviceStateUnrecoverable(
                "device failing and the native host engine is unavailable"
            ) from err
        self.quarantine()
        self._host_led = scrub_ops.build_host_ledger(model, self.config)
        self._engine = HostEngine(self._host_led, self.config.max_probe)
        self._resync_host_state_from_mirror(model)
        self._index_stale = True
        self._device_stale = True
        self._ledger = None  # lazily re-materialized for queries/checkpoints
        self.scrub_disarm()  # the host ledger IS the authority now
        self.degraded_to_host_engine = True
        if _obs.enabled:
            _obs.counter("device_recovery.degraded").inc()
        warnings.warn(
            f"device dispatch failed {self.device_fault_limit} consecutive "
            f"times ({err!r}); degraded to the native host engine "
            "(device path disabled for this process)",
            RuntimeWarning,
            stacklevel=3,
        )

    # -- host-engine mode (host_engine.py) -----------------------------------

    @property
    def ledger(self):
        """The device (jnp) ledger.  In host-engine mode the numpy mirror is
        the authority; the device view is materialized on first access after
        engine commits (queries, checkpoints, digests, sharding)."""
        if self._engine is not None and self._device_stale:
            self._ledger = self._host_led.to_device()
            self._device_stale = False
        return self._ledger

    @ledger.setter
    def ledger(self, value) -> None:
        if (
            getattr(self, "_shard_mesh", None) is not None
            and getattr(self, "_ledger_is_sharded", False)
            and value is not None
            and np.ndim(value.accounts.count) == 0
        ):
            # External install of a single-layout ledger (checkpoint
            # restore, state sync) while sharded mode is live: re-place it
            # into the owner-partitioned layout.  Internal sharded commits
            # assign sharded values (vector counts) and pass through; the
            # sequential-fallback window flips _ledger_is_sharded off so
            # its single-layout intermediate states also pass through.
            from .parallel import sharded as shard_mod

            value = shard_mod.shard_ledger(value, self._shard_mesh)
            self._refresh_shard_bounds(value)
        self._ledger = value
        self._canon = None
        if getattr(self, "_engine", None) is not None:
            # External ledger swap (checkpoint restore, state sync): refresh
            # the host mirror — it must mirror the new authority exactly.
            from .host_engine import HostLedger

            self._host_led = HostLedger.from_device(value)
            self._engine.ledger = self._host_led
            self._device_stale = False

    def _query_ledger(self):
        """The single-layout ledger view queries/lookups/checkpoints probe:
        identity when sharding is off; under TB_SHARDS a cached canonical
        un-sharding of the live ledger (content-exact, single-device probe
        layout), rebuilt lazily after a commit invalidates it.  Every query
        kernel (index, scans, history, point lookups) and the checkpoint
        serializer thus keep their existing single-device programs."""
        if self._shard_mesh is None or not self._ledger_is_sharded:
            return self.ledger
        if self._canon is None:
            from .parallel import sharded as shard_mod

            self._canon = shard_mod.unshard_ledger(
                self._ledger, self._shard_mesh
            )
            if _obs.enabled:
                _obs.counter("sharding.unshards").inc()
        return self._canon

    def checkpoint_ledger(self):
        """The ledger snapshot checkpoints serialize: canonical single-
        device layout, so a checkpoint restores into ANY shard config (and
        every replica of a homogeneous cluster writes byte-identical
        arrays — the converters are deterministic)."""
        return self._query_ledger()

    def _engine_grow(
        self, accounts: int = 0, transfers: int = 0, posted: int = 0,
        history: int = 0,
    ) -> None:
        """Load-factor management for the host tables (mirror of
        _grow_if_needed, same <= 0.5 policy, same host-side bounds)."""
        led = self._host_led
        for which, need in (
            ("accounts", self._accounts_bound + accounts),
            ("transfers", self._transfers_bound + transfers),
            ("posted", self._posted_bound + posted),
        ):
            cap = self._target_capacity(getattr(led, which).capacity, need)
            if cap != getattr(led, which).capacity:
                self._engine.grow(which, cap)
        if history and self._history_bound + history > led.history_capacity:
            led.grow_history(self._history_bound + history)

    def _engine_commit(
        self, operation: str, batch: np.ndarray, timestamp: int
    ) -> List[Tuple[int, int]]:
        count = len(batch)
        if count == 0:
            return []
        # Invalidate derived views BEFORE dispatching: a partial application
        # (EngineError after some events applied) must not leave queries
        # serving the pre-commit device ledger.
        self._device_stale = True
        self._index_stale = True
        if operation == "create_accounts":
            if bool((batch["flags"] & types.AccountFlags.HISTORY).any()):
                self._history_accounts_possible = True
            if bool((batch["flags"] & _LIMIT_FLAGS).any()):
                self._limit_accounts_possible = True
            self._engine_grow(accounts=count)
            codes = self._engine.create_accounts(batch, timestamp)
            self._accounts_bound += count
        else:
            pv_count, hist_count = self._transfer_growth_counts(batch)
            self._engine_grow(
                transfers=count, posted=pv_count, history=hist_count
            )
            codes = self._engine.create_transfers(batch, timestamp)
            self._transfers_bound += count
            self._posted_bound += pv_count
            self._history_bound += hist_count
        results = self._compress(codes, count)
        self._update_commit_timestamp(codes, count, timestamp)
        return results

    def _index_fresh(self) -> None:
        """Engine commits bypass the per-batch index append; rebuild the
        derived index from the (refreshed) ledger before serving a query."""
        if self._engine is not None and self._index_stale:
            self.index.reset()
            self.scans_transfers.reset()
            self.scans_accounts.reset()
            self._index_stale = False

    def _sanitize_arm_tripwire(self) -> None:
        """TB_SANITIZE: baseline the compile count at a known-legitimate
        compile point (end of warmup, after a growth rehash).  Serving
        dispatches past this point must not compile; _d2h_codes checks."""
        if not self._sanitize:
            return
        from . import jaxenv

        if not jaxenv.instrument_compiles():
            # No listener -> compile_count() is frozen and every delta
            # would be a vacuous 0.  Stay DISARMED (base None) and say so,
            # rather than reporting the serving path compile-free.
            _san._warn_unarmed("serving commit path")
            self._sanitize_compile_base = None
            return
        self._sanitize_compile_base = jaxenv.compile_count()
        self._sanitize_grace = False
        self._sanitize_soft = False

    def _sanitize_absorb_compiles(self) -> None:
        """Fold compiles made by a NON-commit entry point (first lookup/
        query/digest after warmup jit-compiles its kernel) into the
        tripwire baseline: they are first-use compiles of read paths, not
        serving-commit recompiles, and must not be attributed to (or
        strict-raise out of) the next commit's readback."""
        if self._sanitize and self._sanitize_compile_base is not None:
            from . import jaxenv

            self._sanitize_compile_base = jaxenv.compile_count()

    def _sanitize_recompile_check(self, where: str) -> None:
        from . import jaxenv

        cur = jaxenv.compile_count()
        if self._sanitize_grace:
            # First readback after a growth rehash: new capacity = new
            # shape class, its compiles are legitimate.  Re-baseline.
            self._sanitize_grace = False
            self._sanitize_compile_base = cur
            return
        delta = cur - self._sanitize_compile_base
        if delta > 0:
            # Re-baseline FIRST so a strict raise (or a burst of late
            # compiles) reports once, not once per readback.  Strict
            # raising is downgraded to the warning (_sanitize_soft, set at
            # a growth or the history-flag flip) and whenever the device
            # fault domain is armed: scrub/merkle check kernels compile
            # lazily at their first cadence point, post-warmup by design.
            self._sanitize_compile_base = cur
            strict_ok = not (
                self._sanitize_soft
                or self._scrub_mirror is not None
                or self._merkle_forest is not None
            )
            _san.recompile_trip(where, delta, strict_ok=strict_ok)

    def warmup(self) -> None:
        """Force-compile the hot commit kernels with zero-count batches so
        the first client request doesn't pay tens of seconds of jit latency
        (the CLI calls this before announcing ``listening``).  The kernels
        are functional — results are discarded, state is untouched.

        Under TB_SANITIZE the end of warmup arms the serving recompile
        tripwire: from here on, a commit dispatch that compiles is a
        size-class bug (warn; raise under TB_SANITIZE_STRICT).

        In host-engine mode there is nothing to compile; instead pre-fault
        the numpy tables (lazily-mapped pages would otherwise fault inside
        the serving hot loop)."""
        try:
            self._warmup_impl()
        finally:
            self._sanitize_arm_tripwire()

    def _warmup_impl(self) -> None:
        if self._engine is not None:
            self._host_led.prefault()
            return
        if self._ledger_is_sharded:
            # Warm the sharded commit kernels (accounts, fast, the full
            # variant for the current waves setting): one zero-count
            # dispatch each, state value-identical.
            soa_a = self._pad_soa(np.zeros(0, dtype=types.ACCOUNT_DTYPE))
            self.ledger, codes_a = self._shard_steps["accounts"](
                self.ledger, soa_a, jnp.uint64(0), jnp.uint64(1)
            )
            soa_t = self._pad_soa(np.zeros(0, dtype=types.TRANSFER_DTYPE))
            self.ledger, codes_f = self._shard_steps["fast"](
                self.ledger, soa_t, jnp.uint64(0), jnp.uint64(1)
            )
            if self.pipeline_depth > 1 or self.group_device_commit:
                # The async sharded engine dispatches the PROBED sharded
                # step — deferred at depth >= 2 AND blocking grouped runs
                # (commit_group_fast routes through it at any depth); a
                # client must never pay its compile mid-request.  Batch
                # is not donated, so the cached zero template is safe.
                r = self._shard_steps["fast_probed"](
                    self.ledger, soa_t, jnp.uint64(0), jnp.uint64(1)
                )
                self.ledger = r[0]
                np.asarray(r[1]), np.asarray(r[2])
            step = self._shard_steps[
                "full_waves" if self.waves_enabled else "full"
            ]
            r = step(self.ledger, soa_t, jnp.uint64(0), jnp.uint64(1))
            self.ledger = r[0]
            np.asarray(codes_a), np.asarray(codes_f), np.asarray(r[1])
            return
        from .ops import transfer_full as tf

        # The kernels donate the ledger buffers: adopt the returned ledger
        # (a zero-count batch applies nothing, so it is value-identical).
        soa_a = self._pad_soa(np.zeros(0, dtype=types.ACCOUNT_DTYPE))
        self.ledger, codes_a = sm.create_accounts(
            self.ledger, soa_a, jnp.uint64(0), jnp.uint64(1)
        )
        soa_t = self._pad_soa(np.zeros(0, dtype=types.TRANSFER_DTYPE))
        cold_checked = (
            jnp.zeros((self.batch_lanes,), jnp.bool_) if self._tiering else None
        )
        # Warm BOTH reachable serving variants for the CURRENT history
        # flag: dispatch selects (has_postvoid=pv_count>0,
        # has_history=self._history_accounts_possible), so a plain batch
        # and a post/void batch must both find their kernel compiled — a
        # client must never pay a kernel compile inside the serving path.
        # (If a HISTORY account is created later the flag flips and the
        # True-history variants compile on first use; warming them here
        # would charge every history-free server two extra compiles.)
        for has_postvoid in (False, True):
            r = tf.create_transfers_full(
                self.ledger, soa_t, jnp.uint64(0), jnp.uint64(1),
                self._bloom_dev, cold_checked,
                max_passes=self.config.jacobi_max_passes,
                has_postvoid=has_postvoid,
                has_history=self._history_accounts_possible,
                use_waves=self.waves_enabled,
            )
            self.ledger, codes_t, kflags = r[0], r[1], r[2]
        if self._fast_path_ok(np.zeros(0, dtype=types.TRANSFER_DTYPE)):
            # Only pay the extra compile when the fast path is reachable
            # (tiering / restored limit flags / blown balance bound disable
            # it for the process lifetime).
            self.ledger, codes_f = sm.create_transfers_fast(
                self.ledger, soa_t, jnp.uint64(0), jnp.uint64(1)
            )
            np.asarray(codes_f)
            if self.pipeline_depth > 1:
                # The pipelined serving engine dispatches the PROBED
                # variant (overflow rides the codes readback in a fresh
                # buffer); a client must never pay its compile mid-request.
                # It donates its batch, so the cached zero-count template
                # gets a throwaway copy here.
                soa_probe = {k: v.copy() for k, v in soa_t.items()}
                self.ledger, codes_p, _ovf, _il, _ih = (
                    sm.create_transfers_fast_probed(
                        self.ledger, soa_probe, jnp.uint64(0), jnp.uint64(1)
                    )
                )
                np.asarray(codes_p)
            if self.group_device_commit:
                # The grouped dispatch is a distinct program (scan over
                # GROUP_K); a client must never pay its compile mid-group.
                stacked = {
                    key: jnp.stack([v] * self.GROUP_K)
                    for key, v in soa_t.items()
                }
                zeros = jnp.zeros((self.GROUP_K,), jnp.uint64)
                self.ledger, codes_g, _govf, _gil, _gih = (
                    _group_fast_dispatch(self.ledger, stacked, zeros,
                                         zeros + 1)
                )
                np.asarray(codes_g)
        np.asarray(codes_a), np.asarray(codes_t), int(kflags)

    # -- prepare (state_machine.zig:503-512) --------------------------------

    def prepare(self, operation: str, count: int, wall_clock_ns: int = 0) -> int:
        if wall_clock_ns > self.prepare_timestamp:
            self.prepare_timestamp = wall_clock_ns
        if operation in ("create_accounts", "create_transfers"):
            self.prepare_timestamp += count
        return self.prepare_timestamp

    # -- batch plumbing ------------------------------------------------------

    def _pad_soa(self, batch: np.ndarray) -> dict:
        n = len(batch)
        assert n <= self.batch_lanes, "batch exceeds configured lanes"
        if n == 0:
            # Zero-count pads recur on every grouped commit (and warmup):
            # the device columns are immutable, so one cached template
            # replaces a fresh alloc + H2D per batch.  Keyed by
            # (dtype, pipeline depth): each depth's warmup/serving variant
            # set owns its template, so flipping the depth (tests, the CLI
            # --pipeline-depth, a re-warm) never evicts or re-materializes
            # another depth's — and a template handed to a BATCH-DONATING
            # kernel variant must always be copied first
            # (create_transfers_fast_probed's contract).
            key = (batch.dtype, self.pipeline_depth)
            cached = self._pad_soa_zero.get(key)
            if cached is not None and self._sanitize:
                # A template handed to a batch-donating kernel without a
                # copy shows up as nonzero columns HERE, at the next
                # commit — not at the next digest mismatch.
                _san.template_guard(
                    cached, where=f"_pad_soa_zero[{key!r}]"
                )
            if cached is None:
                padded = np.zeros(self.batch_lanes, dtype=batch.dtype)
                cached = {
                    k: jnp.asarray(v) for k, v in types.to_soa(padded).items()
                }
                self._pad_soa_zero[key] = cached
            return cached
        padded = np.zeros(self.batch_lanes, dtype=batch.dtype)
        padded[:n] = batch
        return {k: jnp.asarray(v) for k, v in types.to_soa(padded).items()}

    @staticmethod
    def _compress(codes: np.ndarray, count: int) -> List[Tuple[int, int]]:
        codes = codes[:count]
        idx = np.flatnonzero(codes)
        # tolist() converts both columns to Python ints in one vector pass.
        return list(zip(idx.tolist(), codes[idx].tolist()))

    @staticmethod
    def _has_intra_batch_dup_ids(batch: np.ndarray) -> bool:
        # id 0 lanes can never insert (id_must_not_be_zero), so repeats of 0
        # are not order-dependent duplicates.
        nonzero = (batch["id_lo"] != 0) | (batch["id_hi"] != 0)
        ids = np.stack([batch["id_hi"][nonzero], batch["id_lo"][nonzero]], axis=1)
        return len(np.unique(ids, axis=0)) < len(ids)

    def commit_batch(
        self, operation: str, batch: np.ndarray, timestamp: int
    ) -> List[Tuple[int, int]]:
        """Commit a batch whose prepare timestamp was already assigned (by
        this replica's prepare(), by the primary, or during WAL replay) —
        the replica's StateMachine.commit() seam (state_machine.zig:894-928).
        """
        if operation not in ("create_accounts", "create_transfers"):
            raise ValueError(f"unknown commit operation {operation}")
        # Replay/backup path: keep the local prepare clock >= the primary's.
        if timestamp > self.prepare_timestamp:
            self.prepare_timestamp = timestamp
        # Attribution stage over the WHOLE blocking commit — dispatch +
        # compute + the readback barrier ("kernel dispatch -> completion",
        # obs/txtrace.STAGES) — so the ledger is backend-honest: XLA-CPU
        # executes inside the jitted call, an async backend inside the
        # _d2h_codes wait; both land here.  Free when attribution is off.
        with txtrace.stage("device_execute"):
            if operation == "create_accounts":
                return self._commit_create_accounts(batch, timestamp)
            return self._commit_create_transfers(batch, timestamp)

    # -- create_accounts -----------------------------------------------------

    def create_accounts(
        self, batch: np.ndarray, wall_clock_ns: int = 0
    ) -> List[Tuple[int, int]]:
        timestamp = self.prepare("create_accounts", len(batch), wall_clock_ns)
        return self._commit_create_accounts(batch, timestamp)

    def _commit_create_accounts(
        self, batch: np.ndarray, timestamp: int
    ) -> List[Tuple[int, int]]:
        return self._guarded_commit(
            "create_accounts", batch, timestamp,
            self._commit_create_accounts_impl,
        )

    def _commit_create_accounts_impl(
        self, batch: np.ndarray, timestamp: int
    ) -> List[Tuple[int, int]]:
        count = len(batch)
        if count == 0:
            return []
        if _obs.enabled:
            _obs.histogram("ops.batch_fill_pct", "%").observe(
                100 * count // self.batch_lanes
            )
        if self._engine is not None:
            return self._engine_commit("create_accounts", batch, timestamp)

        any_linked = bool((batch["flags"] & types.AccountFlags.LINKED).any())
        if self.force_sequential or (
            any_linked and self._has_intra_batch_dup_ids(batch)
        ):
            return self._sequential("create_accounts", batch, timestamp)

        self._note_shard_inserts("accounts", batch, count)
        self._grow_if_needed(accounts=count)
        if bool((batch["flags"] & types.AccountFlags.HISTORY).any()):
            if not self._history_accounts_possible and self._sanitize:
                # The has_history=True kernel variants first-compile at
                # the next transfer dispatch (warmup deliberately skips
                # them) — a legitimate compile, not a size-class bug.
                self._sanitize_soft = True
            self._history_accounts_possible = True
        if bool((batch["flags"] & _LIMIT_FLAGS).any()):
            self._limit_accounts_possible = True
        soa = self._pad_soa(batch)
        if self._ledger_is_sharded:
            # Same codes, owner-local inserts (parallel/sharded.py); the
            # probe_overflow check below reads the per-shard lane vector.
            self.ledger, codes = self._shard_steps["accounts"](
                self.ledger, soa, jnp.uint64(count), jnp.uint64(timestamp)
            )
        else:
            self.ledger, codes = sm.create_accounts(
                self.ledger, soa, jnp.uint64(count), jnp.uint64(timestamp)
            )
        codes, overflow = self._d2h_codes(
            codes, self.ledger.accounts.probe_overflow
        )
        self._accounts_bound += count
        if bool(np.any(overflow)):
            # Load-factor management keeps this unreachable; losing inserts
            # silently is the one unacceptable outcome, so fail loud.
            raise RuntimeError("accounts probe overflow during insert")
        self._scan_append_accounts(soa, codes, count)
        results = self._compress(codes, count)
        self._update_commit_timestamp(codes, count, timestamp)
        return results

    # -- create_transfers ----------------------------------------------------

    def create_transfers(
        self, batch: np.ndarray, wall_clock_ns: int = 0
    ) -> List[Tuple[int, int]]:
        timestamp = self.prepare("create_transfers", len(batch), wall_clock_ns)
        return self._commit_create_transfers(batch, timestamp)

    def _commit_create_transfers(
        self, batch: np.ndarray, timestamp: int
    ) -> List[Tuple[int, int]]:
        return self._guarded_commit(
            "create_transfers", batch, timestamp,
            self._commit_create_transfers_impl,
        )

    def _commit_create_transfers_impl(
        self, batch: np.ndarray, timestamp: int
    ) -> List[Tuple[int, int]]:
        count = len(batch)
        if count == 0:
            return []
        if _obs.enabled:
            _obs.histogram("ops.batch_fill_pct", "%").observe(
                100 * count // self.batch_lanes
            )
        if self._engine is not None:
            return self._engine_commit("create_transfers", batch, timestamp)

        self._note_balance_bound(batch)
        if self.force_sequential:
            return self._sequential("create_transfers", batch, timestamp)

        if self._ledger_is_sharded:
            return self._sharded_commit_transfers(batch, timestamp, count)

        if self._fast_path_ok(batch):
            return self._commit_fast(batch, timestamp, count)

        from .ops import transfer_full as tf

        pv_count, hist_count = self._transfer_growth_counts(batch)
        self._grow_if_needed(transfers=count, posted=pv_count, history=hist_count)
        soa = self._pad_soa(batch)
        cold_checked = (
            jnp.zeros((self.batch_lanes,), jnp.bool_) if self._tiering else None
        )
        # STATIC phase hints: a batch with no post/void lanes skips the
        # four pending-side probe loops and the posted write; a ledger that
        # provably holds no HISTORY-flagged account skips the 21-column
        # history append.  Each (hint, hint) pair is its own jit variant.
        has_postvoid = pv_count > 0
        has_history = self._history_accounts_possible
        use_waves = self.waves_enabled
        for _attempt in range(8):
            r = tf.create_transfers_full(
                self.ledger, soa, jnp.uint64(count), jnp.uint64(timestamp),
                self._bloom_dev, cold_checked,
                max_passes=self.config.jacobi_max_passes,
                has_postvoid=has_postvoid, has_history=has_history,
                use_waves=use_waves,
            )
            self.ledger, codes, kflags = r[0], r[1], r[2]
            wave_vec = r[3] if use_waves else None
            # The kflags scalar read IS this path's blocking device sync
            # (the codes transfer below rides an already-complete dispatch).
            kflags, wave_host = self._full_kflags_sync(kflags, wave_vec)
            if kflags == 0:
                results = self._full_commit_success(
                    soa, codes, count, pv_count, hist_count, timestamp,
                    wave_host,
                )
                # Deferred tier rebalance: eviction is only safe BETWEEN
                # batches (mid-loop it would invalidate the certification
                # and the batch's hot gathers).
                self._maybe_evict_between_batches()
                return results
            ev0 = self._evictions
            if kflags & tf.FLAG_COLD:
                # Possible cold-tier ids: resolve exactly on the host,
                # rehydrate any real cold rows into the hot table, and
                # certify the batch so Bloom false positives terminate.
                self._resolve_cold(batch)
                # Any eviction voids the certification: freshly-cold rows
                # must be re-detected by the Bloom on the next attempt.
                cold_checked = (
                    jnp.ones((self.batch_lanes,), jnp.bool_)
                    if self._evictions == ev0
                    else jnp.zeros((self.batch_lanes,), jnp.bool_)
                )
                continue
            if kflags & tf.FLAG_SEQ:
                # Order-dependent batch (balancing / limit accounts / deep
                # intra-batch chains): exact sequential execution.
                return self._sequential("create_transfers", batch, timestamp)
            # Probe overflow despite load management (hash clustering):
            # grow the flagged tables and retry — the kernel applied nothing.
            self._grow_flagged(kflags)
            if self._tiering and self._evictions != ev0 and cold_checked is not None:
                cold_checked = jnp.zeros((self.batch_lanes,), jnp.bool_)
        raise RuntimeError("transfer kernel could not place batch after growth")

    def _full_kflags_sync(self, kflags, wave_vec):
        """The general kernel's blocking commit barrier, shared by the
        single-device and sharded dispatch loops: the kflags scalar read
        (plus the 11-scalar wave profile riding the SAME sync when armed),
        timed so the e2e decomposition sees the device wait."""
        self._injected_fault_check()
        t0 = _time.perf_counter()
        if wave_vec is not None and _obs.enabled:
            got = jax.device_get(  # tblint: ignore[host-sync] commit barrier
                (kflags, wave_vec)
            )
            kflags, wave_host = int(got[0]), got[1]
        else:
            kflags = int(kflags)
            wave_host = None
        wait = _time.perf_counter() - t0
        self.disp_wait_s += wait
        self.disp_count += 1
        if _obs.enabled:
            _obs.counter("ops.dispatch").inc()
            _obs.histogram("ops.dispatch_wait_us", "us").observe(wait * 1e6)
        return kflags, wave_host

    def _full_commit_success(self, soa, codes, count, pv_count, hist_count,
                             timestamp, wave_host):
        """Post-commit bookkeeping of a COMMITTED general-kernel batch
        (kflags == 0), shared by both dispatch loops.  Only committed
        batches feed the wave occupancy series — a routed or retried
        attempt applied nothing and would overstate them."""
        if wave_host is not None:
            self._record_wave_metrics(wave_host)
        codes = np.asarray(codes)
        self._transfers_bound += count
        self._posted_bound += pv_count
        self._history_bound += hist_count
        self._index_append(soa, codes, count)
        results = self._compress(codes, count)
        self._update_commit_timestamp(codes, count, timestamp)
        return results

    def _record_wave_metrics(self, wave_host) -> None:
        """Wave occupancy series (docs/observability.md): wave_host is the
        kernel's int32[11] = (passes, bound, hist[9]) profile vector."""
        passes, bound = int(wave_host[0]), int(wave_host[1])
        hist = [int(v) for v in wave_host[2:]]
        if bound > 0:
            _obs.counter("waves.batches_scheduled").inc()
            _obs.histogram("waves.bound_passes", "passes").observe(bound)
        else:
            _obs.counter("waves.batches_unscheduled").inc()
        _obs.histogram("waves.jacobi_passes", "passes").observe(passes)
        total = sum(hist)
        if total:
            _obs.histogram("waves.wave0_pct", "%").observe(
                100 * hist[0] // total
            )

    def _sharded_commit_transfers(
        self, batch: np.ndarray, timestamp: int, count: int
    ) -> List[Tuple[int, int]]:
        """The sharded live commit path (docs/sharding.md): cross-shard
        transfers settle through a two-phase split inside the jitted step —
        each shard probes/validates its local partition (the debit and
        credit legs of a cross-shard lane resolve on different shards), ONE
        psum-combined context exchange carries every leg's outcome to every
        shard, the pure validation core runs replicated, and balances/
        inserts apply owner-locally.  Result codes and balances are
        byte-identical to the single-device kernels; linked chains, in-batch
        pending refs, and history accounts fall back to the sequential path
        exactly like the wave scheduler's unschedulable exit."""
        from .ops import transfer_full as tf

        if self._tiering or self.cold.count:
            # The mesh kernels carry no bloom, so a cold (evicted) id
            # would silently read as not-found there.  Tiered transfer
            # commits route through the sequential fallback's canonical
            # window, where the existing host-exact cold resolution
            # (_resolve_cold) applies unchanged — correctness over
            # throughput while the tier is active.
            return self._sequential("create_transfers", batch, timestamp)

        self._note_cross_shard(batch, count)
        self._note_shard_inserts("transfers", batch, count)
        cnt, ts = jnp.uint64(count), jnp.uint64(timestamp)
        if self._fast_path_ok(batch):
            self._grow_if_needed(transfers=count)
            soa = self._pad_soa(batch)
            self.ledger, codes = self._shard_steps["fast"](
                self.ledger, soa, cnt, ts
            )
            codes, overflow = self._d2h_codes(
                codes, self.ledger.transfers.probe_overflow
            )
            self._transfers_bound += count
            if bool(np.any(overflow)):
                raise RuntimeError(
                    "transfers probe overflow during fast insert"
                )
            if _obs.enabled:
                _obs.counter("sharding.batches").inc()
            self._index_append(soa, codes, count)
            results = self._compress(codes, count)
            self._update_commit_timestamp(codes, count, timestamp)
            return results

        pv_count, hist_count = self._transfer_growth_counts(batch)
        self._grow_if_needed(
            transfers=count, posted=pv_count, history=hist_count
        )
        soa = self._pad_soa(batch)
        use_waves = self.waves_enabled
        step = self._shard_steps["full_waves" if use_waves else "full"]
        for _attempt in range(8):
            r = step(self.ledger, soa, cnt, ts)
            self.ledger, codes, kflags = r[0], r[1], r[2]
            wave_vec = r[3] if use_waves else None
            kflags, wave_host = self._full_kflags_sync(kflags, wave_vec)
            if kflags == 0:
                if _obs.enabled:
                    _obs.counter("sharding.batches").inc()
                return self._full_commit_success(
                    soa, codes, count, pv_count, hist_count, timestamp,
                    wave_host,
                )
            if kflags & tf.FLAG_SEQ:
                # Order-dependent (linked / balancing-chain / limit
                # cascade), in-batch pending refs, or history accounts:
                # the unschedulable exit.
                return self._sequential("create_transfers", batch, timestamp)
            # No FLAG_COLD on the mesh path (tiering is single-device);
            # remaining bits are probe-overflow growth requests.
            self._grow_flagged(kflags)
        raise RuntimeError(
            "sharded transfer kernel could not place batch after growth"
        )

    def _note_shard_inserts(self, which: str, batch: np.ndarray,
                            count: int):
        """Advance the per-shard attempted-insert bound for ``which`` by
        this batch's id owners (over-approximation, like the global
        bounds: rejected lanes still count).  Called BEFORE the growth
        decision, mirroring the global bound+count discipline.  Returns
        the per-shard owner counts (None off the mesh) — the deferred
        dispatch path records them as pipeline.shard.* lane occupancy."""
        if self._shard_mesh is None or count == 0:
            return None
        from .ops.scrub import mix64_np

        owners = (
            mix64_np(
                batch["id_lo"][:count].astype(np.uint64),
                batch["id_hi"][:count].astype(np.uint64),
            ) & np.uint64(self.shards - 1)
        ).astype(np.int64)
        counts = np.bincount(owners, minlength=self.shards)
        self._shard_insert_bounds[which] += counts
        return counts

    def _refresh_shard_bounds(self, ledger) -> None:
        """Re-floor the per-shard bounds at the actual live per-shard
        counts (external install, sequential-fallback reshard, recovery)
        — the same floor discipline restore_host_state applies to the
        global bounds."""
        if self._shard_mesh is None:
            return
        self._shard_insert_bounds = {
            "accounts": np.asarray(ledger.accounts.count).astype(np.int64),
            "transfers": np.asarray(ledger.transfers.count).astype(np.int64),
        }

    def _note_cross_shard(self, batch: np.ndarray, count: int) -> None:
        """Cross-shard accounting, host-side (one mix64 pass per side): a
        lane whose debit and credit accounts hash to different owners
        settles through the psum leg exchange (docs/sharding.md).  Post/
        void lanes carry zero account ids on both sides and count as
        same-shard — the pending legs they resolve were classified when
        the pending transfer committed."""
        from .ops.scrub import mix64_np

        mask = np.uint64(self.shards - 1)
        dr = mix64_np(
            batch["debit_account_id_lo"].astype(np.uint64),
            batch["debit_account_id_hi"].astype(np.uint64),
        ) & mask
        cr = mix64_np(
            batch["credit_account_id_lo"].astype(np.uint64),
            batch["credit_account_id_hi"].astype(np.uint64),
        ) & mask
        cross = int((dr != cr).sum())
        self.shard_lanes_total += count
        self.shard_lanes_cross += cross
        if _obs.enabled:
            _obs.counter("sharding.lanes").inc(count)
            _obs.counter("sharding.cross_shard_lanes").inc(cross)
            _obs.histogram("sharding.cross_shard_pct", "%").observe(
                100 * cross // max(count, 1)
            )

    # -- online shard split (docs/reconfiguration.md) ------------------------
    #
    # An N -> 2N split executed WHILE SERVING: the old layout keeps
    # committing; between batches the engine ships the owner-changed row
    # subset through the vsr/statesync codec (per-chunk Merkle
    # verification against the source tree), catches up changed slots in
    # delta rounds, and cuts over only after the staged full state passes
    # the whole-state checksum gate AND the new layout's per-shard scrub
    # lanes fold to the canonical digest.  Any verification failure
    # abandons the split and keeps serving the old layout — graceful
    # degradation, never a wedge.  Migration state is volatile by design:
    # a crash mid-migration restarts on the old layout (clean rollback)
    # and the split is simply re-armed.

    @property
    def reshard_active(self) -> bool:
        return self._reshard is not None

    def reshard_begin(
        self, target_shards: int, *, verify: bool = True,
        chunk_rows: int = 512, corrupt_chunks=(), corrupt_persistent=False,
    ) -> bool:
        """Arm an online N -> 2N shard split.  Returns True when the
        migration is armed (idempotent while one is in flight); False —
        counted, logged, never a wedge — when this machine cannot split.
        ``corrupt_chunks``/``corrupt_persistent`` are fault-injection
        hooks (VOPR reconfig kind): flip a byte in the numbered migration
        chunks, transiently or on every retry."""
        if self._reshard is not None:
            return True
        reason = None
        if self.shards < 2 or self._shard_mesh is None:
            reason = "machine is not in sharded mode"
        elif target_shards != self.shards * 2:
            reason = f"{self.shards} -> {target_shards} is not a doubling"
        elif self._engine is not None:
            reason = "host engine is the commit authority"
        elif self._tiering or self.cold.count:
            reason = "cold tier active (evicted rows have no leaves)"
        elif len(jax.devices()) < target_shards:
            reason = (
                f"{target_shards} shards need {target_shards} devices, "
                f"have {len(jax.devices())}"
            )
        else:
            for cap in (self.config.accounts_capacity,
                        self.config.transfers_capacity,
                        self.config.posted_capacity):
                if cap % target_shards:
                    reason = "capacity not divisible by the target shards"
        if reason is not None:
            self.reshard_stats["abandons"] += 1
            if _obs.enabled:
                _obs.counter("reconfig.reshard_abandoned").inc()
            warnings.warn(
                f"shard split refused: {reason} (serving continues on the "
                f"current layout)", RuntimeWarning, stacklevel=2,
            )
            return False
        self.reshard_stats["splits_started"] += 1
        self._reshard = {
            "target": int(target_shards), "verify": bool(verify),
            "chunk_rows": int(chunk_rows), "round": 0, "queue": [],
            "src": None, "trees": None, "wire": None,
            "shipped_leaves": None, "shipped_mask": None, "chunks_sent": 0,
            "corrupt_chunks": set(int(c) for c in corrupt_chunks),
            "corrupt_persistent": bool(corrupt_persistent),
        }
        if _obs.enabled:
            _obs.counter("reconfig.reshard_started").inc()
            _obs.gauge("reconfig.reshard_active").set(1)
        return True

    def reshard_abort(self) -> None:
        """Operator abort: drop the migration, keep serving the old
        layout untouched."""
        if self._reshard is not None:
            self._reshard_abandon("operator abort")

    def reshard_step(self, max_chunks: int = 8) -> str:
        """Advance an active split by up to ``max_chunks`` verified
        migration chunks; call between commit batches (the replica tick /
        VOPR driver seam).  Returns 'idle' (no split), 'migrating',
        'done' (cutover installed this step) or 'abandoned'."""
        rs = self._reshard
        if rs is None:
            return "idle"
        from .vsr import statesync as _ss  # lazy: machine sits below vsr

        for _ in range(max_chunks):
            rs = self._reshard
            if rs is None:
                return "abandoned"
            if not rs["queue"]:
                status = self._reshard_advance()
                if status != "migrating":
                    return status
                continue
            pad, slots = rs["queue"].pop(0)
            tree = rs["trees"][pad]
            cap = _ss.pad_capacity(rs["src"], pad)
            chunk_id = rs["chunks_sent"]
            rows = None
            for attempt in (0, 1):
                corrupt = chunk_id in rs["corrupt_chunks"] and (
                    attempt == 0 or rs["corrupt_persistent"]
                )
                body = _ss.ship_chunk(
                    rs["src"], tree, pad, slots, corrupt=corrupt
                )
                if not rs["verify"]:
                    # Scrub-off negative control: install unaudited.
                    rows = _ss.unpack_rows(rs["src"], pad, slots, body)
                    break
                rows = _ss.verify_chunk(rs["src"], tree, pad, slots, body)
                if rows is not None:
                    break
                self.reshard_stats["chunk_retries"] += 1
                if _obs.enabled:
                    _obs.counter("reconfig.chunk_retries").inc()
            if rows is None:
                return self._reshard_abandon(
                    f"chunk {chunk_id} ({pad}) failed verification twice"
                )
            for k in _ss.per_slot_keys(rs["src"], pad):
                rs["wire"][pad][k][slots] = rows[k]
            # Record the SOURCE leaf as shipped even unaudited: with
            # verification off a corrupted chunk must stay divergent all
            # the way to cutover (the auditor's job to catch), not be
            # silently re-shipped clean next round.
            rs["shipped_leaves"][pad][slots] = tree[cap + slots]
            rs["shipped_mask"][pad][slots] = True
            rs["chunks_sent"] += 1
            self.reshard_stats["chunks"] += 1
            self.reshard_stats["bytes_migrated"] += len(body)
            if _obs.enabled:
                _obs.counter("reconfig.bytes_migrated").inc(len(body))
        return "migrating"

    def _reshard_snapshot(self):
        """Fresh canonical flat-array snapshot + trees (the statesync
        responder's view of THIS machine's live state)."""
        from .vsr import checkpoint as _ckpt
        from .vsr import statesync as _ss

        arrays = {
            k: np.asarray(v)
            for k, v in _ckpt.ledger_to_arrays(self.checkpoint_ledger()).items()
        }
        return arrays, _ss.build_trees(arrays)

    def _reshard_advance(self) -> str:
        """Queue drained: take a fresh snapshot, enqueue the moved slots
        whose leaves changed since their last ship (delta round), or cut
        over when a round comes back empty."""
        from .parallel import sharded as shard_mod
        from .vsr import statesync as _ss

        rs = self._reshard
        arrays, trees = self._reshard_snapshot()
        if rs["src"] is not None and any(
            _ss.pad_capacity(arrays, pad) != _ss.pad_capacity(rs["src"], pad)
            for pad in _ss.PADS
        ):
            # A table grew mid-migration: leaf indexes are incomparable
            # across capacities — restart the split from scratch (counted;
            # the old layout served throughout).
            self.reshard_stats["restarts"] += 1
            if _obs.enabled:
                _obs.counter("reconfig.reshard_restarts").inc()
            rs["wire"] = None
        if rs["wire"] is None:
            rs["wire"] = {
                pad: {
                    k: np.zeros_like(arrays[k])
                    for k in _ss.per_slot_keys(arrays, pad)
                }
                for pad in _ss.PADS
            }
            rs["shipped_leaves"] = {
                pad: np.zeros(_ss.pad_capacity(arrays, pad), np.uint64)
                for pad in _ss.PADS
            }
            rs["shipped_mask"] = {
                pad: np.zeros(_ss.pad_capacity(arrays, pad), bool)
                for pad in _ss.PADS
            }
            rs["round"] = 0
            # Full-transfer baseline the differential protocol is judged
            # against: every live row of every pad.
            self.reshard_stats["bytes_full"] = sum(
                int((
                    (arrays[f"{pad}/key_lo"] | arrays[f"{pad}/key_hi"]) != 0
                ).sum()) * _ss.row_bytes(arrays, pad)
                for pad in _ss.PADS
            )
        queue = []
        for pad in _ss.PADS:
            cap = _ss.pad_capacity(arrays, pad)
            moved = shard_mod.split_moved_mask(
                arrays[f"{pad}/key_lo"], arrays[f"{pad}/key_hi"], self.shards
            )
            leaves = trees[pad][cap:]
            need = moved & (
                ~rs["shipped_mask"][pad]
                | (leaves != rs["shipped_leaves"][pad])
            )
            for piece in _ss.chunk_slots(
                np.nonzero(need)[0], rs["chunk_rows"]
            ):
                queue.append((pad, piece))
        rs["src"], rs["trees"] = arrays, trees
        if not queue:
            return self._reshard_cutover(arrays, trees)
        rs["queue"] = queue
        if rs["round"] > 0:
            self.reshard_stats["catchup_rounds"] += 1
        rs["round"] += 1
        return "migrating"

    def _reshard_cutover(self, arrays, trees) -> str:
        """The cutover rule (docs/reconfiguration.md): staged state =
        stayed rows (never left their device) + wire rows (each chunk
        Merkle-verified); it must pass the whole-state checksum gate, and
        the NEW layout's per-shard scrub lanes must fold to the canonical
        digest, before the swap.  Any gate failure abandons — the old
        layout was never touched."""
        from jax.sharding import Mesh

        from .parallel import sharded as shard_mod
        from .vsr import checkpoint as _ckpt
        from .vsr import statesync as _ss

        rs = self._reshard
        staged = {k: v.copy() for k, v in arrays.items()}
        for pad in _ss.PADS:
            moved = shard_mod.split_moved_mask(
                arrays[f"{pad}/key_lo"], arrays[f"{pad}/key_hi"], self.shards
            )
            slots = np.nonzero(moved)[0]
            for k in _ss.per_slot_keys(arrays, pad):
                staged[k][slots] = rs["wire"][pad][k][slots]
        if rs["verify"] and (
            _ss.arrays_checksum(staged) != _ss.arrays_checksum(arrays)
        ):
            return self._reshard_abandon("whole-state checksum gate failed")
        digest_want = _ss.np_digest(arrays)
        devs = jax.devices()
        new_mesh = Mesh(np.array(devs[: rs["target"]]), (shard_mod.AXIS,))
        new_steps = shard_mod.machine_steps(
            new_mesh, self.config.jacobi_max_passes
        )
        sharded_led = shard_mod.shard_ledger(
            _ckpt.arrays_to_ledger(staged), new_mesh
        )
        # Per-shard commitment gate: the 2N scrub lanes (wrap-add partial
        # folds, one per shard) must sum to the canonical accounts digest.
        lanes = np.asarray(new_steps["scrub"](sharded_led)).astype(np.uint64)
        with np.errstate(over="ignore"):
            got = int(lanes[:, 0].sum(dtype=np.uint64))
        if rs["verify"] and got != digest_want:
            return self._reshard_abandon(
                "per-shard commitment roots do not fold to the canonical "
                "digest"
            )
        old_shards = self.shards
        self.shards = rs["target"]
        self._shard_mesh = new_mesh
        self._shard_steps = new_steps
        self._ledger = sharded_led  # already placed on the new mesh
        self._ledger_is_sharded = True
        self._canon = None
        self._refresh_shard_bounds(sharded_led)
        self._merkle_mark_dirty()
        # First dispatches on the 2N mesh legitimately jit-compile: the
        # TB_SANITIZE recompile tripwire gets the same grace as growth.
        self._sanitize_grace = True
        self._sanitize_soft = True
        self.reshard_stats["splits_completed"] += 1
        audited = rs["verify"]
        self._reshard = None
        if _obs.enabled:
            _obs.counter("reconfig.reshard_completed").inc()
            _obs.gauge("reconfig.reshard_active").set(0)
            _obs.gauge("sharding.shards").set(self.shards)
        if audited:
            # Converter sanity on the audited path only: with verification
            # disabled (the scrub-off negative control) an installed
            # divergence is the AUDITOR's to catch downstream.
            assert int(self.digest()) == digest_want, (
                f"post-cutover digest diverged after {old_shards} -> "
                f"{self.shards} split"
            )
        return "done"

    def _reshard_abandon(self, reason: str) -> str:
        self.reshard_stats["abandons"] += 1
        self._reshard = None
        if _obs.enabled:
            _obs.counter("reconfig.reshard_abandoned").inc()
            _obs.gauge("reconfig.reshard_active").set(0)
        warnings.warn(
            f"shard split abandoned: {reason} (serving continues on the "
            f"{self.shards}-shard layout)", RuntimeWarning, stacklevel=3,
        )
        return "abandoned"

    def _note_balance_bound(self, batch: np.ndarray) -> None:
        """Over-approximate the largest possible single balance field after
        this batch (fast-path precondition P3: the overflow ladder cannot
        fire below 2^126). Non-balancing amounts add at most count * max.
        A balancing lane's clamp is NOT bounded by the pre-batch balance
        (chained balancing lanes in one batch compound against the running
        balance), but it IS capped at u64-max per lane: a zero-amount
        balancing transfer's ceiling is maxInt(u64) (transfer_full.py
        amount0; state_machine.zig:1288), and a nonzero amount is already
        counted under count * max. Ledgers that blow the bound just lose
        the fast path — correctness never depends on it."""
        if self._balance_bound >= _BOUND_CLAMP or len(batch) == 0:
            return
        mx = (int(batch["amount_hi"].max()) << 64) | int(batch["amount_lo"].max())
        n_bal = int((
            (batch["flags"]
             & (types.TransferFlags.BALANCING_DEBIT
                | types.TransferFlags.BALANCING_CREDIT)) != 0
        ).sum())
        self._balance_bound += len(batch) * mx + n_bal * ((1 << 64) - 1)
        if self._balance_bound > _BOUND_CLAMP:
            self._balance_bound = _BOUND_CLAMP

    def _fast_path_ok(self, batch: np.ndarray) -> bool:
        """Plain-transfer batches run the round-1 fast kernel.  Measured
        cost ratio (bench.py run_kernel_profile, XLA-CPU): the general
        kernel is ~2-3x the fast kernel per batch; on TPU the gap is
        expected to widen toward the op-count ratio (the general kernel's
        sorted ladders + Jacobi fixpoint are launch-overhead-bound at 8192
        lanes — see utils/roofline.py OVERHEAD_US).  The preconditions are
        ops/state_machine.py's P1-P4, checked host-side in a few vector ops
        over the batch."""
        if (
            self._tiering
            or self._history_accounts_possible
            or self._limit_accounts_possible
            or self._balance_bound >= (1 << 126)
        ):
            return False
        if bool((batch["flags"] & _SLOW_TRANSFER_FLAGS).any()):
            return False
        if bool(batch["amount_hi"].any()):
            return False
        return True

    # -- grouped device commit ----------------------------------------------

    @property
    def group_device_commit(self) -> bool:
        if self._group_device_commit is None:
            import os

            env = os.environ.get("TB_GROUP_COMMIT")
            self._group_device_commit = (
                env == "1" if env in ("0", "1")
                else jax.default_backend() == "tpu"
            )
        return self._group_device_commit

    @group_device_commit.setter
    def group_device_commit(self, value: bool) -> None:
        self._group_device_commit = value

    @property
    def waves_enabled(self) -> bool:
        """Conflict-index wave scheduler for the general commit kernel
        (TB_WAVES env; DEFAULT ON since the PR 10 soak — the pinned
        regression seed set replayed green under TB_WAVES=1 x TB_SHARDS
        {0, 2}, WAVES_SOAK.json; docs/waves.md records the decision).
        TB_WAVES=0 is bit-for-bit the pre-waves path — the kernel
        compiles the exact pre-waves program.  On, the general kernel
        computes a per-batch conflict index over the touched
        (debit, credit) account slots and commits certified batches after
        a PROVED number of Jacobi passes instead of waiting for the
        stability pass — same codes, same balances (docs/waves.md)."""
        if self._waves_enabled is None:
            import os

            self._waves_enabled = os.environ.get("TB_WAVES", "1") != "0"
        return self._waves_enabled

    @waves_enabled.setter
    def waves_enabled(self, value: bool) -> None:
        self._waves_enabled = bool(value)

    @property
    def fuse_batches(self) -> bool:
        """Cross-batch conflict fusion (TB_FUSE env, default OFF; the CLI's
        --fuse-batches overrides).  Read by the replica's dispatch lane:
        runs of non-conflicting client batches (vsr/overload.plan_fusion's
        admission-time conflict index) fuse into one wider padded dispatch
        on the EXISTING jit size classes.  Off is bit-identical — no
        signature is computed, every run dispatches exactly as before."""
        if self._fuse_batches is None:
            from .vsr import overload

            self._fuse_batches = overload.fusion_enabled()
        return self._fuse_batches

    @fuse_batches.setter
    def fuse_batches(self, value: bool) -> None:
        self._fuse_batches = bool(value)

    @property
    def merkle_async(self) -> bool:
        """Deferred commitment lane (TB_MERKLE_ASYNC env, default OFF; the
        CLI's --merkle-async overrides).  On, committed batches enqueue
        touched-row records instead of paying the O(batch * log cap)
        leaf->root refresh inside the dispatch closure; merkle_settle()
        drains the lane at every point a maintained root is observed
        (scrub check, get_proof, reply-root stamping, merkle_roots), so
        roots remain exactly as certified today — they just no longer
        serialize the commit stream.  Off is bit-identical pre-lane
        behavior.  No-op unless TB_MERKLE is armed."""
        if self._merkle_async is None:
            import os

            self._merkle_async = os.environ.get("TB_MERKLE_ASYNC", "") == "1"
        return self._merkle_async

    @merkle_async.setter
    def merkle_async(self, value: bool) -> None:
        value = bool(value)
        if not value and self._merkle_async and self._merkle_pending:
            # Turning the lane off must not strand queued records (callers
            # toggle at quiescent points: setup, tests, bench arms).
            self.merkle_settle()
        self._merkle_async = value

    @property
    def pipeline_depth(self) -> int:
        """Deferred-readback depth (TB_PIPELINE env, default 2; the CLI's
        --pipeline-depth overrides).  Depth 1 disables deferral — every
        commit blocks on its own codes readback, exactly the pre-pipeline
        serving path; depth >= 2 pipelines one commit group (deeper
        values reserved, currently equivalent to 2)."""
        if self._pipeline_depth is None:
            self._pipeline_depth = pipeline_depth_default()
        return self._pipeline_depth

    @pipeline_depth.setter
    def pipeline_depth(self, value: int) -> None:
        self._pipeline_depth = max(1, int(value))

    def _dispatch_lane(self):
        """The single-thread FIFO executor deferred dispatches run on.

        On backends whose execute BLOCKS the dispatching thread (XLA-CPU:
        jax runs the computation synchronously inside the call), a deferred
        handle alone overlaps nothing — the lane restores the async-
        dispatch property: device execute happens GIL-free on this thread
        while the serving thread journals, stages the next upload, and
        builds replies.  On async backends (TPU) the submit returns as
        soon as the dispatch is enqueued, so the lane adds one cheap hop.
        ONE worker == dispatch order == op order; growth rides each
        closure so the ledger chain never interleaves."""
        if self._lane is None:
            import concurrent.futures

            self._lane = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tb-dispatch"
            )
        return self._lane

    def _lane_dispatch(self, dispatch, deferred):
        """Run (deferred=False) or submit (deferred=True) a commit closure,
        timed as the ``device_execute`` attribution stage: on XLA-CPU the
        jitted calls compute synchronously inside the closure, on an async
        backend the closure is the enqueue and the deferred resolve's
        ``readback`` stage carries the completion wait.  The lane thread's
        stage observations land in the same process-global ledger."""
        if not txtrace.active:
            return (
                self._dispatch_lane().submit(dispatch) if deferred
                else dispatch()
            )

        def staged():
            with txtrace.stage("device_execute"):
                return dispatch()

        return (
            self._dispatch_lane().submit(staged) if deferred else staged()
        )

    # Fixed scan length for the grouped dispatch: ONE jit variant (warmed at
    # startup), groups pad with zero-count batches (the kernel applies
    # nothing for count=0).  An empty step costs ~the kernel's launch-free
    # body (us-scale on TPU); per-batch dispatch through a remote-TPU
    # tunnel costs ~60 ms, so amortizing GROUP_K batches per dispatch is
    # the difference between the device serving path being RTT-bound and
    # kernel-bound.
    GROUP_K = 32

    def _stage_acquire(self):
        """One cached staging buffer set for the grouped H2D upload, from
        the free-list (or freshly allocated when every cached set is still
        referenced by an in-flight dispatch): jax may alias a numpy buffer
        straight into the device transfer (zero-copy on XLA-CPU), so a set
        must never be refilled while a dispatch that reads it is in flight
        — DeviceCommitHandle.resolve releases the set back here."""
        if self._stage_pool:
            return self._stage_pool.pop()
        bufs = {}
        for name in types.TRANSFER_DTYPE.names:
            dt = types.TRANSFER_DTYPE.fields[name][0]
            if dt == np.uint16:
                dt = np.dtype(np.uint32)  # to_soa's widening
            bufs[name] = np.zeros((self.GROUP_K, self.batch_lanes), dt)
        return (bufs, [0] * self.GROUP_K)

    def _stage_release(self, stage) -> None:
        if self._sanitize:
            # Donation poisoning: anything still reading this set after
            # release (the runtime use-after-donate) sees 0xA5 garbage,
            # not stale plausible rows.  Mark every lane dirty so the
            # next _stage_group occupant zeroes its full tail.
            bufs, dirty = stage
            _san.poison(bufs.values())
            for j in range(len(dirty)):
                dirty[j] = self.batch_lanes
        self._stage_pool.append(stage)

    def _stage_group(self, batches: List[np.ndarray]):
        """Staged H2D upload for the grouped dispatch: host-side stack of
        the run's batches into a cached staging buffer set, then ONE
        jax.device_put per field — replacing the previous K x fields
        separate transfers plus a device-side jnp.stack.  Dirty-row
        tracking zeroes only the lanes the set's previous occupant
        touched.  Returns (device columns, staging set) — the caller owns
        the set until its dispatch resolved."""
        stage = self._stage_acquire()
        bufs, dirty = stage
        for name, buf in bufs.items():
            for j in range(self.GROUP_K):
                n = len(batches[j]) if j < len(batches) else 0
                if dirty[j] > n:
                    buf[j, n:dirty[j]] = 0
                if n:
                    buf[j, :n] = batches[j][name]
        for j in range(self.GROUP_K):
            dirty[j] = len(batches[j]) if j < len(batches) else 0
        return (
            {name: jax.device_put(buf) for name, buf in bufs.items()}, stage
        )

    def commit_group_fast(
        self, batches: List[np.ndarray], timestamps: List[int],
        deferred: bool = False,
    ):
        """Commit a RUN of fast-path-eligible create_transfers batches in
        ONE device dispatch (lax.scan over the stacked batches) with ONE
        device->host codes transfer.

        Returns per-batch results index-aligned with ``batches``, or None
        when the run is not groupable — caller falls back to per-batch
        commits.  Scan order == batch order, and each batch carries its
        own already-assigned prepare timestamp, so results are
        bit-identical to committing the run batch by batch.

        ``deferred=True`` returns a DeviceCommitHandle instead of blocking
        on the codes readback: the dispatch is in flight, and the caller
        resolves the handle (in dispatch order) when it needs the results
        — dispatch N+1 then overlaps readback N."""
        if (
            not self.group_device_commit
            or self._engine is not None
            or self.force_sequential
            or not (2 <= len(batches) <= self.GROUP_K)
        ):
            return None
        counts = [len(b) for b in batches]
        if any(c == 0 or c > self.batch_lanes for c in counts):
            return None
        # Eligibility is ORDER-dependent (the balance bound grows per
        # batch): note bounds exactly as the per-batch path would.  On a
        # mid-run refusal, restore the entry bound — the per-batch fallback
        # re-notes every batch itself, and double-counting the prefix would
        # ratchet the monotonic bound toward the 2^126 threshold and
        # permanently cost the fast path (ADVICE r4).
        bound0 = self._balance_bound
        for b in batches:
            self._note_balance_bound(b)
            if not self._fast_path_ok(b):
                self._balance_bound = bound0
                return None
        if timestamps[-1] > self.prepare_timestamp:
            # Replay/backup parity with commit_batch's clock catch-up.
            self.prepare_timestamp = timestamps[-1]
        self._scrub_maybe_check()  # no-op unless armed, due, and lane idle
        if self._ledger_is_sharded:
            # Grouped stacking over the mesh (docs/sharding.md
            # composition): K per-batch shard_map dispatches inside ONE
            # lane closure, ONE deferred readback for the whole run.
            return self._commit_group_fast_sharded(
                batches, timestamps, counts, deferred
            )
        k = len(batches)
        stacked, stage = self._stage_group(batches)
        cnt = jnp.asarray(
            counts + [0] * (self.GROUP_K - k), dtype=jnp.uint64
        )
        tss = jnp.asarray(
            timestamps + [timestamps[-1]] * (self.GROUP_K - k),
            dtype=jnp.uint64,
        )
        # Host row bounds advance at SUBMIT (not readback): the next
        # group's growth decision must see this group's inserts coming,
        # and the closure's growth target is snapshotted HERE so it never
        # depends on how far the serving thread raced ahead.
        need = self._transfers_bound + sum(counts)
        for c in counts:
            self._transfers_bound += c
        # TB_MERKLE_ASYNC: the knob is read ONCE here on the serving
        # thread — the closure must not re-read it at execute time (a
        # toggle racing an in-flight lane would split one run's updates
        # across modes).
        merkle_closure = self._merkle_forest is not None and not self.merkle_async

        def dispatch():
            # Growth + dispatch + index maintenance stay ONE unit so the
            # FIFO lane preserves the ledger chain (the appends need THIS
            # ledger live).
            self._grow_if_needed(transfers_need=need)
            # The ONE-worker FIFO lane orders every ledger write, and the
            # serving thread reads self.ledger only after resolve()'s join
            # (or lane.shutdown(wait=True) in reset paths).
            (self.ledger, codes, overflow,  # tblint: ignore[lane-race] FIFO+join
             id_lo, id_hi) = _group_fast_dispatch(
                self.ledger, stacked, cnt, tss
            )
            for j in range(k):
                self._index_append_device(
                    id_lo[j], id_hi[j], codes[j], counts[j],
                )
            if merkle_closure:
                # Commitment updates ride the ledger chain on the lane,
                # PER BATCH: one key-size class per workload shape, so
                # variable run lengths never hit fresh jit variants
                # mid-serving (concatenating the run would key the update
                # program on k — a compile per distinct run length).
                for j in range(k):
                    self._merkle_update_transfers_batches([batches[j]])
            return codes, overflow

        armed_mirror = self._scrub_mirror is not None
        armed = armed_mirror or self._merkle_forest is not None
        result = self._lane_dispatch(dispatch, deferred)
        handle = DeviceCommitHandle(
            self, result, counts, timestamps, stacked=True, stage=stage,
            # Batch retention feeds mirror recovery re-dispatch; the
            # forest needs no retention (a mismatch escalates to the
            # durable-state rebuild instead).
            batches=list(batches) if armed_mirror else None,
            deferred=deferred,
        )
        if self._merkle_forest is not None and not merkle_closure:
            # Deferred commitment lane: queue the run's touch records on
            # the serving thread; settle barriers replay them in order.
            for b in batches:
                self._merkle_lane_enqueue("create_transfers", b)
        if deferred:
            self._deferred_submitted(sum(counts))
        if armed:
            self._inflight_handles.append(handle)
        if deferred:
            return handle
        return handle.resolve()  # ONE D2H for the whole group

    def _commit_group_fast_sharded(self, batches, timestamps, counts,
                                   deferred):
        """Grouped/deferred commit stacking over the mesh (the async
        sharded engine, docs/sharding.md composition section): the run's
        batches are staged H2D on the serving thread, then ONE dispatch-
        lane closure drives the cached ``sharded.machine_steps``
        fast_probed program once per batch — per-batch shard_map dispatch
        (the scan-grouped single-device program would re-trace per mesh
        layout; the per-shard lanes are the parallelism lever here) with
        the ledger chain threaded through, growth snapshotted at submit,
        and ONE deferred D2H readback (codes + per-shard overflow lanes)
        for the whole run.  Results are bit-identical to committing the
        run batch by batch through the blocking sharded fast path."""
        k = len(batches)
        total = 0
        owner_sum = np.zeros(max(self.shards, 1), np.int64)
        for b, c in zip(batches, counts):
            self._note_cross_shard(b, c)
            owners = self._note_shard_inserts("transfers", b, c)
            if owners is not None:
                owner_sum += owners
            total += c
        soas = [self._pad_soa(b) for b in batches]  # serving-thread staging
        cnts = [jnp.uint64(c) for c in counts]
        tss = [jnp.uint64(t) for t in timestamps]
        # Submit-time growth snapshot (see commit_group_fast / the
        # shard_bounds note in _grow_if_needed).
        need = self._transfers_bound + total
        self._transfers_bound += total
        snap = {name: v.copy()
                for name, v in self._shard_insert_bounds.items()}
        step = self._shard_steps["fast_probed"]
        # Knob read once at submit (see commit_group_fast).
        merkle_closure = self._merkle_forest is not None and not self.merkle_async

        def dispatch():
            self._grow_if_needed(transfers_need=need, shard_bounds=snap)
            codes_out, ovf_out = [], []
            for j in range(k):
                # Same handoff as the single-device closure above: ONE
                # FIFO lane worker, serving-thread reads behind the join.
                self.ledger, codes, overflow = step(  # tblint: ignore[lane-race] FIFO+join
                    self.ledger, soas[j], cnts[j], tss[j]
                )
                self._index_append_device(
                    soas[j]["id_lo"], soas[j]["id_hi"], codes, counts[j]
                )
                if merkle_closure:
                    self._merkle_update_transfers_batches([batches[j]])
                codes_out.append(codes)
                ovf_out.append(overflow)
            if _obs.enabled:
                _obs.counter("sharding.batches").inc(k)
            return tuple(codes_out), tuple(ovf_out)

        armed_mirror = self._scrub_mirror is not None
        armed = armed_mirror or self._merkle_forest is not None
        result = self._lane_dispatch(dispatch, deferred)
        handle = DeviceCommitHandle(
            self, result, list(counts), list(timestamps), stacked=True,
            batches=list(batches) if armed_mirror else None,
            deferred=deferred,
        )
        if self._merkle_forest is not None and not merkle_closure:
            for b in batches:
                self._merkle_lane_enqueue("create_transfers", b)
        if deferred:
            self._deferred_submitted(total, owner_sum)
        if armed:
            self._inflight_handles.append(handle)
        if deferred:
            return handle
        return handle.resolve()  # ONE D2H for the whole run

    def _commit_fast(
        self, batch: np.ndarray, timestamp: int, count: int
    ) -> List[Tuple[int, int]]:
        self._grow_if_needed(transfers=count)
        soa = self._pad_soa(batch)
        self.ledger, codes = sm.create_transfers_fast(
            self.ledger, soa, jnp.uint64(count), jnp.uint64(timestamp)
        )
        # Overflow flag rides the codes readback: one sync, not two.
        codes, overflow = self._d2h_codes(
            codes, self.ledger.transfers.probe_overflow
        )
        self._transfers_bound += count
        if int(overflow):
            # Load-factor management keeps this unreachable; losing inserts
            # silently is the one unacceptable outcome, so fail loud.
            raise RuntimeError("transfers probe overflow during fast insert")
        self._index_append(soa, codes, count)
        results = self._compress(codes, count)
        self._update_commit_timestamp(codes, count, timestamp)
        return results

    def commit_fast_deferred(
        self, batch: np.ndarray, timestamp: int
    ) -> Optional[DeviceCommitHandle]:
        """Dispatch ONE fast-path create_transfers batch and return a
        deferred readback handle, or None when the batch is not fast-path
        eligible (caller falls back to the blocking commit_batch path).

        Semantically identical to the _commit_fast route — same kernel
        body, same codes, same bookkeeping — only the readback timing
        moves: the probed kernel variant carries the overflow flag in a
        fresh output buffer so resolve() works even after a later dispatch
        donated this ledger (see sm.create_transfers_fast_probed; under
        TB_SHARDS the sharded fast_probed step plays the same role with
        per-shard overflow lanes)."""
        count = len(batch)
        if (
            self._engine is not None
            or self.force_sequential
            or count == 0
            or count > self.batch_lanes
        ):
            return None
        bound0 = self._balance_bound
        self._note_balance_bound(batch)
        if not self._fast_path_ok(batch):
            # The blocking fallback re-notes the batch itself; leaving this
            # note in place would double-count it against the monotonic
            # bound (same discipline as commit_group_fast's mid-run
            # refusal).
            self._balance_bound = bound0
            return None
        if timestamp > self.prepare_timestamp:
            # Replay/backup parity with commit_batch's clock catch-up.
            self.prepare_timestamp = timestamp
        self._scrub_maybe_check()  # no-op unless armed, due, and lane idle
        if _obs.enabled:
            _obs.histogram("ops.batch_fill_pct", "%").observe(
                100 * count // self.batch_lanes
            )
        owners = None
        if self._ledger_is_sharded:
            self._note_cross_shard(batch, count)
            owners = self._note_shard_inserts("transfers", batch, count)
        soa = self._pad_soa(batch)  # staged on the serving thread
        cnt, ts = jnp.uint64(count), jnp.uint64(timestamp)
        # Snapshot the growth target pre-submit (see _grow_if_needed).
        need = self._transfers_bound + count
        self._transfers_bound += count
        # Knob read once at submit (see commit_group_fast).
        merkle_closure = self._merkle_forest is not None and not self.merkle_async
        if self._ledger_is_sharded:
            snap = {name: v.copy()
                    for name, v in self._shard_insert_bounds.items()}
            step = self._shard_steps["fast_probed"]

            def dispatch():
                # The sharded probed step donates only the ledger (the
                # replicated batch may alias pooled host buffers); the
                # overflow lanes ride a fresh output.
                self._grow_if_needed(transfers_need=need, shard_bounds=snap)
                self.ledger, codes, overflow = step(self.ledger, soa, cnt, ts)
                self._index_append_device(
                    soa["id_lo"], soa["id_hi"], codes, count
                )
                if merkle_closure:
                    self._merkle_update_transfers_batches([batch])
                if _obs.enabled:
                    _obs.counter("sharding.batches").inc()
                return codes, overflow
        else:
            def dispatch():
                self._grow_if_needed(transfers_need=need)
                # The probed kernel donates BOTH the ledger and the staged
                # SoA (the pad columns become scratch instead of pinned
                # inputs); index maintenance uses the passed-through id
                # columns — the donated ``soa`` dict must not be touched
                # after this call.
                (self.ledger, codes, overflow,  # tblint: ignore[lane-race] FIFO+join
                 id_lo, id_hi) = (
                    sm.create_transfers_fast_probed(self.ledger, soa, cnt, ts)
                )
                self._index_append_device(id_lo, id_hi, codes, count)
                if merkle_closure:
                    # Commitment update rides the ledger chain; keys come
                    # from the retained HOST batch (the staged SoA was
                    # donated above).
                    self._merkle_update_transfers_batches([batch])
                return codes, overflow

        armed_mirror = self._scrub_mirror is not None
        armed = armed_mirror or self._merkle_forest is not None
        fut = self._lane_dispatch(dispatch, True)
        handle = DeviceCommitHandle(
            self, fut, [count], [timestamp], stacked=False,
            batches=[batch] if armed_mirror else None, deferred=True,
        )
        if self._merkle_forest is not None and not merkle_closure:
            self._merkle_lane_enqueue("create_transfers", batch)
        self._deferred_submitted(count, owners)
        if armed:
            self._inflight_handles.append(handle)
        return handle

    def _maybe_evict_between_batches(self) -> None:
        hot_max = self.hot_transfers_capacity_max
        if hot_max is not None and self._transfers_bound * 2 > hot_max and (
            self.ledger.transfers.capacity >= hot_max
        ):
            self.evict_cold()

    # -- cold tier (ops/cold.py) --------------------------------------------

    def _resolve_cold(self, batch: np.ndarray) -> None:
        """Host-exact resolution of a FLAG_COLD batch: rehydrate every cold
        row referenced by id or pending_id into the hot table."""
        ids = {
            (int(r["id_lo"]), int(r["id_hi"])) for r in batch
        } | {
            (int(r["pending_id_lo"]), int(r["pending_id_hi"])) for r in batch
        }
        ids.discard((0, 0))
        found = self.cold.lookup_many(sorted(ids))
        if not found:
            return
        # Skip ids already hot (an earlier rehydration): double-inserting a
        # key would corrupt the hot table's uniqueness invariant.
        keys = sorted(found)
        hot_found, _ = sm.lookup_transfers(
            self.ledger,
            jnp.asarray([k[0] for k in keys], jnp.uint64),
            jnp.asarray([k[1] for k in keys], jnp.uint64),
        )
        hot_found = np.asarray(hot_found)
        rows = [found[k] for k, h in zip(keys, hot_found) if not h]
        if rows:
            self._rehydrate(np.stack(rows).view(types.TRANSFER_DTYPE))

    def _rehydrate(self, rows: np.ndarray) -> None:
        """Insert cold rows back into the hot table (immutable duplicates of
        their cold copies; a later eviction may spill them again)."""
        from .ops import hash_table as ht_mod

        # No eviction here (evictions mid-commit invalidate the batch's
        # certification); a slightly-elevated load factor until the next
        # between-batches rebalance is fine.
        self._grow_if_needed(transfers=len(rows), evict_ok=False)
        n = len(rows)
        lanes = max(self.batch_lanes, 1 << (n - 1).bit_length() if n else 1)
        padded = np.zeros(lanes, dtype=types.TRANSFER_DTYPE)
        padded[:n] = rows
        soa = {k: jnp.asarray(v) for k, v in types.to_soa(padded).items()}
        mask = jnp.arange(lanes) < n
        id_lo, id_hi = soa.pop("id_lo"), soa.pop("id_hi")
        row_cols = {
            name: soa[name].astype(dt)
            for name, dt in sm.TRANSFER_COLS.items()
        }
        transfers, _ = ht_mod.insert(
            self.ledger.transfers, id_lo, id_hi, mask, row_cols,
            self.config.max_probe,
        )
        if bool(np.asarray(transfers.probe_overflow)):
            raise RuntimeError("cold rehydration overflowed the hot table")
        self.ledger = self.ledger.replace(transfers=transfers)
        self._transfers_bound += n
        self._merkle_mark_dirty()  # rows appeared outside a commit batch

    def evict_cold(self, frac: Optional[float] = None) -> int:
        """Spill the oldest ~frac of live hot transfers to the cold store.
        Deterministic given the ledger state; called at checkpoint
        boundaries by the replica, or directly under memory pressure.
        Returns the number of rows evicted."""
        assert self._engine is None, "tiering runs on the device path"
        if self._shard_mesh is not None and self._ledger_is_sharded:
            # Tiering under TB_SHARDS (the long-excluded VOPR scenario,
            # folded back in PR 20): eviction is a canonical-layout
            # concern — pull the ledger single-layout (the _sequential
            # window discipline), run the EXISTING exact eviction
            # unchanged, re-place onto the mesh.  Determinism: both
            # converters and the threshold selection are deterministic,
            # so replicas evicting at the same op boundary stay
            # byte-identical.
            from .parallel import sharded as shard_mod

            self._ledger = shard_mod.unshard_ledger(
                self._ledger, self._shard_mesh
            )
            self._ledger_is_sharded = False
            try:
                return self._evict_cold_impl(frac)
            finally:
                self._ledger = shard_mod.shard_ledger(
                    self._ledger, self._shard_mesh
                )
                self._ledger_is_sharded = True
                self._canon = None
                self._refresh_shard_bounds(self._ledger)
        return self._evict_cold_impl(frac)

    def _evict_cold_impl(self, frac: Optional[float] = None) -> int:
        from .ops import cold as cold_mod

        if not self._tiering:
            self._tiering = True
            self._bloom_np = np.zeros(((1 << self._bloom_log2) // 32,), np.uint32)
        if frac is None:
            frac = self.config.eviction_fraction
        num = max(1, min(999, int(frac * 1000)))
        threshold = cold_mod.eviction_threshold(self.ledger.transfers, num, 1000)
        k = self.ledger.transfers.capacity
        n, key_lo, key_hi, cols = cold_mod.extract_evicted(
            self.ledger.transfers, threshold, k
        )
        rows = cold_mod.rows_to_numpy(n, key_lo, key_hi, cols)
        if len(rows) == 0:
            return 0
        self.cold.append_run(rows)
        self.ledger = self.ledger.replace(
            transfers=cold_mod.drop_evicted(self.ledger.transfers, threshold)
        )
        cold_mod.bloom_add_host(
            self._bloom_np, rows["id_lo"].astype(np.uint64),
            rows["id_hi"].astype(np.uint64),
        )
        self._maybe_grow_bloom()
        self._bloom_dev = jnp.asarray(self._bloom_np)
        self._transfers_bound = max(0, self._transfers_bound - len(rows))
        self._evictions += 1
        self._merkle_mark_dirty()  # rows left the hot table wholesale
        if _obs.enabled:
            # The tier rebalance is this runtime's compaction stage
            # (replica pipeline naming: prefetch/commit/compact/checkpoint).
            _obs.counter("ops.compactions").inc()
            _obs.counter("ops.rows_evicted").inc(len(rows))
        # The query index stores ids (not slots), so it stays valid; row
        # resolution for cold ids happens in get_account_transfers.
        return len(rows)

    def _maybe_grow_bloom(self) -> None:
        """Keep >= ~12 bits per cold id (false-positive rate ~1e-3 at 4
        hashes); rebuild from the runs at the next power of two if not."""
        while self.cold.count * 12 > (1 << self._bloom_log2):
            self._bloom_log2 += 2
            self._bloom_np = self.cold.rebuild_bloom(self._bloom_log2)

    def _transfer_growth_counts(self, batch: np.ndarray) -> Tuple[int, int]:
        """(posted rows, history rows) this batch could append at most —
        host-computable from flags, keeping the posted/history stores from
        growing with plain-transfer volume."""
        pv = int(
            (
                (batch["flags"]
                 & (types.TransferFlags.POST_PENDING_TRANSFER
                    | types.TransferFlags.VOID_PENDING_TRANSFER)) != 0
            ).sum()
        )
        hist = (len(batch) - pv) if self._history_accounts_possible else 0
        return pv, hist

    @staticmethod
    def _target_capacity(capacity: int, needed_rows: int) -> int:
        """Smallest power-of-two capacity keeping load factor <= 0.5."""
        while needed_rows * 2 > capacity:
            capacity *= 2
        return capacity

    def _shard_peak_floor(self, which: str, cap: int, bounds=None) -> int:
        """Under sharding, capacity must also keep the PEAK shard's
        attempted-insert bound under half its cap/n local region — the
        per-shard twin of the global load<=0.5 policy (hash skew can
        overfill one shard while the global count looks fine, and a
        fast-path probe overflow is fatal).

        ``bounds`` overrides the live per-shard bounds: deferred dispatch
        closures pass a submit-time snapshot so the growth moment never
        depends on how far the serving thread raced ahead (the sharded
        twin of the transfers_need snapshot)."""
        if bounds is None:
            bounds = self._shard_insert_bounds
        if self._ledger_is_sharded and which in bounds:
            peak = int(bounds[which].max())
            while peak * 2 > cap // self.shards:
                cap *= 2
        return cap

    def _table_grow(self, table, name: str, capacity: int):
        """ht.grow, layout-aware: a sharded table rehashes per shard
        (owners are the low hash bits, so rows never migrate between
        shards; only local homes change)."""
        from .ops import hash_table as ht

        # Growth rehashes every slot: the commitment forest (whose arrays
        # are capacity-shaped) rebuilds from the grown layout at the next
        # update/check (docs/commitments.md "growth rehash").
        self._merkle_mark_dirty()
        if self._sanitize and self._sanitize_compile_base is not None:
            # The grown capacity is a NEW shape class: the grow kernel and
            # the next commit dispatch legitimately compile.  Open the
            # one-readback grace window, and downgrade strict raising for
            # the rest of this arm period (variants not yet run at the new
            # capacity first-compile arbitrarily later).
            self._sanitize_grace = True
            self._sanitize_soft = True
        if self._ledger_is_sharded:
            from .parallel import sharded as shard_mod

            if _obs.enabled:
                _obs.counter("sharding.grows").inc()
            return shard_mod.grow_sharded_table(
                table, name, capacity, self._shard_mesh
            )
        return ht.grow(table, capacity)

    def _grow_if_needed(
        self, accounts: int = 0, transfers: int = 0, posted: int = 0,
        history: int = 0, evict_ok: bool = True,
        transfers_need: Optional[int] = None, shard_bounds=None,
    ) -> None:
        """Keep every table's load factor under 0.5 using host-side row
        bounds (no device sync; bounds only overestimate).

        ``transfers_need``: an explicit row target snapshotted by the
        caller — the deferred dispatch closures run on the lane thread
        while the serving thread keeps advancing _transfers_bound, so a
        live read here would make the growth moment timing-dependent.
        ``shard_bounds`` is the per-shard twin (a submit-time snapshot of
        _shard_insert_bounds) for the same reason."""
        led = self.ledger
        cap = self._shard_peak_floor("accounts", self._target_capacity(
            led.accounts.capacity, self._accounts_bound + accounts
        ), bounds=shard_bounds)
        if cap != led.accounts.capacity:
            led = led.replace(
                accounts=self._table_grow(led.accounts, "accounts", cap)
            )
        cap = self._shard_peak_floor("transfers", self._target_capacity(
            led.transfers.capacity,
            transfers_need if transfers_need is not None
            else self._transfers_bound + transfers,
        ), bounds=shard_bounds)
        if cap != led.transfers.capacity:
            hot_max = self.hot_transfers_capacity_max
            if hot_max is not None and cap > hot_max and (
                led.transfers.capacity >= hot_max
            ):
                if evict_ok:
                    # At the hot ceiling: spill the old half to the cold
                    # store instead of growing (BASELINE config 4 tiering).
                    self.ledger = led
                    self.evict_cold()
                    led = self.ledger
                # else: accept elevated load until the between-batches
                # rebalance (MAX_PROBE absorbs it).
            else:
                if hot_max is not None:
                    cap = min(cap, max(hot_max, led.transfers.capacity))
                if cap != led.transfers.capacity:
                    led = led.replace(
                        transfers=self._table_grow(
                            led.transfers, "transfers", cap
                        )
                    )
        posted_need = self._posted_bound + posted
        if self._ledger_is_sharded:
            # Posted keys (pending timestamps) are not host-computable per
            # shard; a conservative 2x target (global load <= 0.25) keeps
            # the peak shard's load under 0.5 except at negligible-tail
            # skew, and the full path's claim overflow still grows+retries.
            posted_need *= 2
        cap = self._target_capacity(led.posted.capacity, posted_need)
        if cap != led.posted.capacity:
            led = led.replace(posted=self._table_grow(led.posted, "posted", cap))
        if history and self._history_bound + history > led.history.capacity:
            led = led.replace(
                history=sm.grow_history(led.history, self._history_bound + history)
            )
            if self._sanitize and self._sanitize_compile_base is not None:
                self._sanitize_grace = True  # new history capacity class
                self._sanitize_soft = True
        self.ledger = led

    def _grow_flagged(self, kflags: int) -> None:
        from .ops import transfer_full as tf

        led = self.ledger
        if kflags & tf.FLAG_GROW_ACCOUNTS:
            led = led.replace(
                accounts=self._table_grow(
                    led.accounts, "accounts", led.accounts.capacity * 2
                )
            )
        if kflags & tf.FLAG_GROW_TRANSFERS:
            hot_max = self.hot_transfers_capacity_max
            if hot_max is not None and led.transfers.capacity >= hot_max:
                # Never allocate past the HBM budget the ceiling encodes:
                # make room by spilling instead (certification is reset by
                # the caller via the eviction counter).
                self.ledger = led
                self.evict_cold()
                led = self.ledger
            else:
                led = led.replace(
                    transfers=self._table_grow(
                        led.transfers, "transfers", led.transfers.capacity * 2
                    )
                )
        if kflags & tf.FLAG_GROW_POSTED:
            led = led.replace(
                posted=self._table_grow(
                    led.posted, "posted", led.posted.capacity * 2
                )
            )
        self.ledger = led

    def _sequential(
        self, operation: str, batch: np.ndarray, timestamp: int
    ) -> List[Tuple[int, int]]:
        if self._shard_mesh is not None and self._ledger_is_sharded:
            # The unschedulable exit of the sharded commit path (linked
            # chains, in-batch pending refs, history accounts, deep
            # cascades — exactly the wave scheduler's fallback set): pull
            # the ledger into the canonical single-device layout, run the
            # EXISTING exact sequential path unchanged (growth, bounds,
            # index bookkeeping included — _ledger_is_sharded is off for
            # the window, so every internal self.ledger assignment stays
            # single-layout), then re-place the result onto the mesh.
            # O(rows) host conversions; routed batches are rare by design.
            from .parallel import sharded as shard_mod

            self.shard_seq_fallbacks += 1
            if _obs.enabled:
                _obs.counter("sharding.seq_fallbacks").inc()
            self._ledger = shard_mod.unshard_ledger(
                self._ledger, self._shard_mesh
            )
            self._ledger_is_sharded = False
            try:
                return self._sequential_impl(operation, batch, timestamp)
            finally:
                self._ledger = shard_mod.shard_ledger(
                    self._ledger, self._shard_mesh
                )
                self._ledger_is_sharded = True
                self._canon = None
                self._refresh_shard_bounds(self._ledger)
        return self._sequential_impl(operation, batch, timestamp)

    def _sequential_impl(
        self, operation: str, batch: np.ndarray, timestamp: int
    ) -> List[Tuple[int, int]]:
        from .ops import scan_path

        count = len(batch)
        if _obs.enabled:
            # Order-dependent batches are latency-bound (lax.scan): track
            # how often serving falls off the vectorized kernels.
            _obs.counter("ops.sequential_batches").inc()
        if operation == "create_accounts":
            self._grow_if_needed(accounts=count)
            if bool((batch["flags"] & types.AccountFlags.HISTORY).any()):
                self._history_accounts_possible = True
            if bool((batch["flags"] & _LIMIT_FLAGS).any()):
                self._limit_accounts_possible = True
            pv_count = hist_count = 0
        else:
            if self.cold.count:
                # The scan path only sees the hot table: rehydrate any cold
                # rows this batch references so its semantics stay exact.
                self._resolve_cold(batch)
            pv_count, hist_count = self._transfer_growth_counts(batch)
            self._grow_if_needed(
                transfers=count, posted=pv_count, history=hist_count
            )

        soa = self._pad_soa(batch)
        kernel = (
            scan_path.create_accounts_seq
            if operation == "create_accounts"
            else scan_path.create_transfers_seq
        )
        # The scan path may tombstone slots (linked-chain rollback) — a
        # mutation the touched-key over-approximation cannot see; the
        # commitment forest rebuilds at the next update/check.
        self._merkle_mark_dirty()
        self.ledger, codes = kernel(
            self.ledger, soa, jnp.uint64(count), jnp.uint64(timestamp)
        )
        codes = self._d2h_codes(codes)
        if operation == "create_accounts":
            self._accounts_bound += count
            self._scan_append_accounts(soa, codes, count)
        else:
            self._transfers_bound += count
            self._posted_bound += pv_count
            self._history_bound += hist_count
            self._index_append(soa, codes, count)
        results = self._compress(codes, count)
        self._update_commit_timestamp(codes, count, timestamp)
        return results

    def _index_append_device(self, id_lo, id_hi, codes_dev, count) -> None:
        """_index_append with a device-resident ok mask: runs INSIDE a
        dispatch-lane closure, right after its kernel, where self.ledger is
        guaranteed live (a deferred handle's resolve may run while a later
        dispatch has already donated this ledger's buffers)."""
        if self.config.lazy_index or self._shard_mesh is not None:
            if not self.index.stale:
                self.index.reset()
            self.scans_transfers.reset()
            return
        lane = jnp.arange(self.batch_lanes, dtype=jnp.uint64)
        ok_dev = (codes_dev == 0) & (lane < jnp.uint64(count))
        watching = self._sanitize and self._sanitize_compile_base is not None

        def _index_events():
            return self.index.shape_class_events + sum(
                ix.shape_class_events
                for ix in self.scans_transfers.indexes.values()
            )

        ev0 = _index_events() if watching else 0
        self.index.append_batch(self.ledger, id_lo, id_hi, ok_dev)
        if self.scans_transfers.indexes:
            self.scans_transfers.append_batch(
                self.ledger, id_lo, id_hi, ok_dev
            )
        if watching and _index_events() != ev0:
            # A Bentley–Saxe carry reached a NEW power-of-two level: its
            # first merge/fill legitimately jit-compiles (bounded:
            # log(rows) levels ever).  Same grace as a table growth.
            self._sanitize_grace = True

    def _index_append(self, soa: dict, codes: np.ndarray, count: int) -> None:
        if self.config.lazy_index or self._shard_mesh is not None:
            # Bulk-ingest mode (and sharded mode, whose per-batch appends
            # would otherwise probe the sharded layout with single-device
            # kernels): invalidate instead of maintaining; the next query
            # rebuilds from the canonical table (+cold runs) in one shot.
            if not self.index.stale:
                self.index.reset()
            self.scans_transfers.reset()
            return
        ok = np.zeros(self.batch_lanes, dtype=bool)
        ok[:count] = codes[:count] == 0
        ok_dev = jnp.asarray(ok)
        self.index.append_batch(
            self.ledger, soa["id_lo"], soa["id_hi"], ok_dev
        )
        if self.scans_transfers.indexes:
            self.scans_transfers.append_batch(
                self.ledger, soa["id_lo"], soa["id_hi"], ok_dev
            )

    def _scan_append_accounts(
        self, soa: dict, codes: np.ndarray, count: int
    ) -> None:
        if not self.scans_accounts.indexes:
            return
        if self.config.lazy_index or self._shard_mesh is not None:
            self.scans_accounts.reset()
            return
        ok = np.zeros(self.batch_lanes, dtype=bool)
        ok[:count] = codes[:count] == 0
        self.scans_accounts.append_batch(
            self.ledger, soa["id_lo"], soa["id_hi"], jnp.asarray(ok)
        )

    def _update_commit_timestamp(
        self, codes: np.ndarray, count: int, timestamp: int
    ) -> None:
        ok_lanes = np.nonzero(codes[:count] == 0)[0]
        if len(ok_lanes):
            self.commit_timestamp = timestamp - count + int(ok_lanes[-1]) + 1

    # -- lookups -------------------------------------------------------------

    def lookup_accounts(self, ids: List[int]) -> np.ndarray:
        """Return found accounts as an ACCOUNT_DTYPE array (misses omitted,
        state_machine.zig:1091-1107)."""
        if not ids:
            return np.zeros(0, dtype=types.ACCOUNT_DTYPE)
        if self._engine is not None:
            return self._engine.lookup_accounts(ids)
        lo = jnp.asarray([i & U64_MAX for i in ids], jnp.uint64)
        hi = jnp.asarray([i >> 64 for i in ids], jnp.uint64)
        found, cols = sm.lookup_accounts(self._query_ledger(), lo, hi)
        found = np.asarray(found)
        self._sanitize_absorb_compiles()  # read-path first-use jit
        host = {k: np.asarray(v) for k, v in cols.items()}
        host["reserved"] = np.zeros(len(ids), np.uint32)
        rows = types.from_soa(host, types.ACCOUNT_DTYPE)
        return rows[found]

    def lookup_transfers(self, ids: List[int]) -> np.ndarray:
        if not ids:
            return np.zeros(0, dtype=types.TRANSFER_DTYPE)
        if self._engine is not None:
            found, rows = self._engine.lookup_transfers(ids)
            return rows[found]  # no cold tier in host mode
        lo = jnp.asarray([i & U64_MAX for i in ids], jnp.uint64)
        hi = jnp.asarray([i >> 64 for i in ids], jnp.uint64)
        found, cols = sm.lookup_transfers(self._query_ledger(), lo, hi)
        found = np.asarray(found)
        self._sanitize_absorb_compiles()  # read-path first-use jit
        host = {k: np.asarray(v) for k, v in cols.items()}
        rows = types.from_soa(host, types.TRANSFER_DTYPE)
        if self.cold.count and not found.all():
            # Misses may be cold (evicted): merge rows from the spill,
            # preserving request order.
            out = []
            for i, ident in enumerate(ids):
                if found[i]:
                    out.append(rows[i])
                else:
                    row = self.cold.lookup(ident & U64_MAX, ident >> 64)
                    if row is not None:
                        out.append(row)
            return (
                np.stack(out).view(types.TRANSFER_DTYPE)
                if out else np.zeros(0, dtype=types.TRANSFER_DTYPE)
            )
        return rows[found]

    # -- queries (state_machine.zig:693-892, 1128-1195) ----------------------

    @staticmethod
    def _filter_window(filt: np.void) -> Optional[Tuple[int, int, int, int, bool, int]]:
        """Validate an AccountFilter and resolve its effective window.

        Mirrors get_scan_from_filter (state_machine.zig:823-837): invalid
        filters yield None -> empty results, not errors.  Returns
        (acct_lo, acct_hi, ts_min, ts_max, descending, limit)."""
        acct_lo = int(filt["account_id_lo"])
        acct_hi = int(filt["account_id_hi"])
        ts_min = int(filt["timestamp_min"])
        ts_max = int(filt["timestamp_max"])
        limit = int(filt["limit"])
        flags = int(filt["flags"])
        valid = (
            (acct_lo, acct_hi) != (0, 0)
            and (acct_lo, acct_hi) != (U64_MAX, U64_MAX)
            and ts_min != U64_MAX
            and ts_max != U64_MAX
            and (ts_max == 0 or ts_min <= ts_max)
            and limit != 0
            and flags & (types.AccountFilterFlags.DEBITS | types.AccountFilterFlags.CREDITS)
            and flags & ~0x7 == 0
            and not bytes(filt["reserved"]).strip(b"\0")
        )
        if not valid:
            return None
        # TimestampRange defaults (lsm/timestamp_range.zig:4-5).
        eff_min = ts_min if ts_min != 0 else 1
        eff_max = ts_max if ts_max != 0 else U64_MAX - 1
        descending = bool(flags & types.AccountFilterFlags.REVERSED)
        return acct_lo, acct_hi, eff_min, eff_max, descending, limit

    def get_account_transfers(self, filt: np.void) -> np.ndarray:
        """Transfers on either side of the filtered account, timestamp-ordered
        (prefetch_get_account_transfers, state_machine.zig:693-723).

        Served from the sorted-runs secondary index (ops/index.py): a few
        binary searches + a bounded gather per level — flat in table capacity
        — instead of round 1's full-table argsort."""
        window = self._filter_window(filt)
        if window is None:
            return np.zeros(0, dtype=types.TRANSFER_DTYPE)
        self._index_fresh()
        acct_lo, acct_hi, ts_min, ts_max, descending, limit = window
        flags = int(filt["flags"])
        # Static candidate cap: the next power of two covering the largest
        # reply (one compiled query program per level layout).
        k = 1 << (QUERY_ROWS_MAX - 1).bit_length()
        valid, tid_lo, tid_hi = self.index.query(
            self._query_ledger(),
            jnp.uint64(acct_lo), jnp.uint64(acct_hi),
            jnp.uint64(ts_min), jnp.uint64(ts_max),
            jnp.bool_(bool(flags & types.AccountFilterFlags.DEBITS)),
            jnp.bool_(bool(flags & types.AccountFilterFlags.CREDITS)),
            k,
            bool(descending),
        )
        return self._resolve_transfer_rows(tid_lo, tid_hi, valid, limit)

    def _resolve_transfer_rows(
        self, tid_lo, tid_hi, valid, limit: int
    ) -> np.ndarray:
        """Resolve timestamp-ordered index hits (transfer ids) to wire rows:
        hot-table batch lookup, adjacent-duplicate dedup, cold-spill merge
        (the ScanLookup role, lsm/scan_lookup.zig)."""
        found, cols = sm.lookup_transfers(
            self._query_ledger(), jnp.asarray(tid_lo), jnp.asarray(tid_hi)
        )
        idx_valid = np.asarray(valid)
        found = np.asarray(found)
        # Dedupe repeated index entries for one transfer id (a rebuild can
        # index a rehydrated transfer from both the hot table and its cold
        # run).  Results are timestamp-ordered, so duplicates are adjacent.
        tl_np, th_np = np.asarray(tid_lo), np.asarray(tid_hi)
        if len(tl_np) > 1:
            dup = np.zeros(len(tl_np), dtype=bool)
            dup[1:] = (
                idx_valid[1:] & idx_valid[:-1]
                & (tl_np[1:] == tl_np[:-1]) & (th_np[1:] == th_np[:-1])
            )
            idx_valid = idx_valid & ~dup
        host = {name: np.asarray(col) for name, col in cols.items()}
        out = types.from_soa(host, types.TRANSFER_DTYPE)
        if self.cold.count and bool((idx_valid & ~found).any()):
            # Index hits whose rows were evicted: resolve from the spill,
            # preserving timestamp order.
            merged = []
            for i in range(len(idx_valid)):
                if not idx_valid[i]:
                    continue
                if found[i]:
                    merged.append(out[i])
                else:
                    row = self.cold.lookup(int(tl_np[i]), int(th_np[i]))
                    if row is not None:
                        merged.append(row)
            rows_np = (
                np.stack(merged).view(types.TRANSFER_DTYPE)
                if merged else np.zeros(0, dtype=types.TRANSFER_DTYPE)
            )
            return rows_np[: min(limit, QUERY_ROWS_MAX)]
        return out[idx_valid & found][: min(limit, QUERY_ROWS_MAX)]

    # -- general composed scans (ops/scan_builder.py) ------------------------

    @staticmethod
    def _scan_window(timestamp_min: int, timestamp_max: int) -> Tuple[int, int]:
        # TimestampRange defaults (lsm/timestamp_range.zig:4-5).
        return (
            timestamp_min if timestamp_min else 1,
            timestamp_max if timestamp_max else U64_MAX - 1,
        )

    def scan_transfers(
        self, expr, timestamp_min: int = 0, timestamp_max: int = 0,
        limit: int = QUERY_ROWS_MAX, reversed: bool = False,
    ) -> np.ndarray:
        """Composed index scan over transfers: any ops/scan_builder.py
        expression (prefix conditions on any indexed field, union /
        intersection / difference to any depth), results timestamp-ordered.
        Strictly more general than the reference's implemented surface
        (scan_builder.zig stubs merge_intersection/merge_difference)."""
        self._index_fresh()
        ts_min, ts_max = self._scan_window(timestamp_min, timestamp_max)
        limit = min(limit, QUERY_ROWS_MAX)
        tid_lo, tid_hi = self.scans_transfers.evaluate(
            expr, self._query_ledger(), ts_min, ts_max, limit, bool(reversed)
        )
        if len(tid_lo) == 0:
            return np.zeros(0, dtype=types.TRANSFER_DTYPE)
        # Pad ids to a power of two so the lookup kernel compiles per size
        # class, not per result count.
        n = len(tid_lo)
        cap = 1 << (n - 1).bit_length()
        pad_lo = np.zeros(cap, np.uint64)
        pad_hi = np.zeros(cap, np.uint64)
        pad_lo[:n], pad_hi[:n] = tid_lo, tid_hi
        valid = np.zeros(cap, bool)
        valid[:n] = True
        return self._resolve_transfer_rows(pad_lo, pad_hi, valid, limit)

    def scan_accounts(
        self, expr, timestamp_min: int = 0, timestamp_max: int = 0,
        limit: int = QUERY_ROWS_MAX, reversed: bool = False,
    ) -> np.ndarray:
        """Composed index scan over accounts (accounts are never evicted, so
        resolution is one batched hot-table lookup)."""
        self._index_fresh()
        ts_min, ts_max = self._scan_window(timestamp_min, timestamp_max)
        limit = min(limit, QUERY_ROWS_MAX)
        tid_lo, tid_hi = self.scans_accounts.evaluate(
            expr, self._query_ledger(), ts_min, ts_max, limit, bool(reversed)
        )
        ids = [int(lo) | (int(hi) << 64) for lo, hi in zip(tid_lo, tid_hi)]
        if not ids:
            return np.zeros(0, dtype=types.ACCOUNT_DTYPE)
        # Pad to a power of two so the lookup kernel compiles per size
        # class, not per result count; id 0 can never exist, so the pad
        # lanes drop out as misses.
        cap = 1 << (len(ids) - 1).bit_length()
        return self.lookup_accounts(ids + [0] * (cap - len(ids)))

    def query_transfers_where(
        self, timestamp_min: int = 0, timestamp_max: int = 0,
        limit: int = QUERY_ROWS_MAX, reversed: bool = False, **conditions,
    ) -> np.ndarray:
        """QueryFilter-style multi-field query: the intersection of
        equality conditions on indexed fields (e.g. ``ledger=1, code=5``) —
        the semantics newer upstream exposes as ``query_transfers`` and
        this reference declares but stubs (scan_builder.zig:184-205)."""
        from .ops import scan_builder as sb

        if not conditions:
            raise ValueError("query_transfers_where needs >=1 condition")
        expr = sb.merge_intersection(
            *(sb.scan_prefix(f, v) for f, v in sorted(conditions.items()))
        )
        return self.scan_transfers(
            expr, timestamp_min, timestamp_max, limit, reversed
        )

    def query_accounts_where(
        self, timestamp_min: int = 0, timestamp_max: int = 0,
        limit: int = QUERY_ROWS_MAX, reversed: bool = False, **conditions,
    ) -> np.ndarray:
        from .ops import scan_builder as sb

        if not conditions:
            raise ValueError("query_accounts_where needs >=1 condition")
        expr = sb.merge_intersection(
            *(sb.scan_prefix(f, v) for f, v in sorted(conditions.items()))
        )
        return self.scan_accounts(
            expr, timestamp_min, timestamp_max, limit, reversed
        )

    def get_account_history(self, filt: np.void) -> np.ndarray:
        """Balance history of a HISTORY-flagged account
        (prefetch_get_account_history, state_machine.zig:736-797): empty
        unless the account exists and carries the flag."""
        from .ops import query

        window = self._filter_window(filt)
        if window is None:
            return np.zeros(0, dtype=types.ACCOUNT_BALANCE_DTYPE)
        acct_lo, acct_hi, ts_min, ts_max, descending, limit = window
        account = self.lookup_accounts([acct_lo | (acct_hi << 64)])
        if len(account) == 0 or not (
            int(account[0]["flags"]) & types.AccountFlags.HISTORY
        ):
            return np.zeros(0, dtype=types.ACCOUNT_BALANCE_DTYPE)
        flags = int(filt["flags"])
        qled = self._query_ledger()
        k = min(qled.history.capacity, QUERY_ROWS_MAX)
        valid, rows = query.scan_history(
            qled,
            jnp.uint64(acct_lo), jnp.uint64(acct_hi),
            jnp.uint64(ts_min), jnp.uint64(ts_max),
            jnp.bool_(bool(flags & types.AccountFilterFlags.DEBITS)),
            jnp.bool_(bool(flags & types.AccountFilterFlags.CREDITS)),
            jnp.bool_(descending),
            k,
        )
        valid = np.asarray(valid)
        host = {name: np.asarray(col) for name, col in rows.items()}
        host["reserved"] = np.zeros(len(valid), dtype="V56")
        out = types.from_soa(host, types.ACCOUNT_BALANCE_DTYPE)
        return out[valid][: min(limit, k)]

    # -- checkpoint surface --------------------------------------------------

    def host_state(self) -> dict:
        """Host-tracked state that must survive restarts (checkpointed
        alongside the device ledger)."""
        return {
            "prepare_timestamp": self.prepare_timestamp,
            "commit_timestamp": self.commit_timestamp,
            "accounts_bound": self._accounts_bound,
            "transfers_bound": self._transfers_bound,
            "posted_bound": self._posted_bound,
            "history_bound": self._history_bound,
            "history_accounts_possible": self._history_accounts_possible,
            "limit_accounts_possible": self._limit_accounts_possible,
            "balance_bound": min(self._balance_bound, _BOUND_CLAMP),
            "cold_manifest": self.cold.manifest(),
            "bloom_log2": self._bloom_log2,
        }

    def restore_host_state(self, state: dict) -> None:
        self.prepare_timestamp = int(state["prepare_timestamp"])
        self.commit_timestamp = int(state["commit_timestamp"])
        # Floor the bounds at the live device counts so checkpoints that
        # predate bound tracking still trigger growth correctly (one sync at
        # restart is fine).
        led = self.ledger

        def _count(table) -> int:
            # Layout-agnostic: sharded tables carry per-shard count vectors.
            return int(np.asarray(table.count).sum())

        self._accounts_bound = max(
            int(state.get("accounts_bound", 0)), _count(led.accounts)
        )
        self._transfers_bound = max(
            int(state.get("transfers_bound", 0)), _count(led.transfers)
        )
        self._posted_bound = max(
            int(state.get("posted_bound", 0)), _count(led.posted)
        )
        self._history_bound = max(
            int(state.get("history_bound", 0)), int(np.asarray(led.history.count))
        )
        self._history_accounts_possible = bool(
            state.get("history_accounts_possible", True)
        )
        # Absent fields (older checkpoints) default to "fast path off" —
        # always safe.
        self._limit_accounts_possible = bool(
            state.get("limit_accounts_possible", True)
        )
        self._balance_bound = int(state.get("balance_bound", _BOUND_CLAMP))
        manifest = state.get("cold_manifest", [])
        if manifest:
            # Cold tier under TB_SHARDS is served by the sequential
            # fallback (mesh kernels carry no bloom): commits route
            # through the canonical single-layout window while any row is
            # cold, so a tiered checkpoint restores sharded just fine.
            self._tiering = True
            self.cold.load_manifest(manifest)
            self._bloom_log2 = int(state.get("bloom_log2", self._bloom_log2))
            self._bloom_np = self.cold.rebuild_bloom(self._bloom_log2)
            self._bloom_dev = jnp.asarray(self._bloom_np)
        elif self.cold.runs:
            # Restored to a pre-eviction checkpoint: drop stale in-memory
            # cold state (files stay; older checkpoints may reference them).
            self.cold.clear()
            self._bloom_np = np.zeros(((1 << self._bloom_log2) // 32,), np.uint32)
            self._bloom_dev = jnp.asarray(self._bloom_np)
        # The ledger was just swapped underneath us (restart or state sync):
        # the derived index no longer matches and rebuilds on next use.
        self.index.reset()
        self.scans_transfers.reset()
        self.scans_accounts.reset()
        self._index_stale = False
        if self.scrub_armed:
            # The new ledger is digest-verified by the caller (checkpoint
            # restore / state-sync install): reseed the mirror and/or
            # rebuild the commitment forest from it.
            self.scrub_arm()

    # -- parity surface ------------------------------------------------------

    def balances_snapshot(self) -> List[Tuple[int, int, int, int, int, int]]:
        """(id, dp, dpo, cp, cpo, ts) sorted by id — comparable with
        ReferenceStateMachine.balances_snapshot()."""
        a = self.ledger.accounts
        key_lo = np.asarray(a.key_lo)
        key_hi = np.asarray(a.key_hi)
        live = (key_lo != 0) | (key_hi != 0)
        cols = {k: np.asarray(v)[live] for k, v in a.cols.items()}
        ids = (key_hi[live].astype(object) << 64) | key_lo[live].astype(object)

        def u128_col(name):
            return (cols[name + "_hi"].astype(object) << 64) | cols[
                name + "_lo"
            ].astype(object)

        out = list(
            zip(
                ids,
                u128_col("debits_pending"),
                u128_col("debits_posted"),
                u128_col("credits_pending"),
                u128_col("credits_posted"),
                (int(t) for t in cols["timestamp"]),
            )
        )
        return sorted(
            (int(a_), int(b), int(c), int(d), int(e), int(f))
            for a_, b, c, d, e, f in out
        )

    def digest(self) -> int:
        out = int(sm.ledger_digest(self.ledger))
        self._sanitize_absorb_compiles()  # read-path first-use jit
        return out
