"""tigerbeetle-tpu: a TPU-native double-entry accounting framework.

A ground-up JAX/XLA re-architecture of the capabilities of TigerBeetle
(reference: /root/reference, Zig): the deterministic batch state machine
(accounts, single/two-phase transfers, linked chains, balance limits, queries)
executes as vectorized device kernels over a struct-of-arrays HBM ledger, behind
the same pluggable state-machine seam the reference uses
(state_machine.zig:34 StateMachineType), with VSR-style replication and a
vmapped fault-injection simulator.

u64 integer lanes require x64 mode; enable it before any array is created.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
