"""Observability: unified metrics registry, host+device trace merge, and
VOPR event visualization.

- ``obs.metrics``   process-global counters/gauges/log2-histograms with a
                    JSON snapshot and a StatsD flush bridge;
- ``obs.profile``   ``jax.profiler`` device capture merged with the host
                    tracer's spans into one Chrome/Perfetto trace;
- ``obs.vopr_viz``  the reference's one-line-per-event cluster status grid
                    (docs/internals/testing.md) for simulator finds.

Import ``metrics.registry`` for recording; everything is disabled (and near
zero-cost) until ``TB_METRICS_PATH`` / ``--metrics-json`` / ``enable()``.
"""

from .metrics import registry  # noqa: F401 — the canonical entry point
