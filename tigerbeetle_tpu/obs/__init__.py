"""Observability: unified metrics registry, host+device trace merge, and
VOPR event visualization.

- ``obs.metrics``   process-global counters/gauges/log2-histograms with a
                    JSON snapshot and a StatsD flush bridge;
- ``obs.profile``   ``jax.profiler`` device capture merged with the host
                    tracer's spans into one Chrome/Perfetto trace;
- ``obs.vopr_viz``  the reference's one-line-per-event cluster status grid
                    (docs/internals/testing.md) for simulator finds;
- ``obs.txtrace``   end-to-end causal tracing (sampled u64 trace ids carved
                    into the wire header, cross-replica Perfetto flows),
                    per-commit-batch stage attribution, and the bounded
                    per-replica blackbox flight recorder (docs/tracing.md).

Import ``metrics.registry`` for recording; everything is disabled (and near
zero-cost) until ``TB_METRICS_PATH`` / ``--metrics-json`` / ``enable()``.
"""

from .metrics import registry  # noqa: F401 — the canonical entry point
