"""End-to-end causal tracing, commit-stage attribution, black-box recorder.

The third observability layer (docs/tracing.md), three coupled pieces:

**TRACE** — a u64 trace id carved from the reserved header bytes
(vsr/wire.py's shared frame prefix, offset 64; zero = untraced = the
legacy wire, bit-identical).  Clients stamp it on a sampled fraction of
requests (``TB_TRACE_SAMPLE=1/N``); the replica copies it request ->
prepare -> reply, and every hop on the way — bus ingress, consensus
prepare/ack/commit, the FIFO dispatch lane, the kernel dispatch, the
merkle path refresh, the fsync barrier, the reply release — emits a
cross-process *flow event* into the host tracer's Chrome buffer.  One
request, one causal chain, across all replicas of a SimCluster or a
real cluster_bus deployment, readable in Perfetto as connected arrows.

**ATTRIBUTE** — a per-commit-batch stage ledger.  Each commit stage
(admission_wait, wal_fsync, dispatch_wait, device_execute,
merkle_refresh, readback, reply_release) reports its duration here;
durations land in ``txtrace.stage.*`` registry histograms (when the
registry is on) and accumulate into an in-process total table that
``bench.py`` surfaces as ``payload.attribution`` — the instrument that
names the dominant per_batch_us term (ROADMAP item 2's deferred
commitment lane is tuned against exactly this).

**BLACKBOX** — a bounded per-replica ring of protocol events (command,
view, op, checksums, queue depths, tick) at one-append cost when
enabled, dumped to a postmortem artifact on oracle failure,
``DeviceStateUnrecoverable``, crash-path exits, and on demand.  VOPR
failing seeds write per-replica dumps next to ``vopr_viz_<seed>.txt``.

Cost discipline (obs/metrics.py's): everything starts OFF.  An untraced
request pays one attribute load + branch per hop site; stage sites pay
the same guard before any clock read; a disabled blackbox is ``None``.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, List, Optional

from ..utils.tracer import tracer
from .metrics import registry as _obs

# Synthetic pid base for per-replica rows in the merged Chrome trace: a
# SimCluster runs every replica in one process, but each replica still
# gets its own Perfetto process row (and the flow arrows visibly cross
# them).  Below obs/profile.DEVICE_PID_BASE (1<<20), above real pids'
# typical range is irrelevant — rows are keyed by exact pid value.
REPLICA_PID_BASE = 1 << 18

# The commit pipeline's stage vocabulary, in pipeline order.  Attribution
# blocks and docs/tracing.md list stages in exactly this order.
STAGES = (
    "admission_wait",   # request queued at the bus -> group pickup
    "wal_fsync",        # journal append + fsync barrier
    "dispatch_wait",    # FIFO dispatch-lane queue time
    "device_execute",   # kernel dispatch -> completion
    "merkle_refresh",   # touched-path leaf->root update kernels
    "readback",         # deferred D2H resolve (codes readback)
    "reply_release",    # reply encode + release to the wire
)


def _mix64(x: int) -> int:
    """splitmix64 finalizer: cheap, well-distributed u64 ids."""
    x &= 0xFFFF_FFFF_FFFF_FFFF
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFF_FFFF_FFFF_FFFF
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0xFFFF_FFFF_FFFF_FFFF
    return x ^ (x >> 31)


def parse_sample(spec: str) -> int:
    """``TB_TRACE_SAMPLE`` grammar -> sample period N (0 = off).

    Accepts ``1/N`` (one in N), a bare integer ``N`` (same), or
    empty/``0`` (off).  Malformed values read as off — a typo must not
    take down a server at import time."""
    spec = (spec or "").strip()
    if not spec:
        return 0
    try:
        if "/" in spec:
            num, den = spec.split("/", 1)
            if int(num) != 1:
                return 0
            return max(0, int(den))
        return max(0, int(spec))
    except ValueError:
        return 0


class TxTracer:
    """Process-global trace-id sampler + flow emitter + stage ledger."""

    def __init__(self) -> None:
        self.sample_every = parse_sample(os.environ.get("TB_TRACE_SAMPLE", ""))
        # Attribution accumulation is independent of sampling: bench arms
        # it for every batch (no sampling) while flow tracing stays off.
        self.attribution = False
        self._seq = 0
        self._lock = threading.Lock()
        # name -> [count, total_us]; plain dict + lock (stage sites are
        # hot-path-adjacent, but only ever taken when attribution is on).
        self._stages: Dict[str, List[float]] = {}
        self._pids_named: set = set()

    # -- sampling / ids ------------------------------------------------------

    @property
    def sampling(self) -> bool:
        return self.sample_every > 0

    @property
    def active(self) -> bool:
        """Any stage site should bother reading the clock."""
        return self.attribution or _obs.enabled

    def maybe_trace(self, key: int = 0) -> int:
        """Return a fresh nonzero u64 trace id for a sampled request, or 0.

        Sampling is a counter (every Nth request), so ``1/1`` traces
        everything and a pinned request sequence yields a deterministic
        id stream; the id itself mixes the sequence with ``key`` (e.g.
        the client id) so concurrent clients cannot collide."""
        n = self.sample_every
        if n <= 0:
            return 0
        with self._lock:
            self._seq += 1
            seq = self._seq
        if seq % n:
            return 0
        return _mix64((seq << 20) ^ key) or 1  # force nonzero

    # -- flow events (the causal chain in the merged Chrome trace) -----------

    def _pid_tid(self, replica: Optional[int]):
        pid = (
            REPLICA_PID_BASE + replica if replica is not None
            else os.getpid()
        )
        tid = threading.get_ident() & 0xFFFF
        if replica is not None and pid not in self._pids_named:
            self._pids_named.add(pid)
            tracer.emit({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"replica r{replica}"},
            })
        return pid, tid

    def hop(self, trace: int, name: str, phase: str = "step",
            replica: Optional[int] = None, **args) -> None:
        """One hop of a traced request's causal chain.

        Emits a 1 us slice named ``name`` plus the Chrome flow event
        (``ph s/t/f`` by ``phase`` start/step/end) that links it to the
        other hops carrying the same trace id.  No-op when the tracer is
        off or the frame is untraced (trace == 0)."""
        if not trace or not tracer.enabled:
            return
        pid, tid = self._pid_tid(replica)
        ts = time.perf_counter_ns() / 1e3
        args["trace"] = f"{trace:#x}"
        tracer.emit({
            "name": name, "ph": "X", "cat": "txtrace",
            "ts": ts, "dur": 1.0, "pid": pid, "tid": tid, "args": args,
        })
        flow = {
            "name": "tx", "cat": "txflow",
            "ph": {"start": "s", "step": "t", "end": "f"}[phase],
            "id": trace, "ts": ts + 0.5, "pid": pid, "tid": tid,
        }
        if phase == "end":
            flow["bp"] = "e"
        tracer.emit(flow)

    @contextlib.contextmanager
    def span(self, trace: int, name: str, replica: Optional[int] = None,
             **args):
        """A timed slice bound into a traced request's flow (a hop with
        real duration).  No-op when untraced or the tracer is off."""
        if not trace or not tracer.enabled:
            yield
            return
        pid, tid = self._pid_tid(replica)
        start = time.perf_counter_ns()
        try:
            yield
        finally:
            end = time.perf_counter_ns()
            args["trace"] = f"{trace:#x}"
            ts = start / 1e3
            tracer.emit({
                "name": name, "ph": "X", "cat": "txtrace",
                "ts": ts, "dur": (end - start) / 1e3,
                "pid": pid, "tid": tid, "args": args,
            })
            tracer.emit({
                "name": "tx", "cat": "txflow", "ph": "t",
                "id": trace, "ts": ts + (end - start) / 2e3,
                "pid": pid, "tid": tid,
            })

    # -- stage ledger (attribution) ------------------------------------------

    def stage_observe(self, name: str, us: float) -> None:
        """Record one commit stage duration.  Callers guard on
        ``txtrace.active`` BEFORE reading any clock (cost discipline)."""
        if _obs.enabled:
            _obs.histogram(f"txtrace.stage.{name}", "us").observe(us)
        if self.attribution:
            with self._lock:
                slot = self._stages.get(name)
                if slot is None:
                    slot = self._stages[name] = [0, 0.0]
                slot[0] += 1
                slot[1] += us

    @contextlib.contextmanager
    def stage(self, name: str):
        """Timed stage block; free (no clock read) when inactive."""
        if not self.active:
            yield
            return
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.stage_observe(name, (time.perf_counter_ns() - t0) / 1e3)

    def stage_totals(self) -> Dict[str, dict]:
        """Accumulated {stage: {count, us}} since the last reset."""
        with self._lock:
            return {
                name: {"count": c, "us": round(us, 1)}
                for name, (c, us) in sorted(self._stages.items())
            }

    def reset_stages(self) -> None:
        with self._lock:
            self._stages.clear()

    @contextlib.contextmanager
    def attribution_scope(self, reset: bool = True):
        """Enable the stage ledger for a block, ALWAYS disable on exit
        (the registry's enabled_scope discipline — txtrace is
        process-global too)."""
        if reset:
            self.reset_stages()
        self.attribution = True
        try:
            yield self
        finally:
            self.attribution = False

    @contextlib.contextmanager
    def sampling_scope(self, every: int = 1):
        """Force a sample period for a block (tests/tools), restoring the
        env-derived value on exit."""
        prev = self.sample_every
        self.sample_every = max(0, int(every))
        try:
            yield self
        finally:
            self.sample_every = prev


class Blackbox:
    """Bounded ring of protocol events: the per-replica flight recorder.

    ``record`` is one slot store + one int add (the sim's hot loop calls
    it per protocol event); the ring overwrites oldest-first past ``cap``
    and ``seq`` preserves the true event count, so a dump states exactly
    how much history was lost."""

    __slots__ = ("name", "cap", "seq", "_ring")

    def __init__(self, name: str, cap: int = 512) -> None:
        assert cap > 0
        self.name = name
        self.cap = cap
        self.seq = 0
        self._ring: List[Optional[tuple]] = [None] * cap

    def record(self, event: str, **kw) -> None:
        self._ring[self.seq % self.cap] = (self.seq, event, kw)
        self.seq += 1

    def snapshot(self) -> List[dict]:
        """Retained events, oldest first."""
        start = max(0, self.seq - self.cap)
        out = []
        for i in range(start, self.seq):
            rec = self._ring[i % self.cap]
            if rec is None:  # pragma: no cover — ring invariant
                continue
            seq, event, kw = rec
            out.append({"seq": seq, "ev": event, **kw})
        return out

    def dump_text(self) -> str:
        """One JSON line per retained event, with a provenance header."""
        import json as _json

        events = self.snapshot()
        lost = self.seq - len(events)
        lines = [
            f"# blackbox {self.name}: {self.seq} events recorded, "
            f"{len(events)} retained (cap {self.cap}), {lost} lost",
        ]
        lines.extend(_json.dumps(e, default=str) for e in events)
        return "\n".join(lines) + "\n"


def dump_blackboxes(boxes, directory: str, prefix: str = "blackbox") -> list:
    """Write one ``<prefix>_<name>.txt`` per recorder; returns the paths.
    Best-effort (postmortem paths must never raise over the original
    failure): an unwritable directory yields an empty list."""
    paths = []
    for box in boxes:
        if box is None:
            continue
        path = os.path.join(directory, f"{prefix}_{box.name}.txt")
        try:
            with open(path, "w") as f:
                f.write(box.dump_text())
        except OSError:
            continue
        paths.append(path)
    return paths


# The process-global tracer (the registry/tracer singleton pattern).
txtrace = TxTracer()
