"""Process-global metrics registry: counters, gauges, log2 histograms.

The reference splits observability between typed Tracy spans (src/tracer.zig)
and StatsD emission (src/statsd.zig); the numbers themselves — how many
commits, how long each pipeline stage took, how full each batch was — live in
ad-hoc locals.  This registry is the missing middle layer: every runtime
layer (vsr, net, ops, sim) records into ONE process-global table of named
series, and three sinks read it:

- a JSON snapshot (``TB_METRICS_PATH`` env / ``--metrics-json`` flags) for
  bench artifacts and tools/devhub.py;
- the StatsD bridge (``flush_statsd``), so the existing UDP path keeps
  carrying the new series;
- direct inspection from tests (deterministic bucket layout).

Cost discipline (the reference's build-time ``tracer_backend=none`` spirit,
at runtime): the registry starts DISABLED and every instrumentation site
guards on ``registry.enabled`` before doing any work — including the
``perf_counter_ns`` reads that feed histograms — so a server that never opts
in pays one attribute load + branch per instrumented event, nothing more.
Handles themselves are dumb slots objects (an ``inc`` is one int add); they
are safe to cache across the enabled flag flipping because the flag gates
the *call sites*, not the handles.

Histograms are bounded log2-bucket (64 buckets: bucket b holds values v with
``v.bit_length() == b``, i.e. [2^(b-1), 2^b); bucket 0 holds v <= 0).  Exact
count/sum/min/max ride alongside, so p100 is exact and single-valued series
report exact percentiles; interior percentiles are the bucket midpoint
clamped to [min, max].  Fixed memory per series, no unbounded sample lists —
the same discipline as the tracer's slot cap.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from typing import Dict, Optional

HIST_BUCKETS = 64


class Counter:
    """Monotonic event count.  ``inc`` is intentionally lock-free: a torn
    read-modify-write under free threading loses a sample, which best-effort
    metrics tolerate (statsd.zig drops on EAGAIN for the same reason)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value


class Histogram:
    """Bounded log2-bucket latency/size histogram (module docstring)."""

    __slots__ = ("name", "unit", "buckets", "count", "total", "min", "max")

    def __init__(self, name: str, unit: str = "") -> None:
        self.name = name
        self.unit = unit
        self.buckets = [0] * HIST_BUCKETS
        self.count = 0
        self.total = 0
        self.min: Optional[int] = None
        self.max: Optional[int] = None

    @staticmethod
    def bucket_of(value: int) -> int:
        if value <= 0:
            return 0
        return min(value.bit_length(), HIST_BUCKETS - 1)

    def observe(self, value: float) -> None:
        v = int(value)
        self.buckets[self.bucket_of(v)] += 1
        self.count += 1
        self.total += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v

    def percentile(self, p: float) -> Optional[float]:
        """Deterministic bucket-resolution percentile: the midpoint of the
        bucket containing the ceil(p% * count)-th sample, clamped to the
        exact [min, max] envelope (so p100 == max exactly)."""
        if self.count == 0:
            return None
        rank = max(1, -(-int(p * self.count) // 100))  # ceil(p/100 * count)
        seen = 0
        for b, n in enumerate(self.buckets):
            seen += n
            if seen >= rank:
                if b == 0:
                    mid = 0.0
                else:
                    lo, hi = 1 << (b - 1), (1 << b) - 1
                    mid = (lo + hi) / 2.0
                return float(min(max(mid, self.min), self.max))
        return float(self.max)

    def snapshot(self) -> dict:
        out = {
            "count": self.count,
            "sum": self.total,
            "min": self.min,
            "max": self.max,
            "unit": self.unit,
        }
        if self.count:
            out["p50"] = self.percentile(50)
            out["p95"] = self.percentile(95)
            out["p99"] = self.percentile(99)
            # Sparse bucket map (most of the 64 buckets are empty).
            out["buckets"] = {
                str(b): n for b, n in enumerate(self.buckets) if n
            }
        return out


class Registry:
    """The process-global series table (module docstring)."""

    def __init__(self, enabled: bool = False) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        # Counter values as of the last statsd flush (deltas are emitted).
        self._statsd_sent: Dict[str, int] = {}

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    @contextlib.contextmanager
    def enabled_scope(self, reset: bool = True):
        """Context manager: enable for the block, ALWAYS disable (and by
        default reset) on exit.  The registry is process-global, so a
        leaked enable() taxes every later test and mixes foreign series
        into the next snapshot — the PR 10 leak class the TB_SANITIZE
        registry guard (sanitize.assert_registry_disabled) and the
        autouse test fixture now police.  Use this instead of a bare
        enable() in tests and tools."""
        if reset:
            self.reset()
        self.enable()
        try:
            yield self
        finally:
            self.disable()
            if reset:
                self.reset()

    def reset(self) -> None:
        """Drop every series (tests; the registry is process-global)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._statsd_sent.clear()

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str, unit: str = "") -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name, unit))
        return h

    # -- sinks ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-ready dict of every series (sorted: deterministic)."""
        with self._lock:
            return {
                "counters": {
                    name: c.value
                    for name, c in sorted(self._counters.items())
                },
                "gauges": {
                    name: g.value for name, g in sorted(self._gauges.items())
                },
                "histograms": {
                    name: h.snapshot()
                    for name, h in sorted(self._histograms.items())
                },
            }

    def dump(self, path: str) -> dict:
        """Write the snapshot as JSON; returns it."""
        snap = self.snapshot()
        with open(path, "w") as f:
            json.dump(snap, f, indent=1)
        return snap

    def flush_statsd(self, statsd) -> None:
        """Bridge the registry onto the existing UDP path
        (utils/statsd.StatsD): counters as deltas since the last flush,
        gauges as gauges, histogram p50/p95/p99 as timing samples.  Never
        raises, never blocks (the StatsD socket is non-blocking).

        The delta watermark (_statsd_sent) is claimed under the lock, so
        concurrent flushes cannot double-emit a delta.  It is registry-
        global: the bridge assumes ONE StatsD sink per process (the CLI
        wires exactly one); multiple distinct sinks would split the deltas
        between them."""
        if statsd is None:
            return
        with self._lock:
            deltas = []
            for name, c in sorted(self._counters.items()):
                value = c.value
                delta = value - self._statsd_sent.get(name, 0)
                if delta:
                    self._statsd_sent[name] = value
                    deltas.append((name, delta))
            gauges = [(n, g.value) for n, g in sorted(self._gauges.items())]
            hists = [
                (n, h.snapshot())
                for n, h in sorted(self._histograms.items())
            ]
        for name, delta in deltas:
            statsd.count(name, delta)
        for name, value in gauges:
            statsd.gauge(name, value)
        for name, h in hists:
            for pct in ("p50", "p95", "p99"):
                if h.get(pct) is not None:
                    statsd.timing(f"{name}.{pct}", h[pct])


# The process-global registry (the reference's comptime-global tracer/statsd
# pattern).  TB_METRICS_PATH enables it at import and dumps at exit;
# --metrics-json flags (cli.py, bench.py) enable it programmatically.
registry = Registry(enabled=bool(os.environ.get("TB_METRICS_PATH")))

if registry.enabled:
    import atexit

    @atexit.register
    def _dump_at_exit() -> None:
        path = os.environ.get("TB_METRICS_PATH", "tb_metrics.json")
        try:
            registry.dump(path)
        except OSError:
            return
        print(f"metrics: wrote snapshot to {path}",
              file=__import__("sys").stderr)
