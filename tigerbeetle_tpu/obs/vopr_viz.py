"""VOPR cluster visualization: one line per state-change, a column per node.

The reference simulator prints a per-event cluster grid (one character per
replica plus the event) so a failing seed reads as a story instead of an
opaque number (docs/internals/testing.md "cluster visualization").  This is
that grid for sim/cluster.SimCluster: each sampled tick where anything
changed emits one line with a fixed-width cell per node —

    status symbol, view : commit_min / op

Symbols:
    *  primary (status normal)
    .  backup  (status normal)
    v  view change
    r  recovering
    !  log_suspect (certification pending — promoted standby, state sync)
    s  standby (non-voting stream consumer)
    x  crashed / not running
    -  retired (promoted-away standby index)

The recorder is strictly read-only over the cluster (no rng draws, no state
mutation), so enabling it cannot shift a seed's fault schedule — the same
discipline as the hash-log oracle.  The line buffer is bounded; when full,
the OLDEST lines drop (the tail — where the failure is — is what matters).
"""

from __future__ import annotations

import collections
from typing import List, Optional

CELL_WIDTH = 14

LEGEND = (
    "legend: * primary  . backup  v view-change  r recovering  "
    "! log-suspect  s standby  x down  - retired;  "
    "cell = symbol view : commit_min / op "
    "(+Sn = n device scrub/dispatch recoveries)"
)


def status_symbol(replica, alive: bool, is_standby: bool) -> str:
    if replica is None or not alive:
        return "x"
    if getattr(replica, "_log_suspect", False):
        return "!"
    status = getattr(replica, "status", "normal")
    if status == "view_change":
        return "v"
    if status == "recovering":
        return "r"
    if is_standby:
        return "s"
    if getattr(replica, "is_primary", False):
        return "*"
    return "."


def node_cell(replica, alive: bool, is_standby: bool) -> str:
    sym = status_symbol(replica, alive, is_standby)
    if replica is None or not alive:
        return sym
    cell = (
        f"{sym}{getattr(replica, 'view', 0)}"
        f":{getattr(replica, 'commit_min', 0)}"
        f"/{getattr(replica, 'op', 0)}"
    )
    # Device fault domain events (docs/fault_domains.md): a replica that
    # detected SDC or survived a dispatch failure shows its recovery count
    # — the grid line where +Sn first appears IS the recovery tick.
    machine = getattr(replica, "machine", None)
    recoveries = getattr(machine, "device_recoveries", 0)
    if recoveries:
        cell += f"+S{recoveries}"
    return cell


class ClusterViz:
    """Bounded recorder of cluster state-change lines (module docstring)."""

    def __init__(self, max_lines: int = 4000) -> None:
        self.lines: collections.deque = collections.deque(maxlen=max_lines)
        self.dropped = 0
        self._last_cells: Optional[List[str]] = None
        self._n_nodes = 0
        self._n_voters = 0

    def sample(self, cluster) -> None:
        """Record one line if any node's cell changed since the last sample
        (one line per cluster-visible event, not per tick)."""
        self._n_nodes = cluster.total
        self._n_voters = cluster.n
        cells = [
            node_cell(
                cluster.replicas[i], cluster.alive[i], i >= cluster.n
            )
            for i in range(cluster.total)
        ]
        if cells == self._last_cells:
            return
        self._last_cells = cells
        if len(self.lines) == self.lines.maxlen:
            self.dropped += 1
        self.lines.append(
            f"{cluster.t:>7}  "
            + "".join(c.ljust(CELL_WIDTH) for c in cells).rstrip()
        )

    def render(self) -> str:
        header = "".join(
            (f"r{i}" if i < self._n_voters else f"s{i}").ljust(CELL_WIDTH)
            for i in range(self._n_nodes)
        ).rstrip()
        out = [LEGEND, f"{'tick':>7}  {header}"]
        if self.dropped:
            out.append(f"  ... {self.dropped} older lines dropped ...")
        out.extend(self.lines)
        return "\n".join(out)
