"""Host+device trace unification: one Chrome/Perfetto JSON for both worlds.

The host side already records Chrome ``trace_event`` spans
(utils/tracer.py — commit, journal_write, checkpoint, ...).  The device side
is captured by ``jax.profiler``, which writes its XLA/TPU timeline as
gzipped Chrome traces (``plugins/profile/<run>/*.trace.json.gz``).  The two
use different clocks: the tracer stamps ``perf_counter_ns``-derived
microseconds, the profiler stamps its own capture-relative epoch.  This
module captures both over the same wall window and rebases the device
events onto the host clock, so a ``state_machine_commit`` span lines up with
the XLA dispatch it triggered — the Tracy-capture experience
(src/tracer.zig's backend) for the TPU runtime.

Alignment method: ``DeviceCapture`` records the host clock at capture start;
on merge, device timestamps are shifted so the earliest device event lands
at that instant.  This is start-anchored (no cross-clock drift correction),
which over bench-scale windows (seconds) keeps span/dispatch adjacency
legible; it is a visualization aid, not a measurement.

Degradation: every profiler interaction is best-effort.  If the platform
has no profiler (or capture fails mid-run), the merge still writes the host
events — a trace with one world beats no trace.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import time
from typing import List, Optional

# Offset added to device pids in the merged trace so a device track can
# never collide with (and silently interleave into) the host process row.
DEVICE_PID_BASE = 1 << 20

# Device event budget for the merged file.  The XLA profiler records EVERY
# op execution — a seconds-long CPU run yields ~1M events and a >100 MB
# JSON no tool opens happily.  Over budget, the longest-duration events
# survive (they are the structure: loops, fusions, dispatches; the dropped
# tail is micro-ops) — the same bounded-buffer discipline as the tracer's
# slot cap, and the drop is reported in the merge stats.
DEVICE_EVENTS_MAX = 200_000


class DeviceCapture:
    """Context manager around ``jax.profiler`` start/stop_trace.

    ``enabled=False`` (or any profiler failure) degrades to a no-op whose
    ``events()`` is empty.  ``host_t0_us`` is the host-tracer-clock instant
    of capture start, used by ``merge`` to rebase device timestamps."""

    def __init__(self, log_dir: str, enabled: bool = True) -> None:
        self.log_dir = log_dir
        self.enabled = enabled
        self.active = False
        self.host_t0_us: Optional[float] = None
        self.error: Optional[str] = None

    def __enter__(self) -> "DeviceCapture":
        if not self.enabled:
            return self
        try:
            import jax.profiler

            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self.active = True
            self.host_t0_us = time.perf_counter_ns() / 1e3
        except Exception as err:  # noqa: BLE001 — capture is best-effort
            # (profiler unavailable on this backend / another trace active);
            # the merged output then carries host events only.
            self.error = f"{type(err).__name__}: {err}"
            self.active = False
        return self

    def __exit__(self, *exc) -> None:
        if not self.active:
            return
        self.active = False
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception as err:  # noqa: BLE001 — see __enter__
            self.error = f"{type(err).__name__}: {err}"

    def events(self) -> List[dict]:
        return load_device_events(self.log_dir)


def load_device_events(log_dir: str) -> List[dict]:
    """Collect Chrome trace events from every ``*.trace.json.gz`` the
    profiler wrote under ``log_dir`` (best-effort: unreadable files skip)."""
    events: List[dict] = []
    pattern = os.path.join(log_dir, "**", "*.trace.json.gz")
    for path in sorted(glob.glob(pattern, recursive=True)):
        try:
            with gzip.open(path, "rt") as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        events.extend(data.get("traceEvents") or [])
    return events


def merge(
    host_events: List[dict],
    device_events: List[dict],
    out_path: str,
    host_t0_us: Optional[float] = None,
    device_events_max: int = DEVICE_EVENTS_MAX,
) -> dict:
    """Write one Chrome trace combining host spans and device events.

    Device timestamps are rebased so the earliest device event lands at
    ``host_t0_us`` (capture start on the host tracer clock); metadata
    events (``ph == "M"``, no ``ts``) pass through unshifted.  Device pids
    are offset by DEVICE_PID_BASE; device events beyond the budget drop
    shortest-first (DEVICE_EVENTS_MAX).  Returns ``{"events",
    "host_events", "device_events", "device_events_dropped"}`` counts."""
    merged: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": os.getpid(),
        "args": {"name": "host (tigerbeetle-tpu tracer)"},
    }]
    merged.extend(host_events)

    meta = [e for e in device_events if "ts" not in e]
    timed = [e for e in device_events if "ts" in e]
    dropped = 0
    if len(timed) > device_events_max:
        timed.sort(key=lambda e: e.get("dur", 0.0), reverse=True)
        dropped = len(timed) - device_events_max
        timed = timed[:device_events_max]
        timed.sort(key=lambda e: e["ts"])
    shift = 0.0
    if timed and host_t0_us is not None:
        shift = host_t0_us - min(e["ts"] for e in timed)
    for e in meta + timed:
        e = dict(e)
        if "ts" in e:
            e["ts"] = e["ts"] + shift
        if "pid" in e and isinstance(e["pid"], int):
            e["pid"] = e["pid"] + DEVICE_PID_BASE
        merged.append(e)

    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
    return {
        "events": len(merged),
        "host_events": len(host_events),
        "device_events": len(meta) + len(timed),
        "device_events_dropped": dropped,
    }


def merge_with_tracer(capture: DeviceCapture, out_path: str) -> dict:
    """Drain the process-global host tracer into a merged trace with
    ``capture``'s device events.  Draining (not copying) hands ownership of
    the events to the merged file — the tracer's own at-exit dump then sees
    an empty buffer and skips, so the merged trace is never overwritten by
    a host-only one."""
    from ..utils.tracer import tracer

    host_events = tracer.drain()
    stats = merge(
        host_events, capture.events(), out_path,
        host_t0_us=capture.host_t0_us,
    )
    if capture.error:
        stats["device_capture_error"] = capture.error
    return stats
