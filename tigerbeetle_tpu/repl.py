"""REPL: interactive/one-shot statement parser -> client requests.

Statement grammar follows the reference repl (src/repl.zig): an operation name
followed by ``field=value`` pairs, ``;``-terminated, with ``|``-combined flag
names and multiple objects per statement separated by ``,``:

    create_accounts id=1 code=10 ledger=700, id=2 code=10 ledger=700;
    create_transfers id=1 debit_account_id=1 credit_account_id=2 amount=10
                     ledger=700 code=10 flags=linked|pending;
    lookup_accounts id=1;
    get_account_transfers account_id=1 flags=debits|credits limit=10;
"""

from __future__ import annotations

import shlex
import sys
from typing import Dict, List, Optional

import numpy as np

from . import types
from .client import Client
from .vsr import wire

_ACCOUNT_FLAGS = {
    "linked": types.AccountFlags.LINKED,
    "debits_must_not_exceed_credits": types.AccountFlags.DEBITS_MUST_NOT_EXCEED_CREDITS,
    "credits_must_not_exceed_debits": types.AccountFlags.CREDITS_MUST_NOT_EXCEED_DEBITS,
    "history": types.AccountFlags.HISTORY,
}

_TRANSFER_FLAGS = {
    "linked": types.TransferFlags.LINKED,
    "pending": types.TransferFlags.PENDING,
    "post_pending_transfer": types.TransferFlags.POST_PENDING_TRANSFER,
    "void_pending_transfer": types.TransferFlags.VOID_PENDING_TRANSFER,
    "balancing_debit": types.TransferFlags.BALANCING_DEBIT,
    "balancing_credit": types.TransferFlags.BALANCING_CREDIT,
}

_FILTER_FLAGS = {
    "debits": types.AccountFilterFlags.DEBITS,
    "credits": types.AccountFilterFlags.CREDITS,
    "reversed": types.AccountFilterFlags.REVERSED,
}

OPERATIONS = (
    "create_accounts", "create_transfers", "lookup_accounts",
    "lookup_transfers", "get_account_transfers", "get_account_history",
    "get_proof",
)


def _parse_flags(value: str, table: Dict[str, int]) -> int:
    out = 0
    for name in value.split("|"):
        name = name.strip()
        if name not in table:
            raise ValueError(f"unknown flag {name!r} (expected {sorted(table)})")
        out |= int(table[name])
    return out


def _parse_objects(tokens: List[str]) -> List[Dict[str, str]]:
    """Split `k=v` tokens into objects at `,` boundaries."""
    objects: List[Dict[str, str]] = [{}]
    for token in tokens:
        while token.endswith(","):
            token = token[:-1]
            if token:
                objects[-1].update(_pair(token))
            objects.append({})
            token = ""
        if token:
            objects[-1].update(_pair(token))
    return [obj for obj in objects if obj]


def _pair(token: str) -> Dict[str, str]:
    if "=" not in token:
        raise ValueError(f"expected field=value, got {token!r}")
    key, value = token.split("=", 1)
    return {key.strip(): value.strip()}


def parse_statement(statement: str):
    """Parse one statement -> (operation, list-of-field-dicts)."""
    statement = statement.strip().rstrip(";").strip()
    if not statement:
        return None
    tokens = shlex.split(statement)
    operation = tokens[0]
    if operation not in OPERATIONS:
        raise ValueError(
            f"unknown operation {operation!r} (expected one of {OPERATIONS})"
        )
    return operation, _parse_objects(tokens[1:])


def build_accounts(objects: List[Dict[str, str]]) -> np.ndarray:
    rows = []
    for obj in objects:
        kwargs = {}
        for key, value in obj.items():
            if key == "flags":
                kwargs["flags"] = _parse_flags(value, _ACCOUNT_FLAGS)
            else:
                kwargs[key] = int(value, 0)
        rows.append(types.account(**kwargs))
    return types.accounts_array(rows)


def build_transfers(objects: List[Dict[str, str]]) -> np.ndarray:
    rows = []
    for obj in objects:
        kwargs = {}
        for key, value in obj.items():
            if key == "flags":
                kwargs["flags"] = _parse_flags(value, _TRANSFER_FLAGS)
            else:
                kwargs[key] = int(value, 0)
        rows.append(types.transfer(**kwargs))
    return types.transfers_array(rows)


def build_filter(objects: List[Dict[str, str]]) -> np.ndarray:
    assert len(objects) == 1, "account filters take exactly one object"
    obj = objects[0]
    rec = np.zeros((), dtype=types.ACCOUNT_FILTER_DTYPE)
    for key, value in obj.items():
        if key == "account_id":
            rec["account_id_lo"] = int(value, 0) & ((1 << 64) - 1)
            rec["account_id_hi"] = int(value, 0) >> 64
        elif key == "flags":
            rec["flags"] = _parse_flags(value, _FILTER_FLAGS)
        else:
            rec[key] = int(value, 0)
    if int(rec["limit"]) == 0:
        rec["limit"] = 8190
    if int(rec["flags"]) == 0:
        rec["flags"] = int(
            types.AccountFilterFlags.DEBITS | types.AccountFilterFlags.CREDITS
        )
    return rec


def _format_row(row: np.void, fields) -> str:
    parts = []
    for name in fields:
        if name.endswith("_lo"):
            base = name[:-3]
            value = (int(row[base + "_hi"]) << 64) | int(row[name])
            parts.append(f"{base}={value}")
        elif name.endswith("_hi") or name == "reserved":
            continue
        else:
            parts.append(f"{name}={int(row[name])}")
    return "  " + " ".join(parts)


def execute_statement(client: Client, statement: str, out=sys.stdout) -> None:
    parsed = parse_statement(statement)
    if parsed is None:
        return
    operation, objects = parsed
    if operation == "create_accounts":
        results = client.create_accounts(build_accounts(objects))
        _print_results(results, types.CreateAccountResult, out)
    elif operation == "create_transfers":
        results = client.create_transfers(build_transfers(objects))
        _print_results(results, types.CreateTransferResult, out)
    elif operation == "lookup_accounts":
        ids = [int(obj["id"], 0) for obj in objects]
        rows = client.lookup_accounts(ids)
        for row in rows:
            print(_format_row(row, types.ACCOUNT_DTYPE.names), file=out)
    elif operation == "lookup_transfers":
        ids = [int(obj["id"], 0) for obj in objects]
        rows = client.lookup_transfers(ids)
        for row in rows:
            print(_format_row(row, types.TRANSFER_DTYPE.names), file=out)
    elif operation == "get_proof":
        # Root-anchored Merkle inclusion proof, verified CLIENT-SIDE
        # before printing (docs/commitments.md): a forged/tampered reply
        # errors instead of rendering.  ``kind=accounts|transfers|posted``
        # selects the pad (default accounts).
        from .ops.merkle import proof_row_dtype

        for obj in objects:
            ident = int(obj["id"], 0)
            kind = obj.get("kind", "accounts")
            proof = client.get_proof(ident, kind=kind)
            if proof is None:
                print(f"  id={ident} kind={kind}: no proof (absent row or "
                      "server runs without merkle commitments)", file=out)
                continue
            print(
                f"  id={ident} kind={kind}: VERIFIED against root="
                f"{proof['root']:#018x} (slot {proof['slot']}, "
                f"{len(proof['siblings'])} siblings)", file=out,
            )
            print(_format_row(proof["row"], proof_row_dtype(kind).names),
                  file=out)
    elif operation in ("get_account_transfers", "get_account_history"):
        body = build_filter(objects).tobytes()
        op = (wire.Operation.get_account_transfers
              if operation == "get_account_transfers"
              else wire.Operation.get_account_history)
        reply = client.request(op, body)
        dtype = (types.TRANSFER_DTYPE if operation == "get_account_transfers"
                 else types.ACCOUNT_BALANCE_DTYPE)
        for row in np.frombuffer(reply, dtype=dtype):
            print(_format_row(row, dtype.names), file=out)


def _print_results(results, enum_cls, out) -> None:
    if not results:
        print("  ok", file=out)
    for index, result in results:
        print(f"  [{index}]: {enum_cls(result).name}", file=out)


def run(client: Client, command: Optional[str] = None) -> None:
    """One-shot (--command) or interactive loop."""
    if command is not None:
        for statement in command.split(";"):
            execute_statement(client, statement)
        return
    print("tigerbeetle-tpu repl (statements end with ';', ctrl-d to exit)")
    buffer = ""
    while True:
        try:
            prompt = "> " if not buffer else ". "
            line = input(prompt)
        except EOFError:
            print()
            return
        buffer += " " + line
        while ";" in buffer:
            statement, buffer = buffer.split(";", 1)
            try:
                execute_statement(client, statement)
            except (ValueError, KeyError, AssertionError) as err:
                print(f"error: {err}")
