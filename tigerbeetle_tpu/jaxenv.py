"""Defensive JAX backend initialization for every fresh-process entry point.

This image pre-arranges a remote-TPU tunnel ("axon"): ``JAX_PLATFORMS=axon``
is baked into the environment, and a sitecustomize module dials the relay and
registers the axon PJRT plugin into EVERY interpreter at startup.  Three
round-1 failures shared that single cause: the driver's bench run died at
backend init (rc=1), the multichip dryrun initialized axon instead of a CPU
mesh and timed out (rc=124), and a test's spawned server subprocess wedged on
interpreter startup.  Every entry point therefore goes through this module:

- ``force_cpu(n)``: guarantee >= n virtual CPU devices in THIS process, even
  if another backend already initialized (clears jax's backend caches and
  re-inits; jax 0.9 keeps a memoized ``get_backend`` that must be cleared too).
- ``ensure_backend()``: best-effort accelerator init with a hang watchdog and
  loud CPU fallback — the benchmark must always emit its JSON line.
- ``child_env()``: environment for spawned python subprocesses that skips the
  sitecustomize relay dial entirely (drop ``PALLAS_AXON_POOL_IPS``) so a
  child interpreter can never block on the tunnel.

The reference has no analogue (a Zig binary owns its process); this is the
TPU-runtime equivalent of src/io.zig:11-16 choosing a working event loop.
"""

from __future__ import annotations

import os
import sys
import threading
import warnings
from typing import List, Optional

__all__ = [
    "force_cpu", "ensure_backend", "child_env", "current_platform",
    "COMPILE_CACHE_DIR", "enable_compile_cache", "instrument_compiles",
    "compile_count", "shard_map",
]

# Set when force_cpu had to settle for fewer virtual devices than requested
# (backend initialized before XLA_FLAGS could take effect, or an old jax).
# Tests and tools can key on this instead of re-deriving it from warnings.
DEGRADED_DEVICE_COUNT: Optional[int] = None

_HOST_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _set_host_device_flag(n: int) -> None:
    """Merge ``--xla_force_host_platform_device_count=n`` into XLA_FLAGS,
    replacing any previous value.  XLA parses the env var once per process at
    first backend creation, so this only takes effect if it runs before init —
    callers still verify the resulting device count."""
    flags = os.environ.get("XLA_FLAGS", "")
    parts = [f for f in flags.split() if not f.startswith(_HOST_COUNT_FLAG)]
    parts.append(f"{_HOST_COUNT_FLAG}={n}")
    os.environ["XLA_FLAGS"] = " ".join(parts)

# Persistent XLA compilation cache, shared by bench.py and tools/tpu_probe.py
# so a recovered TPU tunnel never re-pays the 20-40 s first compile.  One
# definition here — two independently-spelled paths would silently diverge.
COMPILE_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
)


def enable_compile_cache() -> str:
    """Point jax at the persistent cache (must run before jax init)."""
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", COMPILE_CACHE_DIR)
    return os.environ["JAX_COMPILATION_CACHE_DIR"]


# Resolved lazily by shard_map(): (impl, vary-check kwarg name).  jax must
# not be imported at module import time (this module's whole point is to
# configure the environment BEFORE the first backend init).
_SHARD_MAP_IMPL = None


def _resolve_shard_map():
    global _SHARD_MAP_IMPL
    if _SHARD_MAP_IMPL is None:
        try:  # jax >= 0.4.35 exposes shard_map at top level
            from jax import shard_map as impl
        except ImportError:  # pragma: no cover
            from jax.experimental.shard_map import shard_map as impl
        # The kwarg disabling the replication/varying-axes check was renamed
        # check_rep -> check_vma across jax versions; detect what this jax
        # takes so every call site stays on one spelling.
        import inspect

        kw = (
            "check_vma"
            if "check_vma" in inspect.signature(impl).parameters
            else "check_rep"
        )
        _SHARD_MAP_IMPL = (impl, kw)
    return _SHARD_MAP_IMPL


def shard_map(f, *, mesh, in_specs, out_specs, check_vma):
    """Version-portable jax.shard_map (the check_rep -> check_vma rename
    shim, jax 0.4.37 vs newer).  ONE shared wrapper for machine.py,
    parallel/sharded.py, and future mesh callers — and one place to drop
    the shim when jax is pinned past the rename."""
    impl, kw = _resolve_shard_map()
    return impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{kw: check_vma},
    )


_COMPILE_LISTENER_INSTALLED = False

# Monotonic count of XLA backend compiles in THIS process, maintained by
# the instrument_compiles listener UNCONDITIONALLY (one int add per
# compile — compiles are rare by definition).  Unlike the jit.compiles
# registry series this does not require the obs registry to be enabled,
# so the bench recompile tripwire and the TB_SANITIZE serving check can
# diff it around timed regions with zero arming ceremony.
_COMPILE_COUNT = 0


def compile_count() -> int:
    """Process-wide XLA backend compile count (0 until instrument_compiles
    has been installed — callers diff deltas, so the base is irrelevant)."""
    return _COMPILE_COUNT


def instrument_compiles() -> bool:
    """Feed jit compile accounting into the obs metrics registry.

    Registers a ``jax.monitoring`` duration listener: every XLA backend
    compile increments ``jit.compiles`` and lands its duration in the
    ``jit.compile_ms`` histogram (re-traces count under ``jit.traces``).
    This is how a bench or server answers "did that latency spike pay a
    compile?" without a profiler attached.  Idempotent; returns whether
    the hook is live.  The listener itself is registered once and gates on
    ``registry.enabled``, so it costs one branch per compile (compiles are
    rare by definition) when metrics are off."""
    global _COMPILE_LISTENER_INSTALLED
    if _COMPILE_LISTENER_INSTALLED:
        return True
    try:
        from jax._src import monitoring
    except ImportError:
        return False
    from .obs.metrics import registry

    def _on_duration(event: str, duration: float, **kwargs) -> None:
        global _COMPILE_COUNT
        if event.endswith("backend_compile_duration"):
            # The bare count is maintained even with the registry off —
            # compile_count() feeds the recompile tripwires.
            _COMPILE_COUNT += 1
        if not registry.enabled:
            return
        if event.endswith("backend_compile_duration"):
            registry.counter("jit.compiles").inc()
            registry.histogram("jit.compile_ms", "ms").observe(
                duration * 1e3
            )
        elif event.endswith("jaxpr_trace_duration"):
            registry.counter("jit.traces").inc()

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # private-API probe: degrade to "no hook"
        return False
    _COMPILE_LISTENER_INSTALLED = True
    return True


def _bridge():
    from jax._src import xla_bridge

    return xla_bridge


def _reset_backends() -> None:
    """Clear all initialized backends and memoized lookups (jax 0.9 private
    API, guarded so a rename degrades to a no-op rather than a crash)."""
    xb = _bridge()
    for fn in ("_clear_backends",):
        try:
            getattr(xb, fn)()
        except Exception:  # tblint: ignore[swallow] private-API probe
            pass
    try:
        xb.get_backend.cache_clear()
    except Exception:  # tblint: ignore[swallow] private-API probe
        pass
    # Newer jax caches the device list on jax.devices too; clear defensively.
    import jax

    for obj in (jax.devices, jax.local_devices):
        try:
            obj.cache_clear()  # type: ignore[attr-defined]
        except Exception:  # tblint: ignore[swallow] private-API probe
            pass


def _pop_non_cpu_factories() -> None:
    xb = _bridge()
    try:
        for name in list(xb._backend_factories):
            if name != "cpu":
                xb._backend_factories.pop(name, None)
    except Exception:  # tblint: ignore[swallow] private-API probe
        pass


def force_cpu(n_devices: Optional[int] = None) -> List:
    """Force this process onto the CPU backend with >= n_devices devices.

    Safe whether or not a backend (even a remote-TPU one) has already
    initialized.  Returns the device list.
    """
    global DEGRADED_DEVICE_COUNT
    DEGRADED_DEVICE_COUNT = None  # re-judged below on every call
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        # Must land in the environment BEFORE the first backend creation:
        # XLA's flag parse is once-per-process, and jax 0.4 has no
        # jax_num_cpu_devices config option, so the env var is the only
        # portable way to get >1 virtual CPU device.
        _set_host_device_flag(n_devices)
    import jax

    xb = _bridge()

    def _try_config(n):
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # tblint: ignore[swallow] verified below
            pass
        if n is not None:
            try:
                # jax >= 0.5 only; older versions rely on XLA_FLAGS above.
                jax.config.update("jax_num_cpu_devices", n)
            except Exception:  # tblint: ignore[swallow] verified below
                pass  # unknown option or backend already up

    initialized = False
    try:
        initialized = xb.backends_are_initialized()
    except Exception:  # tblint: ignore[swallow] private-API probe
        pass
    if initialized:
        _reset_backends()
    _pop_non_cpu_factories()
    _try_config(n_devices)

    devs = jax.devices()
    ok = devs and devs[0].platform == "cpu" and (
        n_devices is None or len(devs) >= n_devices
    )
    if not ok:
        # A backend slipped in (or too few devices): hard reset and re-init.
        _reset_backends()
        _pop_non_cpu_factories()
        _try_config(n_devices)
        devs = jax.devices()
    if not devs or devs[0].platform != "cpu":
        raise RuntimeError(
            f"force_cpu: CPU backend unavailable, got {devs!r}"
        )
    if n_devices is not None and len(devs) < n_devices:
        # A backend initialized before our XLA_FLAGS could take effect (the
        # flag parse is once-per-process).  Raising here used to take down
        # the whole test collection; degrade to what exists instead —
        # device-count-sensitive callers (tests/test_sharded.py's mesh
        # fixture) check DEGRADED_DEVICE_COUNT or len() of the returned
        # list and skip/shrink accordingly.
        DEGRADED_DEVICE_COUNT = len(devs)
        warnings.warn(
            f"force_cpu: wanted {n_devices} CPU devices, got {len(devs)} "
            "(backend initialized before XLA_FLAGS took effect); "
            "continuing with the available devices",
            RuntimeWarning,
            stacklevel=2,
        )
    return devs


def current_platform() -> Optional[str]:
    """Platform of the default backend if one is initialized, else None
    (without triggering initialization)."""
    try:
        xb = _bridge()
        if not xb.backends_are_initialized():
            return None
        import jax

        return jax.devices()[0].platform
    except Exception:
        return None


def _reexec_argv() -> List[str]:
    """argv for re-exec'ing this interpreter with the same program.

    Launched via ``python -m mod``: argv[0] is the module FILE, which cannot
    be re-run as a plain script (relative imports lose their package) —
    re-exec with -m and the original name.  (spec.name == "__main__" means
    zipapp/directory execution — argv already re-runs correctly as-is.)
    """
    argv = sys.argv
    spec = getattr(sys.modules.get("__main__"), "__spec__", None)
    if spec is not None and spec.name and spec.name != "__main__":
        mod = spec.name
        if mod.endswith(".__main__"):
            mod = mod[: -len(".__main__")]
        argv = ["-m", mod] + argv[1:]
    return list(argv)


def ensure_backend(
    timeout_s: float = 240.0, announce=print, reexec: bool = True,
    retry_tpu: bool = False,
) -> str:
    """Initialize the default backend (accelerator if the env provides one),
    falling back to CPU loudly on failure or hang.  Returns the platform name.

    The watchdog probes ``jax.devices()`` on a daemon thread.  On a clean
    exception we reset and fall back to CPU in-process.  On a HANG we cannot
    recover in-process (the init thread holds jax's backend lock), so we
    re-exec the interpreter with a scrubbed environment: the sitecustomize
    relay dial is skipped and ``JAX_PLATFORMS=cpu`` pins the fallback.

    ``retry_tpu``: give the accelerator ONE more chance before the CPU
    fallback — the first hang re-execs with the tunnel env intact (a relay
    dial racing interpreter start is transient more often than not), the
    second re-execs to CPU as usual.  Benchmarks opt in (a TPU number is
    worth one extra watchdog window); servers and tests do not.
    """
    if os.environ.get("TB_TPU_RETRY"):
        # Second attempt after a hang: don't spend another full window.
        timeout_s = min(timeout_s, 120.0)
    result: dict = {}

    def probe():
        try:
            import jax

            devs = jax.devices()
            result["platform"] = devs[0].platform
            result["n"] = len(devs)
        except Exception as e:  # noqa: BLE001 — report any init failure
            result["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        if not reexec:
            # Caller runs inside a host process we must not re-exec (the
            # driver importing entry()): best-effort in-process CPU
            # fallback — works when the hang is the remote dial itself
            # rather than a held backend-registry lock.  The fallback gets
            # its OWN watchdog: force_cpu's jax.devices() can block on the
            # very lock the stuck probe thread holds, and hanging forever
            # is strictly worse than raising.
            announce(
                f"# backend init hung >{timeout_s:.0f}s; "
                "attempting in-process CPU fallback", file=sys.stderr,
            )
            fb: dict = {}

            def fallback():
                try:
                    force_cpu()
                    fb["ok"] = True
                except Exception as err:  # noqa: BLE001 — reported below
                    fb["err"] = err

            ft = threading.Thread(target=fallback, daemon=True)
            ft.start()
            ft.join(min(60.0, timeout_s))
            if fb.get("ok"):
                return "cpu"
            raise RuntimeError(
                "backend init hung and the in-process CPU fallback "
                f"{'failed: ' + repr(fb['err']) if 'err' in fb else 'also hung'}"
            )
        if os.environ.get("TB_TPU_REEXEC"):
            raise RuntimeError("backend init hung twice; giving up")
        if retry_tpu and not os.environ.get("TB_TPU_RETRY"):
            announce(
                f"# backend init hung >{timeout_s:.0f}s; retrying the "
                "accelerator once before CPU fallback",
                file=sys.stderr,
            )
            env = dict(os.environ)  # tunnel env INTACT: retry the dial
            env["TB_TPU_RETRY"] = "1"
            os.execve(sys.executable, [sys.executable] + _reexec_argv(), env)
        announce(
            f"# backend init hung >{timeout_s:.0f}s; re-exec on CPU",
            file=sys.stderr,
        )
        env = child_env(cpu=True)
        env["TB_TPU_REEXEC"] = "1"
        os.execve(sys.executable, [sys.executable] + _reexec_argv(), env)
    if "error" in result:
        announce(
            f"# accelerator init failed ({type(result['error']).__name__}: "
            f"{result['error']}); falling back to CPU",
            file=sys.stderr,
        )
        force_cpu()
        return "cpu"
    return result["platform"]


def child_env(
    cpu: bool = True, n_devices: Optional[int] = None, base: Optional[dict] = None
) -> dict:
    """Environment for spawning a python subprocess that must never block on
    the remote-TPU tunnel: the sitecustomize dial is keyed on
    ``PALLAS_AXON_POOL_IPS``, so dropping it yields a clean interpreter."""
    env = dict(os.environ if base is None else base)
    for key in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH", "_AXON_REGISTERED"):
        env.pop(key, None)
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        # Replace, don't append: force_cpu() may have already written its
        # own device-count flag into the inherited XLA_FLAGS, and XLA's
        # handling of duplicate flags is undocumented.
        parts = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(_HOST_COUNT_FLAG)]
        parts.append(f"{_HOST_COUNT_FLAG}={n_devices}")
        env["XLA_FLAGS"] = " ".join(parts)
    return env
