"""Sharded (multi-chip) state machine: the ledger partitioned over a device mesh.

The reference scales by *replicating* the whole state machine over a TCP bus
(SURVEY §2.8-2.9; message_bus.zig) — every replica holds all state.  On a TPU
slice we can additionally *shard* one state machine across chips, with XLA
collectives over ICI doing the data movement:

- Ownership: account/transfer keys are assigned to shards by the low bits of
  their hash (owner = mix64(key) & (n_shards-1)); the remaining bits index an
  open-addressing table local to the owner (hash_shift in ops/hash_table.py),
  so probe chains never cross chips.
- Gather phase: every shard probes its local table for the whole (replicated)
  batch, masks to the keys it owns, and one ``psum`` per gathered quantity
  combines the results — after which every shard holds the full gather context
  (~1 MiB per table per batch riding ICI).
- Validation: the pure passes (ops/state_machine.py transfer_codes /
  account_codes) run *replicated* on every shard — deterministic, no
  communication.
- Apply phase: balance deltas are planned over global slot ids (replicated),
  then each shard scatters only the slots it owns; inserts likewise. No
  further communication.

Determinism: every collective is a sum of disjoint (owner-masked) terms, and
all apply-phase writes are owner-local — byte-identical to the single-chip
kernels, which the tests check on a virtual 8-device CPU mesh.

Scope: the sharded kernels cover plain create_accounts/create_transfers
(the benchmark shape), point lookups, AND the fully-general two-phase/
balancing kernel (sharded_create_transfers_full): ops/transfer_full.py's
round-3 split into GatherCtx -> pure core -> apply means the mesh path
builds the context with masked probes + psum combines, runs the identical
Jacobi/ladder math replicated on every shard, and applies owner-locally.
Admission: history-flagged accounts stay single-chip (history is an
append-ordered log, not a hash-partitioned table) — the kernel routes such
batches instead of applying; cold tiering is likewise a single-chip
concern (no bloom on the mesh path).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..u128 import mix64
from ..ops import hash_table as ht
from ..ops import state_machine as sm
from ..ops.state_machine import (
    ACCOUNT_COLS,
    Ledger,
    MAX_PROBE,
    POSTED_COLS,
    TRANSFER_COLS,
    TransferCtx,
)

# One shared version-portable wrapper (check_rep -> check_vma rename shim)
# lives in jaxenv so machine.py, this module, and future mesh callers stay
# on a single spelling — re-exported here for existing importers.
from ..jaxenv import shard_map  # noqa: F401  (re-export)

AXIS = "shard"


def make_sharded_ledger(
    mesh: Mesh,
    accounts_capacity: int,
    transfers_capacity: int,
    posted_capacity: int,
    history_capacity: int = 1,
) -> Ledger:
    """Build a Ledger whose table arrays are sharded over ``mesh`` axis 0.

    Capacities are *global* (power of two, divisible by the shard count).
    Table ``count``/``probe_overflow`` become per-shard vectors of length
    n_shards.  The history log is NOT hash-partitioned (it is an
    append-ordered log): it stays a real single-device History, replicated
    over the mesh (spec P()) and written only by the sequential fallback —
    the sharded kernels route history-touching batches (FLAG_SEQ) instead
    of applying them."""
    n = mesh.devices.size
    for cap in (accounts_capacity, transfers_capacity, posted_capacity):
        assert cap % n == 0 and (cap & (cap - 1)) == 0

    def table(capacity, col_specs):
        return ht.Table(
            key_lo=np.zeros((capacity,), np.uint64),
            key_hi=np.zeros((capacity,), np.uint64),
            tombstone=np.zeros((capacity,), np.bool_),
            cols={k: np.zeros((capacity,), dt) for k, dt in col_specs.items()},
            count=np.zeros((n,), np.uint64),
            probe_overflow=np.zeros((n,), np.bool_),
        )

    ledger = Ledger(
        accounts=table(accounts_capacity, ACCOUNT_COLS),
        transfers=table(transfers_capacity, TRANSFER_COLS),
        posted=table(posted_capacity, POSTED_COLS),
        history=sm.make_history(history_capacity),
    )
    shard = NamedSharding(mesh, P(AXIS))
    repl = NamedSharding(mesh, P())
    return Ledger(
        accounts=jax.tree_util.tree_map(
            lambda x: jax.device_put(x, shard), ledger.accounts
        ),
        transfers=jax.tree_util.tree_map(
            lambda x: jax.device_put(x, shard), ledger.transfers
        ),
        posted=jax.tree_util.tree_map(
            lambda x: jax.device_put(x, shard), ledger.posted
        ),
        history=jax.tree_util.tree_map(
            lambda x: jax.device_put(x, repl), ledger.history
        ),
    )


def _specs_like(tree):
    """Ledger partition specs: tables shard over axis 0, history (an
    append-ordered log the mesh kernels never touch) stays replicated."""
    if isinstance(tree, Ledger):
        return Ledger(
            accounts=jax.tree_util.tree_map(lambda _: P(AXIS), tree.accounts),
            transfers=jax.tree_util.tree_map(
                lambda _: P(AXIS), tree.transfers
            ),
            posted=jax.tree_util.tree_map(lambda _: P(AXIS), tree.posted),
            history=jax.tree_util.tree_map(lambda _: P(), tree.history),
        )
    return jax.tree_util.tree_map(lambda _: P(AXIS), tree)


def _replicated_like(tree):
    return jax.tree_util.tree_map(lambda _: P(), tree)


class _ShardGather:
    """Per-shard masked probe + psum combine for one key set."""

    def __init__(self, table: ht.Table, lo, hi, n_shards: int, shift: int):
        my = jax.lax.axis_index(AXIS).astype(jnp.uint64)
        h = mix64(lo, hi)
        self.owner_mask = (h & jnp.uint64(n_shards - 1)) == my
        look = ht.lookup(table, lo, hi, MAX_PROBE, hash_shift=shift)
        local_cap = table.capacity
        self.found_l = look.found & self.owner_mask
        self.slot_l = look.slot
        self.overflow_l = look.overflow  # local probe exhaustion (bool)
        gslot = my * jnp.uint64(local_cap) + look.slot
        self.found = (
            jax.lax.psum(self.found_l.astype(jnp.uint32), AXIS) > 0
        )
        self.gslot = jax.lax.psum(
            jnp.where(self.found_l, gslot, jnp.uint64(0)), AXIS
        )

    def rows(self, table: ht.Table) -> Dict[str, jax.Array]:
        local = ht.gather_cols(table, self.slot_l, self.found_l)
        return {k: jax.lax.psum(v, AXIS) for k, v in local.items()}


def sharded_create_transfers(mesh: Mesh, probed: bool = False):
    """Build the jitted sharded create_transfers step for ``mesh``.

    Returns fn(ledger, batch, count, timestamp) -> (ledger, codes), with the
    ledger sharded per make_sharded_ledger and batch/count/timestamp
    replicated.

    ``probed`` (STATIC) additionally returns the per-shard transfers
    probe_overflow lanes widened into a FRESH uint32[n_shards] output —
    the sharded twin of sm.create_transfers_fast_probed: a deferred
    readback handle must be able to fetch the overflow flag after a later
    dispatch on the FIFO lane has donated this ledger, and riding the
    codes readback it costs zero extra syncs (docs/commit_pipeline.md)."""
    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def local_step(ledger: Ledger, batch, count, timestamp):
        acc, tr = ledger.accounts, ledger.transfers
        local_acc_cap = acc.capacity

        dr_g = _ShardGather(
            acc, batch["debit_account_id_lo"], batch["debit_account_id_hi"],
            n_shards, shift,
        )
        cr_g = _ShardGather(
            acc, batch["credit_account_id_lo"], batch["credit_account_id_hi"],
            n_shards, shift,
        )
        ex_g = _ShardGather(tr, batch["id_lo"], batch["id_hi"], n_shards, shift)

        lane = jnp.arange(batch["id_lo"].shape[0], dtype=jnp.int32)
        valid = lane < count.astype(jnp.int32)
        ctx = TransferCtx(
            dr_found=dr_g.found & valid,
            cr_found=cr_g.found & valid,
            dr_slot=dr_g.gslot,
            cr_slot=cr_g.gslot,
            dr=dr_g.rows(acc),
            cr=cr_g.rows(acc),
            ex_found=ex_g.found & valid,
            e=ex_g.rows(tr),
        )

        # Replicated validation (identical on every shard).
        codes, ok, ts, pending = sm.transfer_codes(batch, ctx, count, timestamp)

        # Balance plan over global slots, applied owner-locally.
        global_cap = local_acc_cap * n_shards
        plan = sm.balance_plan(
            ctx.dr_slot, ctx.cr_slot, ok,
            batch["amount_lo"], pending, global_cap,
        )
        my = jax.lax.axis_index(AXIS).astype(jnp.uint64)
        base = my * jnp.uint64(local_acc_cap)
        in_range = (plan.s_slot >= base) & (
            plan.s_slot < base + jnp.uint64(local_acc_cap)
        )
        local_plan = sm.BalancePlan(
            s_slot=jnp.where(in_range, plan.s_slot - base, jnp.uint64(local_acc_cap)),
            head=plan.head & in_range,
            deltas=plan.deltas,
        )
        accounts = sm.apply_balance_plan(acc, local_plan)

        # Owner-local transfer inserts.
        rows = sm.transfer_rows(batch, count, timestamp)
        transfers, _ = ht.insert(
            tr, batch["id_lo"], batch["id_hi"],
            ok & ex_g.owner_mask, rows, MAX_PROBE, hash_shift=shift,
        )

        out = ledger.replace(accounts=accounts, transfers=transfers)
        if probed:
            # Fresh (non-aliasing) per-shard overflow lanes: local (1,)
            # widens to the global uint32[n_shards] vector.
            return out, codes, transfers.probe_overflow.astype(jnp.uint32)
        return out, codes

    def step(ledger, batch, count, timestamp):
        out_specs = (_specs_like(ledger), P())
        if probed:
            out_specs = out_specs + (P(AXIS),)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger), _replicated_like(batch), P(), P()),
            out_specs=out_specs,
            # vma-checking is off because ht.lookup's probe while_loop mixes
            # replicated (keys) and shard-varying (table) carry values; the
            # library kernels are backend-agnostic and cannot pvary-annotate.
            # Correctness is covered by byte-parity vs single-chip in
            # tests/test_sharded.py instead.
            check_vma=False,
        )(ledger, batch, count, timestamp)

    return jax.jit(step, donate_argnames=("ledger",))


def sharded_create_transfers_full(
    mesh: Mesh, max_passes: int = None, use_waves: bool = False
):
    """The fully-general transfer kernel (two-phase/balancing/limits) over
    the device mesh.  ``max_passes`` mirrors LedgerConfig.jacobi_max_passes
    (defaults to the kernel's budget) so both serving paths honor the knob.

    Context is gathered by masked probes + psum (after which every shard
    holds the full replicated GatherCtx), the pure Jacobi/ladder core runs
    replicated, and claims/scatters/inserts apply owner-locally — so the
    result is byte-identical to the single-chip kernel. History-flagged
    accounts route (FLAG_SEQ) instead of applying: history is an ordered
    append log, which stays a single-chip structure.

    ``use_waves`` (STATIC; TB_WAVES at the machine level) arms the
    conflict-index wave scheduler INSIDE the replicated kernel core: the
    hazard-lane wave bounds are computed over the shard-local batch view
    (which is the full replicated batch, so every shard certifies the same
    bound) and certified batches commit after the proved pass count — the
    exact docs/waves.md semantics, now on the mesh path.  On, a FOURTH
    replicated int32[11] wave-profile vector is returned.

    Returns fn(ledger, batch, count, timestamp) -> (ledger, codes, kflags
    [, wave_vec]).
    """
    from ..ops import transfer_full as _tf

    if max_passes is None:
        max_passes = _tf._MAX_PASSES
    from ..ops import transfer_full as tf
    from ..ops.state_machine import TF_POST, TF_VOID

    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def _view(g: _ShardGather, table: ht.Table, found) -> tf.AccountView:
        rows = g.rows(table)
        return tf.AccountView(
            found=found,
            slot=g.gslot,
            flags=rows["flags"],
            ledger=rows["ledger"],
            bal={
                f + l: rows[f + l]
                for f in ("debits_pending", "debits_posted",
                          "credits_pending", "credits_posted")
                for l in ("_lo", "_hi")
            },
        )

    def local_step(ledger: Ledger, batch, count, timestamp):
        acc, tr, posted_t = ledger.accounts, ledger.transfers, ledger.posted
        n = batch["id_lo"].shape[0]
        lane = jnp.arange(n, dtype=jnp.int32)
        valid = lane < count.astype(jnp.int32)
        postvoid = (
            ((batch["flags"] & TF_POST) != 0) | ((batch["flags"] & TF_VOID) != 0)
        ) & valid

        ex_g = _ShardGather(tr, batch["id_lo"], batch["id_hi"], n_shards, shift)
        # Zero-mask by `valid` exactly like the single-chip gather
        # (ex_found = found & valid there): every current consumer is gated
        # on ex_found anyway, but an unmasked row would be a latent
        # byte-parity divergence if e_tab ever gains another consumer.
        e_tab = {
            k: jnp.where(ex_g.found & valid, v, jnp.zeros_like(v))
            for k, v in ex_g.rows(tr).items()
        }
        p_g = _ShardGather(
            tr, batch["pending_id_lo"], batch["pending_id_hi"], n_shards, shift
        )
        p_tab_found = p_g.found & postvoid
        # Zero-mask rows exactly like the single-chip gather (mask includes
        # postvoid): the core treats zeros as "no row".
        p_tab = {
            k: jnp.where(p_tab_found, v, jnp.zeros_like(v))
            for k, v in p_g.rows(tr).items()
        }

        drT_g = _ShardGather(
            acc, batch["debit_account_id_lo"], batch["debit_account_id_hi"],
            n_shards, shift,
        )
        crT_g = _ShardGather(
            acc, batch["credit_account_id_lo"], batch["credit_account_id_hi"],
            n_shards, shift,
        )
        pdr_g = _ShardGather(
            acc, p_tab["debit_account_id_lo"], p_tab["debit_account_id_hi"],
            n_shards, shift,
        )
        pcr_g = _ShardGather(
            acc, p_tab["credit_account_id_lo"], p_tab["credit_account_id_hi"],
            n_shards, shift,
        )
        postedT_g = _ShardGather(
            posted_t, p_tab["timestamp"], jnp.zeros_like(p_tab["timestamp"]),
            n_shards, shift,
        )
        postedT_found = postedT_g.found & p_tab_found
        postedT_val = postedT_g.rows(posted_t)["fulfillment"]

        def any_shard(local_bool):
            return jax.lax.psum(local_bool.astype(jnp.uint32), AXIS) > 0

        probe_grow = (
            jnp.where(
                any_shard(drT_g.overflow_l | crT_g.overflow_l
                          | pdr_g.overflow_l | pcr_g.overflow_l),
                jnp.uint32(tf.FLAG_GROW_ACCOUNTS), jnp.uint32(0),
            )
            | jnp.where(
                any_shard(ex_g.overflow_l | p_g.overflow_l),
                jnp.uint32(tf.FLAG_GROW_TRANSFERS), jnp.uint32(0),
            )
            | jnp.where(
                any_shard(postedT_g.overflow_l),
                jnp.uint32(tf.FLAG_GROW_POSTED), jnp.uint32(0),
            )
        )

        ctx = tf.GatherCtx(
            ex_found=ex_g.found & valid,
            e_tab=e_tab,
            p_tab_found=p_tab_found,
            p_tab=p_tab,
            drT=_view(drT_g, acc, drT_g.found & valid),
            crT=_view(crT_g, acc, crT_g.found & valid),
            pdr=_view(pdr_g, acc, pdr_g.found & p_tab_found),
            pcr=_view(pcr_g, acc, pcr_g.found & p_tab_found),
            postedT_found=postedT_found,
            postedT_val=postedT_val,
            probe_grow=probe_grow,
            accounts_capacity=jnp.uint64(acc.capacity * n_shards),
        )
        plan = tf._kernel_core(
            ctx, batch, count, timestamp, max_passes, use_waves=use_waves
        )

        # History admission: the mesh ledger has no history log — route
        # instead of silently dropping rows.
        route = plan.route | jnp.where(
            jnp.any(plan.do_hist), jnp.uint32(tf.FLAG_SEQ), jnp.uint32(0)
        )

        # Owner-local claims (insert-probe overflow routes with nothing
        # applied, exactly like single-chip).
        t_claim, t_ovf = ht.claim_slots(
            tr, batch["id_lo"], batch["id_hi"],
            plan.ok & ex_g.owner_mask, MAX_PROBE, hash_shift=shift,
        )
        my = jax.lax.axis_index(AXIS).astype(jnp.uint64)
        pk_owner = (
            mix64(plan.posted_key, jnp.zeros_like(plan.posted_key))
            & jnp.uint64(n_shards - 1)
        ) == my
        p_claim, p_ovf = ht.claim_slots(
            posted_t, plan.posted_key, jnp.zeros_like(plan.posted_key),
            plan.pv_ok & pk_owner, MAX_PROBE, hash_shift=shift,
        )
        kflags = (
            probe_grow
            | route
            | jnp.where(
                any_shard(t_ovf), jnp.uint32(tf.FLAG_GROW_TRANSFERS),
                jnp.uint32(0),
            )
            | jnp.where(
                any_shard(p_ovf), jnp.uint32(tf.FLAG_GROW_POSTED),
                jnp.uint32(0),
            )
        )
        commit = kflags == jnp.uint32(0)

        # Balance scatter: global slot runs, owner-local writes.
        local_cap = acc.capacity
        base = my * jnp.uint64(local_cap)
        in_range = (plan.s_slot >= base) & (
            plan.s_slot < base + jnp.uint64(local_cap)
        )
        scat = plan.scat & commit & in_range
        sentinel = jnp.uint64(local_cap)
        accounts = ht.scatter_cols(
            acc, jnp.where(scat, plan.s_slot - base, sentinel), scat,
            plan.bal_incl,
        )

        ins_rows = {
            name: plan.row[name].astype(dt)
            for name, dt in TRANSFER_COLS.items()
        }
        transfers = ht.write_rows(
            tr, batch["id_lo"], batch["id_hi"], t_claim,
            plan.ok & commit & ex_g.owner_mask, ins_rows,
        )
        posted_out = ht.write_rows(
            posted_t, plan.posted_key, jnp.zeros_like(plan.posted_key),
            p_claim, plan.pv_ok & commit & pk_owner,
            {"fulfillment": jnp.where(plan.post, jnp.uint32(1), jnp.uint32(2))},
        )

        out = ledger.replace(
            accounts=accounts, transfers=transfers, posted=posted_out
        )
        if use_waves:
            wave_vec = jnp.concatenate([
                plan.passes.reshape(1), plan.wave_bound.reshape(1),
                plan.wave_hist,
            ])
            return out, plan.codes, kflags, wave_vec
        return out, plan.codes, kflags

    def step(ledger, batch, count, timestamp):
        out_specs = (_specs_like(ledger), P(), P())
        if use_waves:
            out_specs = out_specs + (P(),)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger), _replicated_like(batch), P(), P()),
            out_specs=out_specs,
            check_vma=False,  # see sharded_create_transfers' justification
        )(ledger, batch, count, timestamp)

    return jax.jit(step, donate_argnames=("ledger",))


def sharded_lookup(mesh: Mesh, table_name: str):
    """Jitted sharded point-lookup over ``ledger.<table_name>``: every
    shard probes its local partition for the replicated id batch; one psum
    per column assembles the full rows on every chip.

    Returns fn(ledger, id_lo, id_hi) -> (found[b], rows{col: [b]})."""
    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def local_step(ledger: Ledger, id_lo, id_hi):
        table = getattr(ledger, table_name)
        g = _ShardGather(table, id_lo, id_hi, n_shards, shift)
        rows = g.rows(table)
        # Match the single-chip lookup shape (sm.lookup_* include the id
        # columns so types.from_soa can build full wire rows).
        rows["id_lo"] = jnp.where(g.found, id_lo, jnp.uint64(0))
        rows["id_hi"] = jnp.where(g.found, id_hi, jnp.uint64(0))
        return g.found, rows

    def step(ledger, id_lo, id_hi):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger), P(), P()),
            out_specs=(P(), P()),
            check_vma=False,  # see sharded_create_transfers' justification
        )(ledger, id_lo, id_hi)

    return jax.jit(step)


def sharded_create_accounts(mesh: Mesh):
    """Jitted sharded create_accounts step for ``mesh``."""
    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def local_step(ledger: Ledger, batch, count, timestamp):
        acc = ledger.accounts
        g = _ShardGather(acc, batch["id_lo"], batch["id_hi"], n_shards, shift)
        lane = jnp.arange(batch["id_lo"].shape[0], dtype=jnp.int32)
        valid = lane < count.astype(jnp.int32)
        codes, ok = sm.account_codes(
            batch, g.found & valid, g.rows(acc), count
        )
        rows = sm.account_rows(batch, count, timestamp)
        accounts, _ = ht.insert(
            acc, batch["id_lo"], batch["id_hi"],
            ok & g.owner_mask, rows, MAX_PROBE, hash_shift=shift,
        )
        return ledger.replace(accounts=accounts), codes

    def step(ledger, batch, count, timestamp):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger), _replicated_like(batch), P(), P()),
            out_specs=(_specs_like(ledger), P()),
            # vma-checking is off because ht.lookup's probe while_loop mixes
            # replicated (keys) and shard-varying (table) carry values; the
            # library kernels are backend-agnostic and cannot pvary-annotate.
            # Correctness is covered by byte-parity vs single-chip in
            # tests/test_sharded.py instead.
            check_vma=False,
        )(ledger, batch, count, timestamp)

    return jax.jit(step, donate_argnames=("ledger",))


# ---------------------------------------------------------------------------
# Per-shard scrub lanes (machine.scrub_check under TB_SHARDS)
# ---------------------------------------------------------------------------


def sharded_scrub_digest(mesh: Mesh):
    """Per-shard scrub fold lanes: uint64[n_shards, 3] where row s is shard
    s's partial (accounts, transfers, posted) fold over its local partition.

    The scrub folds are wrap-adds over live rows (ops/scrub.py), so the
    GLOBAL digests are the per-shard lanes summed mod 2^64 — the host
    compares that sum against the mirror's expectation, and the lanes
    themselves localize a mismatch to one shard.  ONE readback through the
    commit-barrier funnel, like the single-device fold."""
    from ..ops import scrub as scrub_ops

    def local_step(ledger: Ledger):
        lanes = jnp.stack([
            scrub_ops._fold_accounts(ledger.accounts),
            scrub_ops._fold_transfers(ledger.transfers),
            scrub_ops._fold_posted(ledger.posted),
        ])
        return lanes[None, :]  # (1, 3) local -> (n_shards, 3) global

    def step(ledger):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger),),
            out_specs=P(AXIS),
            check_vma=False,  # see sharded_create_transfers' justification
        )(ledger)

    # Deliberately NOT donated: the scrub must never consume the ledger.
    return jax.jit(step)


# ---------------------------------------------------------------------------
# Per-shard Merkle subtrees (machine merkle mode under TB_SHARDS)
# ---------------------------------------------------------------------------
#
# The commitment forest (ops/merkle.py) composes with sharding as one
# subtree per shard over the shard's LOCAL slot layout: heaps carry a
# leading shard partition (global uint64[n * 2 * local_cap] sharded over
# the mesh axis), updates touch owner-locally (a non-owned key is simply
# absent from the local table, so its probe misses and the lane drops),
# and the canonical live commitment is the per-shard roots folded by
# wrap-sum — read back through the same per-shard uint64 lanes the scrub
# fold uses.  Pending references resolve through the _ShardGather psum
# (the pending transfer's row lives on ONE shard; its posted key and
# account sides must reach THEIR owners).
#
# Under TB_MERKLE_ASYNC (docs/commitments.md deferred lane) the update
# steps below run from machine.merkle_settle() instead of inside each
# commit closure: the settle drains COALESCED touch records (up to
# batch_lanes rows per step call) through these same jitted programs —
# same size classes, same owner-local probe semantics — so the deferred
# lane composes with sharding with no sharded-specific state.  Settle
# runs only on a drained dispatch lane (the closures swap/donate the
# sharded ledger buffers), which the hard barriers guarantee.


def merkle_steps(mesh: Mesh) -> Dict[str, object]:
    """Jitted sharded merkle build/update/verify/roots steps, cached
    process-wide like machine_steps."""
    key = (
        tuple(int(d.id) for d in mesh.devices.flat),
        mesh.axis_names,
        "merkle",
    )
    steps = _STEP_CACHE.get(key)
    if steps is not None:
        return steps
    from ..ops import merkle as mk

    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def build_local(ledger: Ledger):
        return mk.build_forest_impl(ledger)

    def build(ledger):
        return shard_map(
            build_local,
            mesh=mesh,
            in_specs=(_specs_like(ledger),),
            out_specs=jax.tree_util.tree_map(
                lambda _: P(AXIS), mk.Forest(0, 0, 0)
            ),
            check_vma=False,  # see sharded_create_transfers' justification
        )(ledger)

    def upd_accounts_local(forest, ledger, lo, hi):
        return mk.update_accounts_impl(
            forest, ledger, lo, hi, max_probe=MAX_PROBE, hash_shift=shift
        )

    def upd_transfers_local(has_postvoid):
        def fn(forest, ledger, id_lo, id_hi, acc_lo, acc_hi,
               pend_lo, pend_hi):
            if has_postvoid:
                # Resolve pending refs cluster-wide: the row lives on one
                # shard; psum carries its posted key + account sides to
                # every shard, whose local touches keep only what they own.
                p_g = _ShardGather(
                    ledger.transfers, pend_lo, pend_hi, n_shards, shift
                )
                rows = p_g.rows(ledger.transfers)

                def masked(name):
                    return jnp.where(p_g.found, rows[name], jnp.uint64(0))

                pend_ts = masked("timestamp")
                acc_lo = jnp.concatenate([
                    acc_lo, masked("debit_account_id_lo"),
                    masked("credit_account_id_lo"),
                ])
                acc_hi = jnp.concatenate([
                    acc_hi, masked("debit_account_id_hi"),
                    masked("credit_account_id_hi"),
                ])
                posted = mk.touch_tree(
                    forest.posted, ledger.posted, pend_ts,
                    jnp.zeros_like(pend_ts), "posted", MAX_PROBE, shift,
                )
            else:
                posted = forest.posted
            transfers = mk.touch_tree(
                forest.transfers, ledger.transfers, id_lo, id_hi,
                "transfers", MAX_PROBE, shift,
            )
            accounts = mk.touch_tree(
                forest.accounts, ledger.accounts, acc_lo, acc_hi,
                "accounts", MAX_PROBE, shift,
            )
            return mk.Forest(
                accounts=accounts, transfers=transfers, posted=posted
            )

        return fn

    def verify_local(forest, ledger):
        return mk.verify_roots_impl(forest, ledger)[None]  # (1, 2, 3)

    def verify(forest, ledger):
        return shard_map(
            verify_local,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(AXIS), mk.Forest(0, 0, 0)),
                _specs_like(ledger),
            ),
            out_specs=P(AXIS),
            check_vma=False,  # see sharded_create_transfers' justification
        )(forest, ledger)

    def roots_local(forest):
        return jnp.stack([
            forest.accounts[1], forest.transfers[1], forest.posted[1]
        ])[None]

    def roots(forest):
        return shard_map(
            roots_local,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(AXIS), mk.Forest(0, 0, 0)),
            ),
            out_specs=P(AXIS),
            check_vma=False,  # see sharded_create_transfers' justification
        )(forest)

    forest_specs = jax.tree_util.tree_map(lambda _: P(AXIS), mk.Forest(0, 0, 0))

    def wrap_update(fn):
        def step(forest, ledger, *keys):
            return shard_map(
                fn,
                mesh=mesh,
                in_specs=(forest_specs, _specs_like(ledger))
                + tuple(P() for _ in keys),
                out_specs=forest_specs,
                check_vma=False,  # see sharded_create_transfers
            )(forest, ledger, *keys)

        return jax.jit(step, donate_argnames=("forest",))

    steps = {
        # build/verify/roots deliberately NOT donated (reads).
        "build": jax.jit(build),
        "verify": jax.jit(verify),
        "roots": jax.jit(roots),
        "update_accounts": wrap_update(upd_accounts_local),
        "update_transfers": wrap_update(upd_transfers_local(False)),
        "update_transfers_pv": wrap_update(upd_transfers_local(True)),
    }
    _STEP_CACHE[key] = steps
    return steps


# ---------------------------------------------------------------------------
# Host-side layout converters (sequential fallback, checkpoints, queries)
# ---------------------------------------------------------------------------
#
# The sharded and single-device layouts hold identical CONTENT under
# different slot assignment: single-device homes at mix64(key) & (C-1);
# sharded homes at shard (mix64 & (n-1)), local slot ((mix64 >> shift) &
# (C/n - 1)).  These converters re-place every live row host-side with the
# exact linear-probe discipline of ht.claim_slots for distinct keys
# (insertion in row order == the batched claim protocol, since unplaced
# lanes sharing a probe slot always share a home).  Both are deterministic
# functions of the input layout, so every replica replaying the same commit
# stream converges to byte-identical canonical arrays (checkpoint file
# checksums must agree across the cluster).  Cost is O(rows) host work —
# paid only at sequential fallbacks, checkpoint captures, and the first
# query after a commit, never on the sharded commit hot path.


def _host_rows(table: ht.Table):
    """(key_lo, key_hi, cols, live_idx) host copies; live rows in slot
    order (deterministic given the layout), tombstones dropped."""
    key_lo = np.asarray(table.key_lo)
    key_hi = np.asarray(table.key_hi)
    tomb = np.asarray(table.tombstone)
    live = ((key_lo != 0) | (key_hi != 0)) & ~tomb
    idx = np.flatnonzero(live)
    cols = {k: np.asarray(v) for k, v in table.cols.items()}
    return key_lo, key_hi, cols, idx


def _probe_place_ref(homes: np.ndarray, region_base: np.ndarray,
                     region_mask: int, capacity: int) -> np.ndarray:
    """Reference linear-probe placement (the original per-row host loop):
    row i lands at the first free slot of region_base[i] + ((homes[i] + k)
    & region_mask).  O(rows) interpreted work — kept as the oracle the
    vectorized _probe_place is pinned bit-identical against
    (tests/test_sharded.py)."""
    occupied = np.zeros(capacity, bool)
    slots = np.empty(len(homes), np.int64)
    for i in range(len(homes)):
        s = int(homes[i])
        base = int(region_base[i])
        while occupied[base + s]:
            s = (s + 1) & region_mask
        occupied[base + s] = True
        slots[i] = base + s
    return slots


def _probe_place(homes: np.ndarray, region_base: np.ndarray, region_mask: int,
                 capacity: int) -> np.ndarray:
    """Vectorized linear-probe placement, bit-identical to
    _probe_place_ref (ROADMAP item 1 follow-up: the canonical-view
    rebuild's per-row host loop was O(live rows) interpreted work — a real
    tax on the first query after every sharded commit).

    Sequential FCFS insertion satisfies one invariant that pins the
    assignment uniquely: every slot a row probes PAST holds a row with a
    smaller row index (it was already there when the later row walked).
    So the fixpoint of a displacement sweep — every unplaced row proposes
    to its current probe slot, each slot keeps the smallest row index it
    has ever been offered (np.minimum.at), losers and stolen-from rows
    advance — IS the sequential assignment, computed in O(max displacement)
    vector rounds instead of O(live rows) interpreted probe walks.  The
    PR 7 claim_slots cost discipline (one upfront (home, lane) ordering
    per round, occupancy as flat vectors, no per-row Python), applied to
    the converter's FCFS protocol; tests/test_sharded.py pins parity
    against the scalar oracle including forced same-home and
    cross-group-displacement collisions."""
    n = len(homes)
    if n == 0:
        return np.empty(0, np.int64)
    base = region_base.astype(np.int64)
    homes64 = homes.astype(np.int64)
    owner = np.full(capacity, n, np.int64)  # n = unowned sentinel
    offset = np.zeros(n, np.int64)
    row_slot = np.full(n, -1, np.int64)
    active = np.arange(n, dtype=np.int64)
    while active.size:
        cur = base[active] + ((homes64[active] + offset[active]) & region_mask)
        prev = owner[cur].copy()
        np.minimum.at(owner, cur, active)
        won = owner[cur] == active
        row_slot[active[won]] = cur[won]
        offset[active[~won]] += 1  # lost the proposal: advance one
        # Stolen-from rows (a smaller index claimed their slot) rejoin one
        # past the stolen slot.  One victim per slot, winners' slots are
        # unique, so victims are unique.
        victims = prev[won]
        victims = victims[victims < n]
        if victims.size:
            offset[victims] = (
                (row_slot[victims] - base[victims] - homes64[victims])
                & region_mask
            ) + 1
            row_slot[victims] = -1
            active = np.concatenate([active[~won], victims])
        else:
            active = active[~won]
    return row_slot


def _fill_table(capacity: int, key_lo, key_hi, cols, slots,
                col_specs) -> ht.Table:
    out_lo = np.zeros(capacity, np.uint64)
    out_hi = np.zeros(capacity, np.uint64)
    out_lo[slots] = key_lo
    out_hi[slots] = key_hi
    out_cols = {}
    for name, dt in col_specs.items():
        buf = np.zeros(capacity, dt)
        buf[slots] = cols[name]
        out_cols[name] = jnp.asarray(buf)
    return ht.Table(
        key_lo=jnp.asarray(out_lo),
        key_hi=jnp.asarray(out_hi),
        tombstone=jnp.zeros((capacity,), jnp.bool_),
        cols=out_cols,
        count=jnp.uint64(len(slots)),
        probe_overflow=jnp.bool_(False),
    )


_COL_SPECS = {
    "accounts": ACCOUNT_COLS,
    "transfers": TRANSFER_COLS,
    "posted": POSTED_COLS,
}


def unshard_ledger(ledger: Ledger, mesh: Mesh) -> sm.Ledger:
    """Canonical single-device Ledger with the sharded ledger's exact
    content (single-device probe layout, scalar counts).  The history log
    is already single-device (replicated) and passes through unchanged."""
    from ..ops.scrub import mix64_np

    def un_table(table: ht.Table, name: str) -> ht.Table:
        cap = table.capacity
        key_lo, key_hi, cols, idx = _host_rows(table)
        k_lo, k_hi = key_lo[idx], key_hi[idx]
        homes = mix64_np(k_lo, k_hi) & np.uint64(cap - 1)
        slots = _probe_place(
            homes, np.zeros(len(idx), np.int64), cap - 1, cap
        )
        return _fill_table(
            cap, k_lo, k_hi, {k: v[idx] for k, v in cols.items()}, slots,
            _COL_SPECS[name],
        )

    return sm.Ledger(
        accounts=un_table(ledger.accounts, "accounts"),
        transfers=un_table(ledger.transfers, "transfers"),
        posted=un_table(ledger.posted, "posted"),
        history=sm.History(
            cols={k: jnp.asarray(np.asarray(v))
                  for k, v in ledger.history.cols.items()},
            count=jnp.uint64(int(np.asarray(ledger.history.count))),
        ),
    )


def _shard_table(table: ht.Table, name: str, mesh: Mesh,
                 new_capacity: int = None) -> ht.Table:
    """Host-side (re)placement of one table into the sharded layout at
    ``new_capacity`` (default: same global capacity) — used by
    shard_ledger and by growth under sharding."""
    from ..ops.scrub import mix64_np

    n = mesh.devices.size
    shift = n.bit_length() - 1
    cap = new_capacity if new_capacity is not None else table.capacity
    assert cap % n == 0 and (cap & (cap - 1)) == 0
    local_cap = cap // n
    key_lo, key_hi, cols, idx = _host_rows(table)
    k_lo, k_hi = key_lo[idx], key_hi[idx]
    h = mix64_np(k_lo, k_hi)
    owner = (h & np.uint64(n - 1)).astype(np.int64)
    homes = (h >> np.uint64(shift)) & np.uint64(local_cap - 1)
    slots = _probe_place(homes, owner * local_cap, local_cap - 1, cap)
    out = _fill_table(
        cap, k_lo, k_hi, {k: v[idx] for k, v in cols.items()}, slots,
        _COL_SPECS[name],
    )
    counts = np.bincount(owner, minlength=n).astype(np.uint64)
    out = out.replace(
        count=counts, probe_overflow=np.zeros((n,), np.bool_)
    )
    spec = NamedSharding(mesh, P(AXIS))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, spec), out)


def shard_ledger(single: sm.Ledger, mesh: Mesh) -> Ledger:
    """Sharded Ledger with the single-device ledger's exact content
    (owner-partitioned probe layout, per-shard count vectors)."""
    repl = NamedSharding(mesh, P())
    return Ledger(
        accounts=_shard_table(single.accounts, "accounts", mesh),
        transfers=_shard_table(single.transfers, "transfers", mesh),
        posted=_shard_table(single.posted, "posted", mesh),
        history=jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.asarray(np.asarray(x)), repl),
            single.history,
        ),
    )


def grow_sharded_table(table: ht.Table, name: str, new_capacity: int,
                       mesh: Mesh) -> ht.Table:
    """ht.grow for a sharded table: owners are the LOW hash bits so every
    row stays on its shard; only the local homes rehash (the hash_shift
    discipline).  Host-side re-placement, same determinism argument as the
    converters."""
    assert new_capacity >= table.capacity
    return _shard_table(table, name, mesh, new_capacity)


# ---------------------------------------------------------------------------
# Jitted step cache (machine.py's serving surface)
# ---------------------------------------------------------------------------

_STEP_CACHE: Dict[tuple, dict] = {}


def machine_steps(mesh: Mesh, max_passes: int) -> dict:
    """The jitted sharded commit/scrub steps for ``mesh``, cached process-
    wide by (device ids, max_passes): a VOPR cluster's replicas (or any two
    machines on one mesh) share ONE set of compiled programs instead of
    re-tracing per machine.  Kernels are pure, so sharing is sound."""
    key = (
        tuple(int(d.id) for d in mesh.devices.flat),
        mesh.axis_names,
        int(max_passes),
    )
    steps = _STEP_CACHE.get(key)
    if steps is None:
        steps = {
            "accounts": sharded_create_accounts(mesh),
            "fast": sharded_create_transfers(mesh),
            # Deferred-dispatch twin (overflow as a fresh output): the
            # commit-pipeline lane under TB_SHARDS dispatches this one.
            "fast_probed": sharded_create_transfers(mesh, probed=True),
            "full": sharded_create_transfers_full(mesh, max_passes),
            "full_waves": sharded_create_transfers_full(
                mesh, max_passes, use_waves=True
            ),
            "scrub": sharded_scrub_digest(mesh),
        }
        _STEP_CACHE[key] = steps
    return steps


# ---------------------------------------------------------------------------
# Online shard split (docs/reconfiguration.md)
# ---------------------------------------------------------------------------


def split_moved_mask(key_lo: np.ndarray, key_hi: np.ndarray,
                     old_shards: int) -> np.ndarray:
    """Boolean mask of canonical slots whose OWNER changes on an
    old_shards -> 2*old_shards split.  Owners are the low hash bits, so
    doubling adds exactly one bit: a live row moves iff
    ``mix64(key) & old_shards != 0`` (it lands on shard s + old_shards),
    and stays resident otherwise.  Empty slots (key == 0) never move —
    only the moved subset crosses the verified migration channel
    (vsr/statesync.ship_chunk / verify_chunk); the stayed subset never
    leaves its device."""
    from ..ops.scrub import mix64_np

    assert old_shards >= 1 and old_shards & (old_shards - 1) == 0
    lo = np.asarray(key_lo, dtype=np.uint64)
    hi = np.asarray(key_hi, dtype=np.uint64)
    live = (lo | hi) != 0
    owners = mix64_np(lo, hi)
    return live & ((owners & np.uint64(old_shards)) != 0)
