"""Sharded (multi-chip) state machine: the ledger partitioned over a device mesh.

The reference scales by *replicating* the whole state machine over a TCP bus
(SURVEY §2.8-2.9; message_bus.zig) — every replica holds all state.  On a TPU
slice we can additionally *shard* one state machine across chips, with XLA
collectives over ICI doing the data movement:

- Ownership: account/transfer keys are assigned to shards by the low bits of
  their hash (owner = mix64(key) & (n_shards-1)); the remaining bits index an
  open-addressing table local to the owner (hash_shift in ops/hash_table.py),
  so probe chains never cross chips.
- Gather phase: every shard probes its local table for the whole (replicated)
  batch, masks to the keys it owns, and one ``psum`` per gathered quantity
  combines the results — after which every shard holds the full gather context
  (~1 MiB per table per batch riding ICI).
- Validation: the pure passes (ops/state_machine.py transfer_codes /
  account_codes) run *replicated* on every shard — deterministic, no
  communication.
- Apply phase: balance deltas are planned over global slot ids (replicated),
  then each shard scatters only the slots it owns; inserts likewise. No
  further communication.

Determinism: every collective is a sum of disjoint (owner-masked) terms, and
all apply-phase writes are owner-local — byte-identical to the single-chip
kernels, which the tests check on a virtual 8-device CPU mesh.

Scope: the sharded kernels cover plain create_accounts/create_transfers
(the benchmark shape), point lookups, AND the fully-general two-phase/
balancing kernel (sharded_create_transfers_full): ops/transfer_full.py's
round-3 split into GatherCtx -> pure core -> apply means the mesh path
builds the context with masked probes + psum combines, runs the identical
Jacobi/ladder math replicated on every shard, and applies owner-locally.
Admission: history-flagged accounts stay single-chip (history is an
append-ordered log, not a hash-partitioned table) — the kernel routes such
batches instead of applying; cold tiering is likewise a single-chip
concern (no bloom on the mesh path).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..u128 import mix64
from ..ops import hash_table as ht
from ..ops import state_machine as sm
from ..ops.state_machine import (
    ACCOUNT_COLS,
    Ledger,
    MAX_PROBE,
    POSTED_COLS,
    TRANSFER_COLS,
    TransferCtx,
)

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_impl

# The kwarg disabling the replication/varying-axes check was renamed
# check_rep -> check_vma across jax versions; detect what this jax takes
# so the call sites below stay on one spelling.
import inspect as _inspect

_VARY_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map_impl).parameters
    else "check_rep"
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma):
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_VARY_KW: check_vma},
    )


AXIS = "shard"


def make_sharded_ledger(
    mesh: Mesh,
    accounts_capacity: int,
    transfers_capacity: int,
    posted_capacity: int,
) -> Ledger:
    """Build a Ledger whose table arrays are sharded over ``mesh`` axis 0.

    Capacities are *global* (power of two, divisible by the shard count).
    Table ``count``/``probe_overflow`` become per-shard vectors of length
    n_shards."""
    n = mesh.devices.size
    for cap in (accounts_capacity, transfers_capacity, posted_capacity):
        assert cap % n == 0 and (cap & (cap - 1)) == 0

    def table(capacity, col_specs):
        return ht.Table(
            key_lo=np.zeros((capacity,), np.uint64),
            key_hi=np.zeros((capacity,), np.uint64),
            tombstone=np.zeros((capacity,), np.bool_),
            cols={k: np.zeros((capacity,), dt) for k, dt in col_specs.items()},
            count=np.zeros((n,), np.uint64),
            probe_overflow=np.zeros((n,), np.bool_),
        )

    # History stays empty on the sharded fast path (history-flagged accounts
    # are excluded by precondition P1); it exists so the Ledger pytree is
    # uniform.  One row per shard keeps every leaf shardable over axis 0.
    ledger = Ledger(
        accounts=table(accounts_capacity, ACCOUNT_COLS),
        transfers=table(transfers_capacity, TRANSFER_COLS),
        posted=table(posted_capacity, POSTED_COLS),
        history=sm.History(
            cols={
                name: np.zeros((n,), dt)
                for name, dt in sm.HISTORY_COLS.items()
            },
            count=np.zeros((n,), np.uint64),
        ),
    )
    spec = NamedSharding(mesh, P(AXIS))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, spec), ledger)


def _specs_like(tree):
    return jax.tree_util.tree_map(lambda _: P(AXIS), tree)


def _replicated_like(tree):
    return jax.tree_util.tree_map(lambda _: P(), tree)


class _ShardGather:
    """Per-shard masked probe + psum combine for one key set."""

    def __init__(self, table: ht.Table, lo, hi, n_shards: int, shift: int):
        my = jax.lax.axis_index(AXIS).astype(jnp.uint64)
        h = mix64(lo, hi)
        self.owner_mask = (h & jnp.uint64(n_shards - 1)) == my
        look = ht.lookup(table, lo, hi, MAX_PROBE, hash_shift=shift)
        local_cap = table.capacity
        self.found_l = look.found & self.owner_mask
        self.slot_l = look.slot
        self.overflow_l = look.overflow  # local probe exhaustion (bool)
        gslot = my * jnp.uint64(local_cap) + look.slot
        self.found = (
            jax.lax.psum(self.found_l.astype(jnp.uint32), AXIS) > 0
        )
        self.gslot = jax.lax.psum(
            jnp.where(self.found_l, gslot, jnp.uint64(0)), AXIS
        )

    def rows(self, table: ht.Table) -> Dict[str, jax.Array]:
        local = ht.gather_cols(table, self.slot_l, self.found_l)
        return {k: jax.lax.psum(v, AXIS) for k, v in local.items()}


def sharded_create_transfers(mesh: Mesh):
    """Build the jitted sharded create_transfers step for ``mesh``.

    Returns fn(ledger, batch, count, timestamp) -> (ledger, codes), with the
    ledger sharded per make_sharded_ledger and batch/count/timestamp
    replicated."""
    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def local_step(ledger: Ledger, batch, count, timestamp):
        acc, tr = ledger.accounts, ledger.transfers
        local_acc_cap = acc.capacity

        dr_g = _ShardGather(
            acc, batch["debit_account_id_lo"], batch["debit_account_id_hi"],
            n_shards, shift,
        )
        cr_g = _ShardGather(
            acc, batch["credit_account_id_lo"], batch["credit_account_id_hi"],
            n_shards, shift,
        )
        ex_g = _ShardGather(tr, batch["id_lo"], batch["id_hi"], n_shards, shift)

        lane = jnp.arange(batch["id_lo"].shape[0], dtype=jnp.int32)
        valid = lane < count.astype(jnp.int32)
        ctx = TransferCtx(
            dr_found=dr_g.found & valid,
            cr_found=cr_g.found & valid,
            dr_slot=dr_g.gslot,
            cr_slot=cr_g.gslot,
            dr=dr_g.rows(acc),
            cr=cr_g.rows(acc),
            ex_found=ex_g.found & valid,
            e=ex_g.rows(tr),
        )

        # Replicated validation (identical on every shard).
        codes, ok, ts, pending = sm.transfer_codes(batch, ctx, count, timestamp)

        # Balance plan over global slots, applied owner-locally.
        global_cap = local_acc_cap * n_shards
        plan = sm.balance_plan(
            ctx.dr_slot, ctx.cr_slot, ok,
            batch["amount_lo"], pending, global_cap,
        )
        my = jax.lax.axis_index(AXIS).astype(jnp.uint64)
        base = my * jnp.uint64(local_acc_cap)
        in_range = (plan.s_slot >= base) & (
            plan.s_slot < base + jnp.uint64(local_acc_cap)
        )
        local_plan = sm.BalancePlan(
            s_slot=jnp.where(in_range, plan.s_slot - base, jnp.uint64(local_acc_cap)),
            head=plan.head & in_range,
            deltas=plan.deltas,
        )
        accounts = sm.apply_balance_plan(acc, local_plan)

        # Owner-local transfer inserts.
        rows = sm.transfer_rows(batch, count, timestamp)
        transfers, _ = ht.insert(
            tr, batch["id_lo"], batch["id_hi"],
            ok & ex_g.owner_mask, rows, MAX_PROBE, hash_shift=shift,
        )

        return ledger.replace(accounts=accounts, transfers=transfers), codes

    def step(ledger, batch, count, timestamp):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger), _replicated_like(batch), P(), P()),
            out_specs=(_specs_like(ledger), P()),
            # vma-checking is off because ht.lookup's probe while_loop mixes
            # replicated (keys) and shard-varying (table) carry values; the
            # library kernels are backend-agnostic and cannot pvary-annotate.
            # Correctness is covered by byte-parity vs single-chip in
            # tests/test_sharded.py instead.
            check_vma=False,
        )(ledger, batch, count, timestamp)

    return jax.jit(step, donate_argnames=("ledger",))


def sharded_create_transfers_full(mesh: Mesh, max_passes: int = None):
    """The fully-general transfer kernel (two-phase/balancing/limits) over
    the device mesh.  ``max_passes`` mirrors LedgerConfig.jacobi_max_passes
    (defaults to the kernel's budget) so both serving paths honor the knob.

    Context is gathered by masked probes + psum (after which every shard
    holds the full replicated GatherCtx), the pure Jacobi/ladder core runs
    replicated, and claims/scatters/inserts apply owner-locally — so the
    result is byte-identical to the single-chip kernel. History-flagged
    accounts route (FLAG_SEQ) instead of applying: history is an ordered
    append log, which stays a single-chip structure.

    Returns fn(ledger, batch, count, timestamp) -> (ledger, codes, kflags).
    """
    from ..ops import transfer_full as _tf

    if max_passes is None:
        max_passes = _tf._MAX_PASSES
    from ..ops import transfer_full as tf
    from ..ops.state_machine import TF_POST, TF_VOID

    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def _view(g: _ShardGather, table: ht.Table, found) -> tf.AccountView:
        rows = g.rows(table)
        return tf.AccountView(
            found=found,
            slot=g.gslot,
            flags=rows["flags"],
            ledger=rows["ledger"],
            bal={
                f + l: rows[f + l]
                for f in ("debits_pending", "debits_posted",
                          "credits_pending", "credits_posted")
                for l in ("_lo", "_hi")
            },
        )

    def local_step(ledger: Ledger, batch, count, timestamp):
        acc, tr, posted_t = ledger.accounts, ledger.transfers, ledger.posted
        n = batch["id_lo"].shape[0]
        lane = jnp.arange(n, dtype=jnp.int32)
        valid = lane < count.astype(jnp.int32)
        postvoid = (
            ((batch["flags"] & TF_POST) != 0) | ((batch["flags"] & TF_VOID) != 0)
        ) & valid

        ex_g = _ShardGather(tr, batch["id_lo"], batch["id_hi"], n_shards, shift)
        # Zero-mask by `valid` exactly like the single-chip gather
        # (ex_found = found & valid there): every current consumer is gated
        # on ex_found anyway, but an unmasked row would be a latent
        # byte-parity divergence if e_tab ever gains another consumer.
        e_tab = {
            k: jnp.where(ex_g.found & valid, v, jnp.zeros_like(v))
            for k, v in ex_g.rows(tr).items()
        }
        p_g = _ShardGather(
            tr, batch["pending_id_lo"], batch["pending_id_hi"], n_shards, shift
        )
        p_tab_found = p_g.found & postvoid
        # Zero-mask rows exactly like the single-chip gather (mask includes
        # postvoid): the core treats zeros as "no row".
        p_tab = {
            k: jnp.where(p_tab_found, v, jnp.zeros_like(v))
            for k, v in p_g.rows(tr).items()
        }

        drT_g = _ShardGather(
            acc, batch["debit_account_id_lo"], batch["debit_account_id_hi"],
            n_shards, shift,
        )
        crT_g = _ShardGather(
            acc, batch["credit_account_id_lo"], batch["credit_account_id_hi"],
            n_shards, shift,
        )
        pdr_g = _ShardGather(
            acc, p_tab["debit_account_id_lo"], p_tab["debit_account_id_hi"],
            n_shards, shift,
        )
        pcr_g = _ShardGather(
            acc, p_tab["credit_account_id_lo"], p_tab["credit_account_id_hi"],
            n_shards, shift,
        )
        postedT_g = _ShardGather(
            posted_t, p_tab["timestamp"], jnp.zeros_like(p_tab["timestamp"]),
            n_shards, shift,
        )
        postedT_found = postedT_g.found & p_tab_found
        postedT_val = postedT_g.rows(posted_t)["fulfillment"]

        def any_shard(local_bool):
            return jax.lax.psum(local_bool.astype(jnp.uint32), AXIS) > 0

        probe_grow = (
            jnp.where(
                any_shard(drT_g.overflow_l | crT_g.overflow_l
                          | pdr_g.overflow_l | pcr_g.overflow_l),
                jnp.uint32(tf.FLAG_GROW_ACCOUNTS), jnp.uint32(0),
            )
            | jnp.where(
                any_shard(ex_g.overflow_l | p_g.overflow_l),
                jnp.uint32(tf.FLAG_GROW_TRANSFERS), jnp.uint32(0),
            )
            | jnp.where(
                any_shard(postedT_g.overflow_l),
                jnp.uint32(tf.FLAG_GROW_POSTED), jnp.uint32(0),
            )
        )

        ctx = tf.GatherCtx(
            ex_found=ex_g.found & valid,
            e_tab=e_tab,
            p_tab_found=p_tab_found,
            p_tab=p_tab,
            drT=_view(drT_g, acc, drT_g.found & valid),
            crT=_view(crT_g, acc, crT_g.found & valid),
            pdr=_view(pdr_g, acc, pdr_g.found & p_tab_found),
            pcr=_view(pcr_g, acc, pcr_g.found & p_tab_found),
            postedT_found=postedT_found,
            postedT_val=postedT_val,
            probe_grow=probe_grow,
            accounts_capacity=jnp.uint64(acc.capacity * n_shards),
        )
        plan = tf._kernel_core(ctx, batch, count, timestamp, max_passes)

        # History admission: the mesh ledger has no history log — route
        # instead of silently dropping rows.
        route = plan.route | jnp.where(
            jnp.any(plan.do_hist), jnp.uint32(tf.FLAG_SEQ), jnp.uint32(0)
        )

        # Owner-local claims (insert-probe overflow routes with nothing
        # applied, exactly like single-chip).
        t_claim, t_ovf = ht.claim_slots(
            tr, batch["id_lo"], batch["id_hi"],
            plan.ok & ex_g.owner_mask, MAX_PROBE, hash_shift=shift,
        )
        my = jax.lax.axis_index(AXIS).astype(jnp.uint64)
        pk_owner = (
            mix64(plan.posted_key, jnp.zeros_like(plan.posted_key))
            & jnp.uint64(n_shards - 1)
        ) == my
        p_claim, p_ovf = ht.claim_slots(
            posted_t, plan.posted_key, jnp.zeros_like(plan.posted_key),
            plan.pv_ok & pk_owner, MAX_PROBE, hash_shift=shift,
        )
        kflags = (
            probe_grow
            | route
            | jnp.where(
                any_shard(t_ovf), jnp.uint32(tf.FLAG_GROW_TRANSFERS),
                jnp.uint32(0),
            )
            | jnp.where(
                any_shard(p_ovf), jnp.uint32(tf.FLAG_GROW_POSTED),
                jnp.uint32(0),
            )
        )
        commit = kflags == jnp.uint32(0)

        # Balance scatter: global slot runs, owner-local writes.
        local_cap = acc.capacity
        base = my * jnp.uint64(local_cap)
        in_range = (plan.s_slot >= base) & (
            plan.s_slot < base + jnp.uint64(local_cap)
        )
        scat = plan.scat & commit & in_range
        sentinel = jnp.uint64(local_cap)
        accounts = ht.scatter_cols(
            acc, jnp.where(scat, plan.s_slot - base, sentinel), scat,
            plan.bal_incl,
        )

        ins_rows = {
            name: plan.row[name].astype(dt)
            for name, dt in TRANSFER_COLS.items()
        }
        transfers = ht.write_rows(
            tr, batch["id_lo"], batch["id_hi"], t_claim,
            plan.ok & commit & ex_g.owner_mask, ins_rows,
        )
        posted_out = ht.write_rows(
            posted_t, plan.posted_key, jnp.zeros_like(plan.posted_key),
            p_claim, plan.pv_ok & commit & pk_owner,
            {"fulfillment": jnp.where(plan.post, jnp.uint32(1), jnp.uint32(2))},
        )

        out = ledger.replace(
            accounts=accounts, transfers=transfers, posted=posted_out
        )
        return out, plan.codes, kflags

    def step(ledger, batch, count, timestamp):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger), _replicated_like(batch), P(), P()),
            out_specs=(_specs_like(ledger), P(), P()),
            check_vma=False,  # see sharded_create_transfers' justification
        )(ledger, batch, count, timestamp)

    return jax.jit(step, donate_argnames=("ledger",))


def sharded_lookup(mesh: Mesh, table_name: str):
    """Jitted sharded point-lookup over ``ledger.<table_name>``: every
    shard probes its local partition for the replicated id batch; one psum
    per column assembles the full rows on every chip.

    Returns fn(ledger, id_lo, id_hi) -> (found[b], rows{col: [b]})."""
    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def local_step(ledger: Ledger, id_lo, id_hi):
        table = getattr(ledger, table_name)
        g = _ShardGather(table, id_lo, id_hi, n_shards, shift)
        rows = g.rows(table)
        # Match the single-chip lookup shape (sm.lookup_* include the id
        # columns so types.from_soa can build full wire rows).
        rows["id_lo"] = jnp.where(g.found, id_lo, jnp.uint64(0))
        rows["id_hi"] = jnp.where(g.found, id_hi, jnp.uint64(0))
        return g.found, rows

    def step(ledger, id_lo, id_hi):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger), P(), P()),
            out_specs=(P(), P()),
            check_vma=False,  # see sharded_create_transfers' justification
        )(ledger, id_lo, id_hi)

    return jax.jit(step)


def sharded_create_accounts(mesh: Mesh):
    """Jitted sharded create_accounts step for ``mesh``."""
    n_shards = mesh.devices.size
    shift = n_shards.bit_length() - 1

    def local_step(ledger: Ledger, batch, count, timestamp):
        acc = ledger.accounts
        g = _ShardGather(acc, batch["id_lo"], batch["id_hi"], n_shards, shift)
        lane = jnp.arange(batch["id_lo"].shape[0], dtype=jnp.int32)
        valid = lane < count.astype(jnp.int32)
        codes, ok = sm.account_codes(
            batch, g.found & valid, g.rows(acc), count
        )
        rows = sm.account_rows(batch, count, timestamp)
        accounts, _ = ht.insert(
            acc, batch["id_lo"], batch["id_hi"],
            ok & g.owner_mask, rows, MAX_PROBE, hash_shift=shift,
        )
        return ledger.replace(accounts=accounts), codes

    def step(ledger, batch, count, timestamp):
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_specs_like(ledger), _replicated_like(batch), P(), P()),
            out_specs=(_specs_like(ledger), P()),
            # vma-checking is off because ht.lookup's probe while_loop mixes
            # replicated (keys) and shard-varying (table) carry values; the
            # library kernels are backend-agnostic and cannot pvary-annotate.
            # Correctness is covered by byte-parity vs single-chip in
            # tests/test_sharded.py instead.
            check_vma=False,
        )(ledger, batch, count, timestamp)

    return jax.jit(step, donate_argnames=("ledger",))
