"""TB_SANITIZE=1 runtime sanitizer: make the tbsan bug classes fail LOUD.

The static suite (tools/tblint rules donation / size-class / lane-race /
shard-rep) proves discipline over the source; this module is its runtime
twin for the cases static analysis cannot close — test/CI-only (the
checks cost real work: buffer fills, D2H template reads), never armed in
production serving.  Three checks, in the VOPR spirit of "assert the
invariant, then search for the violation":

- DONATION POISONING — when a pooled staging set goes back on the
  machine's free-list, every byte is filled with the 0xA5 sentinel.  A
  use-after-release (the runtime shape of use-after-donate: a dispatch
  closure or index append still holding the pooled numpy mirror after
  resolve released it) now reads screaming garbage instead of stale
  plausible rows, and ``assert_not_poisoned`` turns it into a hard error
  at the consumer.  The cached zero-count pad template gets the dual
  check: ``template_guard`` verifies it is still all-zero at every reuse,
  so a kernel that donated it (the machine._pad_soa contract) is caught
  at the NEXT commit, not at the next digest mismatch.

- RECOMPILE TRIPWIRE — ``compile_tripwire`` diffs
  ``jaxenv.compile_count()`` around a region that must not compile
  (serving after warmup, a bench timed loop).  The PR 10 merkle
  recompile bug was found after the fact in bench p99; the tripwire
  makes the same class fail at the region, with the count.

- REGISTRY LEAK GUARD — ``assert_registry_disabled`` catches a test or
  tool that enabled the process-global obs registry and leaked it on
  (the PR 10 metrics-registry leak class): every later test then
  silently pays recording costs and inherits foreign series.

Every trip increments both a module-local counter (``counts()`` — works
with the registry off) and, when the registry is enabled, a
``sanitize.*`` series so CI smokes can assert them in METRICS.json.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Optional

import numpy as np

__all__ = [
    "SanitizeError", "enabled", "strict", "SENTINEL_BYTE",
    "poison", "is_poisoned", "assert_not_poisoned", "template_guard",
    "compile_tripwire", "assert_registry_disabled", "counts",
]

#: Every byte of a poisoned buffer (0xA5A5... in every lane width): not
#: 0x00 (a plausible pad), not 0xFF (a plausible sentinel id), and odd in
#: every field so poisoned ids/amounts can never look committed.
SENTINEL_BYTE = 0xA5


class SanitizeError(AssertionError):
    """A sanitizer invariant was violated (loud by design)."""


def enabled() -> bool:
    """TB_SANITIZE=1 arms the runtime checks (test/CI-only)."""
    return os.environ.get("TB_SANITIZE", "") not in ("", "0")


def strict() -> bool:
    """TB_SANITIZE_STRICT=1 escalates tripwire warnings to raises."""
    return os.environ.get("TB_SANITIZE_STRICT", "") not in ("", "0")


# Module-local trip counters: assertable without the obs registry.
_COUNTS: Dict[str, int] = {}


def counts() -> Dict[str, int]:
    """Snapshot of the sanitizer's own trip counters."""
    return dict(_COUNTS)


def _count(name: str, n: int = 1) -> None:
    _COUNTS[name] = _COUNTS.get(name, 0) + n
    from .obs.metrics import registry

    # The registry series keep their documented TB_SANITIZE=1 semantics:
    # a plain bench run that arms a compile_tripwire must not make an
    # operator's METRICS.json claim the sanitizer ran.  The module-local
    # count above still records for such callers.
    if registry.enabled and enabled():
        registry.counter(f"sanitize.{name}").inc(n)


def _reset_counts() -> None:
    """Tests only."""
    _COUNTS.clear()


# -- donation poisoning ------------------------------------------------------

def poison(buffers: Iterable[np.ndarray]) -> int:
    """Fill each numpy buffer with the sentinel byte; returns how many
    buffers were poisoned.  Used by machine._stage_release on every
    pooled staging set under TB_SANITIZE."""
    n = 0
    for buf in buffers:
        np.asarray(buf).view(np.uint8).fill(SENTINEL_BYTE)
        n += 1
    if n:
        _count("donation_poisons", n)
    return n


def is_poisoned(buf) -> bool:
    """True when the buffer is entirely sentinel bytes (a released pooled
    buffer nobody refilled).  Empty buffers are never poisoned."""
    flat = np.asarray(buf).view(np.uint8)
    return flat.size > 0 and bool((flat == SENTINEL_BYTE).all())


def assert_not_poisoned(buf, where: str = "buffer") -> None:
    """Consumer-side check: reading a fully-poisoned buffer IS the
    use-after-donate, stopped at the read instead of the digest."""
    if is_poisoned(buf):
        _count("use_after_donate")
        raise SanitizeError(
            f"use-after-donate: {where} is sentinel-poisoned (0x"
            f"{SENTINEL_BYTE:02X} fill) — it was released/donated and "
            "must not be read again"
        )


def template_guard(template: Dict[str, object],
                   where: str = "cached zero template") -> None:
    """Verify a cached zero-count template is still all-zero.  A donated
    template (machine._pad_soa's contract: batch-donating kernels must
    get a COPY) shows up here as XLA scratch at the next reuse."""
    _count("template_checks")
    for name, col in template.items():
        host = np.asarray(col)
        if host.size and host.any():
            _count("template_corruptions")
            raise SanitizeError(
                f"{where}: column {name!r} is no longer zero — the "
                "template was donated to a kernel (copy before donating)"
            )


# -- recompile tripwire ------------------------------------------------------

def _warn_unarmed(where: str) -> None:
    """The jax.monitoring listener failed to install (private-API drift):
    compile_count() is frozen and every tripwire delta is vacuously 0.
    Say so loudly ONCE — a silent always-green tripwire is worse than
    none."""
    if _COUNTS.get("tripwire_unarmed"):
        _COUNTS["tripwire_unarmed"] += 1
        return
    _count("tripwire_unarmed")
    import sys

    print(
        f"# SANITIZE: compile listener unavailable (jax.monitoring import "
        f"failed) — the recompile tripwire for {where!r} cannot observe "
        "compiles; its zero count is VACUOUS",
        file=sys.stderr,
    )

class TripwireReport:
    """Result of one compile_tripwire region.  ``armed`` is False when
    the jax.monitoring listener could not install — the count is then
    VACUOUS (always 0), not proof of a compile-free region."""

    __slots__ = ("label", "compiles", "armed")

    def __init__(self, label: str) -> None:
        self.label = label
        self.compiles = 0
        self.armed = False


class compile_tripwire:
    """Context manager asserting ZERO XLA compiles inside the region.

    Requires jaxenv.instrument_compiles() (installed on entry).  On a
    nonzero delta: counts ``sanitize.recompiles``, warns loudly, and —
    when ``raise_on_trip`` (default: TB_SANITIZE_STRICT) — raises
    SanitizeError.  The report object is yielded so callers (bench timed
    loops) can record the count either way; ``quiet=True`` suppresses
    this module's stderr warning for callers that print their own
    context-specific one (bench names per_batch_us / payload.harness)."""

    def __init__(self, label: str,
                 raise_on_trip: Optional[bool] = None,
                 quiet: bool = False) -> None:
        self.report = TripwireReport(label)
        self._raise = raise_on_trip
        self._quiet = quiet
        self._base = 0

    def __enter__(self) -> TripwireReport:
        from . import jaxenv

        self.report.armed = jaxenv.instrument_compiles()
        if not self.report.armed:
            _warn_unarmed(self.report.label)
        self._base = jaxenv.compile_count()
        return self.report

    def __exit__(self, exc_type, exc, tb) -> None:
        from . import jaxenv

        delta = jaxenv.compile_count() - self._base
        self.report.compiles = delta
        if delta and exc_type is None:
            _count("recompiles", delta)
            if not self._quiet:
                import sys

                print(
                    f"# SANITIZE: {delta} XLA compile(s) inside "
                    f"{self.report.label!r} — a region that must not "
                    "compile (warmup bled into the clock / an input shape "
                    "is not size-class stable)",
                    file=sys.stderr,
                )
            if self._raise if self._raise is not None else strict():
                raise SanitizeError(
                    f"recompile tripwire: {delta} compile(s) inside "
                    f"{self.report.label!r}"
                )


def recompile_trip(where: str, delta: int, strict_ok: bool = True) -> None:
    """Record ``delta`` unexpected compiles observed in ``where`` (the
    machine's post-warmup serving check): count, warn loudly, raise under
    TB_SANITIZE_STRICT.  Callers re-baseline so one burst warns once.

    ``strict_ok=False`` downgrades a strict raise to the warning: the
    machine passes it after a capacity growth, when kernel variants not
    yet exercised at the NEW capacity may legitimately first-compile long
    after the growth's one-readback grace window closed."""
    _count("recompiles", delta)
    import sys

    print(
        f"# SANITIZE: {delta} XLA compile(s) in {where} after warmup — "
        "an input shape or static arg is not size-class stable "
        "(tools/tblint --rule size-class names the usual suspects)",
        file=sys.stderr,
    )
    if strict_ok and strict():
        raise SanitizeError(
            f"recompile tripwire: {delta} compile(s) in {where} "
            "after warmup"
        )


# -- metrics-registry leak guard ---------------------------------------------

def assert_registry_disabled(where: str = "teardown") -> None:
    """The process-global obs registry must be DISABLED outside an
    explicitly-armed scope; a leaked enable taxes every later test and
    mixes foreign series into the next snapshot (the PR 10 leak class).
    Disables the registry before raising so one leak doesn't cascade."""
    from .obs.metrics import registry

    if registry.enabled:
        _count("registry_leaks")
        # Disable (stop the cascade) but do NOT reset: the leaked series
        # are the postmortem evidence of WHAT ran enabled.
        registry.disable()
        raise SanitizeError(
            f"metrics-registry leak at {where}: the process-global obs "
            "registry was left ENABLED — wrap enable() in "
            "registry.enabled_scope() or try/finally disable()+reset()"
        )
