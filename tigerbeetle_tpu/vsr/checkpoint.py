"""Checkpoint snapshots: durable images of the device-resident ledger.

The reference persists state-machine data through the LSM forest into grid
blocks at every checkpoint (replica.zig:3153-3169).  Here the working set is
the HBM ledger itself, so a checkpoint is: device→host transfer of the table
arrays, one atomically-written compressed snapshot file per checkpoint op, and
the snapshot's whole-file AEGIS checksum + state-machine digest recorded in
the superblock (superblock.py).  Restart = load snapshot (verify checksum) +
replay WAL ops beyond the checkpoint (journal.py).

Snapshot files live next to the data file as ``<data>.checkpoint.<op>``;
the previous snapshot is removed only after the superblock referencing the
new one is durable.
"""

from __future__ import annotations

import io
import json
import os
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .checksum import checksum
from ..ops import hash_table as ht
from ..ops import state_machine as sm
from ..utils.fs import atomic_write

TABLE_NAMES = ("accounts", "transfers", "posted")
# Per-table fields that are NOT per-slot columns (scalars) — shared with the
# LSM forest's delta computation and the sparse base encoder: the two must
# agree or a scalar gets treated as a (capacity,)-shaped column.
TABLE_SCALARS = ("count", "probe_overflow")


def _table_arrays(prefix: str, table: ht.Table, out: Dict[str, np.ndarray]) -> None:
    out[f"{prefix}/key_lo"] = np.asarray(table.key_lo)
    out[f"{prefix}/key_hi"] = np.asarray(table.key_hi)
    out[f"{prefix}/tombstone"] = np.asarray(table.tombstone)
    out[f"{prefix}/count"] = np.asarray(table.count)
    out[f"{prefix}/probe_overflow"] = np.asarray(table.probe_overflow)
    for name, col in table.cols.items():
        out[f"{prefix}/cols/{name}"] = np.asarray(col)


def _load_table(prefix: str, z, keys=None) -> ht.Table:
    cols = {}
    cols_prefix = f"{prefix}/cols/"
    for key in keys if keys is not None else z.files:
        if key.startswith(cols_prefix):
            cols[key[len(cols_prefix):]] = jnp.asarray(z[key])
    return ht.Table(
        key_lo=jnp.asarray(z[f"{prefix}/key_lo"]),
        key_hi=jnp.asarray(z[f"{prefix}/key_hi"]),
        tombstone=jnp.asarray(z[f"{prefix}/tombstone"]),
        cols=cols,
        count=jnp.asarray(z[f"{prefix}/count"]),
        probe_overflow=jnp.asarray(z[f"{prefix}/probe_overflow"]),
    )


def path_for(data_path: str, op: int) -> str:
    return f"{data_path}.checkpoint.{op}"


def ledger_to_arrays(ledger: sm.Ledger) -> Dict[str, np.ndarray]:
    """Flatten a ledger into the snapshot's flat key->array dict (the same
    keys the npz uses); shared with the LSM forest's delta computation."""
    arrays: Dict[str, np.ndarray] = {}
    for name in TABLE_NAMES:
        _table_arrays(name, getattr(ledger, name), arrays)
    for name, col in ledger.history.cols.items():
        arrays[f"history/cols/{name}"] = np.asarray(col)
    arrays["history/count"] = np.asarray(ledger.history.count)
    return arrays


def arrays_to_ledger(arrays) -> sm.Ledger:
    """Inverse of ledger_to_arrays; accepts any mapping with npz-style keys
    (an NpzFile or a plain dict)."""
    keys = arrays.files if hasattr(arrays, "files") else arrays.keys()
    return sm.Ledger(
        accounts=_load_table("accounts", arrays, keys),
        transfers=_load_table("transfers", arrays, keys),
        posted=_load_table("posted", arrays, keys),
        history=sm.History(
            cols={
                key[len("history/cols/"):]: jnp.asarray(arrays[key])
                for key in keys
                if key.startswith("history/cols/")
            },
            count=jnp.asarray(arrays["history/count"]),
        )
        if "history/count" in keys
        else sm.make_history(1),
    )


# Marker key identifying a sparse base snapshot (occupied rows only).
SPARSE_MARKER = "sparse_base_v1"


def sparsify_arrays(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Occupied-rows-only encoding of a snapshot dict.

    With preallocated tables (config.zig-style static allocation) the hash
    arrays are mostly empty; a dense base write costs O(capacity) — measured
    as a multi-second, cluster-wide stall when three replicas hit their
    aligned first checkpoint on 2^22-slot tables.  Sparse encoding makes
    checkpoint cost scale with data instead: a row is kept iff any of its
    columns holds a nonzero byte (zero rows are empty hash slots by
    construction — key 0 is the empty sentinel and tombstones are flagged),
    so expansion is bit-exact."""
    out: Dict[str, np.ndarray] = {SPARSE_MARKER: np.uint64(1)}
    for t in TABLE_NAMES:
        prefix = f"{t}/"
        per_slot = [
            k for k in arrays
            if k.startswith(prefix)
            and k.split("/")[-1] not in TABLE_SCALARS
        ]
        cap = arrays[f"{t}/key_lo"].shape[0]
        occ = np.zeros(cap, dtype=bool)
        for k in per_slot:
            occ |= arrays[k] != 0
        (slots,) = np.nonzero(occ)
        out[f"{t}/capacity"] = np.uint64(cap)
        out[f"{t}/slots"] = slots.astype(np.uint64)
        for k in per_slot:
            out[f"sp/{k}"] = arrays[k][slots]
        out[f"{t}/count"] = arrays[f"{t}/count"]
        out[f"{t}/probe_overflow"] = arrays[f"{t}/probe_overflow"]
    hcount = int(arrays["history/count"])
    hcap = 0
    for k in arrays:
        if k.startswith("history/cols/"):
            hcap = arrays[k].shape[0]
            out[f"sp/{k}"] = arrays[k][:hcount]
    out["history/capacity"] = np.uint64(hcap)
    out["history/count"] = arrays["history/count"]
    return out


def densify_arrays(arrays) -> Dict[str, np.ndarray]:
    """Inverse of sparsify_arrays; passes dense snapshots through unchanged
    (old checkpoints stay loadable)."""
    keys = list(arrays.files if hasattr(arrays, "files") else arrays.keys())
    if SPARSE_MARKER not in keys:
        return {k: arrays[k] for k in keys}
    out: Dict[str, np.ndarray] = {}
    for t in TABLE_NAMES:
        cap = int(arrays[f"{t}/capacity"])
        slots = np.asarray(arrays[f"{t}/slots"]).astype(np.int64)
        prefix = f"sp/{t}/"
        for k in keys:
            if k.startswith(prefix):
                rows = np.asarray(arrays[k])
                full = np.zeros((cap,) + rows.shape[1:], dtype=rows.dtype)
                full[slots] = rows
                out[k[3:]] = full
        out[f"{t}/count"] = np.asarray(arrays[f"{t}/count"])
        out[f"{t}/probe_overflow"] = np.asarray(
            arrays[f"{t}/probe_overflow"]
        )
    hcap = int(arrays["history/capacity"])
    hcount = int(arrays["history/count"])
    for k in keys:
        if k.startswith("sp/history/cols/"):
            rows = np.asarray(arrays[k])
            full = np.zeros((hcap,) + rows.shape[1:], dtype=rows.dtype)
            full[:hcount] = rows
            out[k[3:]] = full
    out["history/count"] = np.asarray(arrays["history/count"])
    for k in keys:
        # Passthrough for non-table payloads (meta, op, ...).
        if (
            k not in out
            and k != SPARSE_MARKER
            and not k.startswith("sp/")
            and not any(
                k.startswith(f"{t}/") for t in TABLE_NAMES + ("history",)
            )
        ):
            out[k] = arrays[k]
    return out


def save(
    data_path: str, op: int, ledger: sm.Ledger, meta: Optional[dict] = None
) -> Tuple[str, int]:
    """Write the snapshot for checkpoint ``op`` atomically; returns
    (path, file_checksum)."""
    return save_arrays(data_path, op, ledger_to_arrays(ledger), meta)


def save_arrays(
    data_path: str, op: int, arrays: Dict[str, np.ndarray],
    meta: Optional[dict] = None,
) -> Tuple[str, int]:
    """save() on a pre-captured host snapshot (ledger_to_arrays output) —
    lets the overlapped-checkpoint thread write without touching device
    state."""
    arrays = dict(arrays)
    arrays["meta"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    ).copy()

    buf = io.BytesIO()
    np.savez(buf, **arrays)  # uncompressed: snapshot speed over size
    blob = buf.getvalue()
    file_checksum = checksum(blob)

    path = path_for(data_path, op)
    atomic_write(path, blob)
    return path, file_checksum


def load(
    data_path: str, op: int, expected_checksum: int
) -> Tuple[sm.Ledger, dict]:
    """Load + verify the snapshot for checkpoint ``op``."""
    path = path_for(data_path, op)
    with open(path, "rb") as f:
        blob = f.read()
    actual = checksum(blob)
    if actual != expected_checksum:
        raise RuntimeError(
            f"checkpoint {path}: checksum mismatch "
            f"(got {actual:#x}, superblock says {expected_checksum:#x})"
        )
    z = np.load(io.BytesIO(blob))
    ledger = arrays_to_ledger(densify_arrays(z))
    meta = json.loads(bytes(z["meta"]).decode()) if "meta" in z.files else {}
    return ledger, meta


def remove_older_than(data_path: str, op: int) -> None:
    """GC snapshots strictly older than ``op`` (called after the superblock
    referencing ``op`` is durable)."""
    directory = os.path.dirname(os.path.abspath(data_path)) or "."
    base = os.path.basename(data_path) + ".checkpoint."
    for entry in os.listdir(directory):
        if entry.startswith(base):
            tail = entry[len(base):]
            if tail.isdigit() and int(tail) < op:
                os.unlink(os.path.join(directory, entry))
