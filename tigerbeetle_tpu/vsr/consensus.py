"""VSR consensus: the multi-replica message-driven participant.

Mirrors the reference replica's consensus protocol (src/vsr/replica.zig):

- Normal operation: the primary (``view % replica_count``) turns requests
  into prepares (op + timestamp assigned, hash-chained — :1308-1337),
  journals locally, and **ring-replicates**: each replica forwards the
  prepare to the next replica in the ring so primary egress stays 1:1
  (:1339-1363).  Backups journal and send prepare_ok to the primary; commit
  happens at a replication quorum (:1469+), in op order, and the primary
  replies to the client (:3678-3836).  Backups learn the commit number from
  prepare headers and periodic commit heartbeats and execute via
  commit_journal (:1591, :3176).
- View change: a backup that stops hearing from the primary broadcasts
  start_view_change for view+1; at a view-change quorum of SVCs each replica
  sends do_view_change (carrying its journal-suffix headers) to the new
  primary, which selects the canonical log — max (log_view, op) — repairs
  any prepares it lacks, and broadcasts start_view (:1702-2013).  Backups
  install the canonical suffix, repair missing bodies, and re-ack the
  uncommitted suffix so it can commit in the new view.
- Repair: request_prepare/request_headers fetch lost WAL entries from peers
  (:2048-2497); a replica whose WAL no longer overlaps the cluster's
  (primary checkpoint beyond its head) state-syncs the latest checkpoint
  snapshot in message-sized chunks (vsr/sync.zig).
- Clock: ping/pong round trips feed the Marzullo-filtered cluster clock
  (clock.py); the primary refuses to assign timestamps while unsynchronized
  (:1322-1325).

The class is transport-agnostic and deterministic: ``on_message`` and
``tick`` return ``(destination, bytes)`` envelopes; time comes from injected
monotonic/realtime sources.  The TCP bus (net/) and the VOPR simulator
(sim/) both drive this same code — the simulator's whole point (SURVEY §4.2)
is that the production consensus path is what gets fault-injected.

Quorums are flexible (vsr.zig:910-986): replication and view-change quorums
need only intersect, so e.g. a 6-replica cluster commits at 3 and
view-changes at 4 (docs/deploy/hardware.md:29-40).

Divergence from the reference, by design: view/log_view are persisted to the
superblock on view change via a quorum write of the full superblock state
(the reference journals view headers separately); and a replica recovering
from restart re-joins via request_start_view instead of a dedicated
recovering_head protocol.
"""

from __future__ import annotations

import dataclasses
import os
import random
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..obs.metrics import registry as _obs
from ..obs.txtrace import txtrace
from . import checkpoint as checkpoint_mod
from . import overload
from . import wire
from .clock import Clock
from .replica import ForestDamage, InvalidRequest, Replica, Session
from .superblock import SuperBlockState

# An outbound envelope: (("replica", index) | ("client", client_id), bytes).
Dst = Tuple[str, int]
Msg = Tuple[Dst, bytes]

NORMAL = "normal"
VIEW_CHANGE = "view_change"
RECOVERING = "recovering"
SYNCING = "syncing"

# Timeout cadences in ticks (a tick is ~10 ms wall / 1 step simulated;
# values mirror the reference's relative cadences, vsr.zig:543-712).
PING_INTERVAL = 25
COMMIT_HEARTBEAT = 10
PREPARE_RESEND = 15
NORMAL_HEARTBEAT = 100       # backup: primary presumed SUSPECT after this
PROBE_GRACE = 50             # direct-ping grace before campaigning
PRIMARY_GAP_MULT = 8         # silence budget: x the EWMA inter-word gap
PRIMARY_BUDGET_CAP = 600     # bounded failover: budget never exceeds this
PRIMARY_ABDICATE = 800       # primary commit-stall ticks before stepping down
_FLOOR_STALL_SYNC = 30       # commit-floor-starved heartbeats before syncing
VIEW_CHANGE_RESEND = 25      # SVC/DVC re-broadcast while in view change
VIEW_CHANGE_ESCALATE = 200   # stuck view change: try the next view
RECOVERING_RESEND = 30       # request_start_view cadence while recovering
REPAIR_INTERVAL = 15
SYNC_RESEND = 30
BLOCK_REPAIR_RESEND = 20     # per-chunk block-repair timeout before rotating

# Merkle-anchored incremental state sync (docs/state_sync.md).
SYNC_ROOTS_ATTEMPTS = 3      # unanswered sync_roots rounds PER PEER before
                             # degrading to the full-checkpoint path (covers
                             # merkle-off peers and version skew: an old
                             # responder never answers the new command)
SYNC_VERIFY_FAILURES = 3     # failed subtree/row verifications (lying or
                             # bit-flipped chunks) before degrading to full
SYNC_DIVERGENCE_MAX = 0.5    # diverging fraction of the top frontier above
                             # which descent cannot win (cold start, long
                             # absence): go straight to the full transfer

# request_blocks/block kind codes <-> forest file kinds.
_BLOCK_KIND_CODE = {
    "manifest": wire.BLOCK_KIND_MANIFEST,
    "base": wire.BLOCK_KIND_BASE,
    "run": wire.BLOCK_KIND_RUN,
    "cold": wire.BLOCK_KIND_COLD,
}
_BLOCK_KIND_NAME = {v: k for k, v in _BLOCK_KIND_CODE.items()}
TICK_NS = 10_000_000  # default tick length; the TCP bus overrides tick_ns


def quorums(replica_count: int) -> Tuple[int, int]:
    """(quorum_replication, quorum_view_change) — flexible quorums that
    always intersect (vsr.zig:910-986): 1/1, 2/2, 2/2, 2/3, 3/3, 3/4."""
    if replica_count == 1:
        return 1, 1
    majority = replica_count // 2 + 1
    q_replication = max(2, replica_count + 1 - majority)
    q_view_change = max(majority, replica_count + 1 - q_replication)
    assert q_replication + q_view_change > replica_count
    return q_replication, q_view_change


@dataclasses.dataclass
class PipelineEntry:
    """One in-flight prepare at the primary (replica.zig PipelineQueue)."""

    op: int
    checksum: int
    client: int                 # 0 for re-certified view-change suffix ops
    ok_from: Set[int] = dataclasses.field(default_factory=set)
    repair_rounds: int = 0      # timeouts spent with the body unreadable


class VsrReplica(Replica):
    """A full consensus participant; see module docstring."""

    def __init__(
        self,
        data_path: str,
        *,
        monotonic=None,
        realtime=None,
        seed: int = 0,
        **kwargs,
    ) -> None:
        import time as _time

        # Production defaults only: the VOPR cluster injects seeded sim
        # clocks through these parameters, so replay never sees wall time.
        realtime = realtime or _time.time_ns  # tblint: ignore[nondet]
        monotonic = monotonic or _time.monotonic_ns  # tblint: ignore[nondet]
        super().__init__(data_path, time_ns=realtime, **kwargs)
        self._monotonic = monotonic
        self._realtime = realtime
        self.status = RECOVERING
        self.log_view = 0
        self.commit_max = 0
        self._log_adopted_op = 0
        self.prng = random.Random(seed)
        # Overload control (vsr/overload.py; TB_OVERLOAD / the CLI's
        # --overload-control, sim injects explicitly).  Off by default:
        # every shed point below then behaves bit-identically to the
        # silent-drop behavior pinned seeds and the bench differential
        # replay against.
        self.overload_control = overload.enabled()
        # Byzantine ingress discipline (docs/fault_domains.md byzantine
        # domain).  ON by default — the checks only reject frames an honest
        # cluster never produces (forged origin fields, commit-checksum
        # conflicts), so every pinned seed replays bit-identically.  The
        # VOPR byzantine kind's negative control forces it off
        # (run_byzantine_seed(verify=False)) to prove the verification is
        # what carries safety, the scrub-off discipline.
        self.ingress_verify = True
        # Plain equivocation-detection count (registry-independent): the
        # VOPR byzantine kind reads it for its proof artifacts.
        self.byzantine_detections = 0
        # Model-checker hooks (sim/mc.py, docs/tbmc.md) — inert by default:
        # ``mc_mutations`` arms a seeded protocol mutation (tbmc's
        # passes-with-defenses / fails-without discipline); the
        # deterministic nonce makes request_start_view a pure function of
        # (replica, view) so canonical-state dedup survives RSV retries.
        self.mc_mutations: frozenset = frozenset()
        self.mc_deterministic_nonce = False
        # Content anchors (op -> canonical header checksum) learned from
        # SOURCE-AUTHENTICATED origins only: commit heartbeats
        # (commit_checksum) and installed view-change windows.  Backups
        # execute an op only when its journaled content parent-chains up to
        # an anchor (_content_certified) — the defense that makes a relayed
        # forged prepare inert: it can enter the journal, but it can never
        # EXECUTE, because no honest primary will ever anchor its checksum.
        self._anchors: Dict[int, int] = {}
        # Wire authentication (vsr/auth.py; docs/fault_domains.md "Byzantine
        # primary").  ``auth`` is a Keychain or None — OFF by default: every
        # frame then carries a zero MAC and the wire is bit-identical to the
        # pre-auth protocol, so pinned seeds and goldens are untouched.
        # Armed, every SOURCE_AUTHENTICATED ingress frame passes
        # _ingress_auth (MAC failures drop-and-count as auth.rejected.*);
        # ``auth_strict`` additionally rejects UNauthenticated replica
        # frames and upgrades certified commits from checksum anchors to
        # authenticated ack CERTIFICATES: prepare_ok is broadcast, and a
        # backup executes an op only once _cert_quorum() distinct
        # MAC-verified acks name its exact journaled checksum — the quorum
        # size guarantees two certificates for the same op intersect in an
        # honest replica, so a lying PRIMARY cannot fork execution.
        self.auth = None
        self.auth_strict = False
        # Ack certificates: op -> {checksum -> acking replica set},
        # accumulated only under auth_strict (bounded by _ACK_CERTS_MAX).
        self._ack_certs: Dict[int, Dict[int, Set[int]]] = {}

        # Journaled prepare headers by op for the live window (chain checks,
        # repair responses, DVC/SV bodies).  Pruned at checkpoint.
        self.headers: Dict[int, np.ndarray] = {}
        # Chain-verification floor: headers for ops >= _verify_floor are
        # known canonical (anchored in an SV/DVC install and parent-chained
        # downward); ops in (commit_min, _verify_floor) are SUSPECT — e.g.
        # a restarted replica's own WAL suffix, which may hold prepares a
        # view change since discarded.  _commit_journal refuses to commit a
        # suspect op (VOPR seed 9002: a stale view-0 register was committed
        # at op 1 because the view-4 SV window never reached down to it).
        self._verify_floor = 0
        # Out-of-order prepares waiting for the chain to catch up.
        self.stash: Dict[int, Tuple[np.ndarray, bytes]] = {}
        # Ops whose canonical header is installed but whose body is missing.
        self.missing: Dict[int, int] = {}  # op -> expected header checksum
        # View-change nack protocol: op -> replicas that provably NEVER
        # journaled the missing body (vsr.zig nacks).  At a nack quorum the
        # body cannot have been quorum-journaled, hence never committed,
        # and the new primary truncates it instead of stalling forever.
        self._nacks: Dict[int, Set[int]] = {}

        self.pipeline: Dict[int, PipelineEntry] = {}
        self.svc_from: Dict[int, Set[int]] = {}
        self.dvc_from: Dict[int, Dict[int, dict]] = {}
        self._dvc_sent_for: Optional[int] = None
        self._new_view_pending: Optional[int] = None
        self._pending_finish: Optional[int] = None

        # Sync state (lagging replica fetching a checkpoint snapshot).
        self.sync_target: Optional[dict] = None
        self.sync_buffer = bytearray()
        # Explicit sync responder (block-repair fallback: primary unknown,
        # rotate through peers); None = target the current view's primary.
        self._sync_peer: Optional[int] = None
        # Merkle-anchored incremental catch-up (docs/state_sync.md).
        # sync_mode_force="full" (TB_SYNC_MODE=full / --sync-mode full /
        # the VOPR forced-fallback control) pins the legacy full-checkpoint
        # transfer; sync_verify=False is the NEGATIVE CONTROL ONLY (the
        # scrub-off discipline): subtree/row/state verification off, so a
        # seeded lying responder demonstrably installs divergent state.
        self.sync_mode_force: Optional[str] = (
            "full" if os.environ.get("TB_SYNC_MODE") == "full" else None
        )
        self.sync_verify = True
        self.sync_divergence_max = SYNC_DIVERGENCE_MAX
        # Plain accounting (registry-independent; the VOPR catch-up kind
        # and tools/sync_smoke.py assert on it): lifetime totals plus the
        # mode the LAST completed install used.
        self.sync_stats = {
            "mode": None, "bytes_incremental": 0, "bytes_full": 0,
            "subtrees_shipped": 0, "rows_installed": 0,
            "chunk_retries": 0, "fallbacks": 0,
        }
        # Requester-side descent state (big numpy arrays — deliberately
        # OUTSIDE the mc capsule: reconstructible by re-entering the roots
        # flow) and the responder-side per-checkpoint pack cache.
        self._sync_local: Optional[dict] = None
        self._sync_pack_cache: Optional[object] = None

        # Peer block repair (grid_blocks_missing.zig's role): damaged
        # checkpoint files being refetched before the replica can open.
        self._block_repair: Optional[dict] = None
        self.blocks_repaired = 0
        # Cold-tier fetch during state sync: a synced checkpoint's
        # cold_manifest references the responder's LOCAL spill files, which
        # we must fetch (by checksum) before the install can complete.
        self._cold_fetch: Optional[dict] = None

        # Tick counters.  First ping fires on the first tick so the cluster
        # clock synchronizes before the first client request.
        self._ticks = 0
        self._last_ping = -PING_INTERVAL
        self._last_commit_sent = 0
        self._last_primary_word = 0
        # Primary-liveness suspicion (reference: RTT-adaptive timeouts,
        # vsr.zig:543-712).  A busy-but-alive primary (long fsync, scheduler
        # preemption on a shared host) must not trigger elections: the
        # silence budget adapts to the observed inter-word gap, and a
        # suspecting backup first probes the primary directly (ping) and
        # campaigns only when the probe too goes unanswered.
        self._primary_gap_ewma = 0.0
        self._probe_sent_at: Optional[int] = None
        self._pong_standdowns = 0
        # Commit-floor starvation / primary commit-stall tracking (see
        # _maybe_start_sync and the abdication branch in tick()).
        self._floor_stall = 0
        self._abdicate_commit_mark = -1
        self._abdicate_ticks = 0
        # Max ops executed per _commit_journal call (None = unlimited).
        # The TCP bus sets this and drains the remainder via its commit
        # pump; the sim/VOPR leaves it unset (single-dispatch determinism).
        self.commit_budget: Optional[int] = None
        # True iff the last _commit_journal call stopped ON BUDGET (vs
        # blocked on repair): the bus spawns its pump only for this case —
        # a repair-blocked backlog would otherwise respawn a no-op task
        # every tick for the whole repair window.
        self.commit_budget_stopped = False
        self._vc_started = 0
        # Consecutive stuck-view-change escalations: doubles the
        # escalation window (phase-lock breaking); resets on progress.
        self._vc_escalations = 0
        self._last_sync_req = 0
        # Tick of the last ACCEPTED sync payload byte: the stall detector
        # that drives responder rotation.  Distinct from _last_sync_req —
        # a checkpoint-refresh (on_commit) re-pins the target and re-sends
        # WITHOUT touching this clock, so a dead responder is still
        # rotated away from even while refreshes keep arriving (the
        # stranded-sync wedge; see _enter_sync(refresh=True)).
        self._sync_progress = 0
        self._heartbeat_jitter = 0
        self._recovering_since = 0
        # Event-loop starvation guard state (tick() liveness fairness).
        self._last_tick_mono = None
        # Env-gated replica event log (the reference's log.zig role): one
        # JSONL file per replica, cheap enough to leave on in benchmarks.
        self._debug_file = None
        dbg = os.environ.get("TB_DEBUG_LOG")
        if dbg:
            self._debug_file = open(
                f"{dbg}.r{self.replica}", "a", buffering=1
            )

        # Adaptive retry timeouts (vsr.zig:543-712): RTT-tracked base +
        # exponential backoff + jitter, reset on progress (vsr/timeout.py).
        from .timeout import Rtt, Timeout

        self.rtt = Rtt()
        self._prepare_timeout = Timeout(
            self.prng, PREPARE_RESEND, PREPARE_RESEND * 8, rtt=self.rtt,
            rtt_multiple=4.0,
        )
        self._vc_timeout = Timeout(
            self.prng, VIEW_CHANGE_RESEND, VIEW_CHANGE_RESEND * 6
        )
        self._rsv_timeout = Timeout(
            self.prng, RECOVERING_RESEND, RECOVERING_RESEND * 8
        )
        self._repair_timeout = Timeout(
            self.prng, REPAIR_INTERVAL, REPAIR_INTERVAL * 8, rtt=self.rtt,
            rtt_multiple=3.0,
        )

        self.clock: Optional[Clock] = None

    # -- identity ------------------------------------------------------------

    def primary_index(self, view: Optional[int] = None) -> int:
        v = self.view if view is None else view
        # primary_offset: committed reconfiguration keeps the serving
        # primary fixed across a quorum-membership flip; 0 forever on a
        # never-reconfigured cluster (docs/reconfiguration.md).
        return (v + self._primary_offset) % self.replica_count

    @property
    def is_standby(self) -> bool:
        """Non-voting member (replica index >= replica_count,
        constants.zig:31-35): consumes the prepare stream, never acks,
        never votes, never becomes primary (replica.zig:4874-4878)."""
        return self.replica >= self.replica_count

    @property
    def node_count(self) -> int:
        return self.replica_count + self.standby_count

    def _init_clock(self) -> None:
        self.clock = Clock(
            self.replica_count, self.replica, self._monotonic, self._realtime
        )
        self.time_ns = self._primary_now
        self._heartbeat_jitter = self.prng.randrange(NORMAL_HEARTBEAT // 2)

    @property
    def is_primary(self) -> bool:
        return self.status == NORMAL and self.primary_index() == self.replica

    def _membership_changed(self, old_rc: int, old_sc: int,
                            view: int) -> None:
        """A reconfigure op committed: fix the primary mapping so THIS
        prepare's view keeps its primary under the new modulus (quorum
        flips never move the primary without a view change), rebuild the
        clock over the new voter set, and persist — all pure functions of
        committed state, so every replica (and every replay) lands on the
        same offset."""
        old_primary = (view + self._primary_offset) % old_rc
        self._primary_offset = (old_primary - view) % self.replica_count
        if self.clock is not None:
            # Rebuild the sample quorum over the new voter set WITHOUT
            # re-drawing jitter or resetting time_ns (determinism: the
            # prng stream must not depend on membership history), and
            # CARRY the learned samples AND the current sync estimate
            # over: dropping them would un-synchronize the clock and make
            # the primary shed every request (BUSY_CLOCK) until a full
            # ping round under the NEW quorum — a needless availability
            # dip on every membership flip (pre-flip samples exclude
            # standbys by design, replica.zig:1274, so a 3+1 -> 4+0
            # promotion can never meet quorum 3 from carried samples
            # alone), and a permanent wedge in the frozen-time model
            # checker.  The wall-clock estimate is not invalidated by a
            # membership flip; its confidence basis is merely stale, and
            # the next pong re-runs Marzullo under the new quorum.
            old_clock = self.clock
            self.clock = Clock(
                self.replica_count, self.replica, self._monotonic,
                self._realtime,
            )
            self.clock.samples = dict(old_clock.samples)
            self.clock.epoch_start_monotonic = (
                old_clock.epoch_start_monotonic
            )
            self.clock.offset_ns = old_clock.offset_ns
            self.clock._synchronized = old_clock._synchronized
        self._persist_view()
        if _obs.enabled:
            _obs.counter(
                "reconfig.promotions" if self.replica_count > old_rc
                else "reconfig.demotions"
            ).inc()

    @property
    def commit_backlog(self) -> bool:
        """Journaled ops known-committed but not yet executed (the bus
        commit pump drains these between dispatches)."""
        return self.commit_min < min(self.commit_max, self.op)

    @property
    def quorum_replication(self) -> int:
        return quorums(self.replica_count)[0]

    @property
    def quorum_view_change(self) -> int:
        rc = self.replica_count
        if "reconfig_stale_quorum" in self.mc_mutations:
            # Seeded mutation (tools/tbmc): the view-change quorum is
            # sized from the membership this process OPENED with,
            # ignoring committed reconfigure ops.  After a 3+1 -> 4+0
            # promotion the stale quorum (2 of 4) no longer intersects
            # every replication quorum (2 + 2 = 4, not > 4), so a view
            # change can canonicalize a history that misses a committed
            # op (mc.py exhibits a machine-checked counterexample at the
            # pinned reconfig scope; replication quorums are unaffected
            # because quorums(3)[0] == quorums(4)[0]).
            rc = self._boot_replica_count
        q = quorums(rc)[1]
        if "vc_quorum" in self.mc_mutations:
            # Seeded mutation (tools/tbmc): the classic off-by-one — view
            # changes complete one vote short, so canonical selection can
            # miss a committed op and refill it (mc.py exhibits a
            # machine-checked counterexample at the pinned scope).
            return max(1, q - 1)
        return q

    # -- lifecycle -----------------------------------------------------------

    def open(self) -> None:
        """Recover durable state; do NOT execute journaled-but-uncommitted
        ops — a restarted replica must first learn commit_max from the
        cluster (a journaled op may have been discarded by a view change
        while we were down)."""
        try:
            recovery = self._open_durable_state()
        except ForestDamage as err:
            if self.replica_count == 1:
                raise  # solo: no peer to repair from
            self._enter_block_repair(
                err.damage, getattr(err, "cold_paths", None)
            )
            return
        self._post_open(recovery)

    def _post_open(self, recovery) -> None:
        self.commit_max = self.commit_min
        self.log_view = getattr(self._sb_state, "log_view", self.view)
        # Adoption watermark rides through restarts: _persist_view rewrites
        # it verbatim until the next log_view advance replaces it.
        self._log_adopted_op = getattr(self._sb_state, "log_adopted_op", 0)
        self._load_chain(recovery)
        self._init_clock()
        if self.replica_count == 1:
            # Sole replica: everything chained is committed by definition.
            self._replay_solo()
            self.status = NORMAL
        elif (
            self.op == 0 and self.commit_min == 0 and self.view == 0
            and self.log_view == 0
            and not getattr(self, "_log_suspect", False)
        ):
            # Freshly formatted cluster: nothing to recover, start normal
            # (the reference's format-then-start path).  A factory-fresh
            # but SUSPECT file (a promoted never-caught-up standby) must
            # instead recover via request_start_view so its certification
            # can actually happen.
            self.status = NORMAL
        else:
            self.status = RECOVERING
            self._recovering_since = self._ticks
        # Arm the device fault domain from this digest-verified state; the
        # ops the cluster re-commits from here advance the mirror like any
        # other commit.  No-op at scrub interval 0.
        self.machine.scrub_arm()

    def _load_chain(self, recovery) -> None:
        """Rebuild the in-memory hash chain from the WAL without executing:
        sets self.op/parent_checksum/headers to the contiguous chained
        suffix anchored at the checkpoint (cf. Replica._replay)."""
        anchor = recovery.entries.get(self.commit_min)
        if anchor is None and self.commit_min == 0:
            anchor = self._restore_root()  # deterministic; see replica.py
        if anchor is not None:
            self.parent_checksum = wire.header_checksum(anchor.header)
            self.headers[self.commit_min] = anchor.header
        else:
            self.parent_checksum = 0
        self.op = self.commit_min
        op = self.commit_min + 1
        parent = self.parent_checksum
        while op in recovery.entries:
            entry = recovery.entries[op]
            if entry.body is None:
                break
            if parent and wire.u128(entry.header, "parent") != parent:
                break
            self.headers[op] = entry.header
            parent = wire.header_checksum(entry.header)
            self.op = op
            op += 1
        if self.op > self.commit_min:
            self.parent_checksum = wire.header_checksum(self.headers[self.op])
        # Everything re-loaded from our own WAL is suspect until it chains
        # into canonical state learned from the cluster (solo replicas ARE
        # the cluster: their WAL is canon by quorum=1).
        self._verify_floor = self.op + 1 if self.replica_count > 1 else 0
        # RECOVERING-HEAD detection (replica.zig status.recovering_head):
        # when recovery shows our chained head is AMPUTATED — headers
        # recovered beyond it with bodies lost, foreign (misdirected-write)
        # slot content, or a persisted commit_min above it — our log must
        # not vouch in a view change.  Presenting a truncated op under our
        # real (possibly highest) log_view would WIN the canonical
        # selection and truncate committed history (storage-adversary seed
        # 31000: a twice-read-faulted ex-primary's (log_view=3, op=24) log
        # beat the intact backup's (log_view=0, op=28)).
        beyond_head = any(op > self.op for op in recovery.entries)
        persisted_commit = getattr(self._sb_state, "commit_min", 0)
        # The DVC invariant behind (log_view, op) canonical selection: a
        # durable log_view asserts the journal holds that view's canonical
        # log through self.op.  The durable log_adopted_op (written only
        # when log_view advances) records how far that log was KNOWN to
        # extend at adoption — a recovered head below it means the adopted
        # suffix died with the crash (bodies never journaled), and a DVC
        # claiming (log_view, short-op) would OUT-RANK an intact older-view
        # log and truncate committed history (VOPR seed 500285: a restarted
        # backup's (log_view=2, op=22) beat the intact (log_view=0, op=29)
        # log and ops 24-28, committed, were refilled with new requests).
        # NOT commit_max: that folds in heartbeat-learned cluster commits a
        # lagging-but-intact backup's journal never held, and using it here
        # falsely marked such backups suspect after a crash — wedging view
        # changes when the primary also died (ADVICE r4 medium).
        persisted_adopted = getattr(self._sb_state, "log_adopted_op", 0)
        # The slot of op+1 is the ONE slot a write could have been mid-
        # flight to at crash time (prepares journal serially, synced per
        # write): nonzero-undecodable content THERE is an ordinary torn
        # tail — never acked (acks follow the sync) — not amputation.
        torn_tail_slot = self.journal.slot(self.op + 1)
        corrupt_slots = [
            s for s in getattr(recovery, "corrupt_slots", ())
            if s != torn_tail_slot
        ]
        self._log_suspect = self.replica_count > 1 and (
            bool(recovery.foreign_slots)
            or bool(corrupt_slots)
            or beyond_head
            or persisted_commit > self.op
            or persisted_adopted > self.op
        )
        self._debug(
            "recovered", op=self.op, commit_min=self.commit_min,
            persisted=persisted_commit, suspect=self._log_suspect,
            entries=len(recovery.entries),
            faulty=len(recovery.faulty_slots),
            corrupt=len(corrupt_slots),
            log_view=self.log_view, view=self.view,
        )

    def _replay_solo(self) -> None:
        """Single-replica replay: execute the whole chained suffix."""
        for op in range(self.commit_min + 1, self.op + 1):
            read = self.journal.read_prepare(op)
            assert read is not None, op
            h, body = read
            self._commit_prepare(h, body, replay=True)
            if self._checkpoint_due():
                self.checkpoint()
        self.commit_max = self.commit_min

    def _persist_view(self) -> None:
        """Quorum-write view/log_view into the superblock so a restarted
        replica never regresses its view (replica.zig view durability).
        commit_min rides along: a restart whose WAL chain ends below it is
        PROOF of an amputated suffix (recovering-head detection)."""
        if self._sb_state is None:
            return
        state = dataclasses.replace(
            self._sb_state, view=self.view, log_view=self.log_view,
            commit_min=max(self._sb_state.commit_min, self.commit_min),
            commit_max=max(self._sb_state.commit_max, self.commit_max),
            log_adopted_op=getattr(self, "_log_adopted_op", 0),
            # Membership + primary mapping ride every view write: a
            # committed reconfiguration must never be forgotten by a
            # crash between its commit and the next checkpoint.
            replica_count=self.replica_count,
            standby_count=self.standby_count,
            primary_offset=self._primary_offset,
        )
        # Through the single merge-point: a concurrent background
        # checkpoint (async_checkpoint) must not be reverted or raced.
        state = self._superblock_install(state)
        self._sb_state = state

    # -- message dispatch ----------------------------------------------------

    def _reject_frame(self, reason: str, **kw) -> List[Msg]:
        """Drop-and-count a provably ill-formed ingress frame (never crash,
        never apply): the byzantine.* rejection family every sink reads."""
        if _obs.enabled:
            _obs.counter(f"byzantine.rejected.{reason}").inc()
        if self._debug_file is not None:
            self._debug("ingress_reject", reason=reason, **kw)
        return []

    def _ingress_auth(self, h: np.ndarray) -> bool:
        """MAC gate for SOURCE_AUTHENTICATED ingress (vsr/auth.py): the
        FIRST call in every handler of a source-authenticated command,
        before any header field is consumed — tblint's ingress-auth rule
        enforces that ordering syntactically.  Auth off: always passes
        (the zero-MAC legacy wire).  Keychain armed: a bad MAC drops-and-
        counts (auth.rejected.mac); a MISSING MAC is accepted-and-counted
        in mixed-version mode (an auth-off peer must not wedge a rolling
        upgrade) but rejected under auth_strict when the frame claims a
        cluster-replica origin."""
        if self.auth is None:
            return True
        if "mac_skip" in self.mc_mutations:
            return True  # seeded defense knockout (docs/tbmc.md)
        mac = wire.header_mac(h)
        if not mac:
            if self.auth_strict and int(h["replica"]) < self.replica_count:
                if _obs.enabled:
                    _obs.counter("auth.rejected.missing").inc()
                self._reject_frame(
                    "auth_missing", claimed=int(h["replica"])
                )
                return False
            if _obs.enabled:
                _obs.counter("auth.accepted.unauthenticated").inc()
            return True
        if "key_confusion" in self.mc_mutations:
            # Seeded knockout: verification forgets WHOSE key must match,
            # so a frame MAC'd under ANY cluster key passes — an adversary
            # can then speak as any peer using only its own key.
            hb = h.tobytes()
            ok = any(
                self.auth.mac(origin, hb) == mac
                for origin in range(self.node_count)
            )
        else:
            ok = self.auth.verify(h)
        if not ok:
            if _obs.enabled:
                _obs.counter("auth.rejected.mac").inc()
            self._reject_frame("auth_mac", claimed=int(h["replica"]))
            return False
        if _obs.enabled:
            _obs.counter("auth.verified").inc()
        return True

    # -- authenticated ack certificates (auth_strict) -------------------------

    _ACK_CERTS_MAX = 64

    def _cert_quorum(self) -> int:
        """Certificate size: > (n + f) / 2 with f = 1, so two certificates
        for the same op share an honest member — the honest single-voice
        rule (one ack per op per honest replica) then forbids certificates
        for two DIFFERENT checksums at one op."""
        return (self.replica_count + 3) // 2

    def _note_ack(self, op: int, checksum: int, replica: int) -> None:
        """Record a MAC-verified prepare_ok toward op's certificate.  An
        already-voted replica naming a SECOND checksum is equivocating:
        keep its first vote and count the evidence (the dedup the
        ``equiv_dedup`` mutation removes)."""
        certs = self._ack_certs.setdefault(op, {})
        if "equiv_dedup" not in self.mc_mutations:
            for have, voters in certs.items():
                if have != checksum and replica in voters:
                    self.byzantine_detections += 1
                    if _obs.enabled:
                        _obs.counter("auth.equivocating_acks").inc()
                    return
        certs.setdefault(checksum, set()).add(replica)
        if len(self._ack_certs) > self._ACK_CERTS_MAX:
            for stale in sorted(self._ack_certs)[
                : len(self._ack_certs) - self._ACK_CERTS_MAX
            ]:
                del self._ack_certs[stale]

    def _ack_certified(self, op: int) -> bool:
        """True iff op's JOURNALED content holds a full ack certificate.
        Only consulted under auth_strict (certificates upgrade the anchor
        check, they do not replace it for the legacy wire); the
        ``cert_downgrade`` mutation is the seeded knockout that falls back
        to anchors alone."""
        h = self.headers.get(op)
        if h is None:
            return False
        voters = self._ack_certs.get(op, {}).get(wire.header_checksum(h))
        return voters is not None and len(voters) >= self._cert_quorum()

    # Commands that only the primary of their stamped view ever originates.
    # Prepares keep the preparing primary's header through ring forwarding
    # and repair fills, so the invariant holds for EVERY honest frame of
    # these commands, current-view or archival — a frame violating it is
    # forged regardless of transport-level source authentication.
    _PRIMARY_ORIGIN_COMMANDS = (
        wire.Command.prepare, wire.Command.commit, wire.Command.start_view,
    )

    def on_message(
        self, h: np.ndarray, command: wire.Command, body: bytes
    ) -> List[Msg]:
        if wire.u128(h, "cluster") != self.cluster:
            return []
        if (
            self.ingress_verify
            and "not_primary" not in self.mc_mutations
            and command in self._PRIMARY_ORIGIN_COMMANDS
            and int(h["replica"]) != self.primary_index(int(h["view"]))
        ):
            return self._reject_frame(
                "not_primary", cmd=command.name,
                claimed=int(h["replica"]), view=int(h["view"]),
            )
        if self._block_repair is not None and command not in (
            wire.Command.block, wire.Command.ping, wire.Command.pong
        ):
            # Until our checkpoint files are whole we have no ledger to
            # serve from and no log to vote with; only repair traffic (and
            # clock pings) may proceed.
            return []
        handler = {
            wire.Command.request: self.on_request_msg,
            wire.Command.prepare: self.on_prepare,
            wire.Command.prepare_ok: self.on_prepare_ok,
            wire.Command.commit: self.on_commit,
            wire.Command.start_view_change: self.on_start_view_change,
            wire.Command.do_view_change: self.on_do_view_change,
            wire.Command.start_view: self.on_start_view,
            wire.Command.request_start_view: self.on_request_start_view,
            wire.Command.request_headers: self.on_request_headers,
            wire.Command.request_prepare: self.on_request_prepare,
            wire.Command.nack_prepare: self.on_nack_prepare,
            wire.Command.headers: self.on_headers,
            wire.Command.ping: self.on_ping,
            wire.Command.pong: self.on_pong,
            wire.Command.request_sync_checkpoint: self.on_request_sync_checkpoint,
            wire.Command.sync_checkpoint: self.on_sync_checkpoint,
            wire.Command.request_sync_roots: self.on_request_sync_roots,
            wire.Command.sync_roots: self.on_sync_roots,
            wire.Command.request_sync_subtree: self.on_request_sync_subtree,
            wire.Command.sync_subtree: self.on_sync_subtree,
            wire.Command.request_blocks: self.on_request_blocks,
            wire.Command.block: self.on_block,
            wire.Command.request_reply: self.on_request_reply,
            wire.Command.reply: self.on_reply_repair,
        }.get(command)
        if handler is None:
            return []
        return handler(h, body)

    def _hdr(self, command: wire.Command, **fields) -> np.ndarray:
        h = wire.new_header(
            command, cluster=self.cluster, view=self.view, **fields
        )
        h["replica"] = self.replica
        return h

    def _broadcast_nodes(self, message: bytes) -> List[Msg]:
        """To every node incl. standbys (the reference's
        send_header_to_other_replicas_and_standbys: pings, commit
        heartbeats, start_view)."""
        return [
            (("replica", r), message)
            for r in range(self.node_count)
            if r != self.replica
        ]

    def _broadcast(self, message: bytes) -> List[Msg]:
        return [
            (("replica", r), message)
            for r in range(self.replica_count)
            if r != self.replica
        ]

    # -- normal operation: client requests ----------------------------------

    def on_request_msg(self, h: np.ndarray, body: bytes) -> List[Msg]:
        """Client request: primary prepares + replicates; backups forward to
        the primary (replica.zig on_request :1308-1337)."""
        if self.status != NORMAL or self.is_standby:
            # Standbys never serve clients (replica.zig:4315 misdirected);
            # dropping (not forwarding) matches the reference.
            return []
        if not self.is_primary:
            return [(("replica", self.primary_index()), wire.encode(h, body))]

        client = wire.u128(h, "client")
        try:
            operation = wire.Operation(int(h["operation"]))
            self._validate_request(operation, body)
        except (ValueError, InvalidRequest):
            return []
        request_n = int(h["request"])

        session = self.sessions.get(client)
        if operation != wire.Operation.register:
            if session is None:
                # Unknown session (never registered, or capacity-evicted by
                # a newer client): the client may re-register and retry.
                return [(("client", client), self._eviction(
                    client, wire.EVICTION_NO_SESSION
                ))]
            if int(h["session"]) != session.session:
                # MISMATCH echoes the OFFENDING session: a client that
                # already re-registered after a capacity eviction discards
                # a stale MISMATCH about its old session (e.g. a backup's
                # forwarded copy of the evicted request) instead of dying
                # to it, while a live duplicate-id client — whose current
                # session matches the echo — surfaces it terminally.
                return [(("client", client), self._eviction(
                    client, wire.EVICTION_SESSION_MISMATCH,
                    session=int(h["session"]),
                ))]
            if request_n == session.request:
                if session.reply_bytes:
                    return [(("client", client), session.reply_bytes)]
                # Sync-restored session without its stored reply (the
                # client_replies zone is local-only): repair it from peers
                # (request_reply, ADVICE round-1 medium; the reference's
                # client_replies.zig read-repair path).
                return self._request_reply_repair(client)
            if request_n < session.request:
                return []
        elif session is not None:
            if session.reply_bytes:
                return [(("client", client), session.reply_bytes)]
            return self._request_reply_repair(client)
        # Drop duplicates already being prepared in the pipeline.
        for entry in self.pipeline.values():
            if entry.client == client:
                return []

        # NEW requests (everything above serves duplicates without needing a
        # timestamp) require a synchronized clock and pipeline headroom
        # (replica.zig:1322, :1330).  With overload control on, each shed is
        # SIGNALED (retryable busy + retry-after hint) instead of silently
        # dropped; off, these paths are bit-identical to before.
        if self.clock.realtime_synchronized is None:
            # Clock syncs via ping/pong rounds: retry after one round.
            return self._shed_request(h, wire.BUSY_CLOCK, PING_INTERVAL)
        if len(self.pipeline) >= self.config.pipeline_prepare_queue_max:
            # The pipeline drains at commit speed: one heartbeat away.
            return self._shed_request(h, wire.BUSY_PIPELINE, COMMIT_HEARTBEAT)
        if self.op + 1 > self.op_prepare_max:
            # WAL full until the in-flight checkpoint lands: the longest of
            # the three conditions — hint half a heartbeat budget.
            return self._shed_request(h, wire.BUSY_WAL, NORMAL_HEARTBEAT // 2)
        if self.commit_max > self.op:
            # Ops at/below the known commit watermark exist that we don't
            # hold headers for (e.g. a recovering-head DVC's commit claim):
            # assigning a FRESH op at their position would fork committed
            # history.  Repair/sync must close the gap first.
            return []

        txtrace.hop(int(h["trace"]), "consensus.ingress",
                    replica=self.replica, request=request_n)
        prepare_h, prepare_body = self._prepare(h, body, operation)
        op = int(prepare_h["op"])
        if self.blackbox is not None:
            self.blackbox.record(
                "prepare_primary", view=self.view, op=op,
                checksum=f"{wire.header_checksum(prepare_h):#x}"[:18],
                pipeline=len(self.pipeline),
            )
        self.headers[op] = prepare_h
        self.pipeline[op] = PipelineEntry(
            op=op,
            checksum=wire.header_checksum(prepare_h),
            client=client,
            ok_from={self.replica},
        )
        out: List[Msg] = []
        if self.auth is not None and self.auth_strict:
            # The primary's own attestation joins the certificate: backups
            # need _cert_quorum() distinct votes, the leader's included.
            self._append_ok(out, prepare_h)
        message = wire.encode(prepare_h, prepare_body)
        successor = self._ring_successor()
        if successor is not None:
            out.append((("replica", successor), message))
        self._maybe_commit_pipeline(out)
        return out

    _BUSY_REASON_NAMES = {
        wire.BUSY_PIPELINE: "pipeline",
        wire.BUSY_WAL: "wal",
        wire.BUSY_CLOCK: "clock",
        wire.BUSY_QUEUE: "queue",
    }

    def _shed_request(
        self, h: np.ndarray, reason: int, retry_after_ticks: int
    ) -> List[Msg]:
        """Shed a new client request the primary cannot admit.  Overload
        control OFF: silent drop, bit-identical to the pre-overload path.
        ON: signal — a retryable busy with a retry-after hint, plus the
        overload.* shed accounting."""
        if not self.overload_control:
            return []
        name = self._BUSY_REASON_NAMES.get(reason, "unknown")
        if _obs.enabled:
            _obs.counter(f"overload.shed.{name}").inc()
            _obs.counter("overload.busy_sent").inc()
        self._debug(
            "shed_request", reason=name,
            client=f"{wire.u128(h, 'client'):#x}",
            request=int(h["request"]),
        )
        client = wire.u128(h, "client")
        message = overload.busy_message(
            self.replica, self.cluster, self.view, h, reason,
            retry_after_ticks,
        )
        return [(("client", client), message)]

    def _primary_now(self) -> int:
        now = self.clock.realtime_synchronized
        assert now is not None
        return now

    def _ring_successor(self) -> Optional[int]:
        """Next replica in the replication ring (replica.zig:1339-1363);
        the last active backup jumps off to the standby ring
        (replica.zig:6067-6101); None when the chain completes."""
        if self.replica_count == 1:
            return None
        if not self.is_standby:
            nxt = (self.replica + 1) % self.replica_count
            if nxt != self.primary_index():
                return nxt
        if self.standby_count == 0:
            return None
        # Standby ring rotates with the view so no standby is permanently
        # last (standby_index_to_replica).
        first_standby = self.replica_count + (self.view % self.standby_count)
        if not self.is_standby:
            return first_standby
        my_index = self.replica - self.replica_count
        next_standby = self.replica_count + (
            (my_index + 1) % self.standby_count
        )
        if next_standby != first_standby:
            return next_standby
        return None

    # -- normal operation: replication ---------------------------------------

    def _request_reply_repair(self, client: int) -> List[Msg]:
        """Ask peers for a client's last stored reply (the sync-restored
        session has the request number but not the reply bytes).  checksum 0
        = 'whatever reply you hold for this client's CURRENT session' — the
        session number in the request stops a lagging peer from serving a
        previous session's reply for an equal request number."""
        req = self._hdr(
            wire.Command.request_reply, client=client,
            session=self.sessions[client].session,
        )
        return self._broadcast(wire.encode(req))

    def on_request_reply(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        client = wire.u128(h, "client")
        s = self.sessions.get(client)
        if s is None or not s.reply_bytes or s.session != int(h["session"]):
            return []
        want = wire.u128(h, "reply_checksum")
        if want:
            stored_h, _ = wire.decode_header(s.reply_bytes[: wire.HEADER_SIZE])
            if wire.header_checksum(stored_h) != want:
                return []
        return [(("replica", int(h["replica"])), s.reply_bytes)]

    def on_reply_repair(self, h: np.ndarray, body: bytes) -> List[Msg]:
        """A repaired reply arriving from a peer: adopt it into the session
        and resend to the client."""
        client = wire.u128(h, "client")
        s = self.sessions.get(client)
        if s is None or s.reply_bytes or int(h["request"]) != s.request:
            return []
        raw = wire.encode(h, body)
        s.reply_bytes = raw
        self._persist_reply(client, raw)
        return [(("client", client), raw)]

    def _persist_reply(self, client: int, raw: bytes) -> None:
        """Write a repaired reply into the local client_replies zone so it
        survives restart (mirrors the normal commit path's store)."""
        try:
            self._store_client_reply(client, raw)
        except OSError as err:
            # Repair is best-effort (the reply still went out over the
            # wire), but a disk that rejects the write is worth a record —
            # a silent swallow here hid a full-disk wedge in round 5.
            self._debug("persist_reply_failed", client=client,
                        error=f"{type(err).__name__}: {err}")

    def on_prepare(self, h: np.ndarray, body: bytes) -> List[Msg]:
        view = int(h["view"])
        op = int(h["op"])
        checksum = wire.header_checksum(h)
        out: List[Msg] = []

        # Repair fills are VIEW-AGNOSTIC: a stored prepare keeps the view it
        # was originally prepared in; its identity is its checksum / position
        # in the hash chain, so responses to request_prepare must be accepted
        # even when their header view predates ours (and even mid
        # view-change — the new primary repairs canonical bodies then).
        if op in self.missing and self.missing[op] == checksum:
            self._fill_missing(h, body)
            if self.status == NORMAL:
                self._append_ok(out, h)
                if self.is_primary:
                    # The primary may already hold ack quorums for this and
                    # later pipeline entries (the commit stalled on OUR
                    # missing/corrupt journal copy — VOPR seed 10058):
                    # commit via the pipeline, which advances commit_max.
                    self._maybe_commit_pipeline(out)
                else:
                    self._commit_journal(out)
            return out

        if op > self.op_prepare_max:
            # WAL bound (vsr.zig op_prepare_max): journaling this would
            # overwrite a ring slot holding an op we have not committed.
            # Drop — don't even stash (a stalled replica would accumulate a
            # ring's worth) — the primary's resends / repair refetch it once
            # our checkpoint advances.
            return out

        if view < self.view:
            if self.status == NORMAL and op <= self.op:
                existing = self.headers.get(op)
                if existing is not None and (
                    wire.header_checksum(existing) == checksum
                ):
                    # Duplicate of an adopted prepare (e.g. the new primary's
                    # resend of a re-certified old-view suffix): re-ack in
                    # the CURRENT view.
                    self._append_ok(out, h)
                elif existing is None and op > self.commit_min:
                    self.stash[op] = (h, body)
                    self._fill_gaps(out)
            return out
        if view > self.view or self.status == RECOVERING:
            # We're behind a view change (or freshly restarted): stash and
            # ask the new primary for start_view.
            self.stash[op] = (h, body)
            return self._request_start_view(view)
        if self.status != NORMAL:
            self.stash[op] = (h, body)
            return []

        self._primary_spoke()
        self.commit_max = max(self.commit_max, int(h["commit"]))

        if op <= self.op:
            existing = self.headers.get(op)
            if existing is not None and wire.header_checksum(existing) == checksum:
                self._append_ok(out, h)
            elif existing is None and op > self.commit_min:
                # Header-gap fill (e.g. a start_view whose header window did
                # not reach back to our commit_min): verify DOWNWARD via the
                # parent link of the next header before adopting.
                self.stash[op] = (h, body)
                self._fill_gaps(out)
            elif existing is not None:
                if "equiv_dedup" in self.mc_mutations:
                    # Seeded knockout (docs/tbmc.md): the keep-first rule
                    # is what makes an honest replica speak ONCE per op.
                    # Adopting-and-acking the conflicting copy lets an
                    # equivocating primary assemble ack certificates for
                    # BOTH forks of the same op.
                    self.journal.write_prepare(wire.encode(h, body))
                    self.headers[op] = h
                    if op == self.op:
                        self.parent_checksum = checksum
                    self._append_ok(out, h)
                elif _obs.enabled:
                    # Two different prepares for the same op in the SAME
                    # view: an honest primary assigns each op once, so this
                    # is equivocation evidence (the conflicting frame is
                    # dropped either way; the commit-checksum anchor
                    # adjudicates which copy is canonical).
                    _obs.counter("byzantine.prepare_conflicts").inc()
            return out

        if op == self.op + 1 and wire.u128(h, "parent") == self.parent_checksum:
            self._journal_prepare(h, body)
            txtrace.hop(int(h["trace"]), "consensus.prepare",
                        replica=self.replica, op=op)
            if self.blackbox is not None:
                self.blackbox.record(
                    "prepare", view=view, op=op,
                    checksum=f"{checksum:#x}"[:18],
                    stash=len(self.stash), missing=len(self.missing),
                )
            self._append_ok(out, h)
            successor = self._ring_successor()
            if successor is not None and successor != int(h["replica"]):
                out.append((("replica", successor), wire.encode(h, body)))
            self._drain_stash(out)
            self._commit_journal(out)
        else:
            if (
                self.ingress_verify
                and op == self.op + 1
                and self.op > self.commit_min
                and _obs.enabled
            ):
                # A same-view prepare extending the chain names a different
                # checksum for our uncommitted head: equivocation evidence.
                # Observability only — a single unauthenticated frame must
                # NOT evict the head (a forged parent claim would discard a
                # journaled, possibly-acked op and poison the repair target
                # with an unfulfillable checksum); adjudication belongs to
                # the source-authenticated anchors (on_commit,
                # _content_certified) and the anchor-certified headers
                # path (on_headers).
                _obs.counter("byzantine.prepare_conflicts").inc()
            # Gap (lost prepare) or fork: stash and repair.
            self.stash[op] = (h, body)
            out.extend(self._repair_gaps())
        return out

    def _journal_prepare(self, h: np.ndarray, body: bytes) -> None:
        self.journal.write_prepare(wire.encode(h, body))
        self.headers[int(h["op"])] = h
        self.op = int(h["op"])
        self.parent_checksum = wire.header_checksum(h)

    def _append_ok(self, out: List[Msg], prepare_h: np.ndarray) -> None:
        """Queue a prepare_ok — unless we are a standby (standbys receive
        and replicate prepares but NEVER ack: they must not count toward
        commit quorums, replica.zig:4877)."""
        if self.is_standby:
            return
        if self.auth is not None and self.auth_strict:
            # Authenticated ack certificates: the ack goes to EVERY replica
            # (not just the primary) so backups can assemble a
            # _cert_quorum() certificate before executing; our own vote is
            # recorded locally (no loopback delivery).
            _, frame = self._send_prepare_ok(prepare_h)
            out.extend(
                (("replica", r), frame)
                for r in range(self.replica_count)
                if r != self.replica
            )
            self._note_ack(
                int(prepare_h["op"]),
                wire.header_checksum(prepare_h), self.replica,
            )
        else:
            out.append(self._send_prepare_ok(prepare_h))

    def _send_prepare_ok(self, prepare_h: np.ndarray) -> Msg:
        txtrace.hop(int(prepare_h["trace"]), "consensus.ack",
                    replica=self.replica, op=int(prepare_h["op"]))
        ok = self._hdr(
            wire.Command.prepare_ok,
            parent=wire.u128(prepare_h, "parent"),
            prepare_checksum=wire.header_checksum(prepare_h),
            client=wire.u128(prepare_h, "client"),
            op=int(prepare_h["op"]),
            commit=self.commit_min,
            timestamp=int(prepare_h["timestamp"]),
            request=int(prepare_h["request"]),
            operation=int(prepare_h["operation"]),
        )
        return (("replica", self.primary_index()), wire.encode(ok, b""))

    def _drain_stash(self, out: List[Msg]) -> None:
        """Chain in any stashed prepares that now fit."""
        while self.op + 1 in self.stash and self.op + 1 <= self.op_prepare_max:
            h, body = self.stash.pop(self.op + 1)
            if wire.u128(h, "parent") != self.parent_checksum:
                break
            self._journal_prepare(h, body)
            self._append_ok(out, h)
        # Prune committed stash entries (gap fills for ops <= self.op with
        # unknown headers stay until _fill_gaps verifies them).
        for op in [o for o in self.stash if o <= self.commit_min]:
            del self.stash[op]

    def _fill_gaps(self, out: List[Msg]) -> None:
        """Adopt stashed prepares for header-gap ops, verifying each against
        the parent link of the header above it (downward hash-chain walk),
        then commit as far as possible."""
        changed = True
        while changed:
            changed = False
            for op in sorted(self.stash, reverse=True):
                if op > self.op or op <= self.commit_min:
                    continue
                if self.headers.get(op) is not None:
                    continue
                nxt = self.headers.get(op + 1)
                if nxt is None:
                    continue
                h, body = self.stash[op]
                if wire.u128(nxt, "parent") == wire.header_checksum(h):
                    self.journal.write_prepare(wire.encode(h, body))
                    self.headers[op] = h
                    del self.stash[op]
                    self._append_ok(out, h)
                    self._repipeline(op, h)
                    changed = True
        self._commit_journal(out)

    def _header_gaps(self, limit: int = 8) -> List[int]:
        """Ops above commit_min with no known header (unrepairable via
        `missing`, which needs a checksum).  Returns the HIGHEST ops of the
        gap: adoption verifies downward from the known header above, so the
        top of the gap must fill first."""
        gaps = [
            op
            for op in range(self.commit_min + 1, self.op + 1)
            if op not in self.headers
        ]
        return gaps[-limit:]

    def on_prepare_ok(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        if int(h["replica"]) >= self.replica_count:
            return []  # a standby's ack must never count (defense in depth)
        if self.auth is not None and self.auth_strict:
            # Certificate assembly: every replica collects MAC-verified
            # acks (the strict-mode broadcast), then a backup retries the
            # commit gate — it may have been waiting on exactly this vote.
            self._note_ack(
                int(h["op"]), wire.u128(h, "prepare_checksum"),
                int(h["replica"]),
            )
            if not self.is_primary:
                out: List[Msg] = []
                self._commit_journal(out)
                return out
        if self.status != NORMAL or not self.is_primary:
            return []
        if int(h["view"]) != self.view:
            return []
        op = int(h["op"])
        entry = self.pipeline.get(op)
        if entry is None or entry.checksum != wire.u128(h, "prepare_checksum"):
            return []
        entry.ok_from.add(int(h["replica"]))
        if len(entry.ok_from) == self.quorum_replication:
            # Reset only on REAL progress (an entry reaching quorum) — a
            # duplicate ok, or oks for other entries, must not starve the
            # re-broadcast of a stuck one.
            self._prepare_timeout.reset(self._ticks)
        out: List[Msg] = []
        self._maybe_commit_pipeline(out)
        return out

    def _maybe_commit_pipeline(self, out: List[Msg]) -> None:
        """Commit pipeline entries in op order as quorums arrive."""
        while True:
            op = self.commit_min + 1
            entry = self.pipeline.get(op)
            if entry is None or len(entry.ok_from) < self.quorum_replication:
                break
            self.commit_max = max(self.commit_max, op)
            self._commit_journal(out)
            if self.commit_min < op:
                break  # body missing (shouldn't happen at the primary)
            self.pipeline.pop(op, None)

    def on_commit(self, h: np.ndarray, body: bytes) -> List[Msg]:
        """Commit-number heartbeat from the primary (replica.zig :1591)."""
        if not self._ingress_auth(h):
            return []
        view = int(h["view"])
        if view < self.view:
            return []
        if self.status == SYNCING:
            # Keep the sync target fresh: if the primary checkpointed again
            # mid-fetch, restart against the new snapshot (the responder
            # only serves its exact current checkpoint).  refresh=True:
            # the restart must NOT reset the progress/resend clocks — a
            # refresh is not progress, and under a sustained flood (a new
            # checkpoint every ~interval ops) resetting them here starved
            # the stall rotation forever while the pinned responder was
            # dead (the stranded-sync wedge).
            new_ckpt = int(h["checkpoint_op"])
            if self.sync_target is not None and (
                new_ckpt > self.sync_target["checkpoint_op"]
            ):
                return self._enter_sync(new_ckpt, refresh=True)
            return []
        if view > self.view or self.status == RECOVERING:
            return self._request_start_view(view)
        if self.status != NORMAL or self.is_primary:
            return []
        self._primary_spoke()
        out: List[Msg] = []
        # Commit-content anchoring (byzantine domain): the heartbeat names
        # the checksum of the op it commits.  If OUR header for that op
        # differs, a forged prepare equivocated its content into our chain
        # — evict the fork and repair the canonical body (by checksum, so
        # repair responses are unforgeable) BEFORE the commit path can
        # execute it.  checksum 0 = unanchored (legacy/pruned): skip.
        want = wire.u128(h, "commit_checksum")
        commit_op = int(h["commit"])
        if self.blackbox is not None:
            self.blackbox.record("commit_heartbeat", view=view,
                                 commit=commit_op)
        if want:
            self._note_anchor(commit_op, want)
        if (
            self.ingress_verify and want and commit_op > self.commit_min
            and "anchor_certify" not in self.mc_mutations
        ):
            mine = self.headers.get(commit_op)
            if mine is not None and wire.header_checksum(mine) != want and (
                self._anchor_trusted(commit_op, want)
            ):
                self.byzantine_detections += 1
                if _obs.enabled:
                    _obs.counter("byzantine.equivocation_detected").inc()
                self._debug(
                    "commit_checksum_conflict", op=commit_op,
                    mine=f"{wire.header_checksum(mine):#x}"[:18],
                )
                self._evict_fork(commit_op, want)
                self.commit_max = max(self.commit_max, commit_op)
                out.extend(self._request_missing())
                return out
            if mine is None and self.missing.get(commit_op, want) != want \
                    and self._anchor_trusted(commit_op, want):
                # A forged frame polluted the repair target for this op;
                # the source-authenticated anchor corrects it (honest runs
                # already record the canonical checksum — this is a no-op
                # there).
                self.missing[commit_op] = want
        self.commit_max = max(self.commit_max, commit_op)
        self._commit_journal(out)
        out.extend(self._maybe_start_sync(int(h["checkpoint_op"])))
        return out

    def _note_anchor(self, op: int, checksum: int) -> None:
        """Record a source-authenticated content anchor; bounded by the
        live journal window (pruned below commit_min)."""
        if op <= self.commit_min and op in self._anchors:
            return
        self._anchors[op] = checksum
        if len(self._anchors) > 64:
            for o in [o for o in self._anchors if o < self.commit_min]:
                del self._anchors[o]

    def _anchor_trusted(self, op: int, checksum: int) -> bool:
        """May this anchor EVICT journaled content / pin repair targets?

        Legacy (auth off): yes — anchors are source-authenticated by the
        transport, and the byzantine fault domain models only Byzantine
        BACKUPS, so a commit heartbeat's anchor is honest by assumption.

        Under strict wire auth the primary SEAT itself is in the threat
        model: its forged heartbeat carries a perfectly valid own-key MAC,
        and a bare anchor must not be able to evict an honest journaled
        prepare (whose ack may already have let the cluster commit it —
        the quorum_journal violation the tbmc byzantine-primary scope
        found).  Destructive anchor actions therefore additionally require
        a REPLICATION QUORUM of MAC-verified acks for the anchored
        checksum: every honest anchor has one (the preparing primary's
        attestation plus the backups that acked — all broadcast under
        strict mode), while a Byzantine primary can muster only its own
        vote for a fork it invented."""
        if self.auth is None or not self.auth_strict:
            return True
        voters = self._ack_certs.get(op, {}).get(checksum)
        if voters is not None and len(voters) >= self.quorum_replication:
            return True
        if _obs.enabled:
            _obs.counter("auth.rejected.unsupported_anchor").inc()
        return False

    def _content_certified(self, op: int) -> bool:
        """True iff the journaled content at ``op`` parent-chains up to a
        source-authenticated anchor (see _anchors).  Walking DOWN from the
        anchor, any non-linking header is a detected fork: evicted, with
        the canonical checksum recorded for repair-by-checksum."""
        if "anchor_certify" in self.mc_mutations:
            # Seeded mutation (tools/tbmc): certified commits compiled out
            # — backups execute whatever chains locally, anchored or not.
            return True
        for a in sorted(o for o in self._anchors if o >= op):
            if a > self.op:
                break  # no headers past our head to walk from
            h = self.headers.get(a)
            if h is None:
                continue
            if wire.header_checksum(h) != self._anchors[a]:
                if not self._anchor_trusted(a, self._anchors[a]):
                    # Vote-unsupported anchor conflicting with our journal:
                    # the anchor itself is the suspect (Byzantine primary
                    # seat) — never certify through it, never evict for it.
                    continue
                self.byzantine_detections += 1
                if _obs.enabled:
                    _obs.counter("byzantine.equivocation_detected").inc()
                self._debug("anchor_fork_evicted", op=a)
                self._evict_fork(a, self._anchors[a])
                return False
            k = a
            while k > op:
                hk = self.headers.get(k)
                below = self.headers.get(k - 1)
                if hk is None or below is None:
                    return False  # header gap: repair must fill first
                parent = wire.u128(hk, "parent")
                if wire.header_checksum(below) != parent:
                    if not self._anchor_trusted(k - 1, parent):
                        return False
                    self.byzantine_detections += 1
                    if _obs.enabled:
                        _obs.counter(
                            "byzantine.equivocation_detected"
                        ).inc()
                    self._debug("anchor_chain_fork_evicted", op=k - 1)
                    self._evict_fork(k - 1, parent)
                    return False
                k -= 1
            return True
        return False

    def _evict_fork(self, op: int, canonical_checksum: int) -> None:
        """An uncommitted header at ``op`` is provably not the canonical
        ``canonical_checksum``: evict it and schedule a repair fetch by the
        canonical checksum.  The chain walk and the repair fill's downward
        cascade (_fill_missing) evict any forged ancestors the same way."""
        assert op > self.commit_min
        self.headers.pop(op, None)
        self.stash.pop(op, None)
        self.pipeline.pop(op, None)
        self._nacks.pop(op, None)
        self.missing[op] = canonical_checksum

    def _extend_verification(self) -> None:
        """Walk the parent chain DOWN from the verification floor, marking
        headers canonical — and EVICTING a header that does not chain (a
        stale fork from a discarded view, surviving in our WAL across a
        restart).  Evicted ops become header gaps; the repair machinery
        fetches the canonical headers, the gap-fill adoption re-verifies
        them downward, and this walk resumes."""
        while self._verify_floor > self.commit_min + 1:
            f = self._verify_floor
            h = self.headers.get(f)
            below = self.headers.get(f - 1)
            if h is None or below is None:
                if self._debug_file is not None:
                    self._debug(
                        "verify_walk_gap", floor=f,
                        have_f=h is not None, have_below=below is not None,
                        commit_min=self.commit_min,
                    )
                return  # a gap: repair must fetch headers first
            if wire.u128(h, "parent") == wire.header_checksum(below):
                self._verify_floor = f - 1
                continue
            del self.headers[f - 1]
            self.stash.pop(f - 1, None)
            self.missing.pop(f - 1, None)
            # A primary's re-certification entry built from the stale
            # header can never quorum (backups ack the canonical checksum);
            # drop it — _repipeline rebuilds it when the canonical header
            # is adopted.
            self.pipeline.pop(f - 1, None)
            return

    def _repipeline(self, op: int, h: np.ndarray) -> None:
        """Primary: (re)create the pipeline entry for an uncommitted op
        whose canonical header was adopted via repair (the entry from
        _finish_view_change may have been built from a since-evicted stale
        header; see _extend_verification)."""
        if self.status != NORMAL or not self.is_primary:
            return
        if not (self.commit_min < op <= self.op):
            return
        checksum = wire.header_checksum(h)
        entry = self.pipeline.get(op)
        if entry is None or entry.checksum != checksum:
            self.pipeline[op] = PipelineEntry(
                op=op, checksum=checksum,
                client=wire.u128(h, "client"), ok_from={self.replica},
            )

    def _commit_journal(self, out: List[Msg]) -> bool:
        """Execute journaled ops up to min(commit_max, op), in order
        (replica.zig commit_journal :3176).

        ``commit_budget`` (set by the TCP bus; None = unlimited for the
        sim/VOPR) bounds the ops executed per call: the reference commits
        through an async IO chain that never monopolizes its event loop
        (replica.zig commit_dispatch stages), and a Python replica must
        match that or a large commit backlog blocks heartbeats AND pongs
        for hundreds of ms — measured cluster-wide as primary-liveness
        probes and client failover spikes.  Returns True iff the call
        stopped on budget with backlog remaining (the bus's commit pump
        resumes on the next loop iteration)."""
        self._extend_verification()
        done = 0
        self.commit_budget_stopped = False
        while self.commit_min < min(self.commit_max, self.op):
            if self.commit_budget is not None and done >= self.commit_budget:
                self.commit_budget_stopped = True
                return True
            op = self.commit_min + 1
            if self.replica_count > 1 and op < self._verify_floor:
                # Suspect suffix (restart before the canonical chain was
                # re-established): committing now could execute a prepare a
                # view change discarded.  Repair verifies or replaces it.
                break
            h = self.headers.get(op)
            if h is None:
                break
            if (
                self.ingress_verify and self.replica_count > 1
                and not self.is_primary and not self._content_certified(op)
            ):
                # CERTIFIED COMMITS (byzantine domain): a backup executes
                # only content that chains to a source-authenticated
                # anchor.  Waiting costs at most one commit-heartbeat
                # interval in honest runs; executing early is how a forged
                # relayed prepare becomes committed state.
                break
            if (
                self.auth is not None and self.auth_strict
                and "cert_downgrade" not in self.mc_mutations
                and self.replica_count > 1 and not self.is_primary
                and not self._ack_certified(op)
            ):
                # AUTHENTICATED CERTIFICATES (auth_strict): anchors alone
                # are not proof against a lying PRIMARY — its own-key
                # heartbeat MAC verifies, so it can anchor forked content.
                # Execution additionally requires _cert_quorum() distinct
                # MAC-verified acks naming this exact checksum; quorum
                # intersection plus the honest one-vote-per-op rule makes
                # a second certificate for different content impossible.
                break
            read = self.journal.read_prepare(op)
            if read is None or wire.header_checksum(read[0]) != (
                wire.header_checksum(h)
            ):
                self.missing[op] = wire.header_checksum(h)
                break
            if self._debug_file is not None or self.blackbox is not None:
                self._debug(
                    "commit_op", op=op,
                    operation=int(read[0]["operation"]),
                    prep_view=int(read[0]["view"]),
                    ts=int(read[0]["timestamp"]),
                )
            txtrace.hop(int(read[0]["trace"]), "consensus.commit",
                        replica=self.replica, op=op)
            reply = self._commit_prepare(read[0], read[1], replay=False)
            entry = self.pipeline.pop(op, None)
            if self.is_primary and reply is not None:
                client = wire.u128(read[0], "client")
                if client:
                    out.append((("client", client), reply))
            if self._checkpoint_due():
                # Checkpoint INSIDE the commit loop, so it lands exactly on
                # op_checkpoint + interval on every replica regardless of
                # commit batching — aligned checkpoint ops make the forests
                # byte-identical across replicas (deterministic deltas),
                # which peer block repair depends on (vsr.zig
                # Checkpoint.checkpoint_after's fixed schedule).
                self.checkpoint()
                self._prune_headers()
            done += 1
        return False

    def _prune_headers(self) -> None:
        floor = self.op_checkpoint - 1
        for op in [o for o in self.headers if o < floor]:
            del self.headers[op]

    # -- view change ---------------------------------------------------------

    def _debug(self, event: str, **kw) -> None:
        box = self.blackbox
        if box is not None:
            # Every debug-channel event also lands in the flight recorder
            # (obs/txtrace.Blackbox): the recorder is on in the simulator
            # even when the debug file is not, so postmortem dumps carry
            # the protocol history leading into a failure.
            rec = {"view": self.view, "status": self.status}
            rec.update(kw)
            box.record(event, **rec)
        if self._debug_file is None:
            return
        import json as _json

        rec = {
            "ms": round(self._monotonic() / 1e6, 1),
            "r": self.replica, "view": self.view,
            "status": self.status, "ev": event,
        }
        rec.update(kw)
        self._debug_file.write(_json.dumps(rec) + "\n")

    def _maybe_clear_log_suspect(self) -> None:
        """A recovering-head replica whose log is REPAIRED may rejoin view
        changes: every byte of amputation evidence has been resolved —
        commits caught up to the durable floor, the hash chain verified
        down to it, no missing bodies, no header gaps.  At that point the
        log provably matches committed history and the suspicion (which
        exists because an amputated WAL cannot prove what it acked) no
        longer applies: anything it once acked and lost was either
        committed (now repaired back in) or nack-truncated (provably never
        committed)."""
        if not getattr(self, "_log_suspect", False):
            return
        persisted = getattr(self._sb_state, "commit_min", 0)
        persisted_adopted = getattr(self._sb_state, "log_adopted_op", 0)
        if (
            self.commit_min >= persisted
            # The head must be restored through EVERY durable watermark:
            # log_adopted_op records how far the durable log_view's log was
            # known to extend at adoption — clearing with a shorter head
            # re-arms the seed-500285 truncation (a clean-voting
            # (log_view, short-op) DVC out-ranking an intact log).  The
            # repair machinery CAN drive op there (the headers exist
            # cluster-wide); heartbeat-learned commit_max it could not.
            and self.op >= max(persisted, persisted_adopted)
            and self._verify_floor <= self.commit_min + 1
            and not self.missing
            and not self._header_gaps()
        ):
            self._log_suspect = False
            self._debug(
                "log_suspect_cleared", op=self.op, commit=self.commit_min
            )

    def _primary_spoke(self, real: bool = True) -> None:
        """Record primary-liveness evidence: fold the silence gap into the
        EWMA (feeds the adaptive suspicion budget) and stand down any
        pending probe.  ``real=False`` marks pong-only evidence — a wedged
        primary whose IO loop still answers pings must not defer elections
        forever, so pong-only stand-downs are capped between real words."""
        if real:
            self._pong_standdowns = 0
        else:
            self._pong_standdowns += 1
            if self._pong_standdowns > 3:
                return  # wedged, not busy: let the election proceed
        gap = self._ticks - self._last_primary_word
        if 0 < gap <= PRIMARY_BUDGET_CAP:
            self._primary_gap_ewma += 0.125 * (gap - self._primary_gap_ewma)
        self._last_primary_word = self._ticks
        self._probe_sent_at = None

    def _begin_view_change(self, new_view: int) -> List[Msg]:
        """Move to view_change status for new_view and broadcast SVC
        (replica.zig on view-change timeout)."""
        if self.is_standby:
            return []  # standbys never campaign
        self._debug("begin_view_change", new_view=new_view)
        assert new_view > self.view or (
            new_view == self.view and self.status != NORMAL
        )
        self.view = new_view
        self.status = VIEW_CHANGE
        self._vc_started = self._ticks
        self._vc_timeout.reset(self._ticks)
        self._dvc_sent_for = None
        self._nacks.clear()
        # A candidacy for an OLDER view is abandoned here: finishing it
        # later (deferred-finish paths) would regress self.view — and
        # durably, via _persist_view — leaving a phantom primary of a dead
        # view.
        self._new_view_pending = None
        self._pending_finish = None
        self.pipeline.clear()
        self._persist_view()
        self.svc_from.setdefault(new_view, set()).add(self.replica)
        svc = self._hdr(wire.Command.start_view_change)
        out = self._broadcast(wire.encode(svc, b""))
        out.extend(self._maybe_send_dvc())
        return out

    def on_start_view_change(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        view = int(h["view"])
        if view < self.view or self.replica_count == 1:
            return []
        if self.is_standby or int(h["replica"]) >= self.replica_count:
            # Standbys neither vote nor count (replica.zig:4613); a standby
            # tracks new views via prepares/commits/request_start_view.
            return []
        if self.sync_target is not None:
            # A syncing replica has no log to vote with; joining the view
            # change would strand the half-fetched snapshot (sync_target
            # survives but nothing resumes it).  Keep syncing; we rejoin
            # via request_start_view after the install.
            return []
        out: List[Msg] = []
        if view > self.view:
            out.extend(self._begin_view_change(view))
        elif self.status == NORMAL:
            # Current view is live; ignore stragglers.
            return []
        self.svc_from.setdefault(view, set()).add(int(h["replica"]))
        out.extend(self._maybe_send_dvc())
        return out

    def _maybe_send_dvc(self) -> List[Msg]:
        """At an SVC quorum, send do_view_change to the new primary
        (replica.zig send_do_view_change)."""
        if self.status != VIEW_CHANGE:
            return []
        # Recovering-head replicas (replica.zig status.recovering_head)
        # SEND their DVC too, flagged log_suspect: the receiver excludes
        # it from the quorum and the donor set unless every replica is
        # present (see on_do_view_change).  The suspicion predicate is
        # narrow (foreign/corrupt slots, recovered headers beyond the
        # head, persisted commit bounds above the head): a benign torn
        # tail leaves no recovered header (the headers ring is written
        # last), so ordinary crash-restarts are not suspect.
        if len(self.svc_from.get(self.view, ())) < self.quorum_view_change:
            return []
        return self._send_dvc()

    def _suspect_flag(self) -> int:
        """0 = clean; 1 = ordinary (amputation-evidence) suspicion;
        2 = PROMOTION suspicion — the retired voter's journal (and acks)
        were deliberately destroyed, so this log must not donate even
        under the all-replicas-present valve (its premise, 'every
        possible acker is inside the quorum', is false after promotion)."""
        if not getattr(self, "_log_suspect", False):
            return 0
        from .superblock import PROMOTION_SUSPECT_OP

        if getattr(self, "_log_adopted_op", 0) >= PROMOTION_SUSPECT_OP:
            return 2
        return 1

    def _send_dvc(self) -> List[Msg]:
        self._dvc_sent_for = self.view
        dvc = self._hdr(
            wire.Command.do_view_change,
            op=self.op,
            commit=self.commit_min,
            checkpoint_op=self.op_checkpoint,
            log_view=self.log_view,
            log_suspect=self._suspect_flag(),
        )
        body = wire.pack_headers(self._suffix_headers())
        message = wire.encode(dvc, body)
        new_primary = self.primary_index()
        if new_primary == self.replica:
            decoded, _, dbody = wire.decode(message)
            return self.on_do_view_change(decoded, dbody)
        return [(("replica", new_primary), message)]

    def _suffix_headers(self) -> List[np.ndarray]:
        """The journal-suffix headers that fit one message body (newest
        last); covers at least a full checkpoint interval by config."""
        k_max = self.config.message_body_size_max // wire.HEADER_SIZE
        ops = sorted(o for o in self.headers if o <= self.op)[-k_max:]
        return [self.headers[o] for o in ops]

    def on_do_view_change(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        view = int(h["view"])
        if view < self.view:
            return []
        if self.is_standby or int(h["replica"]) >= self.replica_count:
            return []  # standbys neither gather nor donate DVCs
        if self.sync_target is not None:
            return []  # syncing: see on_start_view_change
        out: List[Msg] = []
        if view > self.view:
            out.extend(self._begin_view_change(view))
        if self.primary_index(view) != self.replica or self.status == NORMAL:
            return out
        try:
            headers = wire.unpack_headers(body)
        except ValueError:
            return out
        # Recovering-head (log_suspect) DVCs are stored but normally
        # neither count toward the quorum nor donate: an amputated WAL
        # cannot prove what it once acked, so counting its vote breaks the
        # commit-quorum/view-change-quorum intersection argument (VOPR
        # seed 500285: a suspect vote let a view change truncate an op a
        # partitioned member had committed).  The way out of suspicion is
        # repair (_maybe_clear_log_suspect).
        #
        # ONE exception (VOPR seed 400396): when EVERY replica's DVC is
        # present, suspect votes are safe — every possible acker of every
        # op is inside the quorum, and a committed op (quorum-journaled,
        # synced writes survive crashes, the fault atlas forbids corrupting
        # a quorum's copies) cannot have vanished from all of them — so the
        # max-(log_view, op) log still contains all committed history.
        # Without this valve an f=0 pair whose both logs are suspect
        # escalates views forever.
        self.dvc_from.setdefault(view, {})[int(h["replica"])] = {
            "log_view": int(h["log_view"]),
            "op": int(h["op"]),
            "commit": int(h["commit"]),
            "headers": headers,
            "suspect": bool(int(h["log_suspect"])),
            "promotion": int(h["log_suspect"]) == 2,
        }
        my_flag = self._suspect_flag()
        self.dvc_from[view][self.replica] = {
            "log_view": self.log_view,
            "op": self.op,
            "commit": self.commit_min,
            "headers": self._suffix_headers(),
            "suspect": my_flag != 0,
            "promotion": my_flag == 2,
        }
        dvcs = self.dvc_from[view]
        clean_n = sum(1 for d in dvcs.values() if not d.get("suspect"))
        if clean_n >= self.quorum_view_change or (
            len(dvcs) == self.replica_count
        ):
            out.extend(self._install_canonical_log(view))
        return out

    def _install_canonical_log(self, view: int) -> List[Msg]:
        """New primary: adopt the log of the DVC with max (log_view, op)
        (replica.zig primary_set_log_from_do_view_change_messages)."""
        dvcs = self.dvc_from[view]
        clean = {r: d for r, d in dvcs.items() if not d.get("suspect")}
        if len(clean) >= self.quorum_view_change:
            # Normal case: only clean logs select (see on_do_view_change).
            donors = clean
        else:
            # All-replicas-present fallback: every acker is in the quorum,
            # so the best log over ALL DVCs still holds committed history
            # — EXCEPT promotion-suspects: their retired predecessor's
            # journal (with the acks it contributed) was destroyed outside
            # the fault atlas, so the valve's premise does not cover them.
            # A committed op still lives on its commit quorum of REAL
            # voter journals, all of which are in dvcs here.
            assert len(dvcs) == self.replica_count
            donors = {
                r: d for r, d in dvcs.items() if not d.get("promotion")
            }
            if not donors:
                # Every log is a promoted identity: the operator destroyed
                # the entire voting history — refuse to invent a canonical
                # log (safety over liveness; view-change timeouts retry).
                return []
        # Donor selection iterates SORTED items: ties on (log_view, op)
        # used to fall to dict insertion order — DVC *arrival* order — so
        # two replicas in identical protocol states could adopt
        # differently-sourced (content-identical) suffixes, and the tbmc
        # canonical-state hash could not collapse them.  At equal
        # (log_view, op) both logs carry that log_view's canonical suffix,
        # so the lowest-replica tie-break is safe by construction.
        canonical = max(
            sorted(donors.items()),
            key=lambda kv: (kv[1]["log_view"], kv[1]["op"]),
        )[1]
        self.commit_max = max(
            [d["commit"] for d in dvcs.values()] + [self.commit_max]
        )
        out: List[Msg] = []
        target_op = canonical["op"]
        if target_op > self.op_prepare_max:
            # Our WAL ring cannot hold the canonical suffix — our checkpoint
            # lags at least a full ring behind the cluster's head.  Neither
            # option at this altitude is safe: installing unclamped would
            # journal repair fills beyond the ring bound (overwriting live
            # slots), and clamping would truncate possibly-committed
            # canonical ops and finish the view with an invented head.  We
            # cannot lead this view.  Fetch the cluster's latest checkpoint
            # instead (sync handlers drop further view-change traffic while
            # sync_target is set); peers' view-change timeouts elect the
            # next primary meanwhile — abdication by silence, as when a
            # syncing replica receives an SVC.
            return self._start_full_sync()
        by_op = {int(ch["op"]): ch for ch in canonical["headers"]}
        # Same below-window suspicion as the backup's SV install: the new
        # primary's OWN uncommitted headers under the canonical window may
        # be forks of a discarded view.
        self._install_headers(
            target_op, by_op, suspect_below=view > self.log_view
        )

        if self.missing:
            # Stay in view_change; repair bodies then finish (tick retries).
            if self._debug_file is not None:
                self._debug(
                    "vc_missing_bodies", new_view=view,
                    missing=sorted(self.missing)[:12],
                    commit_max=self.commit_max, target=int(target_op),
                )
            self._new_view_pending = view
            out.extend(self._request_missing(dvcs))
            return out
        return out + self._finish_view_change(view)

    def journal_has(self, op: int, checksum: int) -> bool:
        read = self.journal.read_prepare(op)
        return read is not None and wire.header_checksum(read[0]) == checksum

    def _install_headers(
        self, target_op: int, by_op: Dict[int, np.ndarray],
        suspect_below: bool = False,
    ) -> None:
        """Adopt a canonical log suffix (shared by the new primary's DVC
        install and the backup's start_view install): truncate uncommitted
        forks beyond ``target_op``, install the canonical headers, journal
        any matching stashed bodies, and record missing bodies for repair.

        ``suspect_below``: the caller is adopting a log for an ADVANCED
        log_view.  Local uncommitted headers BELOW the installed window
        were certified under the old log and may be forks the view change
        discarded — a stale never-quorumed prepare there chains perfectly
        onto the replica's own old suffix and would commit as soon as
        commit_max catches up (VOPR seed 401021: replica joins view 8 with
        a view-0 register at op 4 that view 1 replaced with a transfer,
        SV window starts above 4, stale register commits => diverging
        op 4 across the cluster).  Raising the verification floor to the
        window start makes the range suspect; the chain walk
        (_extend_verification) evicts non-linking headers and repair
        refetches the canonical ones."""
        # Local invariant: NEVER truncate below our own committed prefix —
        # those ops are executed state; deleting their headers and letting
        # the new view refill the slots would re-commit different ops over
        # an already-applied ledger (nondeterministic divergence).
        target_op = max(target_op, self.commit_min)
        if self.op > target_op:
            for op in [o for o in self.headers if o > target_op]:
                del self.headers[op]
                self.stash.pop(op, None)
            self.op = target_op
        self.missing = {
            op: cs for op, cs in self.missing.items() if op <= target_op
        }
        for op in sorted(by_op):
            if op <= self.commit_min:
                continue
            if op > target_op:
                # Beyond the caller's clamp (the WAL bound, op_prepare_max):
                # installing these would record missing bodies whose fills
                # journal past the ring's safe window.
                continue
            ch = by_op[op]
            checksum = wire.header_checksum(ch)
            mine = self.headers.get(op)
            if mine is not None and wire.header_checksum(mine) == checksum:
                continue
            self.headers[op] = ch
            self.missing.pop(op, None)
            stashed = self.stash.get(op)
            if stashed is not None and (
                wire.header_checksum(stashed[0]) == checksum
            ):
                self.journal.write_prepare(wire.encode(*stashed))
                self.stash.pop(op, None)
                continue
            if not self.journal_has(op, checksum):
                self.missing[op] = checksum
        self.op = max(self.op, target_op)
        head = self.headers.get(self.op)
        if head is not None:
            self.parent_checksum = wire.header_checksum(head)
        # The installed window is quorum-selected canonical content arriving
        # over a source-authenticated SV/DVC: anchor it for certified
        # commits (sparsely + the top, to keep certification walks short).
        for op_a in by_op:
            if self.commit_min < op_a <= target_op and (
                op_a == target_op or op_a % 16 == 0
            ):
                self._note_anchor(
                    op_a, wire.header_checksum(by_op[op_a])
                )
        # The installed window is canonical by construction: lower the
        # verification floor to its CONTIGUOUS-from-head start (never raise
        # it — a narrow SV on an already-verified log must not re-suspect
        # history).  A gapped window (the sender itself had an evicted
        # header under repair) must not vouch for local headers under its
        # gaps — only ops the window actually covers become verified;
        # anything below stays suspect until the chain walk links it.
        if target_op in by_op:
            w = target_op
            while w - 1 in by_op and w - 1 > self.commit_min:
                w -= 1
            w = max(self.commit_min + 1, w)
            self._verify_floor = min(self._verify_floor, w)
            if suspect_below and w > self.commit_min + 1:
                # Log ADVANCED and the window does not reach the commit
                # floor: the uncovered range is suspect (see docstring).
                self._verify_floor = max(self._verify_floor, w)
        self._verify_floor = min(self._verify_floor, self.op + 1)

    def _request_missing(self, dvcs=None) -> List[Msg]:
        """request_prepare for every missing body, spread over peers.

        The starting peer ROTATES per call: a fixed per-op target would ask
        the same replica forever, and that replica's own copy can be
        latently corrupt (found by the VOPR read-fault family) — the healthy
        peer would never be asked and repair would never complete."""
        out: List[Msg] = []
        peers = [r for r in range(self.replica_count) if r != self.replica]
        if not peers:
            return out
        self._repair_rotation = getattr(self, "_repair_rotation", 0) + 1
        for i, (op, checksum) in enumerate(sorted(self.missing.items())):
            peer = peers[(i + self._repair_rotation) % len(peers)]
            req = self._hdr(
                wire.Command.request_prepare,
                prepare_op=op,
                prepare_checksum=checksum,
            )
            out.append((("replica", peer), wire.encode(req)))
        return out

    def _finish_view_change(self, view: int) -> List[Msg]:
        """All canonical bodies journaled: become primary of the new view
        (replica.zig primary_start_view_as_the_new_primary)."""
        assert self.primary_index(view) == self.replica
        # A header gap in [commit_min+1, op] (canonical DVC window narrower
        # than the suffix) must route through repair, not crash the view
        # change (ADVICE round-1): request the gap and finish on a later
        # attempt (the view-change resend timer re-triggers us).
        gap = [
            o for o in range(self.commit_min + 1, self.op + 1)
            if o not in self.headers
        ]
        if gap:
            self._new_view_pending = view  # repair machinery re-finishes
            req = self._hdr(
                wire.Command.request_headers, op_min=gap[0], op_max=gap[-1]
            )
            return self._broadcast(wire.encode(req))
        self.status = NORMAL
        self.view = view
        self.log_view = view
        self._new_view_pending = None
        self._debug("view_normal_primary", new_view=view)
        self._log_suspect = False  # the canonical quorum log is ours now
        self._vc_escalations = 0   # progress: escalation backoff resets
        # Adoption watermark: every canonical body IS journaled here (the
        # gap check above), so the new log_view's log provably extends to
        # self.op — the one moment this fact is cheap and certain.
        self._log_adopted_op = self.op
        self._persist_view()
        self.svc_from.pop(view, None)
        self.dvc_from.pop(view, None)
        # Re-certify the uncommitted suffix in the new view: pipeline entries
        # that commit once backups ack them after start_view.
        self.pipeline.clear()
        for op in range(self.commit_min + 1, self.op + 1):
            h = self.headers[op]
            self.pipeline[op] = PipelineEntry(
                op=op,
                checksum=wire.header_checksum(h),
                client=wire.u128(h, "client"),
                ok_from={self.replica},
            )
        sv = self._hdr(
            wire.Command.start_view,
            op=self.op,
            commit=self.commit_min,
            checkpoint_op=self.op_checkpoint,
        )
        body = wire.pack_headers(self._suffix_headers())
        out = self._broadcast_nodes(wire.encode(sv, body))
        self._maybe_commit_pipeline(out)
        return out

    def on_start_view(self, h: np.ndarray, body: bytes) -> List[Msg]:
        """Backup installs the new view's canonical log
        (replica.zig on_start_view :1702+)."""
        if not self._ingress_auth(h):
            return []
        # A nonce-carrying SV is a response to a request_start_view: accept
        # it only if it answers OUR outstanding request (unsolicited
        # broadcasts carry nonce 0 and pass).
        nonce = wire.u128(h, "nonce")
        if nonce and nonce != getattr(self, "_rsv_nonce", None):
            return []
        if nonce:
            self._rsv_nonce = None
        view = int(h["view"])
        if view < self.view or (view == self.view and self.status == NORMAL):
            return []
        log_advanced = view > getattr(self, "log_view", 0)
        if self.sync_target is not None:
            # Keep fetching; a view change only moves where chunks come from.
            if view > self.view:
                self.view = view
            return []
        try:
            headers = wire.unpack_headers(body)
        except ValueError:
            return []
        out: List[Msg] = []
        target_op = int(h["op"])
        by_op = {int(ch["op"]): ch for ch in headers}

        self.view = view
        self.log_view = view
        self.commit_max = max(self.commit_max, int(h["commit"]))
        self._primary_spoke()
        self.pipeline.clear()
        self._dvc_sent_for = None
        self.svc_from = {v: s for v, s in self.svc_from.items() if v > view}
        # Adoption watermark: the SV header certifies the new log_view's
        # canonical log through target_op.  Persisting it BEFORE our bodies
        # land is deliberate — a crash mid-install must restart suspect
        # (presenting (log_view, short-op) would win canonical selection
        # and truncate committed history: seed 500285).
        self._log_adopted_op = target_op
        self._persist_view()

        # If the cluster's checkpoint is beyond our journal head, peers no
        # longer hold the WAL range we'd need — adopting the canonical head
        # first would falsify the sync trigger and wedge us with
        # unrepairable gaps.  State-sync the snapshot instead.
        sv_checkpoint = int(h["checkpoint_op"])
        if sv_checkpoint > self.op:
            self.status = NORMAL  # transitional; _maybe_start_sync -> SYNCING
            sync = self._maybe_start_sync(sv_checkpoint)
            if sync:
                # Escaping the view change via state sync is progress too:
                # the escalation backoff resets on every NORMAL-entry path.
                self._vc_escalations = 0
                return sync

        self.status = NORMAL
        self._debug("view_normal_backup", new_view=int(h["view"]))
        self._vc_escalations = 0   # progress: escalation backoff resets
        # WAL bound: adopt at most a ring's worth beyond our checkpoint;
        # commits advance the checkpoint and repair fetches the rest.
        self._install_headers(
            min(target_op, self.op_prepare_max), by_op,
            suspect_below=log_advanced,
        )
        # The canonical log just replaced whatever a misdirected write may
        # have clobbered: our log is certified again.
        self._log_suspect = False

        # Ack the uncommitted suffix so the new primary can commit it —
        # but never a SUSPECT header (below the verification floor): it may
        # be a fork of a discarded view, and an ack would vouch for it.
        for op in range(self.commit_min + 1, self.op + 1):
            hh = self.headers.get(op)
            if (
                hh is not None and op not in self.missing
                and op >= self._verify_floor
            ):
                self._append_ok(out, hh)
        out.extend(self._request_missing())
        self._commit_journal(out)
        return out

    def _request_start_view(self, view: int) -> List[Msg]:
        # The nonce pairs the SV response to THIS request so a stale
        # same-view snapshot cannot be installed (message_header.zig
        # StartView.nonce; ADVICE round-1).
        if self.mc_deterministic_nonce:
            # Model-checker mode (sim/mc.py): a prng draw would make two
            # otherwise identical states hash apart, so the nonce is a
            # pure function of (replica, view) — still unique per pairing.
            self._rsv_nonce = ((self.replica + 1) << 32) | (
                view & 0xFFFF_FFFF
            )
        else:
            self._rsv_nonce = self.prng.getrandbits(64)
        req = wire.new_header(
            wire.Command.request_start_view,
            cluster=self.cluster,
            view=view,
            nonce=self._rsv_nonce,
        )
        req["replica"] = self.replica
        return [(("replica", self.primary_index(view)), wire.encode(req))]

    def on_request_start_view(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        if self.status != NORMAL or not self.is_primary:
            return []
        if int(h["view"]) > self.view:
            return []
        sv = self._hdr(
            wire.Command.start_view,
            op=self.op,
            commit=self.commit_min,
            checkpoint_op=self.op_checkpoint,
            nonce=wire.u128(h, "nonce"),
        )
        body_out = wire.pack_headers(self._suffix_headers())
        return [(("replica", int(h["replica"])), wire.encode(sv, body_out))]

    # -- repair (replica.zig :2048-2497) --------------------------------------

    def _repair_gaps(self) -> List[Msg]:
        """Request prepares between our head and the lowest stashed op."""
        if not self.stash:
            return []
        out: List[Msg] = []
        lowest = min(self.stash)
        primary = self.primary_index()
        for op in range(self.op + 1, min(lowest, self.op + 1 + 8)):
            req = self._hdr(
                wire.Command.request_prepare, prepare_op=op, prepare_checksum=0
            )
            out.append((("replica", primary), wire.encode(req)))
        return out

    def on_request_prepare(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        op = int(h["op"]) if "op" in h.dtype.names else int(h["prepare_op"])
        checksum = wire.u128(h, "prepare_checksum")
        read = self.journal.read_prepare(op)
        if read is None or (
            checksum and wire.header_checksum(read[0]) != checksum
        ):
            if checksum and op > self.commit_min and (
                self.journal.never_had(op, checksum)
                # A PROMOTED identity's never_had proves nothing about the
                # RETIRED voter's journal, which may have journaled (and
                # acked) this very op — a nack under the inherited index
                # would let a nack quorum "prove" a committed op never
                # committed (seed 601346: promoted r0's self-nack + one
                # honest nack truncated committed ops 12-13, which view 4
                # refilled).  Until certified, stay silent.
                and self._suspect_flag() != 2
            ):
                # We provably never journaled it: nack, so a view-change
                # primary can prove a globally-lost uncommitted body was
                # never quorum-journaled and truncate it (vsr.zig nacks).
                nack = self._hdr(
                    wire.Command.nack_prepare,
                    prepare_op=op,
                    prepare_checksum=checksum,
                )
                return [(("replica", int(h["replica"])), wire.encode(nack))]
            return []
        ph, pbody = read
        return [(("replica", int(h["replica"])), wire.encode(ph, pbody))]

    def on_nack_prepare(self, h: np.ndarray, body: bytes) -> List[Msg]:
        """A peer provably never journaled a body we're missing.  As the
        new primary of a pending view change, a nack quorum proves the op
        was never quorum-journaled — so it never committed — and the
        canonical suffix truncates at it instead of wedging the view
        change forever (vsr.zig nack protocol; VOPR seed 10133)."""
        if not self._ingress_auth(h):
            return []
        op = int(h["prepare_op"])
        checksum = wire.u128(h, "prepare_checksum")
        if int(h["view"]) != self.view:
            # Stale nack from before our view change (e.g. delayed by a
            # clogged link, sent while repair ran in an older view, and the
            # sender may have journaled the body since): only nacks stamped
            # with OUR view may count toward truncation.
            return []
        if self.missing.get(op) != checksum:
            return []
        self._nacks.setdefault(op, set()).add(int(h["replica"]))
        if not (
            (self.status == VIEW_CHANGE and self._new_view_pending is not None)
            # A recovering-head replica repairing ITSELF may also truncate
            # at a nack quorum: the proof (no commit quorum was ever
            # possible for this op) is role-independent, and truncating the
            # unrepairable suffix is its only path out of suspicion
            # (_maybe_clear_log_suspect) — without it, a cluster whose
            # every voter is suspect escalates views forever (VOPR seed
            # 400396).
            or (getattr(self, "_log_suspect", False) and op > self.commit_min)
        ):
            return []
        # Nack threshold: with n - q_replication + 1 provably-never-had
        # replicas (counting ourselves), fewer than q_replication can ever
        # have journaled it — no commit quorum was possible.
        nackers = set(self._nacks.get(op, ()))
        if self.journal.never_had(op, checksum) and self._suspect_flag() != 2:
            # Same promotion guard as the nack response path: the
            # inherited journal cannot testify for the retired voter's.
            nackers.add(self.replica)
        if len(nackers) < self.replica_count - self.quorum_replication + 1:
            return []
        # Truncate the canonical suffix from the nack-proven op: everything
        # above it chains from it and could never commit past it anyway.
        assert op > self.commit_min
        for x in [x for x in self.headers if x >= op]:
            del self.headers[x]
        for x in [x for x in self.stash if x >= op]:
            del self.stash[x]
        for x in [x for x in self.missing if x >= op]:
            del self.missing[x]
        for x in [x for x in self._nacks if x >= op]:
            del self._nacks[x]
        self.op = op - 1
        head = self.headers.get(self.op)
        self.parent_checksum = (
            wire.header_checksum(head) if head is not None else 0
        )
        self._verify_floor = min(self._verify_floor, self.op + 1)
        if not self.missing:
            self._pending_finish = self._new_view_pending
        return []

    def on_request_headers(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        op_min, op_max = int(h["op_min"]), int(h["op_max"])
        selected = [
            self.headers[o]
            for o in sorted(self.headers)
            if op_min <= o <= op_max
        ]
        k_max = self.config.message_body_size_max // wire.HEADER_SIZE
        selected = selected[:k_max]
        if not selected:
            return []
        reply = self._hdr(wire.Command.headers)
        return [
            (("replica", int(h["replica"])),
             wire.encode(reply, wire.pack_headers(selected)))
        ]

    def on_headers(self, h: np.ndarray, body: bytes) -> List[Msg]:
        """Merge repair headers: adopt chained extensions of our log."""
        if not self._ingress_auth(h):
            return []
        try:
            headers = wire.unpack_headers(body)
        except ValueError:
            return []
        out: List[Msg] = []
        # Gap fill (descending, so each adoption chain-validates against the
        # already-known next header): headers below our op that a narrow DVC
        # window left missing during a view change (ADVICE round-1).  Bodies
        # may already be local (stash/journal) — mirror _install_headers.
        for ch in sorted(headers, key=lambda x: -int(x["op"])):
            op = int(ch["op"])
            if self.commit_min < op <= self.op and op not in self.headers:
                nxt = self.headers.get(op + 1)
                checksum = wire.header_checksum(ch)
                if nxt is not None and wire.u128(nxt, "parent") == checksum:
                    self.headers[op] = ch
                    stashed = self.stash.get(op)
                    if stashed is not None and (
                        wire.header_checksum(stashed[0]) == checksum
                    ):
                        self.journal.write_prepare(wire.encode(*stashed))
                        self.stash.pop(op, None)
                        self._repipeline(op, ch)
                    elif not self.journal_has(op, checksum):
                        self.missing[op] = checksum
                    else:
                        self._repipeline(op, ch)
        # Anchor-certified cover of the response (byzantine domain): ops
        # whose header matches a SOURCE-AUTHENTICATED anchor, extended
        # downward through the response's own parent links.  Only this
        # certified set may testify against our journaled head — a forged
        # headers response cannot reproduce an anchored checksum, so it
        # can never evict an honest head (checksums are not MACs; a single
        # unauthenticated frame must not pick repair targets).
        certified: set = set()
        if self.ingress_verify:
            by_op = {int(ch["op"]): ch for ch in headers}
            for a in sorted(by_op, reverse=True):
                if a in certified:
                    continue
                if self._anchors.get(a) != wire.header_checksum(by_op[a]):
                    continue
                if not self._anchor_trusted(a, self._anchors[a]):
                    # Byzantine-primary defense: an anchor without a
                    # replication quorum of MAC-verified votes certifies
                    # nothing — it may be the adversary's own forged
                    # heartbeat vouching for its own forged headers.
                    continue
                k = a
                while k in by_op:
                    certified.add(k)
                    below = by_op.get(k - 1)
                    if below is None or wire.header_checksum(below) != (
                        wire.u128(by_op[k], "parent")
                    ):
                        break
                    k -= 1
        for ch in sorted(headers, key=lambda x: int(x["op"])):
            op = int(ch["op"])
            if op > self.op_prepare_max:
                break  # WAL bound: cannot take bodies this far ahead yet
            if (
                self.ingress_verify
                and op == self.op + 1
                and op in certified
                and wire.u128(ch, "parent") != self.parent_checksum
                and self.op > self.commit_min
                and not self.is_primary
                and self.op not in self.pipeline
            ):
                # The ANCHORED canonical suffix chains from a different
                # checksum for our uncommitted head than we journaled: our
                # head is a fork (a forged variant slipped into the ring),
                # and without eviction suffix adoption would wedge forever
                # — the byzantine ring tail's repair responses never link
                # onto a forged head.  The parent named by a certified
                # header IS canonical, so the checksum-matched refetch is
                # satisfiable by any honest peer.
                self.byzantine_detections += 1
                if _obs.enabled:
                    _obs.counter("byzantine.equivocation_detected").inc()
                self._debug(
                    "headers_head_fork_evicted", op=self.op,
                )
                self._evict_fork(self.op, wire.u128(ch, "parent"))
                out.extend(self._request_missing())
                break  # re-adopt on the next repair round, head-first
            if op == self.op + 1 and wire.u128(ch, "parent") == (
                self.parent_checksum
            ):
                if self.ingress_verify and op not in certified:
                    # PR 6 gap, closed: a single unauthenticated headers
                    # frame could still PROPOSE repair targets — extending
                    # our head and pinning `missing[op]` to a checksum no
                    # honest peer can serve.  Repair-target selection now
                    # routes exclusively through the anchor-certified set;
                    # an uncertified extension waits for the next commit
                    # heartbeat to anchor it (one heartbeat of latency in
                    # honest runs, never a wedge).
                    if _obs.enabled:
                        _obs.counter(
                            "byzantine.rejected.uncertified_extension"
                        ).inc()
                    continue
                self.headers[op] = ch
                self.missing[op] = wire.header_checksum(ch)
                self.op = op
                self.parent_checksum = wire.header_checksum(ch)
        out.extend(self._request_missing())
        return out

    def _fill_missing(self, h: np.ndarray, body: bytes) -> None:
        op = int(h["op"])
        self.journal.write_prepare(wire.encode(h, body))
        # Install the header too: a fork evicted by the commit-checksum
        # anchor (_evict_fork) left only the `missing` entry — the fill is
        # what restores the canonical header.  (For the ordinary
        # missing-body case the header is already this one: checksum
        # identity covers every header byte.)
        self.headers[op] = h
        if op == self.op:
            # Refilled the HEAD: re-anchor the chain tip or the next fresh
            # prepare would be checked against the evicted fork's checksum.
            self.parent_checksum = wire.header_checksum(h)
        del self.missing[op]
        self._nacks.pop(op, None)
        # Downward cascade: the canonical fill names its parent's checksum.
        # A predecessor that does not match is a forged ancestor
        # (equivocated into our chain before the anchor caught it): evict
        # it and repair by the now-known canonical checksum, all the way
        # down until the chain meets honest history.
        if self.ingress_verify and op - 1 > self.commit_min:
            below = self.headers.get(op - 1)
            parent = wire.u128(h, "parent")
            if below is not None and wire.header_checksum(below) != parent \
                    and self._anchor_trusted(op - 1, parent):
                self.byzantine_detections += 1
                if _obs.enabled:
                    _obs.counter("byzantine.equivocation_detected").inc()
                self._debug("chain_fork_evicted", op=op - 1)
                self._evict_fork(op - 1, parent)
        self._repipeline(op, h)
        self._repair_timeout.reset(self._ticks)  # repair progressing
        if getattr(self, "_new_view_pending", None) is not None and (
            not self.missing
        ):
            # All repairs done: finish becoming primary.
            pending = self._new_view_pending
            self._pending_finish = pending

    # -- peer block repair (grid_blocks_missing.zig's role) -------------------
    #
    # A replica that finds its checkpoint FILES (manifest / base snapshot /
    # delta runs) corrupt or missing at open does not discard its state:
    # each file is content-addressed by a checksum pinned from above (the
    # superblock pins the manifest, the manifest pins base + runs), so the
    # replica fetches exactly the damaged files from peers, chunk by chunk,
    # verifies them against the pinned checksums, and then opens normally.
    # Only if no peer can serve the bytes (peers checkpointed past us and
    # GC'd, or histories diverged) does it fall back to full state sync.

    def _enter_block_repair(self, damage, cold_paths=None) -> None:
        self._init_clock()
        self.status = RECOVERING
        self._recovering_since = self._ticks
        self._block_repair = {
            "queue": list(damage),      # [(kind, ident, checksum), ...]
            "buf": bytearray(),         # bytes of queue[0] fetched so far
            "peer": self._next_peer(self.replica),
            "attempts": 0,              # timed-out requests since progress
            "requested": False,
            # Cold entries are addressed by checksum; this maps each to the
            # relative file name the fetched bytes install under.
            "cold_paths": dict(cold_paths or {}),
            # Fire the first request on the very next tick, not after a
            # full resend interval.
            "last_req": self._ticks - BLOCK_REPAIR_RESEND,
        }

    def _next_peer(self, p: int) -> int:
        p = (p + 1) % self.replica_count
        if p == self.replica:
            p = (p + 1) % self.replica_count
        return p

    def _request_block(self) -> List[Msg]:
        br = self._block_repair
        kind, ident, expect = br["queue"][0]
        req = self._hdr(
            wire.Command.request_blocks,
            block_kind=_BLOCK_KIND_CODE[kind],
            block_id=ident,
            block_checksum=expect,
            offset=len(br["buf"]),
        )
        br["requested"] = True
        br["last_req"] = self._ticks
        return [(("replica", br["peer"]), wire.encode(req))]

    def _tick_block_repair(self) -> List[Msg]:
        br = self._block_repair
        if self._ticks - br["last_req"] < BLOCK_REPAIR_RESEND:
            return []
        if br["requested"]:
            # The outstanding request timed out: rotate peers and restart
            # the current file (a different peer's chunks must align).
            br["attempts"] += 1
            br["peer"] = self._next_peer(br["peer"])
            br["buf"] = bytearray()
            if br["attempts"] >= 3 * self.replica_count:
                return self._block_repair_fallback()
        return self._request_block()

    def on_request_blocks(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        kind = _BLOCK_KIND_NAME.get(int(h["block_kind"]))
        if kind is None:
            return []
        expect = wire.u128(h, "block_checksum")
        offset = int(h["offset"])
        if kind == "cold":
            path = self.machine.cold.locate_by_checksum(expect)
        else:
            path = self.forest.locate_block(kind, int(h["block_id"]), expect)
        if path is None:
            return []
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                total = f.tell()
                if offset >= total:
                    return []
                f.seek(offset)
                chunk = f.read(self.config.message_body_size_max)
        except OSError:
            return []
        resp = self._hdr(
            wire.Command.block,
            block_kind=int(h["block_kind"]),
            block_id=int(h["block_id"]),
            block_checksum=expect,
            offset=offset,
            total=total,
        )
        return [(("replica", int(h["replica"])), wire.encode(resp, chunk))]

    def on_block(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        br = self._block_repair
        if br is None and self._cold_fetch is not None:
            return self._on_cold_block(h, body)
        if br is None or not br["queue"]:
            return []
        kind, ident, expect = br["queue"][0]
        if (
            int(h["block_kind"]) != _BLOCK_KIND_CODE[kind]
            or wire.u128(h, "block_checksum") != expect
        ):
            return []  # stale response for a file we already finished
        if int(h["offset"]) != len(br["buf"]):
            return self._request_block()
        br["buf"].extend(body)
        br["attempts"] = 0
        if len(br["buf"]) < int(h["total"]):
            return self._request_block()
        if kind == "cold":
            rel = br["cold_paths"].get(expect)
            installed = rel is not None and self.machine.cold.install_file(
                rel, expect, bytes(br["buf"])
            )
        else:
            installed = self.forest.repair_block(
                kind, ident, expect, bytes(br["buf"])
            )
        if not installed:
            # Bytes don't hash to the pinned checksum (corrupt/malicious
            # peer): retry the whole file from the next peer.
            br["buf"] = bytearray()
            br["peer"] = self._next_peer(br["peer"])
            return self._request_block()
        br["queue"].pop(0)
        br["buf"] = bytearray()
        self.blocks_repaired += 1
        if br["queue"]:
            return self._request_block()
        return self._finish_block_repair()

    def _finish_block_repair(self) -> List[Msg]:
        """All queued files repaired: re-verify and open.  A repaired
        manifest may reveal more damage (its base/runs were unknowable
        while it was corrupt) — requeue and keep going."""
        try:
            recovery = self._open_durable_state()
        except ForestDamage as err:
            br = self._block_repair
            br["queue"] = list(err.damage)
            # A repaired forest may reveal COLD damage next (or vice
            # versa): the path map must follow the new queue, or the
            # receiver can never install the fetched bytes and livelocks
            # re-requesting the same file.
            br["cold_paths"] = dict(getattr(err, "cold_paths", None) or {})
            br["buf"] = bytearray()
            br["attempts"] = 0
            return self._request_block()
        self._block_repair = None
        self._post_open(recovery)
        if self.status == RECOVERING:
            return self._request_start_view(self.view)
        return []

    def _block_repair_fallback(self) -> List[Msg]:
        """No peer holds our damaged files: discard the local checkpoint
        and fetch the cluster's latest full snapshot (state sync)."""
        self._block_repair = None
        self.journal.recover()  # journal rings are independent of the forest
        return self._start_full_sync()

    # -- state sync (vsr/sync.zig) --------------------------------------------

    def _start_full_sync(self) -> List[Msg]:
        """Enter state sync targeting the cluster's LATEST checkpoint
        (checkpoint_op 0 = whatever the responder has).  Single entry point
        for every full-sync trigger — block-repair fallback, lagging-primary
        abdication, hostile-manifest restart — so sync-entry invariants
        (abandoning a pending view finish, resetting the fetch buffer) hold
        on every path."""
        self._sync_peer = self._next_peer(
            self._sync_peer if self._sync_peer is not None else self.replica
        )
        return self._enter_sync(0)

    def _enter_sync(self, checkpoint_op: int, *, refresh: bool = False) -> List[Msg]:
        """The ONLY sync-entry point (targeted or latest): sync-entry
        invariants hold on every path — notably abandoning any pending view
        finish, or _finish_view_change(stale view) would regress self.view
        after the sync installs.

        Picks the transport: Merkle-anchored incremental catch-up
        (docs/state_sync.md) when this replica runs commitments and is not
        forced full; the byte-exact full-checkpoint transfer otherwise.
        ``refresh=True`` (a checkpoint-refresh restart, on_commit) keeps
        the resend/progress clocks UNTOUCHED so a dead pinned responder is
        still rotated away from even while refreshes keep arriving."""
        self._new_view_pending = None
        self._pending_finish = None
        self.status = SYNCING
        self.sync_buffer = bytearray()
        self._sync_local = None
        prev = self.sync_target if refresh else None
        if not refresh:
            self._last_sync_req = self._ticks
            self._sync_progress = self._ticks
        if prev is not None and prev.get("mode", "full") == "full":
            # A fallback (or an initial full choice) is STICKY for the
            # whole sync episode: a refresh must not re-enter the roots
            # flow — among merkle-off peers under a sustained flood that
            # would reset the unanswered-rounds budget every refresh and
            # livelock the rejoin (the refresh twin of the stranded-sync
            # wedge).
            self.sync_target = {
                "checkpoint_op": checkpoint_op, "total": None,
                "mode": "full",
            }
            return self._request_sync_chunk()
        if self._sync_incremental_wanted():
            self.sync_target = {
                "checkpoint_op": checkpoint_op, "total": None,
                "mode": "roots",
                # Attempt/failure budgets survive refreshes for the same
                # reason the full choice does: each unanswered round must
                # COUNT, however often the cluster checkpoints.
                "roots_attempts": (
                    prev.get("roots_attempts", 0) if prev else 0
                ),
                "verify_failures": (
                    prev.get("verify_failures", 0) if prev else 0
                ),
                "descend_attempts": (
                    prev.get("descend_attempts", 0) if prev else 0
                ),
            }
            return self._request_sync_roots()
        self.sync_target = {
            "checkpoint_op": checkpoint_op, "total": None, "mode": "full",
        }
        return self._request_sync_chunk()

    def _sync_incremental_wanted(self) -> bool:
        """Attempt the incremental path iff this replica runs Merkle
        commitments (the np trees need the leaf contract armed cluster-
        wide) and nothing forces the proven full transfer."""
        if self.sync_mode_force == "full":
            return False
        return bool(getattr(self.machine, "merkle_enabled", False))

    def _maybe_start_sync(self, primary_checkpoint_op: int) -> List[Msg]:
        """If the primary's checkpoint is beyond our journal *head*, our WAL
        no longer overlaps the cluster's and ordinary repair cannot catch us
        up: fetch the checkpoint snapshot.  (A backup merely lagging in
        commits — head >= the checkpoint — repairs via the WAL instead.)

        Second trigger, commit-floor starvation: a replica whose NEXT
        commit (commit_min+1) sits at or below the cluster's checkpoint and
        is header-gapped, missing, or under the verification floor may be
        permanently unrepairable — peers prune headers below their
        checkpoint (_prune_headers) and recycle those WAL slots, so chain
        repair can have nobody left to answer (VOPR seed 400816: a
        restarted replica with a damaged WAL prefix wedges at commit 0
        while the cluster checkpoints past it).  Repair gets a grace of
        _FLOOR_STALL_SYNC heartbeats; genuine progress resets the
        counter."""
        if self.sync_target is not None:
            return []
        nxt = self.commit_min + 1
        if primary_checkpoint_op >= nxt and primary_checkpoint_op > 0 and (
            self.headers.get(nxt) is None
            or nxt in self.missing
            or nxt < self._verify_floor
        ):
            self._floor_stall += 1
            if self._floor_stall >= _FLOOR_STALL_SYNC:
                self._floor_stall = 0
                self._debug(
                    "floor_stall_sync", commit_min=self.commit_min,
                    cluster_checkpoint=primary_checkpoint_op,
                )
                return self._enter_sync(primary_checkpoint_op)
        else:
            self._floor_stall = 0
        if primary_checkpoint_op <= self.op:
            return []
        return self._enter_sync(primary_checkpoint_op)

    def _request_sync_chunk(self) -> List[Msg]:
        req = self._hdr(
            wire.Command.request_sync_checkpoint,
            checkpoint_op=self.sync_target["checkpoint_op"],
            offset=len(self.sync_buffer),
        )
        target = (
            self._sync_peer if self._sync_peer is not None
            else self.primary_index()
        )
        return [(("replica", target), wire.encode(req))]

    def on_request_sync_checkpoint(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        checkpoint_op = int(h["checkpoint_op"])
        offset = int(h["offset"])
        # checkpoint_op 0 = "whatever is latest" (block-repair fallback:
        # the requester's own checkpoint is unusable, any current one will do).
        if checkpoint_op == 0:
            checkpoint_op = self.op_checkpoint
        if checkpoint_op != self.op_checkpoint or self.op_checkpoint == 0:
            return []
        try:
            # Materialized once per checkpoint op (forest caches the file);
            # each chunk request seeks and reads only its window, so a full
            # sync costs O(total) responder IO, not O(total^2/chunk).
            path, file_checksum = self.forest.materialize_file(
                self.op_checkpoint
            )
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                total = f.tell()
                if offset >= total:
                    return []
                f.seek(offset)
                chunk = f.read(self.config.message_body_size_max)
        except (OSError, AssertionError):
            return []
        resp = self._hdr(
            wire.Command.sync_checkpoint,
            checkpoint_op=self.op_checkpoint,
            offset=offset,
            total=total,
            file_checksum=file_checksum,
            commit_max=self.commit_min,
        )
        return [(("replica", int(h["replica"])), wire.encode(resp, chunk))]

    def on_sync_checkpoint(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        if self.sync_target is None:
            return []
        if self.sync_target.get("mode", "full") != "full":
            # A stale full-path chunk (e.g. from before an incremental
            # retry) must not pollute the descent state.
            return []
        if self._cold_fetch is not None:
            # Snapshot already fully fetched; a late/duplicate chunk must
            # not re-trigger the install (it would reset the in-progress
            # cold-run fetch and livelock).
            return []
        checkpoint_op = int(h["checkpoint_op"])
        if self.sync_target["checkpoint_op"] == 0 and not self.sync_buffer:
            # "Latest" request: pin to whichever checkpoint answered first.
            self.sync_target["checkpoint_op"] = checkpoint_op
        if checkpoint_op != self.sync_target["checkpoint_op"]:
            return []
        if int(h["offset"]) != len(self.sync_buffer):
            return self._request_sync_chunk()
        self.sync_buffer.extend(body)
        self.sync_stats["bytes_full"] += len(body)
        if _obs.enabled:
            _obs.counter("sync.bytes_full").inc(len(body))
        self.sync_target["total"] = int(h["total"])
        self.sync_target["file_checksum"] = wire.u128(h, "file_checksum")
        self.sync_target["commit_max"] = int(h["commit_max"])
        if len(self.sync_buffer) < self.sync_target["total"]:
            self._last_sync_req = self._ticks
            self._sync_progress = self._ticks
            return self._request_sync_chunk()
        return self._install_sync_checkpoint()

    def _sync_responder(self) -> int:
        return (
            self._sync_peer if self._sync_peer is not None
            else self.primary_index()
        )

    # -- Merkle-anchored incremental catch-up (docs/state_sync.md) ------------
    #
    # Requester flow: request_sync_roots -> (verify top frontiers) ->
    # batched binary descent over DIVERGING interior nodes only
    # (request_sync_subtree kind=descend; each children pair verified
    # against its already-verified parent) -> diverging LEAF rows fetched
    # in budget-sized batches (kind=rows; each row re-hashed against its
    # verified leaf) -> append-only history tail (kind=history) -> the
    # reconstructed state must hash to the responder's advertised
    # whole-state checksum before installing through the SAME tail the
    # full path uses (_install_sync_state).  Any verification failure
    # rotates the responder and re-requests; any structural mismatch
    # (capacity/schema/cold/divergence threshold) degrades to the proven
    # full-checkpoint transfer — a mixed-version cluster never wedges.

    def _sync_rotate_peer(self) -> None:
        self._sync_peer = self._next_peer(
            self._sync_peer if self._sync_peer is not None
            else self.primary_index()
        )

    def _sync_obs(self, name: str, n: int = 1) -> None:
        if _obs.enabled:
            _obs.counter(name).inc(n)

    def _sync_pack_for(self, op: int):
        """Responder-side per-checkpoint pack (canonical arrays + trees +
        install gates), built once and cached until the checkpoint moves."""
        from . import statesync

        cached = self._sync_pack_cache
        if cached is not None and cached.op == op:
            return cached
        try:
            arrays, meta = self.forest.canonical_arrays(op)
        except (OSError, RuntimeError, AssertionError, ValueError, KeyError):
            return None
        pack = statesync.SyncPack(op, arrays, meta)
        self._sync_pack_cache = pack
        return pack

    def _request_sync_roots(self) -> List[Msg]:
        req = self._hdr(
            wire.Command.request_sync_roots,
            checkpoint_op=self.sync_target["checkpoint_op"],
        )
        return [(("replica", self._sync_responder()), wire.encode(req))]

    def on_request_sync_roots(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        if self.op_checkpoint == 0 or not getattr(
            self.machine, "merkle_enabled", False
        ):
            # Merkle-off responders stay silent: the requester counts the
            # unanswered rounds and degrades to the full path, exactly as
            # it does for a pre-sync-roots peer (version skew).
            return []
        want = int(h["checkpoint_op"])
        if want and want != self.op_checkpoint:
            return []
        # The state-sync summary is checkpoint-derived: capture already ran
        # behind the settle barrier (machine.merkle_canonical_roots drains
        # the TB_MERKLE_ASYNC commitment lane before the roots are read),
        # so a deferred-lane backlog on THIS replica can never skew the
        # roots a rejoining peer descends against.  Consensus commits are
        # per-op besides (TB_FUSE never engages here), keeping peer forests
        # byte-identical — docs/commitments.md composition sections.
        pack = self._sync_pack_for(self.op_checkpoint)
        if pack is None:
            return []
        if len(pack.roots_body) > self.config.message_body_size_max:
            # Pathological summary (e.g. an enormous session table): stay
            # silent rather than ship an oversized frame; the requester
            # falls back to the chunked full transfer.
            return []
        resp = self._hdr(
            wire.Command.sync_roots,
            checkpoint_op=pack.op,
            commit_max=self.commit_min,
            ledger_digest=pack.digest,
            state_checksum=pack.state_checksum,
        )
        return [(("replica", int(h["replica"])),
                 wire.encode(resp, pack.roots_body))]

    def on_sync_roots(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        from . import checkpoint as ckpt_mod
        from . import statesync

        target = self.sync_target
        if target is None or target.get("mode") != "roots":
            return []
        checkpoint_op = int(h["checkpoint_op"])
        if target["checkpoint_op"] == 0:
            target["checkpoint_op"] = checkpoint_op
        if checkpoint_op != target["checkpoint_op"]:
            return []
        info = statesync.unpack_roots(body)
        if info is None:
            # Malformed or forged summary (top frontier not folding to the
            # stated roots): reject-and-refetch from a rotated peer.
            return self._sync_verify_failed("roots")
        self.sync_stats["bytes_incremental"] += len(body)
        self._sync_obs("sync.bytes_incremental", len(body))
        self._sync_progress = self._ticks
        # Structural gates: anything the descent cannot reconcile routes
        # to the byte-exact full transfer (docs/state_sync.md fallback
        # matrix) instead of wedging or installing garbage.
        if info["meta"].get("machine", {}).get("cold_manifest"):
            return self._sync_fallback("cold_manifest")
        arrays = ckpt_mod.ledger_to_arrays(self.machine.checkpoint_ledger())
        if statesync.schema(arrays) != info["schema"]:
            return self._sync_fallback("schema")
        for pad in statesync.PADS:
            if statesync.pad_capacity(arrays, pad) != (
                info["pads"][pad]["capacity"]
            ):
                return self._sync_fallback("capacity")
        hist_keys = statesync.history_keys(arrays)
        local_hist = int(arrays["history/count"])
        if local_hist > info["history_count"]:
            return self._sync_fallback("history_regression")
        # Compare our trees' top frontiers against the verified summary:
        # clean subtrees are skipped wholesale, diverging positions seed
        # the descent queues (leaf positions go straight to row fetch).
        trees = statesync.build_trees(arrays)
        want: Dict[str, Dict[int, int]] = {}
        diff: Dict[str, list] = {}
        rows_needed: Dict[str, list] = {}
        diverging = 0
        for pad in statesync.PADS:
            cap = info["pads"][pad]["capacity"]
            depth = statesync.top_depth(cap)
            theirs = info["pads"][pad]["top"]
            mine = statesync.frontier(trees[pad], depth)
            base = 1 << depth
            want[pad] = {}
            diff[pad] = []
            rows_needed[pad] = []
            for i in range(len(theirs)):
                tv = int(theirs[i])
                if tv == int(mine[i]):
                    continue
                diverging += 1
                pos = base + i
                want[pad][pos] = tv
                if base == cap:  # the top frontier IS the leaf level
                    rows_needed[pad].append(pos - cap)
                else:
                    diff[pad].append(pos)
        # What a full transfer of this state would ship (the responder
        # materializes DENSE arrays): the descent aborts to the full path
        # the moment its own projected bill exceeds the divergence
        # threshold's share of this — cold starts and long absences
        # degrade after a few cheap interior rounds instead of shipping
        # the whole ledger twice, row by row.
        full_est = sum(
            info["pads"][pad]["capacity"]
            * statesync.row_bytes(arrays, pad)
            for pad in statesync.PADS
        ) + info["history_count"] * statesync.history_row_bytes(arrays)
        self._sync_local = {
            "arrays": arrays,
            "trees": trees,
            "info": info,
            "want": want,
            "diff": diff,
            "rows_needed": rows_needed,
            "row_patches": {pad: [] for pad in statesync.PADS},
            "history": {
                "start": local_hist,
                "next": local_hist,
                "total": info["history_count"],
                "chunks": [],
            },
            "hist_keys": hist_keys,
            "outstanding": None,
            "bytes": len(body),
            "full_est": full_est,
        }
        target["mode"] = "descend"
        target["commit_max"] = int(h["commit_max"])
        target["ledger_digest"] = int(h["ledger_digest"])
        target["state_checksum"] = wire.u128(h, "state_checksum")
        self._debug(
            "sync_roots", checkpoint_op=checkpoint_op,
            diverging_top=diverging, full_est=full_est,
        )
        return self._sync_request_next()

    def _sync_batch_limits(self) -> Tuple[int, int]:
        """(descend nodes per request, history rows per request) under the
        message body budget (requests carry 8 B/node, replies 16 B/node)."""
        budget = self.config.message_body_size_max
        return max(1, budget // 16), budget

    def _sync_request_next(self) -> List[Msg]:
        """Issue the next batched request of the descent, or finalize.
        Work items are consumed only when their VERIFIED reply arrives, so
        a rotation retransmits the same batch to the next peer."""
        from . import statesync
        from .checksum import checksum as _checksum

        sl = self._sync_local
        if sl is None:
            return self._sync_fallback("lost_state")
        target = self.sync_target
        ckpt = target["checkpoint_op"]
        nodes_max, budget = self._sync_batch_limits()
        # Projected bill so far: session bytes + the rows already known
        # diverging + a floor for the interior still to resolve.  Crossing
        # the threshold's share of the full-transfer estimate means the
        # descent cannot win — degrade before shipping the ledger twice.
        projected = sl["bytes"] + sum(
            len(sl["rows_needed"][pad])
            * statesync.row_bytes(sl["arrays"], pad)
            for pad in statesync.PADS
        ) + 32 * sum(len(sl["diff"][pad]) for pad in statesync.PADS)
        if projected > self.sync_divergence_max * sl["full_est"]:
            return self._sync_fallback("divergence")
        for pad_i, pad in enumerate(statesync.PADS):
            if sl["diff"][pad]:
                nodes = np.asarray(
                    sl["diff"][pad][:nodes_max], dtype="<u8"
                )
                payload = nodes.tobytes()
                sl["outstanding"] = {
                    "pad": pad_i, "kind": wire.SYNC_DESCEND,
                    "list": nodes, "count": len(nodes), "start": 0,
                    "list_checksum": _checksum(payload) & ((1 << 64) - 1),
                }
                req = self._hdr(
                    wire.Command.request_sync_subtree,
                    checkpoint_op=ckpt, count=len(nodes), pad=pad_i,
                    kind=wire.SYNC_DESCEND,
                )
                return [(("replica", self._sync_responder()),
                         wire.encode(req, payload))]
        for pad_i, pad in enumerate(statesync.PADS):
            if sl["rows_needed"][pad]:
                per_row = statesync.row_bytes(sl["arrays"], pad)
                rows_max = max(1, budget // max(1, per_row))
                slots = np.asarray(
                    sorted(sl["rows_needed"][pad][:rows_max]), dtype="<u8"
                )
                payload = slots.tobytes()
                sl["outstanding"] = {
                    "pad": pad_i, "kind": wire.SYNC_ROWS,
                    "list": slots, "count": len(slots), "start": 0,
                    "list_checksum": _checksum(payload) & ((1 << 64) - 1),
                }
                req = self._hdr(
                    wire.Command.request_sync_subtree,
                    checkpoint_op=ckpt, count=len(slots), pad=pad_i,
                    kind=wire.SYNC_ROWS,
                )
                return [(("replica", self._sync_responder()),
                         wire.encode(req, payload))]
        hist = sl["history"]
        if hist["next"] < hist["total"]:
            per_row = statesync.history_row_bytes(sl["arrays"])
            count = max(1, budget // per_row)
            sl["outstanding"] = {
                "pad": statesync.HISTORY_PAD, "kind": wire.SYNC_HISTORY,
                "list": None, "count": count, "start": hist["next"],
                "list_checksum": 0,
            }
            req = self._hdr(
                wire.Command.request_sync_subtree,
                checkpoint_op=ckpt, count=count, pad=statesync.HISTORY_PAD,
                kind=wire.SYNC_HISTORY, start=hist["next"],
            )
            return [(("replica", self._sync_responder()),
                     wire.encode(req))]
        return self._sync_finalize()

    def on_request_sync_subtree(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        from . import statesync
        from .checksum import checksum as _checksum

        if self.op_checkpoint == 0 or not getattr(
            self.machine, "merkle_enabled", False
        ):
            return []
        if int(h["checkpoint_op"]) != self.op_checkpoint:
            return []
        pack = self._sync_pack_for(self.op_checkpoint)
        if pack is None:
            return []
        kind = int(h["kind"])
        pad_i = int(h["pad"])
        budget = self.config.message_body_size_max
        requester = ("replica", int(h["replica"]))
        if kind == wire.SYNC_HISTORY:
            start = int(h["start"])
            total = int(pack.arrays["history/count"])
            per_row = statesync.history_row_bytes(pack.arrays)
            count = min(
                max(1, int(h["count"])), max(1, budget // per_row),
                max(0, total - start),
            )
            payload = statesync.pack_history(pack.arrays, start, count)
            resp = self._hdr(
                wire.Command.sync_subtree,
                checkpoint_op=pack.op, start=start, total=total,
                count=count, pad=statesync.HISTORY_PAD,
                kind=wire.SYNC_HISTORY, list_checksum=0,
            )
            return [(requester, wire.encode(resp, payload))]
        if pad_i >= len(statesync.PADS) or kind not in (
            wire.SYNC_DESCEND, wire.SYNC_ROWS
        ):
            return []
        pad = statesync.PADS[pad_i]
        cap = statesync.pad_capacity(pack.arrays, pad)
        if len(body) % 8 != 0:
            return []  # malformed node/slot list
        items = np.frombuffer(body, dtype="<u8")
        if len(items) != int(h["count"]) or len(items) == 0:
            return []
        list_checksum = _checksum(body) & ((1 << 64) - 1)
        if kind == wire.SYNC_DESCEND:
            if len(items) > budget // 16 or int(items.max()) >= cap or (
                int(items.min()) < 1
            ):
                return []
            payload = statesync.children(pack.trees[pad], items).tobytes()
        else:
            if int(items.max()) >= cap:
                return []
            payload = statesync.pack_rows(pack.arrays, pad, items)
            if len(payload) > budget:
                return []  # malformed over-budget request
        resp = self._hdr(
            wire.Command.sync_subtree,
            checkpoint_op=pack.op, count=len(items), pad=pad_i, kind=kind,
            list_checksum=list_checksum,
        )
        return [(requester, wire.encode(resp, payload))]

    def on_sync_subtree(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        from . import statesync

        target = self.sync_target
        sl = self._sync_local
        if target is None or target.get("mode") != "descend" or sl is None:
            return []
        if int(h["checkpoint_op"]) != target["checkpoint_op"]:
            return []
        out = sl["outstanding"]
        if out is None:
            return []
        if int(h["pad"]) != out["pad"] or int(h["kind"]) != out["kind"]:
            return []  # stale reply for an earlier request
        if int(h["list_checksum"]) != out["list_checksum"]:
            return []  # a delayed duplicate answering a DIFFERENT list
        if out["kind"] != wire.SYNC_HISTORY and (
            int(h["count"]) != out["count"]
        ):
            return []  # history replies may clamp count; others may not
        kind = out["kind"]
        self.sync_stats["bytes_incremental"] += len(body)
        sl["bytes"] += len(body)
        self._sync_obs("sync.bytes_incremental", len(body))
        self._sync_progress = self._ticks
        self._last_sync_req = self._ticks
        target["descend_attempts"] = 0  # progress re-arms the budget
        if kind == wire.SYNC_DESCEND:
            pad = statesync.PADS[out["pad"]]
            nodes = out["list"]
            if len(body) != 16 * len(nodes):
                # Malformed/truncated children list (incl. non-multiple-
                # of-8 bodies np.frombuffer would raise on): a lying
                # chunk, not a crash.
                return self._sync_verify_failed("children_shape")
            values = np.frombuffer(body, dtype="<u8")
            if self.sync_verify and not statesync.verify_children(
                values, nodes, sl["want"][pad]
            ):
                return self._sync_verify_failed("children")
            tree = sl["trees"][pad]
            cap = sl["info"]["pads"][pad]["capacity"]
            # Consume the batch, enqueue only DIVERGING children.
            del sl["diff"][pad][: len(nodes)]
            for i, node in enumerate(nodes):
                for side in (0, 1):
                    child = 2 * int(node) + side
                    theirs = int(values[2 * i + side])
                    if theirs == int(tree[child]):
                        continue
                    sl["want"][pad][child] = theirs
                    if child >= cap:
                        sl["rows_needed"][pad].append(child - cap)
                    else:
                        sl["diff"][pad].append(child)
            sl["outstanding"] = None
            return self._sync_request_next()
        if kind == wire.SYNC_ROWS:
            pad = statesync.PADS[out["pad"]]
            slots = out["list"]
            cap = sl["info"]["pads"][pad]["capacity"]
            rows = statesync.unpack_rows(sl["arrays"], pad, slots, body)
            if rows is None:
                return self._sync_verify_failed("rows_shape")
            if self.sync_verify and not statesync.verify_rows(
                rows, pad, slots, sl["want"][pad], cap
            ):
                return self._sync_verify_failed("rows")
            served = set(int(s) for s in slots)
            sl["rows_needed"][pad] = [
                s for s in sl["rows_needed"][pad] if s not in served
            ]
            sl["row_patches"][pad].append((slots, rows))
            self.sync_stats["subtrees_shipped"] += 1
            self.sync_stats["rows_installed"] += len(slots)
            self._sync_obs("sync.subtrees_shipped")
            self._sync_obs("sync.rows_installed", len(slots))
            sl["outstanding"] = None
            return self._sync_request_next()
        # SYNC_HISTORY
        hist = sl["history"]
        start = int(h["start"])
        count = int(h["count"])
        if start != hist["next"]:
            return []
        if int(h["total"]) != hist["total"]:
            # The responder's history length contradicts the verified
            # summary: treat as a lying/stale chunk.
            return self._sync_verify_failed("history_total")
        if count <= 0 or start + count > hist["total"]:
            # A forged count past the verified total would blow the
            # bounded install slice at finalize — reject it here.
            return self._sync_verify_failed("history_shape")
        chunk = statesync.unpack_history(sl["arrays"], count, body)
        if chunk is None:
            return self._sync_verify_failed("history_shape")
        hist["chunks"].append((start, count, chunk))
        hist["next"] = start + count
        sl["outstanding"] = None
        return self._sync_request_next()

    def _sync_verify_failed(self, what: str) -> List[Msg]:
        """A lying or bit-flipped chunk: never installed — reject, count,
        rotate to the next peer, and retransmit the SAME batch (work is
        consumed only on verified replies).  Persistent failure degrades
        to the full transfer."""
        target = self.sync_target
        self.sync_stats["chunk_retries"] += 1
        self._sync_obs("sync.chunk_retries")
        self._debug("sync_chunk_rejected", what=what)
        target["verify_failures"] = target.get("verify_failures", 0) + 1
        if target["verify_failures"] > SYNC_VERIFY_FAILURES:
            return self._sync_fallback("verify_failures")
        self._sync_rotate_peer()
        if target.get("mode") == "descend" and self._sync_local is not None:
            self._sync_local["outstanding"] = None
            return self._sync_request_next()
        return self._request_sync_roots()

    def _sync_fallback(self, reason: str) -> List[Msg]:
        """Degrade to the byte-exact full-checkpoint transfer (the choice
        is logged and counted; docs/state_sync.md fallback matrix)."""
        self.sync_stats["fallbacks"] += 1
        self._sync_obs("sync.fallbacks")
        self._sync_obs(f"sync.fallback.{reason}")
        self._debug("sync_fallback", reason=reason)
        op = self.sync_target["checkpoint_op"] if self.sync_target else 0
        self._sync_local = None
        self.sync_target = {
            "checkpoint_op": op, "total": None, "mode": "full",
        }
        self.sync_buffer = bytearray()
        self._last_sync_req = self._ticks
        self._sync_progress = self._ticks
        return self._request_sync_chunk()

    def _sync_finalize(self) -> List[Msg]:
        """Descent drained: reconstruct the responder's checkpoint state
        from our own state + the verified patches, gate on the whole-state
        checksum, serialize our own checkpoint blob, and install through
        the same tail as the full path."""
        from . import checkpoint as ckpt_mod
        from . import statesync

        sl = self._sync_local
        target = self.sync_target
        op = target["checkpoint_op"]
        info = sl["info"]
        arrays = {
            k: np.array(v, copy=True) for k, v in sl["arrays"].items()
        }
        for pad in statesync.PADS:
            for slots, rows in sl["row_patches"][pad]:
                idx = slots.astype(np.int64)
                for key, vals in rows.items():
                    arrays[key][idx] = vals
            arrays[f"{pad}/count"] = np.array(info["pads"][pad]["count"])
            arrays[f"{pad}/probe_overflow"] = np.array(
                info["pads"][pad]["probe_overflow"]
            )
        # History: the responder's capacity + our verified prefix + the
        # fetched append-only tail.
        hist = sl["history"]
        hcap = info["history_capacity"]
        for key in sl["hist_keys"]:
            old = sl["arrays"][key]
            grown = np.zeros((hcap,) + old.shape[1:], dtype=old.dtype)
            keep = min(hist["start"], hcap, old.shape[0])
            grown[:keep] = old[:keep]
            arrays[key] = grown
        for start, count, chunk in hist["chunks"]:
            for key, vals in chunk.items():
                arrays[key][start:start + count] = vals
        arrays["history/count"] = np.array(
            np.uint64(hist["total"])
        )
        if self.sync_verify:
            got = statesync.arrays_checksum(arrays)
            if got != target.get("state_checksum"):
                # The tree's covered columns could not explain the whole
                # divergence (or a bug/liar slipped through): NEVER
                # install — fetch the byte-exact blob instead.
                return self._sync_fallback("state_checksum")
        ledger = ckpt_mod.arrays_to_ledger(arrays)
        meta = info["meta"]
        _path, file_checksum = ckpt_mod.save_arrays(
            self.data_path, op, ckpt_mod.sparsify_arrays(arrays), meta
        )
        self.sync_stats["mode"] = "incremental"
        self._sync_obs("sync.mode.incremental")
        self._debug(
            "sync_incremental_install", checkpoint_op=op,
            bytes=self.sync_stats["bytes_incremental"],
            rows=self.sync_stats["rows_installed"],
        )
        return self._install_sync_state(
            ledger, meta, op, file_checksum, target.get("commit_max", op)
        )

    def _request_cold_chunk(self) -> List[Msg]:
        cf = self._cold_fetch
        _basename, checksum = cf["queue"][0]
        req = self._hdr(
            wire.Command.request_blocks,
            block_kind=wire.BLOCK_KIND_COLD,
            block_id=0,
            block_checksum=checksum,
            offset=len(cf["buf"]),
        )
        return [(("replica", self._sync_responder()), wire.encode(req))]

    def _on_cold_block(self, h: np.ndarray, body: bytes) -> List[Msg]:
        cf = self._cold_fetch
        if not cf["queue"] or int(h["block_kind"]) != wire.BLOCK_KIND_COLD:
            return []
        basename, checksum = cf["queue"][0]
        if wire.u128(h, "block_checksum") != checksum:
            return []
        if int(h["offset"]) != len(cf["buf"]):
            return self._request_cold_chunk()
        cf["buf"].extend(body)
        cf["attempts"] = 0
        # Progress resets the sync resend timer, or the tick would wipe an
        # in-flight multi-chunk transfer every SYNC_RESEND ticks.
        self._last_sync_req = self._ticks
        self._sync_progress = self._ticks
        if len(cf["buf"]) < int(h["total"]):
            return self._request_cold_chunk()
        if not self.machine.cold.install_file(
            basename, checksum, bytes(cf["buf"])
        ):
            cf["buf"] = bytearray()
            return self._request_cold_chunk()
        cf["queue"].pop(0)
        cf["buf"] = bytearray()
        if cf["queue"]:
            return self._request_cold_chunk()
        # All spill files present: complete the deferred install.
        self._cold_fetch = None
        return self._install_sync_checkpoint()

    def _install_sync_checkpoint(self) -> List[Msg]:
        """Install a fully-fetched checkpoint snapshot and rejoin."""
        from ..utils.fs import atomic_write

        target = self.sync_target
        op = target["checkpoint_op"]
        path = checkpoint_mod.path_for(self.data_path, op)
        # Durably in place BEFORE the superblock/manifest reference its
        # checksum — a crash in between must find the full blob on disk.
        atomic_write(path, bytes(self.sync_buffer))
        try:
            ledger, meta = checkpoint_mod.load(
                self.data_path, op, target["file_checksum"]
            )
        except RuntimeError:
            # Corrupt/raced snapshot: restart the fetch from scratch.
            self.sync_buffer = bytearray()
            self._last_sync_req = self._ticks
            return self._request_sync_chunk()
        # Cold tier: the checkpoint's cold_manifest names spill files LOCAL
        # to the responder — fetch (by checksum) any we lack before the
        # install can complete (re-entered once the fetch drains).
        cold_manifest = meta["machine"].get("cold_manifest", [])
        if cold_manifest and self.machine.cold.directory:
            try:
                damage = self.machine.cold.verify_manifest(cold_manifest)
            except ValueError:
                # Malicious/corrupt manifest (path-traversing entry): restart
                # the sync at whatever-is-latest from the NEXT responder.
                # Re-pinning the hostile peer's checkpoint_op would drop
                # every honest responder's reply (they serve only their own
                # checkpoint) and livelock the fetch.
                return self._start_full_sync()
            if damage:
                self._cold_fetch = {
                    "queue": damage,        # [(basename, checksum), ...]
                    "buf": bytearray(),
                    "attempts": 0,
                }
                self._last_sync_req = self._ticks
                return self._request_cold_chunk()
        self._cold_fetch = None
        self.sync_stats["mode"] = "full"
        self._sync_obs("sync.mode.full")
        return self._install_sync_state(
            ledger, meta, op, target["file_checksum"],
            target.get("commit_max", op),
        )

    def _install_sync_state(
        self, ledger, meta: dict, op: int, file_checksum: int,
        commit_max: int,
    ) -> List[Msg]:
        """The shared install tail of BOTH sync transports (full blob and
        incremental reconstruction): swap machine state, adopt sessions,
        reset the log around the snapshot, seal the superblock, rejoin.
        May raise loudly (DeviceStateUnrecoverable) when the snapshot is
        unservable in this machine mode — e.g. a cold-tier manifest at a
        sharded rejoiner — rather than wedging silently."""
        # A background checkpoint still in flight refers to the pre-sync
        # ledger; land it BEFORE the snapshot replaces machine/forest state
        # (its anchor then loses the _superblock_install merge below).
        self._checkpoint_drain()
        self.machine.ledger = ledger
        self.machine.restore_host_state(meta["machine"])
        self.sessions = {
            int(client_hex, 16): Session(
                client=int(client_hex, 16),
                session=s["session"],
                request=s["request"],
                reply_bytes=b"",
                slot=s["slot"],
            )
            for client_hex, s in meta.get("sessions", {}).items()
        }
        self.op_checkpoint = op
        self.commit_min = op
        self.commit_max = max(self.commit_max, commit_max)
        self.op = op
        self.headers = {}
        self.stash.clear()
        self.missing.clear()
        self.parent_checksum = 0
        self._verify_floor = op + 1  # nothing above the snapshot known yet
        self._log_suspect = False    # snapshot replaced the clobbered WAL
        # The snapshot (committed state through op) IS our log now; the
        # old adoption watermark referred to a WAL the sync replaced.
        self._log_adopted_op = op
        manifest_checksum = self.forest.adopt_base(
            ledger, meta, op, file_checksum
        )
        state = SuperBlockState(
            cluster=self.cluster,
            replica=self.replica,
            replica_count=self.replica_count,
            standby_count=self.standby_count,  # membership rides every write
            primary_offset=self._primary_offset,
            view=self.view,
            log_view=self.log_view,
            commit_min=self.commit_min,
            commit_max=self.commit_max,
            log_adopted_op=self._log_adopted_op,
            op_checkpoint=op,
            checkpoint_file_checksum=file_checksum,
            ledger_digest=self.machine.digest(),
            prepare_timestamp=self.machine.prepare_timestamp,
            commit_timestamp=self.machine.commit_timestamp,
            manifest_checksum=manifest_checksum,
        )
        state = self._superblock_install(state)
        self._sb_state = state
        self.forest.gc()
        self.sync_target = None
        self.sync_buffer = bytearray()
        self._sync_local = None
        self._sync_peer = None
        # Any view finish deferred before the sync refers to pre-snapshot
        # state; resuming it would regress the view.  Rejoin fresh.
        self._new_view_pending = None
        self.status = RECOVERING
        self._recovering_since = self._ticks
        return self._request_start_view(self.view)

    # -- clock ----------------------------------------------------------------

    def on_ping(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        pong = self._hdr(
            wire.Command.pong,
            ping_timestamp_monotonic=int(h["ping_timestamp_monotonic"]),
            pong_timestamp_wall=self._realtime(),
        )
        out = [(("replica", int(h["replica"])), wire.encode(pong))]
        # A RECOVERING replica learns newer views from ping headers: its
        # request_start_view targets the primary of ITS view, so in a
        # QUIESCENT cluster (no prepares flowing to bump it) a restart
        # into a stale view wedged forever — the view-change escape valve
        # is voters-only, so a restarted STANDBY never recovered (round-5
        # standby VOPR find, seed 13: standby stuck 'recovering' at view 3
        # under a view-4 cluster).  Adopt the view and re-aim the RSV.
        if self.status == RECOVERING and int(h["view"]) > self.view:
            self.view = int(h["view"])
            self._persist_view()
            out.extend(self._request_start_view(self.view))
        return out

    def on_pong(self, h: np.ndarray, body: bytes) -> List[Msg]:
        if not self._ingress_auth(h):
            return []
        ping_mono = int(h["ping_timestamp_monotonic"])
        if int(h["replica"]) < self.replica_count:
            # Standby clocks never affect cluster time (replica.zig:1274).
            self.clock.learn(
                int(h["replica"]), ping_mono, int(h["pong_timestamp_wall"])
            )
        # Feed the retry timeouts' RTT estimate (vsr.zig:593-634).
        self.rtt.sample(
            (self._monotonic() - ping_mono) / getattr(self, "tick_ns", TICK_NS)
        )
        # A pong from the current primary is liveness evidence — this is
        # what stands down a suspicion probe (see tick()'s two-stage
        # primary timeout).
        if (
            self.status == NORMAL
            and not self.is_primary
            and self._probe_sent_at is not None
            and int(h["replica"]) == self.primary_index()
        ):
            self._primary_spoke(real=False)
        return []

    # -- tick (timeouts; vsr.zig:543-712) -------------------------------------

    def tick(self) -> List[Msg]:
        self._ticks += 1
        out: List[Msg] = []
        if self.clock is not None:
            self.clock.tick()
        if self.blackbox is not None:
            # One ring append per tick: the recorder's heartbeat row —
            # op/commit watermarks and queue depths, the numbers a
            # postmortem reads first.
            self.blackbox.record(
                "tick", t=self._ticks, view=self.view, status=self.status,
                op=self.op, commit=self.commit_min,
                stash=len(self.stash), missing=len(self.missing),
                pipeline=len(self.pipeline),
            )
        if self.replica_count == 1:
            return out

        # Event-loop starvation guard: if OUR tick loop just slept through
        # several tick periods (host overload, GC, scheduler preemption on a
        # shared core), every liveness observation in that gap is stale —
        # the primary may have spoken while we weren't listening.  Refresh
        # the primary-liveness clock instead of campaigning on evidence
        # gathered while we ourselves were asleep (the reference's clock
        # code treats monotonic jumps with the same suspicion,
        # clock.zig monotonic discipline).  tick_ns is stamped by the TCP
        # bus (net/cluster_bus.py); the VOPR virtual clock leaves it unset
        # and keeps full control of liveness timing.
        tick_ns = getattr(self, "tick_ns", None)
        if tick_ns:
            now = self._monotonic()
            last = self._last_tick_mono
            self._last_tick_mono = now
            if last is not None and now - last > 4 * tick_ns:
                # Stale evidence: discount exactly the slept-through gap
                # from the silence clock (WITHOUT feeding the gap EWMA —
                # the gap was ours, not the primary's) and stand down any
                # probe raised on pre-sleep observations.  Advancing by the
                # gap, not resetting to now, keeps failover live: a backup
                # with RECURRING stalls (commit chunks, GC) would otherwise
                # re-arm the full budget on every stall and never elect a
                # replacement for a genuinely dead primary.
                slept = int((now - last) / tick_ns)
                self._last_primary_word = min(
                    self._ticks, self._last_primary_word + slept
                )
                self._probe_sent_at = None
                self._debug(
                    "tick_starved", gap_ms=round((now - last) / 1e6, 1)
                )

        # A repaired recovering-head log may rejoin view changes.
        self._maybe_clear_log_suspect()

        # Deferred view-change completion after repairs.
        if getattr(self, "_pending_finish", None) is not None:
            view = self._pending_finish
            self._pending_finish = None
            if self.status == VIEW_CHANGE and not self.missing:
                out.extend(self._finish_view_change(view))

        if self._ticks - self._last_ping >= PING_INTERVAL:
            self._last_ping = self._ticks
            ping = self._hdr(
                wire.Command.ping,
                checkpoint_op=self.op_checkpoint,
                ping_timestamp_monotonic=self.clock.ping_timestamp(),
            )
            out.extend(self._broadcast_nodes(wire.encode(ping)))

        if self._block_repair is not None:
            out.extend(self._tick_block_repair())
            return out

        if self.sync_target is not None:
            # A sync in flight is the only way forward regardless of what
            # status a concurrent view change left us in — resume it rather
            # than stranding the half-fetched snapshot.
            self.status = SYNCING
            if self._ticks - self._last_sync_req >= SYNC_RESEND:
                self._last_sync_req = self._ticks
                mode = self.sync_target.get("mode", "full")
                if self._cold_fetch is not None:
                    cf = self._cold_fetch
                    cf["attempts"] += 1
                    if cf["attempts"] >= 3 * self.replica_count:
                        # No reachable replica serves these cold runs
                        # (GC'd past this checkpoint): restart the sync at
                        # whatever is latest instead of waiting forever.
                        self._cold_fetch = None
                        self.sync_target = {
                            "checkpoint_op": 0, "total": None,
                            "mode": "full",
                        }
                        self.sync_buffer = bytearray()
                        if self._sync_peer is not None:
                            self._sync_peer = self._next_peer(self._sync_peer)
                        out.extend(self._request_sync_chunk())
                    else:
                        if self._sync_peer is not None:
                            self._sync_peer = self._next_peer(self._sync_peer)
                        cf["buf"] = bytearray()
                        out.extend(self._request_cold_chunk())
                    return out
                # Sync-PROGRESS stall (no payload accepted for a full
                # resend interval — distinct from the resend clock, which
                # checkpoint-refreshes legitimately restart): the current
                # responder is dead or pruned past our target — rotate.
                if self._ticks - self._sync_progress >= SYNC_RESEND:
                    if self._sync_peer is not None:
                        # Explicit-peer sync (block-repair fallback, or an
                        # earlier rotation): a silent responder means we
                        # guessed wrong — rotate.
                        self._sync_peer = self._next_peer(self._sync_peer)
                    else:
                        # Targeted sync whose default responder (the
                        # primary) went silent for a full resend interval:
                        # rotate through peers from here on.  Every replica
                        # at the target checkpoint serves sync, and a
                        # syncing replica abstains from view changes — so a
                        # DEAD primary would otherwise wedge both this
                        # replica (polling a corpse forever) and the
                        # cluster (one abstainer can break the view-change
                        # quorum).  Found by the overload fault kind: a
                        # flood-lagged replica synced exactly when the
                        # primary died.  Seed the rotation PAST the silent
                        # primary (seeding from self.replica can land right
                        # back on the corpse and burn another full resend
                        # interval of the election budget).
                        self._sync_peer = self._next_peer(
                            self.primary_index()
                        )
                    # Stalled long enough that the rotation clock must
                    # restart with the new responder.
                    self._sync_progress = self._ticks
                if mode == "roots":
                    t = self.sync_target
                    t["roots_attempts"] = t.get("roots_attempts", 0) + 1
                    if t["roots_attempts"] > SYNC_ROOTS_ATTEMPTS * max(
                        1, self.replica_count - 1
                    ):
                        # Nobody speaks sync_roots (merkle-off peers,
                        # version skew): the proven full transfer.
                        out.extend(self._sync_fallback("unsupported"))
                    else:
                        out.extend(self._request_sync_roots())
                elif mode == "descend":
                    t = self.sync_target
                    t["descend_attempts"] = t.get("descend_attempts", 0) + 1
                    if t["descend_attempts"] > SYNC_ROOTS_ATTEMPTS * max(
                        1, self.replica_count - 1
                    ):
                        # The roots responder vanished mid-descent and no
                        # peer serves subtrees (e.g. the only other
                        # merkle-on replica died): the full transfer is
                        # still served by everyone — take it instead of
                        # rotating forever.
                        out.extend(self._sync_fallback("unresponsive"))
                    elif self._sync_local is None:
                        out.extend(self._sync_fallback("lost_state"))
                    else:
                        self._sync_local["outstanding"] = None
                        out.extend(self._sync_request_next())
                else:
                    out.extend(self._request_sync_chunk())
            return out

        if self.status == NORMAL and self.is_primary:
            # Commit-stall abdication: a primary that journals prepares but
            # cannot EXECUTE them (e.g. restarted with an unrepairable WAL
            # prefix whose headers the cluster has pruned — VOPR seed
            # 400816) wedges the whole cluster while looking alive: its
            # prepares keep resetting every backup's liveness clock.  If
            # commit_min hasn't advanced for PRIMARY_ABDICATE ticks while
            # committable work exists, step down — the next view's primary
            # commits from its intact chain, and this replica's floor-
            # stall sync (see _maybe_start_sync) heals it as a backup.
            if self.commit_max > self.commit_min or self.pipeline:
                if self.commit_min == self._abdicate_commit_mark:
                    self._abdicate_ticks += 1
                else:
                    self._abdicate_commit_mark = self.commit_min
                    self._abdicate_ticks = 0
                if self._abdicate_ticks >= PRIMARY_ABDICATE:
                    self._abdicate_ticks = 0
                    self._debug(
                        "primary_abdicate", commit_min=self.commit_min,
                        commit_max=self.commit_max,
                    )
                    out.extend(self._begin_view_change(self.view + 1))
                    return out
            else:
                self._abdicate_ticks = 0
            if self._ticks - self._last_commit_sent >= COMMIT_HEARTBEAT:
                self._last_commit_sent = self._ticks
                # commit_checksum anchors the heartbeat to the CONTENT of
                # the committed head, not just its number: backups verify
                # it against their own header for that op, so a Byzantine
                # peer equivocating prepare bodies is detected before the
                # forged op ever executes (see on_commit).  0 when the
                # header is gone (pruned below a checkpoint) — legacy
                # frames decode the same way, so the field is skippable.
                head = self.headers.get(self.commit_min)
                commit = self._hdr(
                    wire.Command.commit,
                    commit=self.commit_min,
                    commit_checksum=(
                        wire.header_checksum(head) if head is not None else 0
                    ),
                    checkpoint_op=self.op_checkpoint,
                    timestamp_monotonic=self.clock.ping_timestamp(),
                )
                out.extend(self._broadcast_nodes(wire.encode(commit)))
            if self.pipeline and self._prepare_timeout.fired(self._ticks):
                # Quorumed-but-uncommitted entries can linger if the commit
                # attempt at ack time stalled on a repairable local fault;
                # retry the pipeline commit before resending.
                self._maybe_commit_pipeline(out)
                # Timeout fallback: re-broadcast unquorumed prepares to all
                # backups (the ring is the fast path, this is the safety
                # net).  Op-sorted, not insertion-ordered: _repipeline
                # re-inserts repaired mid-suffix entries out of order, and
                # resend emission order must be a function of protocol
                # state, not arrival history (tbmc canonical hashing).
                for entry in [
                    self.pipeline[o] for o in sorted(self.pipeline)
                ]:
                    if len(entry.ok_from) >= self.quorum_replication:
                        continue
                    read = self.journal.read_prepare(entry.op)
                    if read is None or (
                        wire.header_checksum(read[0]) != entry.checksum
                    ):
                        # OUR copy is unreadable (latent fault on the slot).
                        # Repair it from any backup that journaled it.
                        self.missing.setdefault(entry.op, entry.checksum)
                        entry.repair_rounds += 1
                        if entry.repair_rounds >= 3 * max(
                            1, self.replica_count - 1
                        ):
                            # Peers can't supply it either: abdicate.  The
                            # view change's nack protocol then proves the
                            # body was never quorum-journaled and truncates
                            # it (VOPR seed 10133) — or repairs it if some
                            # replica does hold it.
                            out.extend(
                                self._begin_view_change(self.view + 1)
                            )
                            break
                        continue
                    message = wire.encode(read[0], read[1])
                    for r in range(self.replica_count):
                        if r != self.replica and r not in entry.ok_from:
                            out.append((("replica", r), message))
            if (self.missing or self.stash or self._header_gaps()) and (
                self._repair_timeout.fired(self._ticks)
            ):
                # The primary repairs too: its own journal copy of a
                # committed-elsewhere op can be latently corrupt (found by
                # the VOPR read-fault family; commit would stall forever).
                out.extend(self._request_missing())
                out.extend(self._repair_gaps())
                gaps = self._header_gaps()
                if gaps:
                    # Header gaps at the PRIMARY (e.g. _extend_verification
                    # evicted a stale below-window fork after a restart+
                    # view-win): fetch canonical headers from the backups —
                    # without this the commit floor never clears.
                    req = self._hdr(
                        wire.Command.request_headers,
                        op_min=gaps[0], op_max=gaps[-1],
                    )
                    out.extend(self._broadcast(wire.encode(req)))

        elif self.status == NORMAL:
            # Backup: watch for a dead primary.  Standbys observe but never
            # call elections (they are not in the view-change quorum).
            # Two-stage suspicion (reference: RTT-adaptive timeouts,
            # vsr.zig:543-712): the silence budget adapts to the observed
            # inter-word gap, and the first firing sends a direct ping —
            # a busy-but-alive primary (long fsync, scheduler preemption)
            # answers from its IO loop and the election is avoided.  Only
            # a probe that ALSO goes unanswered starts the view change.
            silent = self._ticks - max(self._last_primary_word, 0)
            budget = min(
                max(NORMAL_HEARTBEAT,
                    int(self._primary_gap_ewma * PRIMARY_GAP_MULT)),
                PRIMARY_BUDGET_CAP,
            ) + self._heartbeat_jitter
            if not self.is_standby and silent >= budget:
                if self._probe_sent_at is None:
                    self._probe_sent_at = self._ticks
                    self._debug("primary_probe", silent_ticks=silent)
                    probe = self._hdr(
                        wire.Command.ping,
                        checkpoint_op=self.op_checkpoint,
                        ping_timestamp_monotonic=self.clock.ping_timestamp(),
                    )
                    out.append(
                        (("replica", self.primary_index()),
                         wire.encode(probe))
                    )
                elif self._ticks - self._probe_sent_at >= PROBE_GRACE:
                    self._debug(
                        "primary_timeout",
                        silent_ticks=silent,
                        probe_ticks=self._ticks - self._probe_sent_at,
                    )
                    self._last_primary_word = self._ticks
                    self._probe_sent_at = None
                    out.extend(self._begin_view_change(self.view + 1))
            # Repair runs INDEPENDENTLY of the suspicion state machine (its
            # own timeout, vsr.zig repair_timeout): a pending probe must not
            # starve gap fill — repairs may be exactly what un-wedges the
            # commit path.  (Re-check NORMAL: the campaign above may have
            # moved us to VIEW_CHANGE this tick.)
            if self.status == NORMAL and (
                self.missing or self.stash or self._header_gaps()
                or self.commit_max > self.op
            ) and self._repair_timeout.fired(self._ticks):
                out.extend(self._request_missing())
                out.extend(self._repair_gaps())
                # Header gaps: request by op with checksum 0 ("whatever you
                # have chained there"); adoption verifies the parent chain.
                primary = self.primary_index()
                for op in self._header_gaps():
                    req = self._hdr(
                        wire.Command.request_prepare,
                        prepare_op=op,
                        prepare_checksum=0,
                    )
                    out.append((("replica", primary), wire.encode(req)))
                if self.commit_max > self.op:
                    # Missing log SUFFIX (commit heartbeats got ahead of our
                    # head, e.g. the tail prepare was lost repeatedly): fetch
                    # the suffix headers; bodies repair via `missing`.
                    req = self._hdr(
                        wire.Command.request_headers,
                        op_min=self.op + 1,
                        op_max=self.commit_max,
                    )
                    out.append((("replica", primary), wire.encode(req)))

        elif self.status == VIEW_CHANGE:
            # Escalation BACKS OFF exponentially: a fixed window phase-
            # locks against repair — seed 700883 escalated through 300+
            # views because the lost-body nack-truncation round trip
            # (request_prepare -> nack quorum) took longer than one
            # window, and every escalation reset the repair from scratch.
            # Doubling the window per consecutive escalation (capped 16x)
            # guarantees the window eventually exceeds any bounded repair
            # RTT.  Deterministic (no prng draw: pinned seeds replay).
            window = VIEW_CHANGE_ESCALATE << min(self._vc_escalations, 4)
            if self._ticks - self._vc_started >= window:
                self._vc_escalations += 1
                out.extend(self._begin_view_change(self.view + 1))
            elif self._vc_timeout.fired(self._ticks):
                svc = self._hdr(wire.Command.start_view_change)
                out.extend(self._broadcast(wire.encode(svc)))
                if self._dvc_sent_for == self.view and (
                    self.primary_index() != self.replica
                ):
                    out.extend(self._send_dvc())
                if self.missing:
                    out.extend(self._request_missing())
                elif self._new_view_pending is not None:
                    # Header-gap finish attempt: re-checks the gap, either
                    # completing the view change or re-requesting headers
                    # (a lost headers response must not wedge us until
                    # escalation).
                    out.extend(
                        self._finish_view_change(self._new_view_pending)
                    )

        elif self.status == RECOVERING:
            if self._rsv_timeout.fired(self._ticks):
                out.extend(self._request_start_view(self.view))
                # If nobody answers (total cluster restart), force a view
                # change so the cluster re-certifies its log.  Time base is
                # entry into RECOVERING, not process age — a replica that
                # re-enters late (post-sync) must give the live primary a
                # chance to answer first.
                if not self.is_standby and (
                    self._ticks - self._recovering_since
                    >= NORMAL_HEARTBEAT + self._heartbeat_jitter
                ):
                    out.extend(self._begin_view_change(self.view + 1))

        return out

    # -- protocol-state capsule (sim/mc.py; docs/tbmc.md) ---------------------
    #
    # snapshot()/restore() capture EVERY field the consensus state machine
    # reads: a cluster step becomes a pure function of (capsule, event).
    # The ledger is folded to its digest — a capsule restores protocol
    # state bit-identically, and either the machine supports mc_snapshot/
    # mc_restore (the model checker's DigestMachine) or restore() asserts
    # the live ledger already sits at the capsule's digest (the production
    # TpuStateMachine: protocol state travels, executed state does not).
    # This is also the exact state surface a MAC/signature layer must
    # cover (ROADMAP item 4).

    _MC_SCALARS = (
        "cluster", "replica", "replica_count", "standby_count",
        "_primary_offset", "_boot_replica_count",
        "view", "log_view", "status", "op", "commit_min", "commit_max",
        "op_checkpoint", "parent_checksum", "_verify_floor", "_log_suspect",
        "_log_adopted_op", "byzantine_detections", "_dvc_sent_for",
        "_new_view_pending", "_pending_finish", "_sync_peer", "_rsv_nonce",
        "_repair_rotation", "commit_budget", "commit_budget_stopped",
        "overload_control", "ingress_verify", "auth_strict",
        "blocks_repaired",
    )
    # Pure-time counters and retry-arm state: behavior-relevant only
    # through WHICH timers are due — which the model checker replaces with
    # explicit mc_fire events — so mc.py excludes this group from the
    # canonical state hash (symmetric interleavings collapse) while the
    # capsule still round-trips it bit-identically.
    _MC_TIME = (
        "_ticks", "_last_ping", "_last_commit_sent", "_last_primary_word",
        "_primary_gap_ewma", "_probe_sent_at", "_pong_standdowns",
        "_floor_stall", "_abdicate_commit_mark", "_abdicate_ticks",
        "_vc_started", "_vc_escalations", "_last_sync_req",
        "_sync_progress",
        "_heartbeat_jitter", "_recovering_since", "_last_tick_mono",
    )
    _MC_CONTAINERS = (
        "headers", "stash", "missing", "_nacks", "_anchors", "_ack_certs",
        "pipeline", "svc_from", "dvc_from", "sessions", "sync_target",
        "_block_repair", "_cold_fetch", "_sb_state",
    )
    _MC_TIMEOUTS = (
        "_prepare_timeout", "_vc_timeout", "_rsv_timeout", "_repair_timeout",
    )
    # Lazily-created attributes (e.g. _repair_rotation) must restore to
    # ABSENT, not None — their getattr defaults are load-bearing.
    # Deliberately NOT in the capsule: _sync_local/_sync_pack_cache (bulk
    # numpy descent state, reconstructible — a restored-elsewhere replica
    # mid-descent degrades to the full transfer via the lost_state
    # fallback) and sync_stats (pure accounting, read by no protocol
    # decision).  Same-instance round trips (snapshot_interpose) keep
    # them as live attributes either way.
    _MC_MISSING = "__mc_missing__"

    def snapshot(self) -> dict:
        """Deep-copied protocol-state capsule; see section docstring."""
        import copy

        machine = self.machine
        if hasattr(machine, "mc_snapshot"):
            machine_cap = machine.mc_snapshot()
        else:
            machine_cap = {
                "folded_digest": machine.digest(),
                "prepare_timestamp": machine.prepare_timestamp,
                "commit_timestamp": machine.commit_timestamp,
            }
        clock_cap = None
        if self.clock is not None:
            clock_cap = {
                "samples": copy.deepcopy(self.clock.samples),
                "epoch_start_monotonic": self.clock.epoch_start_monotonic,
                "offset_ns": self.clock.offset_ns,
                "synchronized": self.clock._synchronized,
            }
        missing = self._MC_MISSING
        return {
            "scalars": {
                k: getattr(self, k, missing) for k in self._MC_SCALARS
            },
            "time": {k: getattr(self, k, missing) for k in self._MC_TIME},
            "containers": {
                k: copy.deepcopy(getattr(self, k, None))
                for k in self._MC_CONTAINERS
            },
            "sync_buffer": bytes(self.sync_buffer),
            "timeouts": {
                k: (t.attempts, t._last, t._interval)
                for k in self._MC_TIMEOUTS
                for t in (getattr(self, k),)
            },
            "rtt": self.rtt.estimate,
            "prng": self.prng.getstate(),
            # The SuperBlock OBJECT's in-memory state, not just the
            # replica's _sb_state cache: checkpoint() bumps sequence from
            # ``superblock.state``, so leaving it out made the next
            # view-persist's sequence a function of EXPLORATION HISTORY
            # (how many installs ever ran on this instance), not of the
            # restored state — a canonical-hash dedup killer the model
            # checker surfaced as a state-space explosion.
            "superblock": copy.deepcopy(self.superblock.state),
            "clock": clock_cap,
            "machine": machine_cap,
        }

    def restore(self, capsule: dict) -> None:
        """Reinstate a snapshot() capsule bit-identically (the capsule is
        deep-copied on the way in, so it stays reusable).  Works on the
        live instance or a freshly constructed one (the model checker's
        restart-into-state path); with a machine that cannot restore
        folded ledger state, the live digest must already match."""
        import copy

        # Order matters on a fresh instance: identity scalars first (the
        # clock needs replica/replica_count), then the clock rebuild
        # (_init_clock draws jitter from the prng), then the time fields
        # and prng state, which overwrite whatever the rebuild drew.
        missing = self._MC_MISSING

        def put(k, v):
            if v is missing or (isinstance(v, str) and v == missing):
                if hasattr(self, k):
                    delattr(self, k)
            else:
                setattr(self, k, v)

        for k, v in capsule["scalars"].items():
            put(k, v)
        clock_cap = capsule["clock"]
        if clock_cap is not None:
            if self.clock is None:
                self._init_clock()
            self.clock.replica_count = self.replica_count
            self.clock.replica = self.replica
            self.clock.samples = copy.deepcopy(clock_cap["samples"])
            self.clock.epoch_start_monotonic = (
                clock_cap["epoch_start_monotonic"]
            )
            self.clock.offset_ns = clock_cap["offset_ns"]
            self.clock._synchronized = clock_cap["synchronized"]
            self.time_ns = self._primary_now
        for k, v in capsule["time"].items():
            put(k, v)
        for k, v in capsule["containers"].items():
            put(k, copy.deepcopy(v))
        self.sync_buffer = bytearray(capsule["sync_buffer"])
        self.prng.setstate(capsule["prng"])
        for k, (attempts, last, interval) in capsule["timeouts"].items():
            t = getattr(self, k)
            t.attempts, t._last, t._interval = attempts, last, interval
        self.rtt.estimate = capsule["rtt"]
        self.superblock.state = copy.deepcopy(capsule["superblock"])
        machine_cap = capsule["machine"]
        if hasattr(self.machine, "mc_restore"):
            self.machine.mc_restore(machine_cap)
        else:
            live = self.machine.digest()
            want = machine_cap["folded_digest"]
            if live != want:
                raise RuntimeError(
                    "capsule folds the ledger to its digest: restore() "
                    f"needs the live ledger at {want:#x}, found {live:#x} "
                    "(docs/tbmc.md — executed state does not travel)"
                )
            self.machine.prepare_timestamp = machine_cap["prepare_timestamp"]
            self.machine.commit_timestamp = machine_cap["commit_timestamp"]

    # -- explicit timeout events (sim/mc.py) ----------------------------------

    MC_TIMEOUT_KINDS = (
        "commit_hb", "prepare", "repair", "suspect",
        "vc_resend", "vc_escalate", "rsv", "recover_campaign",
    )

    def mc_enabled_timeouts(self) -> List[str]:
        """Timeout kinds that could act in the current status — the model
        checker's enumerable timer alphabet (virtual time is abstracted:
        WHICH timer fires is the exploration dimension, not when)."""
        kinds: List[str] = []
        if self.replica_count == 1 or self.clock is None:
            return kinds
        repairable = bool(
            self.missing or self.stash or self._header_gaps()
        )
        if self.status == NORMAL and self.is_primary:
            kinds.append("commit_hb")
            if self.pipeline:
                kinds.append("prepare")
            if repairable:
                kinds.append("repair")
        elif self.status == NORMAL:
            if not self.is_standby:
                kinds.append("suspect")
            if repairable or self.commit_max > self.op:
                kinds.append("repair")
        elif self.status == VIEW_CHANGE:
            kinds.extend(("vc_resend", "vc_escalate"))
        elif self.status == RECOVERING:
            kinds.append("rsv")
            if not self.is_standby:
                kinds.append("recover_campaign")
        return kinds

    def mc_fire(self, kind: str) -> List[Msg]:
        """Force exactly the named timer due and run one tick() — every
        other timer is quieted, so the tick's output is a deterministic
        function of the protocol capsule and ``kind`` alone."""
        assert kind in self.MC_TIMEOUT_KINDS, kind
        # Virtual time leaps between model-checker events; the exact span
        # is irrelevant (every timer below is re-armed explicitly).
        self._ticks += 1000
        t = self._ticks + 1  # the value tick() observes after increment

        def due(tm) -> None:
            tm._last = t - max(1, tm._interval)

        for name in self._MC_TIMEOUTS:
            getattr(self, name)._last = t  # quiet
        self._last_ping = t
        self._last_commit_sent = t
        self._last_primary_word = t
        self._probe_sent_at = None
        self._recovering_since = t
        self._vc_started = t
        if kind == "commit_hb":
            self._last_commit_sent = t - COMMIT_HEARTBEAT
        elif kind == "prepare":
            due(self._prepare_timeout)
        elif kind == "repair":
            due(self._repair_timeout)
        elif kind == "suspect":
            # Fold the two-stage suspicion (silence budget + unanswered
            # probe) into one campaign event.  The +1000 leap above keeps
            # t comfortably past the largest possible budget, so the
            # silence window is always satisfiable without clamping to 0.
            self._last_primary_word = t - (
                PRIMARY_BUDGET_CAP + NORMAL_HEARTBEAT
                + self._heartbeat_jitter + 1
            )
            self._probe_sent_at = t - PROBE_GRACE
        elif kind == "vc_resend":
            due(self._vc_timeout)
        elif kind == "vc_escalate":
            self._vc_started = t - (
                VIEW_CHANGE_ESCALATE << min(self._vc_escalations, 4)
            )
        elif kind == "rsv":
            due(self._rsv_timeout)
        elif kind == "recover_campaign":
            due(self._rsv_timeout)
            self._recovering_since = t - (
                NORMAL_HEARTBEAT + self._heartbeat_jitter
            )
        return self.tick()
