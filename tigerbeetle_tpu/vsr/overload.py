"""Overload control: priority classes, bounded admission, explicit busy.

The reference treats overload as a design constraint discharged statically
(static allocation, bounded queues, client eviction — message_pool.zig,
client_sessions.zig); this port carries the same *bounds* but, before this
module, not the *behavior*: a full pipeline / WAL / send queue silently
dropped the message and the client burned its whole 30 s timeout before
retrying.  This module is the shared vocabulary for the fourth fault domain
(docs/fault_domains.md): overload.

Three transport-agnostic pieces, used by the TCP buses (net/), the
consensus primary (vsr/consensus.py), and the VOPR overload governor
(sim/cluster.py):

- **Priority classes** (``classify``): every wire command maps to one of
  four drain/shed classes.  A client flood must never starve a view change
  or repair — the election traffic that would *end* the overload is
  exactly what naive FIFO queues drop first.

- **AdmissionQueue**: a bounded multi-class queue that drains
  highest-priority-first with per-client round-robin fairness inside the
  client class (one hot client cannot monopolize the pipeline), and sheds
  lowest-priority-first on overflow.  With ``priority=False`` it degrades
  to a plain bounded FIFO with tail drop — the negative control the VOPR
  liveness oracle must demonstrably fail against.

- **busy signaling** helpers: shed a *new client request*, don't drop it —
  reply with a retryable ``Command.busy`` carrying a retry-after tick hint
  (wire.BUSY_*), so the client backs off deliberately instead of timing
  out blindly.

Everything is gated: ``enabled()`` reads ``TB_OVERLOAD`` (the CLI's
``--overload-control`` sets it), and the off path is bit-identical to the
pre-overload behavior — pinned VOPR seeds and the bench differential
replay unchanged.
"""

from __future__ import annotations

import os
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Tuple

from . import wire

# Drain order: lower class number drains first, higher sheds first.
CLASS_VIEW_CHANGE = 0   # elections + liveness probes: ends the overload
CLASS_REPAIR = 1        # repair/sync: heals the cluster under pressure
CLASS_PREPARE = 2       # prepare/commit/reply: the replication stream
CLASS_CLIENT = 3        # client requests: the load being shed

CLASS_NAMES = {
    CLASS_VIEW_CHANGE: "view_change",
    CLASS_REPAIR: "repair",
    CLASS_PREPARE: "prepare",
    CLASS_CLIENT: "client",
}

_COMMAND_CLASS = {
    # View change + the liveness probes that trigger/settle it.  Pings are
    # deliberately here: the primary-suspicion probe and the Marzullo clock
    # both ride ping/pong, and a flood that starves them first fakes a dead
    # primary and then blocks the resulting election.
    wire.Command.start_view_change: CLASS_VIEW_CHANGE,
    wire.Command.do_view_change: CLASS_VIEW_CHANGE,
    wire.Command.start_view: CLASS_VIEW_CHANGE,
    wire.Command.request_start_view: CLASS_VIEW_CHANGE,
    wire.Command.nack_prepare: CLASS_VIEW_CHANGE,
    wire.Command.ping: CLASS_VIEW_CHANGE,
    wire.Command.pong: CLASS_VIEW_CHANGE,
    # Repair + state sync.
    wire.Command.request_headers: CLASS_REPAIR,
    wire.Command.request_prepare: CLASS_REPAIR,
    wire.Command.headers: CLASS_REPAIR,
    wire.Command.request_reply: CLASS_REPAIR,
    wire.Command.request_blocks: CLASS_REPAIR,
    wire.Command.block: CLASS_REPAIR,
    wire.Command.request_sync_checkpoint: CLASS_REPAIR,
    wire.Command.sync_checkpoint: CLASS_REPAIR,
    # The replication stream and its client-visible tail.
    wire.Command.prepare: CLASS_PREPARE,
    wire.Command.prepare_ok: CLASS_PREPARE,
    wire.Command.commit: CLASS_PREPARE,
    wire.Command.reply: CLASS_PREPARE,
    # Client plane.
    wire.Command.request: CLASS_CLIENT,
    wire.Command.ping_client: CLASS_CLIENT,
    wire.Command.pong_client: CLASS_CLIENT,
    wire.Command.eviction: CLASS_CLIENT,
    wire.Command.busy: CLASS_CLIENT,
}


def classify(command: wire.Command) -> int:
    """Drain/shed class for a wire command (unknown commands shed first)."""
    return _COMMAND_CLASS.get(command, CLASS_CLIENT)


def enabled(env: Optional[dict] = None) -> bool:
    """TB_OVERLOAD gate ('' / '0' / 'off' all mean off)."""
    value = (env if env is not None else os.environ).get("TB_OVERLOAD", "")
    return str(value).strip().lower() not in ("", "0", "off", "false")


def busy_message(
    replica_index: int,
    cluster: int,
    view: int,
    request_h,
    reason: int,
    retry_after_ticks: int,
) -> bytes:
    """Encode the explicit shed signal for one client request header."""
    h = wire.new_header(
        wire.Command.busy,
        cluster=cluster,
        view=view,
        request_checksum=wire.header_checksum(request_h),
        client=wire.u128(request_h, "client"),
        request=int(request_h["request"]),
        retry_after_ticks=int(retry_after_ticks),
        reason=int(reason),
    )
    h["replica"] = replica_index
    return wire.encode(h)


class AdmissionQueue:
    """Bounded, class-prioritized ingress queue with per-client fairness.

    ``offer`` either admits an item or returns the items shed to make room
    (possibly the offered item itself); ``pop`` drains one item —
    highest-priority class first; within CLASS_CLIENT, round-robin over
    client ids so one hot client cannot monopolize the drain budget.
    ``priority=False`` turns both knobs off (bounded FIFO, tail drop): the
    VOPR's negative control.

    Counters are plain attributes (the caller mirrors them into the obs
    registry); the queue itself has no metrics dependency so the sim can
    use it without arming the registry.
    """

    def __init__(self, cap: int, priority: bool = True) -> None:
        assert cap > 0
        self.cap = cap
        self.priority = priority
        self.size = 0
        self.admitted = 0
        self.shed = 0
        self.shed_by_class: Dict[int, int] = {c: 0 for c in CLASS_NAMES}
        self.depth_peak = 0
        # priority mode: one deque per non-client class + per-client deques
        # with a round-robin rotation for the client class.
        self._classes: Dict[int, Deque] = {
            CLASS_VIEW_CHANGE: deque(),
            CLASS_REPAIR: deque(),
            CLASS_PREPARE: deque(),
        }
        self._clients: "OrderedDict[int, Deque]" = OrderedDict()
        # FIFO mode: a single deque of (cls, client, item).
        self._fifo: Deque = deque()

    def __len__(self) -> int:
        return self.size

    # -- intake ---------------------------------------------------------------

    def offer(self, cls: int, client: int, item) -> List[Tuple[int, int, object]]:
        """Enqueue; returns the list of (cls, client, item) SHED to honor
        the cap (empty when admitted without eviction).  In priority mode a
        full queue evicts from the lowest-priority tail — so a view-change
        message displaces a queued client request, never the reverse; an
        offered item that is itself the lowest priority is shed directly.
        FIFO mode is plain tail drop."""
        shed: List[Tuple[int, int, object]] = []
        if not self.priority:
            if self.size >= self.cap:
                self._count_shed(cls)
                return [(cls, client, item)]
            self._fifo.append((cls, client, item))
            self.size += 1
            self._note_depth()
            self.admitted += 1
            return shed
        if self.size >= self.cap:
            victim = self._evict_lowest(cls, client)
            if victim is None:
                self._count_shed(cls)
                return [(cls, client, item)]
            shed.append(victim)
        if cls == CLASS_CLIENT:
            self._clients.setdefault(client, deque()).append(item)
        else:
            self._classes[cls].append(item)
        self.size += 1
        self._note_depth()
        self.admitted += 1
        return shed

    def _note_depth(self) -> None:
        if self.size > self.depth_peak:
            self.depth_peak = self.size

    def _count_shed(self, cls: int) -> None:
        self.shed += 1
        self.shed_by_class[cls] = self.shed_by_class.get(cls, 0) + 1

    def _evict_lowest(self, incoming_cls: int, incoming_client: int = 0):
        """Drop one queued item to admit the incoming one; None if nothing
        qualifies.  A higher-priority arrival evicts from the lowest class
        present.  A CLIENT-class arrival may also displace the FATTEST
        client's tail when that backlog exceeds the arriving client's own
        by more than one — max-min fairness at ADMISSION, not just drain:
        a flood that fills the queue cannot lock other clients out, but
        equal-share clients never churn each other out either."""
        for cls in (CLASS_CLIENT, CLASS_PREPARE, CLASS_REPAIR):
            if cls < incoming_cls or (
                cls == incoming_cls and cls != CLASS_CLIENT
            ):
                return None
            if cls == CLASS_CLIENT:
                # Shed from the FATTEST client's tail: the hot client pays
                # for its own flood before anyone else does.
                if not self._clients:
                    continue
                fat = max(
                    self._clients, key=lambda c: len(self._clients[c])
                )
                q = self._clients[fat]
                if incoming_cls == CLASS_CLIENT:
                    mine = len(self._clients.get(incoming_client, ()))
                    if len(q) <= mine + 1:
                        return None  # equal shares: shed the arrival
                item = q.pop()
                if not q:
                    del self._clients[fat]
                self.size -= 1
                self._count_shed(cls)
                return (cls, fat, item)
            q = self._classes[cls]
            if q:
                item = q.pop()
                self.size -= 1
                self._count_shed(cls)
                return (cls, 0, item)
        return None

    # -- drain ----------------------------------------------------------------

    def pop(self) -> Optional[Tuple[int, int, object]]:
        """Dequeue one item, or None when empty."""
        if self.size == 0:
            return None
        self.size -= 1
        if not self.priority:
            return self._fifo.popleft()
        for cls in (CLASS_VIEW_CHANGE, CLASS_REPAIR, CLASS_PREPARE):
            q = self._classes[cls]
            if q:
                return (cls, 0, q.popleft())
        # Client class: round-robin — serve the head of the least-recently-
        # served client's deque, then rotate it to the back.
        client, q = next(iter(self._clients.items()))
        item = q.popleft()
        self._clients.move_to_end(client)
        if not q:
            del self._clients[client]
        return (CLASS_CLIENT, client, item)


# -- cross-batch conflict index (TB_FUSE; docs/commit_pipeline.md) ------------
#
# Index-Based Scheduling for Parallel SMR (PAPERS.md 1911.11329): compute a
# cheap per-batch conflict index AHEAD of dispatch — at the admission seam,
# where batches are still opaque FIFO units — so the dispatch lane can fuse
# runs of provably independent client batches into one wider padded dispatch.
# This is the cross-batch analogue of the TB_WAVES in-batch hazard lanes
# (ops/transfer_full.py): where waves schedule dependent lanes WITHIN one
# batch, the signature below certifies independence BETWEEN batches, over the
# same touched-(debit, credit)-account-slot vocabulary plus the inserted and
# referenced transfer ids.
#
# Safety stance: the signature is a conservative disjointness certificate.
# Two fused fast-path batches can only couple through (a) a duplicate
# transfer id (the second insert's `exists` result depends on the first) or
# (b) a shared account row (balance reads — unobservable on the fast path,
# whose preconditions outlaw limits/balancing/overflow, but kept in the
# signature anyway: over-rejection is always safe, under-rejection never
# happens because equal keys hash equally).  Everything heavier — two-phase,
# balancing, linked chains — is flag-unfusable and the machine's own
# fast-path refusal is the final bit-identical fallback.

# Mixed-hash namespace salts: a transfer id equal to an account id is NOT a
# conflict, so the two key spaces hash into disjoint streams.
_SIG_SALT_ID = 0x9E3779B97F4A7C15
_SIG_SALT_ACCOUNT = 0xC2B2AE3D27D4EB4F
# Flags that make a batch unfusable outright (the fast path refuses them
# anyway — machine._SLOW_TRANSFER_FLAGS — but rejecting here keeps the
# refusal off the dispatch path): two-phase fulfillment, balancing, linked.
_UNFUSABLE_FLAGS = 0x3D  # LINKED | POST | VOID | BALANCING_DEBIT/CREDIT


def fusion_enabled(env: Optional[dict] = None) -> bool:
    """TB_FUSE gate ('' / '0' / 'off' all mean off; the CLI's
    --fuse-batches sets it).  Off is bit-identical: no signature is ever
    computed and every run dispatches exactly as before."""
    value = (env if env is not None else os.environ).get("TB_FUSE", "")
    return str(value).strip().lower() not in ("", "0", "off", "false")


def _mix64(hi, lo, salt: int):
    """Cheap 64-bit key mix (splitmix-style) over (hi, lo) uint64 columns.
    Collisions only ever OVER-reject a fusion candidate."""
    import numpy as np

    with np.errstate(over="ignore"):
        x = (hi.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
             ^ lo.astype(np.uint64)) + np.uint64(salt)
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
    return x


def conflict_signature(batch):
    """Sorted-unique uint64 conflict index of one create_transfers batch:
    mixed hashes of the inserted transfer ids, any referenced pending ids,
    and both touched account sides.  None when the batch carries an
    unfusable flag (two-phase / balancing / linked — in-batch coupling the
    cross-batch certificate cannot speak for).  Computed host-side in a few
    vector ops — cheap enough to ride the admission loop ahead of
    dispatch."""
    import numpy as np

    if len(batch) == 0:
        return np.zeros(0, np.uint64)
    flags = batch["flags"]
    if bool((flags & _UNFUSABLE_FLAGS).any()):
        return None
    keys = [
        _mix64(batch["id_hi"], batch["id_lo"], _SIG_SALT_ID),
        _mix64(batch["debit_account_id_hi"], batch["debit_account_id_lo"],
               _SIG_SALT_ACCOUNT),
        _mix64(batch["credit_account_id_hi"], batch["credit_account_id_lo"],
               _SIG_SALT_ACCOUNT),
    ]
    pend = (batch["pending_id_lo"] != 0) | (batch["pending_id_hi"] != 0)
    if bool(pend.any()):
        keys.append(_mix64(
            batch["pending_id_hi"][pend], batch["pending_id_lo"][pend],
            _SIG_SALT_ID,
        ))
    return np.unique(np.concatenate(keys))


def plan_fusion(batches, timestamps, max_lanes: int):
    """Greedy fusion plan over one run of consecutive create_transfers
    batches: returns ``(segments, conflict_rejects)`` where segments is a
    list of (start, stop) index ranges — each segment's batches fuse into
    ONE padded dispatch — covering the run in order.

    A batch joins the open segment only when ALL of:

    - the fused row count stays within ``max_lanes`` (the batch-lanes pad
      the fast kernel already compiles for — fusing must land on EXISTING
      jit size classes, never mint new ones);
    - its prepare timestamp is CONTIGUOUS with the segment
      (``ts[j] - count[j] == ts[j-1]``): per-lane timestamps derive as
      ``ts - count + lane + 1``, so contiguity makes the fused dispatch's
      lane timestamps bit-identical to the per-batch ones;
    - its conflict signature is disjoint from the segment's running union
      (and neither side is flag-unfusable).

    Only signature overlaps count toward ``conflict_rejects`` — capacity
    and contiguity breaks are scheduling geometry, not conflicts."""
    import numpy as np

    n = len(batches)
    segments: List[Tuple[int, int]] = []
    rejects = 0
    sigs = [conflict_signature(b) for b in batches]
    start = 0
    seg_rows = len(batches[0]) if n else 0
    seg_sig = sigs[0] if n else None
    for j in range(1, n):
        fusable = seg_sig is not None and sigs[j] is not None
        fits = seg_rows + len(batches[j]) <= max_lanes
        contiguous = (
            int(timestamps[j]) - len(batches[j]) == int(timestamps[j - 1])
        )
        disjoint = fusable and (
            np.intersect1d(seg_sig, sigs[j], assume_unique=True).size == 0
        )
        if fusable and fits and contiguous and not disjoint:
            rejects += 1
        if fusable and fits and contiguous and disjoint:
            seg_rows += len(batches[j])
            seg_sig = np.union1d(seg_sig, sigs[j])
            continue
        segments.append((start, j))
        start = j
        seg_rows = len(batches[j])
        seg_sig = sigs[j]
    if n:
        segments.append((start, n))
    return segments, rejects
