"""Host-side codec + tree math for Merkle-anchored incremental state sync.

The reference ships whole checkpoints on state sync (src/vsr/sync.zig —
its grid is content-addressed, so a lagging replica fetches every block
of the target checkpoint); here the commitment trees (ops/merkle.py)
make the transfer *differential*.

The transport story (docs/state_sync.md): a catching-up replica compares
the responder checkpoint's per-pad commitment trees against trees built
over its OWN (stale-checkpoint or live-but-lagging) canonical state and
ships only what diverges — O(diff · log capacity) bytes instead of the
full checkpoint blob.  Everything here is numpy on the CANONICAL flat
array snapshot (vsr/checkpoint.ledger_to_arrays keys), shared by both
sides of the protocol:

- ``build_trees``: heap-layout np commitment trees (ops/merkle.np_tree —
  the same leaves the on-device forest maintains and checkpoints anchor)
  for the three pads, straight from a flat arrays dict.
- ``children`` / ``verify_children``: the batched binary descent — a
  reply carries the 2 children of each requested node, each pair
  verified against the ALREADY-VERIFIED parent value (mix64(l, r) ==
  parent), so the chain of trust grows root-downward and a lying
  responder is caught at the first forged level.
- ``pack_rows`` / ``unpack_rows`` / ``verify_rows``: diverging leaf rows
  as raw per-slot column slices in sorted-key order (zero per-row
  framing overhead); each row re-hashes to its verified leaf value.
- ``pack_history`` / ``unpack_history``: the append-only history tail
  (no tree covers it; the final state checksum does).
- ``arrays_checksum``: AEGIS over EVERY canonical array byte in sorted
  key order — the reconstructed state must hash to the responder's
  advertised value before it may install, making incremental and full
  rejoins byte-identical by construction.

The wire envelope (commands, headers) lives in vsr/wire.py; the protocol
state machine in vsr/consensus.py.
"""

from __future__ import annotations

import io
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ops import merkle as merkle_ops
from .checksum import checksum

# Pad order is wire contract: request/response headers carry the index.
PADS = ("accounts", "transfers", "posted")
HISTORY_PAD = 3

# Scalar (non per-slot) keys per pad, as in vsr/checkpoint.py.
_SCALARS = ("count", "probe_overflow")

U64 = np.uint64


# -- canonical array access --------------------------------------------------


def per_slot_keys(arrays: Dict[str, np.ndarray], pad: str) -> List[str]:
    """Sorted per-slot array keys for ``pad`` — the shared row layout both
    encoder and decoder derive independently (sorted: the order IS the
    wire contract, so it must not depend on dict insertion history)."""
    prefix = f"{pad}/"
    return sorted(
        k for k in arrays
        if k.startswith(prefix) and k.split("/")[-1] not in _SCALARS
    )


def history_keys(arrays: Dict[str, np.ndarray]) -> List[str]:
    return sorted(k for k in arrays if k.startswith("history/cols/"))


def schema(arrays: Dict[str, np.ndarray]) -> dict:
    """Column layout fingerprint: {pad: [[key, dtype_str], ...]} for the
    three pads + history.  A requester whose own schema differs (version
    skew) must fall back to the full-checkpoint path — raw row packing
    is only sound between identical layouts.  JSON-shaped (lists, not
    tuples) so a wire round trip compares equal."""
    out = {}
    for pad in PADS:
        out[pad] = [
            [k, arrays[k].dtype.str] for k in per_slot_keys(arrays, pad)
        ]
    out["history"] = [
        [k, arrays[k].dtype.str] for k in history_keys(arrays)
    ]
    return out


def pad_capacity(arrays: Dict[str, np.ndarray], pad: str) -> int:
    return int(arrays[f"{pad}/key_lo"].shape[0])


def row_bytes(arrays: Dict[str, np.ndarray], pad: str) -> int:
    """Packed bytes per slot for ``pad`` (sum of per-slot itemsizes)."""
    return sum(arrays[k].dtype.itemsize for k in per_slot_keys(arrays, pad))


def history_row_bytes(arrays: Dict[str, np.ndarray]) -> int:
    return sum(arrays[k].dtype.itemsize for k in history_keys(arrays)) or 1


# -- commitment trees over flat arrays ---------------------------------------


def pad_leaves(arrays: Dict[str, np.ndarray], pad: str) -> np.ndarray:
    cols = {
        name: arrays[f"{pad}/cols/{name}"]
        for name in merkle_ops._LEAF_COLS[pad]
    }
    return merkle_ops.np_leaves(
        arrays[f"{pad}/key_lo"], arrays[f"{pad}/key_hi"], cols, pad
    )


def build_trees(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Heap-layout np tree per pad (root at [1], leaves at [cap + slot])."""
    return {pad: merkle_ops.np_tree(pad_leaves(arrays, pad)) for pad in PADS}


def np_digest(arrays: Dict[str, np.ndarray]) -> int:
    """The convergence-oracle fold (ops/state_machine.ledger_digest twin):
    wrap-sum of the accounts leaves — bit-identical because the merkle
    accounts leaves ARE the scrub fold's per-slot addends."""
    with np.errstate(over="ignore"):
        return int(pad_leaves(arrays, "accounts").sum(dtype=U64))


def children(tree: np.ndarray, nodes: np.ndarray) -> np.ndarray:
    """u64[2n]: the (left, right) child values of each heap node —
    interleaved pairs, the descent reply payload."""
    nodes = nodes.astype(np.int64)
    out = np.empty(2 * len(nodes), U64)
    out[0::2] = tree[2 * nodes]
    out[1::2] = tree[2 * nodes + 1]
    return out


def verify_children(
    values: np.ndarray, nodes: np.ndarray, want: Dict[int, int]
) -> bool:
    """Each received (l, r) pair must combine to the already-verified
    parent value: mix64(l, r) == want[node]."""
    if len(values) != 2 * len(nodes):
        return False
    left = values[0::2]
    right = values[1::2]
    combined = merkle_ops.mix64_np(
        left.astype(U64), right.astype(U64)
    )
    return all(
        int(combined[i]) == want.get(int(n), -1)
        for i, n in enumerate(nodes)
    )


def leaf_level(cap: int) -> int:
    """Heap index of the first leaf (== capacity)."""
    return cap


# -- row payloads ------------------------------------------------------------


def pack_rows(
    arrays: Dict[str, np.ndarray], pad: str, slots: np.ndarray
) -> bytes:
    """Raw per-slot slices in sorted-key order — no per-row framing; the
    receiver re-derives the layout from its own (schema-checked) arrays."""
    slots = slots.astype(np.int64)
    return b"".join(
        np.ascontiguousarray(arrays[k][slots]).tobytes()
        for k in per_slot_keys(arrays, pad)
    )


def unpack_rows(
    arrays: Dict[str, np.ndarray], pad: str, slots: np.ndarray, body: bytes
) -> Optional[Dict[str, np.ndarray]]:
    """Split a pack_rows payload back into {key: values[len(slots)]},
    using the RECEIVER's arrays only for layout (shapes/dtypes).  None on
    a length mismatch (truncated/garbage payload)."""
    n = len(slots)
    out: Dict[str, np.ndarray] = {}
    off = 0
    for k in per_slot_keys(arrays, pad):
        dt = arrays[k].dtype
        size = dt.itemsize * n
        if off + size > len(body):
            return None
        out[k] = np.frombuffer(body[off:off + size], dtype=dt).copy()
        off += size
    if off != len(body):
        return None
    return out


def rows_leaves(rows: Dict[str, np.ndarray], pad: str) -> np.ndarray:
    """Leaf hashes of unpacked rows (verification: each received row must
    hash to the already-verified leaf value for its slot)."""
    cols = {
        name: rows[f"{pad}/cols/{name}"]
        for name in merkle_ops._LEAF_COLS[pad]
    }
    return merkle_ops.np_leaves(
        rows[f"{pad}/key_lo"], rows[f"{pad}/key_hi"], cols, pad
    )


def verify_rows(
    rows: Dict[str, np.ndarray], pad: str, slots: np.ndarray,
    want: Dict[int, int], cap: int,
) -> bool:
    leaves = rows_leaves(rows, pad)
    return all(
        int(leaves[i]) == want.get(cap + int(s), -1)
        for i, s in enumerate(slots)
    )


# -- history tail ------------------------------------------------------------


def pack_history(
    arrays: Dict[str, np.ndarray], start: int, count: int
) -> bytes:
    return b"".join(
        np.ascontiguousarray(arrays[k][start:start + count]).tobytes()
        for k in history_keys(arrays)
    )


def unpack_history(
    arrays: Dict[str, np.ndarray], count: int, body: bytes
) -> Optional[Dict[str, np.ndarray]]:
    out: Dict[str, np.ndarray] = {}
    off = 0
    for k in history_keys(arrays):
        dt = arrays[k].dtype
        size = dt.itemsize * count
        if off + size > len(body):
            return None
        out[k] = np.frombuffer(body[off:off + size], dtype=dt).copy()
        off += size
    if off != len(body):
        return None
    return out


# -- whole-state byte identity -----------------------------------------------


def arrays_checksum(arrays: Dict[str, np.ndarray]) -> int:
    """AEGIS over every canonical array byte (names + shapes + content,
    sorted key order).  The install gate: a reconstructed state must hash
    to the responder's advertised value, which makes an incremental
    rejoin byte-identical to a full-transfer rejoin BY CONSTRUCTION —
    any divergence the tree's covered columns cannot see (or any bug in
    the descent) routes to the full-checkpoint fallback instead of
    installing."""
    h = []
    for k in sorted(arrays):
        if k == "meta":
            continue
        arr = np.ascontiguousarray(arrays[k])
        h.append(k.encode())
        h.append(str(arr.shape).encode())
        h.append(arr.tobytes())
    return checksum(b"\x00".join(h))


# -- the sync_roots body pack ------------------------------------------------

# Top-frontier depth: the sync_roots body carries each pad's nodes at
# this relative depth below the root (2^depth values, clamped to the
# tree's own height) so a requester can skip entire clean subtrees
# before the first descent round trip.
TOP_DEPTH = 6


def frontier(tree: np.ndarray, depth: int) -> np.ndarray:
    """The 2^depth heap values at ``depth`` levels below the root."""
    lo = 1 << depth
    return tree[lo: 2 * lo].copy()


def fold_frontier(values: np.ndarray) -> int:
    """Fold a frontier level back up to the root value."""
    x = values.astype(U64)
    while len(x) > 1:
        x = merkle_ops.mix64_np(x[0::2], x[1::2])
    return int(x[0])


def top_depth(cap: int) -> int:
    return min(TOP_DEPTH, max(0, cap.bit_length() - 1))


def pack_roots(
    arrays: Dict[str, np.ndarray],
    trees: Dict[str, np.ndarray],
    meta: dict,
) -> bytes:
    """The sync_roots reply body: per-pad capacity/scalars/root/top
    frontier, history shape, schema, and the checkpoint meta JSON."""
    payload: Dict[str, np.ndarray] = {}
    for pad in PADS:
        cap = pad_capacity(arrays, pad)
        payload[f"{pad}/capacity"] = U64(cap)
        payload[f"{pad}/count"] = np.asarray(arrays[f"{pad}/count"])
        payload[f"{pad}/probe_overflow"] = np.asarray(
            arrays[f"{pad}/probe_overflow"]
        )
        payload[f"{pad}/root"] = np.asarray(trees[pad][1])
        payload[f"{pad}/top"] = frontier(trees[pad], top_depth(cap))
    hk = history_keys(arrays)
    payload["history/capacity"] = U64(
        arrays[hk[0]].shape[0] if hk else 0
    )
    payload["history/count"] = np.asarray(arrays["history/count"])
    payload["schema"] = np.frombuffer(
        json.dumps(schema(arrays), sort_keys=True).encode(), dtype=np.uint8
    ).copy()
    payload["meta"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    ).copy()
    buf = io.BytesIO()
    np.savez(buf, **payload)
    # zlib: the schema/meta JSON and the npz zero padding dominate the
    # raw size; compressed, the summary fits one message body even at
    # the 8 KiB test_min budget (the responder still refuses to answer
    # if a pathological session table pushes it past the budget — the
    # requester then degrades to the full-checkpoint path).
    import zlib

    return zlib.compress(buf.getvalue(), 6)


def unpack_roots(body: bytes) -> Optional[dict]:
    """Parse a sync_roots body; verifies each pad's top frontier folds to
    its stated root (the first link of the chain of trust).  None on any
    malformed/forged payload."""
    import zlib

    try:
        z = np.load(io.BytesIO(zlib.decompress(body)))
        out: dict = {"pads": {}}
        for pad in PADS:
            cap = int(z[f"{pad}/capacity"])
            top = np.asarray(z[f"{pad}/top"], dtype=U64)
            root = int(z[f"{pad}/root"])
            if cap <= 0 or cap & (cap - 1):
                return None
            if len(top) != 1 << top_depth(cap):
                return None
            if fold_frontier(top) != root:
                return None
            out["pads"][pad] = {
                "capacity": cap,
                "count": np.asarray(z[f"{pad}/count"]),
                "probe_overflow": np.asarray(z[f"{pad}/probe_overflow"]),
                "root": root,
                "top": top,
            }
        out["history_capacity"] = int(z["history/capacity"])
        out["history_count"] = int(z["history/count"])
        # Bound responder-supplied shapes BEFORE anything allocates or
        # slices from them (a forged summary must be rejected here, not
        # crash the requester past the verification chain): history must
        # fit its capacity and the capacity must be allocatable.
        if not (
            0 <= out["history_count"] <= out["history_capacity"] <= 1 << 26
        ):
            return None
        for pad in PADS:
            if out["pads"][pad]["capacity"] > 1 << 28:
                return None
        out["schema"] = json.loads(bytes(z["schema"]).decode())
        out["meta"] = json.loads(bytes(z["meta"]).decode())
        return out
    except (ValueError, KeyError, OSError, json.JSONDecodeError,
            zlib.error):
        return None


# -- responder-side pack -----------------------------------------------------


class SyncPack:
    """Everything a responder needs to serve one checkpoint's incremental
    sync, built once per checkpoint op and cached (vsr/consensus.py):
    the canonical flat arrays, their trees, and the install gates."""

    def __init__(self, op: int, arrays: Dict[str, np.ndarray], meta: dict):
        self.op = op
        self.arrays = {k: v for k, v in arrays.items() if k != "meta"}
        self.meta = meta or {}
        self.trees = build_trees(self.arrays)
        self.digest = np_digest(self.arrays)
        self.state_checksum = arrays_checksum(self.arrays)
        self.roots_body = pack_roots(self.arrays, self.trees, self.meta)


# -- online reshard migration (docs/reconfiguration.md) -----------------------
#
# An N -> 2N shard split reuses this codec verbatim: the split adds one
# owner bit, so a canonical slot either stays on its shard or moves to
# shard s+N.  Only the MOVED subset crosses the wire; every chunk is a
# pack_rows payload re-hashed against the already-built source tree
# (verify_rows), and the staged full state must pass arrays_checksum
# before the new layout may take over.  The helpers below carve the
# moved subset into bounded chunks and audit each one — the machine-side
# engine (machine.TpuStateMachine.reshard_*) drives them between
# commits.


def chunk_slots(slots: np.ndarray, chunk: int) -> List[np.ndarray]:
    """Split a slot vector into <= chunk-sized contiguous pieces (wire
    bound: one migration message per piece)."""
    slots = np.asarray(slots, dtype=np.int64)
    if len(slots) == 0:
        return []
    return [slots[i:i + chunk] for i in range(0, len(slots), max(1, chunk))]


def ship_chunk(
    arrays: Dict[str, np.ndarray], tree: np.ndarray, pad: str,
    slots: np.ndarray, corrupt: bool = False,
) -> bytes:
    """Responder side of one migration chunk: a pack_rows payload for
    ``slots``.  ``corrupt`` flips one byte of the key_lo segment (fault
    injection: a lying or bit-flipped migration source) — keys are
    leaf-covered for every pad, so the receiver's verify_chunk must
    catch it, or, with verification disabled, install the divergence the
    auditor then catches (the scrub-off discipline)."""
    body = bytearray(pack_rows(arrays, pad, slots))
    if corrupt and body:
        off = 0
        for k in per_slot_keys(arrays, pad):
            size = arrays[k].dtype.itemsize * len(slots)
            if k.endswith("/key_lo"):
                body[off + size // 2] ^= 0x40
                break
            off += size
        else:  # pragma: no cover - every pad has a key_lo
            body[len(body) // 2] ^= 0x40
    return bytes(body)


def verify_chunk(
    arrays: Dict[str, np.ndarray], tree: np.ndarray, pad: str,
    slots: np.ndarray, body: bytes,
) -> Optional[Dict[str, np.ndarray]]:
    """Receiver side: unpack against the receiver's own schema and
    re-hash every row against the verified source tree leaf for its
    slot.  None => reject the chunk (retry, then abandon the split)."""
    rows = unpack_rows(arrays, pad, slots, body)
    if rows is None:
        return None
    cap = pad_capacity(arrays, pad)
    want = {cap + int(s): int(tree[cap + int(s)]) for s in slots}
    if not verify_rows(rows, pad, slots, want, cap):
        return None
    return rows
