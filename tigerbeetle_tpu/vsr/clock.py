"""Fault-tolerant cluster clock: Marzullo agreement over ping/pong offsets.

Mirrors the reference clock (src/vsr/clock.zig:15+, src/vsr/marzullo.zig):
each replica samples every peer's wall clock via ping/pong round trips; a
sample bounds the peer's offset relative to our monotonic clock by
``[offset - rtt/2, offset + rtt/2]``.  Marzullo's algorithm intersects the
interval sets to find the smallest interval agreed on by a majority of
remotes; the midpoint corrects our wall clock.  The primary refuses to assign
timestamps until its clock is synchronized with a replication quorum
(replica.zig:1322-1325), bounding cross-view timestamp skew.

Epochs: samples age; after ``epoch_max_ns`` the window is re-armed from fresh
samples so a remote's drift cannot accumulate (clock.zig epoch rotation).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Interval:
    """One remote's clock-offset bound (marzullo.zig Tuple)."""

    lower: int  # ns
    upper: int  # ns

    def __post_init__(self):
        assert self.lower <= self.upper


def marzullo_smallest_interval(intervals: List[Interval]) -> Tuple[Interval, int]:
    """Find the smallest interval consistent with the largest number of
    sources (marzullo.zig:1+ ``smallest_interval``).

    Returns (interval, sources_true): the best interval and how many source
    intervals contain it.  Empty input yields a zero interval with 0 sources.
    """
    if not intervals:
        return Interval(0, 0), 0
    # Sweep over interval endpoints: +1 entering an interval, -1 leaving.
    edges: List[Tuple[int, int]] = []
    for iv in intervals:
        edges.append((iv.lower, -1))  # -1 sorts "start" before "end" at ties
        edges.append((iv.upper, +1))
    edges.sort()
    best = 0
    count = 0
    best_lower = edges[0][0]
    best_upper = edges[0][0]
    lower = 0
    for offset, kind in edges:
        if kind == -1:
            count += 1
            lower = offset
        else:
            # Closing an interval: [lower, offset] had `count` sources.
            if count > best:
                best = count
                best_lower, best_upper = lower, offset
            count -= 1
    return Interval(best_lower, best_upper), best


class Clock:
    """Per-replica clock state (clock.zig ClockType).

    ``monotonic()`` and ``realtime()`` come from the injected time source so
    the simulator can drive virtual time deterministically.
    """

    def __init__(
        self,
        replica_count: int,
        replica: int,
        monotonic,
        realtime,
        epoch_max_ns: int = 60 * 1_000_000_000,
        offset_tolerance_ns: int = 10 * 1_000_000_000,
    ) -> None:
        self.replica_count = replica_count
        self.replica = replica
        self.monotonic = monotonic
        self.realtime = realtime
        self.epoch_max_ns = epoch_max_ns
        self.offset_tolerance_ns = offset_tolerance_ns
        # Latest sample per remote replica: (monotonic_at_sample, Interval).
        self.samples: Dict[int, Tuple[int, Interval]] = {}
        self.epoch_start_monotonic = monotonic()
        # Learned offset: realtime ≈ monotonic + offset.
        self.offset_ns: Optional[int] = None
        self._synchronized = False

    # -- sampling (ping/pong round trips) ------------------------------------

    def ping_timestamp(self) -> int:
        """Monotonic timestamp to stamp into an outgoing ping."""
        return self.monotonic()

    def learn(self, remote: int, ping_monotonic: int, remote_realtime: int) -> None:
        """Learn from a pong: we sent ping at ``ping_monotonic`` (our
        monotonic), remote replied with its wall clock ``remote_realtime``
        (clock.zig learn: one sample per round trip, rtt bounds the error)."""
        if remote == self.replica:
            return
        m_now = self.monotonic()
        rtt = m_now - ping_monotonic
        if rtt < 0:
            return  # time source misbehaved; drop sample
        # Remote's wall clock was sampled somewhere inside the round trip;
        # express as bounds on (their_realtime - our_monotonic).
        mid = remote_realtime - (ping_monotonic + rtt // 2)
        self.samples[remote] = (
            m_now, Interval(mid - rtt // 2 - 1, mid + rtt // 2 + 1)
        )
        self._synchronize()

    def _window_intervals(self) -> List[Interval]:
        cutoff = self.monotonic() - self.epoch_max_ns
        return [iv for (m, iv) in self.samples.values() if m >= cutoff]

    def _synchronize(self) -> None:
        """Re-run Marzullo over the sample window (clock.zig synchronize)."""
        intervals = self._window_intervals()
        # Our own clock is a source too — trusted only to within the
        # cluster's offset tolerance (a zero-width own interval would make
        # a 2-replica cluster unsynchronizable whenever wall skew exceeds
        # the RTT: own ∩ peer = ∅ and quorum(2) = 2 can never be met).
        own = self.realtime() - self.monotonic()
        own_half = self.offset_tolerance_ns // 2
        intervals.append(Interval(own - own_half, own + own_half))
        interval, sources = marzullo_smallest_interval(intervals)
        # Quorum: a majority of the cluster must agree (clock.zig
        # window_tuples quorum = replica_count majority).
        quorum = self.replica_count // 2 + 1
        if sources >= quorum:
            self.offset_ns = (interval.lower + interval.upper) // 2
            self._synchronized = (
                interval.upper - interval.lower <= self.offset_tolerance_ns
            )
        else:
            self._synchronized = self.replica_count == 1

    @property
    def realtime_synchronized(self) -> Optional[int]:
        """Cluster-agreed wall time in ns, or None if not synchronized —
        the primary drops requests in that state (replica.zig:1322-1325)."""
        if self.replica_count == 1:
            return self.realtime()
        if not self._synchronized or self.offset_ns is None:
            return None
        return self.monotonic() + self.offset_ns

    def tick(self) -> None:
        """Expire stale epochs (clock.zig tick)."""
        m = self.monotonic()
        if m - self.epoch_start_monotonic > self.epoch_max_ns:
            self.epoch_start_monotonic = m
            stale = [
                r for r, (sampled, _) in self.samples.items()
                if sampled < m - self.epoch_max_ns
            ]
            for r in stale:
                del self.samples[r]
            self._synchronize()
