"""Adaptive timeouts: RTT-tracked base + exponential backoff + jitter.

The reference's ``Timeout`` (vsr.zig:543-712) backs off exponentially each
time it fires without progress and adds seeded jitter so replicas don't
synchronize their retries; RTT-sensitive timeouts (prepare resend) scale
with the measured round trip (vsr.zig:593-634).  Round 1 used fixed tick
cadences (VERDICT round-1 missing #9) — under loss or latency variance
that either hammers the network or waits far too long.

Ticks are the consensus tick (~10 ms wall / 1 simulated step).
"""

from __future__ import annotations

import random


class Rtt:
    """Exponentially-weighted RTT estimate in ticks (min 1)."""

    def __init__(self, initial_ticks: float = 3.0) -> None:
        self.estimate = float(initial_ticks)

    def sample(self, ticks: float) -> None:
        # EWMA alpha 1/8 (the classic RTO smoothing constant).
        self.estimate += (max(ticks, 0.0) - self.estimate) / 8.0

    @property
    def ticks(self) -> float:
        return max(1.0, self.estimate)


class Timeout:
    """One retry timeout: fires when ``elapsed >= current interval``; each
    backoff() doubles the interval (capped) and re-jitters; reset() returns
    to the base after progress."""

    def __init__(
        self,
        prng: random.Random,
        base_ticks: int,
        max_ticks: int,
        rtt: Rtt | None = None,
        rtt_multiple: float = 2.0,
    ) -> None:
        self.prng = prng
        self.base = base_ticks
        self.max = max_ticks
        self.rtt = rtt
        self.rtt_multiple = rtt_multiple
        self.attempts = 0
        self._last = 0
        self._interval = self._compute()

    def _compute(self) -> int:
        base = float(self.base)
        if self.rtt is not None:
            base = max(base, self.rtt.ticks * self.rtt_multiple)
        # ``max`` is a HARD ceiling — an outlier RTT sample (e.g. a pong
        # crossing a healed partition) must not push intervals past it.
        base = min(base, float(self.max))
        # Exponential backoff capped, then full jitter on the backoff part
        # (vsr.zig exponential_backoff_with_jitter).
        backoff = min(float(self.max), base * (2 ** min(self.attempts, 6)))
        jitter = self.prng.uniform(0, max(0.0, backoff - base))
        return max(1, int(base + jitter))

    def reset(self, now: int) -> None:
        """Progress happened: back to the base interval."""
        self.attempts = 0
        self._last = now
        self._interval = self._compute()

    def fired(self, now: int) -> bool:
        """True when due; arms the next (backed-off) interval."""
        if now - self._last < self._interval:
            return False
        self.attempts += 1
        self._last = now
        self._interval = self._compute()
        return True

    def next_backoff(self) -> int:
        """Clockless retry helper: record one more failed attempt and
        return the next jittered backed-off interval in ticks.  For retry
        loops that sleep rather than poll a tick clock (client reconnect,
        the machine's device re-dispatch); reset() returns to the base
        after progress, exactly as with fired()."""
        self.attempts += 1
        self._interval = self._compute()
        return self._interval
