"""Zoned data-file storage with positional I/O and explicit durability.

Mirrors the reference's storage discipline (src/storage.zig:14+, zone layout
src/vsr.zig:67-152): one data file per replica, divided into fixed zones —
superblock copies, WAL header ring, WAL prepare ring, client replies.  All
writes are positional (pwrite) with explicit fsync barriers; all formats carry
AEGIS checksums so recovery never trusts unverified bytes.

TPU-native divergence: the reference's grid zone (LSM block storage) is
replaced by checkpoint snapshot files of the device-resident ledger
(checkpoint.py) — the HBM table *is* the working set, so durability is
WAL + periodic snapshot instead of an on-disk LSM (SURVEY §2.4 TPU mapping).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from ..config import ClusterConfig

SUPERBLOCK_COPIES = 4
SUPERBLOCK_COPY_SIZE = 4096


@dataclasses.dataclass(frozen=True)
class Layout:
    """Zone offsets/sizes derived from the cluster config (vsr.zig:67-152)."""

    config: ClusterConfig

    @property
    def superblock_offset(self) -> int:
        return 0

    @property
    def superblock_size(self) -> int:
        return SUPERBLOCK_COPIES * SUPERBLOCK_COPY_SIZE

    @property
    def wal_headers_offset(self) -> int:
        return self.superblock_offset + self.superblock_size

    @property
    def wal_headers_size(self) -> int:
        return self.config.journal_slot_count * self.config.header_size

    @property
    def wal_prepares_offset(self) -> int:
        return self.wal_headers_offset + self.wal_headers_size

    @property
    def wal_prepares_size(self) -> int:
        return self.config.journal_slot_count * self.config.message_size_max

    @property
    def client_replies_offset(self) -> int:
        return self.wal_prepares_offset + self.wal_prepares_size

    @property
    def client_replies_size(self) -> int:
        return self.config.clients_max * self.config.message_size_max

    @property
    def total_size(self) -> int:
        return self.client_replies_offset + self.client_replies_size


class Storage:
    """Positional I/O over the zoned data file."""

    def __init__(self, path: str, config: Optional[ClusterConfig] = None) -> None:
        self.path = path
        self.config = config or ClusterConfig()
        self.layout = Layout(self.config)
        self.fd = os.open(path, os.O_RDWR)

    @classmethod
    def format(cls, path: str, config: Optional[ClusterConfig] = None) -> "Storage":
        """Create + size the data file (sparse; zeroes read back from holes)."""
        config = config or ClusterConfig()
        layout = Layout(config)
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o644)
        try:
            os.ftruncate(fd, layout.total_size)
            os.fsync(fd)
        finally:
            os.close(fd)
        # fsync the directory so the file's existence is durable.
        dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        return cls(path, config)

    def read(self, offset: int, size: int) -> bytes:
        assert offset + size <= self.layout.total_size
        data = os.pread(self.fd, size, offset)
        if len(data) < size:  # reading a hole at EOF boundary
            data = data + b"\x00" * (size - len(data))
        return data

    def write(self, offset: int, data: bytes) -> None:
        assert offset + len(data) <= self.layout.total_size
        written = os.pwrite(self.fd, data, offset)
        assert written == len(data)

    def sync(self) -> None:
        os.fsync(self.fd)

    def close(self) -> None:
        if self.fd >= 0:
            os.close(self.fd)
            self.fd = -1

    def __enter__(self) -> "Storage":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
