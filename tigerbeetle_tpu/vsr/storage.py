"""Zoned data-file storage with positional I/O and explicit durability.

Mirrors the reference's storage discipline (src/storage.zig:14+, zone layout
src/vsr.zig:67-152): one data file per replica, divided into fixed zones —
superblock copies, WAL header ring, WAL prepare ring, client replies.  All
writes are positional (pwrite) with explicit fsync barriers; all formats carry
AEGIS checksums so recovery never trusts unverified bytes.

TPU-native divergence: the reference's grid zone (LSM block storage) is
replaced by checkpoint snapshot files of the device-resident ledger
(checkpoint.py) — the HBM table *is* the working set, so durability is
WAL + periodic snapshot instead of an on-disk LSM (SURVEY §2.4 TPU mapping).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from ..config import ClusterConfig

SUPERBLOCK_COPIES = 4
SUPERBLOCK_COPY_SIZE = 4096


def _align_down(x: int, a: int) -> int:
    return x - (x % a)


def _align_up(x: int, a: int) -> int:
    return x + (-x % a)


@dataclasses.dataclass(frozen=True)
class Layout:
    """Zone offsets/sizes derived from the cluster config (vsr.zig:67-152)."""

    config: ClusterConfig

    @property
    def superblock_offset(self) -> int:
        return 0

    @property
    def superblock_size(self) -> int:
        return SUPERBLOCK_COPIES * SUPERBLOCK_COPY_SIZE

    @property
    def wal_headers_offset(self) -> int:
        return self.superblock_offset + self.superblock_size

    @property
    def wal_headers_size(self) -> int:
        return self.config.journal_slot_count * self.config.header_size

    @property
    def wal_prepares_offset(self) -> int:
        return self.wal_headers_offset + self.wal_headers_size

    @property
    def wal_prepares_size(self) -> int:
        return self.config.journal_slot_count * self.config.message_size_max

    @property
    def client_replies_offset(self) -> int:
        return self.wal_prepares_offset + self.wal_prepares_size

    @property
    def client_replies_size(self) -> int:
        return self.config.clients_max * self.config.message_size_max

    @property
    def total_size(self) -> int:
        return self.client_replies_offset + self.client_replies_size


SECTOR = 4096  # direct-IO alignment unit (config.zig sector_size)


class Storage:
    """Positional I/O over the zoned data file.

    ``direct_io`` opens the file O_DIRECT (storage.zig:14+ requires it in
    production: page-cache writeback lies about durability and masks latent
    sector errors).  O_DIRECT demands sector-aligned offsets, lengths, AND
    user buffers; Python bytes are unaligned, so all direct transfers stage
    through a page-aligned mmap buffer, and sub-sector writes (the 256-byte
    WAL header slots) read-modify-write their containing sectors — the
    journal's dual rings + checksums already treat a torn sector as a torn
    write.  Filesystems without O_DIRECT (tmpfs) fall back to buffered+fsync
    unless ``direct_io_required``."""

    def __init__(
        self,
        path: str,
        config: Optional[ClusterConfig] = None,
        direct_io: bool = False,
        direct_io_required: bool = False,
    ) -> None:
        self.path = path
        self.config = config or ClusterConfig()
        self.layout = Layout(self.config)
        self.direct_io = False
        if direct_io and hasattr(os, "O_DIRECT"):
            try:
                self.fd = os.open(path, os.O_RDWR | os.O_DIRECT)
                self.direct_io = True
            except OSError:
                if direct_io_required:
                    raise
                self.fd = os.open(path, os.O_RDWR)
        else:
            if direct_io and direct_io_required:
                raise OSError("O_DIRECT unsupported on this platform")
            self.fd = os.open(path, os.O_RDWR)
        if self.direct_io:
            import threading

            # Page-aligned staging areas, large enough for the biggest
            # single transfer (a full prepare slot) plus edge sectors —
            # PER THREAD: the background checkpoint thread
            # (replica.async_checkpoint) writes the superblock while the
            # serving thread journals prepares; a shared buffer would
            # interleave their bytes on disk.
            self._buf_size = (
                _align_up(self.config.message_size_max, SECTOR) + 2 * SECTOR
            )
            self._buf_local = threading.local()

    def _staging(self):
        import mmap

        buf = getattr(self._buf_local, "buf", None)
        if buf is None:
            buf = mmap.mmap(-1, self._buf_size)
            self._buf_local.buf = buf
        return buf

    @classmethod
    def format(cls, path: str, config: Optional[ClusterConfig] = None) -> "Storage":
        """Create + size the data file (sparse; zeroes read back from holes)."""
        config = config or ClusterConfig()
        layout = Layout(config)
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o644)
        try:
            os.ftruncate(fd, layout.total_size)
            # Preallocate extents now (storage.zig pre-sizes the same way):
            # lazy allocation would otherwise happen on first write of each
            # WAL slot, in the serving hot path, where it serializes against
            # the concurrent group fsync on the filesystem journal (measured
            # 11 ms/MB vs 0.4 ms/MB on ext4).  Holes also stop reading back
            # as holes, so `sync` needs no metadata commit (see sync()).
            try:
                os.posix_fallocate(fd, 0, layout.total_size)
            except OSError:
                pass  # fs without fallocate (tmpfs): lazy allocation
            # (Deliberately NOT zero-writing the WAL zones, unlike the
            # reference's format: on burst-credit cloud block devices the
            # ~1 GiB write drains the device's burst bucket — measured 128 s
            # and degraded IO for minutes after — which costs far more than
            # the one-time unwritten-extent conversion on each slot's first
            # write.)
            os.fsync(fd)
        finally:
            os.close(fd)
        # fsync the directory so the file's existence is durable.
        dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        return cls(path, config)

    def read(self, offset: int, size: int) -> bytes:
        assert offset + size <= self.layout.total_size
        if self.direct_io:
            return self._read_direct(offset, size)
        data = os.pread(self.fd, size, offset)
        if len(data) < size:  # reading a hole at EOF boundary
            data = data + b"\x00" * (size - len(data))
        return data

    def write(self, offset: int, data: bytes) -> None:
        assert offset + len(data) <= self.layout.total_size
        if self.direct_io:
            self._write_direct(offset, data)
            return
        written = os.pwrite(self.fd, data, offset)
        assert written == len(data)

    # -- direct-IO staging ----------------------------------------------------

    def _read_direct(self, offset: int, size: int) -> bytes:
        step = self._buf_size - 2 * SECTOR
        out = bytearray()
        while size > 0:
            n = min(size, step)
            out += self._read_direct_one(offset, n)
            offset += n
            size -= n
        return bytes(out)

    def _read_sector(self, view, file_offset: int) -> None:
        """Read one sector into ``view`` (len SECTOR), zero-filling holes."""
        got = os.preadv(self.fd, [view], file_offset)
        if got < SECTOR:
            view[got:SECTOR] = b"\x00" * (SECTOR - got)

    def _read_direct_one(self, offset: int, size: int) -> bytes:
        lo = _align_down(offset, SECTOR)
        hi = _align_up(offset + size, SECTOR)
        span = hi - lo
        view = memoryview(self._staging())[:span]
        got = os.preadv(self.fd, [view], lo)
        if got < span:  # hole at EOF boundary
            view[got:span] = b"\x00" * (span - got)
        return bytes(view[offset - lo : offset - lo + size])

    def _write_direct(self, offset: int, data: bytes) -> None:
        step = self._buf_size - 2 * SECTOR
        mv = memoryview(data)
        while len(mv) > 0:
            n = min(len(mv), step)
            self._write_direct_one(offset, mv[:n])
            offset += n
            mv = mv[n:]

    def _write_direct_one(self, offset: int, data) -> None:
        lo = _align_down(offset, SECTOR)
        hi = _align_up(offset + len(data), SECTOR)
        span = hi - lo
        view = memoryview(self._staging())[:span]
        # Read-modify-write ONLY the partially-overwritten edge sectors
        # (a WAL prepare is sector-aligned at its start with an unaligned
        # tail — reading the whole span back would double the device IO on
        # the hot path).  The checksummed formats treat a torn sector
        # exactly like a torn write.
        if offset != lo:
            self._read_sector(view[:SECTOR], lo)
        end = offset + len(data)
        if end != hi and (hi - SECTOR) != lo:
            self._read_sector(view[span - SECTOR : span], hi - SECTOR)
        elif end != hi and offset == lo:
            # Single-sector span with an unaligned tail only.
            self._read_sector(view[:SECTOR], lo)
        view[offset - lo : offset - lo + len(data)] = data
        written = os.pwritev(self.fd, [view], lo)
        assert written == span

    def sync(self) -> None:
        # fdatasync: data + the metadata needed to read it back.  The file's
        # size and extents are fixed at format() (ftruncate + fallocate), so
        # a full fsync would only add filesystem-journal commits for mtime —
        # pure contention on the serving path.
        os.fdatasync(self.fd)

    def close(self) -> None:
        if self.fd >= 0:
            os.close(self.fd)
            self.fd = -1
        if self.direct_io:
            buf = getattr(self._buf_local, "buf", None)
            if buf is not None:
                buf.close()
                self._buf_local.buf = None
            # Other threads' staging mmaps are reclaimed with the thread.

    def __enter__(self) -> "Storage":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
