"""AOF: append-only file of committed prepares (src/aof.zig).

The reference optionally appends every prepare to a flat file with a
synchronous write before executing it (replica.zig:3741-3746) — an
independent, portable audit log that can rebuild or cross-check the cluster
(e.g. migrate to different hardware, or diff two clusters' histories).

Entries are exact wire-format prepare messages (self-framing: the 256-byte
header carries the size and both checksums), so the wire codec is the AOF
codec and `iterate` can validate every entry standalone.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple

import numpy as np

from . import wire


class AOF:
    def __init__(self, path: str, fsync_each: bool = True) -> None:
        self.path = path
        self.fsync_each = fsync_each
        self.fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
        # Repair a torn tail from a prior crash: truncate to the last valid
        # entry boundary so appended entries stay frameable.
        valid = valid_length(path)
        if valid < os.fstat(self.fd).st_size:
            os.ftruncate(self.fd, valid)
            os.fsync(self.fd)

    def append(self, message: bytes) -> None:
        """Append one prepare (wire bytes), durably (aof.zig O_SYNC)."""
        written = os.write(self.fd, message)
        assert written == len(message)
        if self.fsync_each:
            os.fsync(self.fd)

    def close(self) -> None:
        if self.fd >= 0:
            os.close(self.fd)
            self.fd = -1


def valid_length(path: str) -> int:
    """Byte length of the valid entry prefix (the tear point, if any)."""
    with open(path, "rb") as f:
        blob = f.read()
    offset = 0
    while offset + wire.HEADER_SIZE <= len(blob):
        try:
            h, command = wire.decode_header(
                blob[offset : offset + wire.HEADER_SIZE]
            )
        except ValueError:
            break
        if command != wire.Command.prepare:
            break
        size = int(h["size"])
        if offset + size > len(blob):
            break
        try:
            wire.verify_body(h, blob[offset + wire.HEADER_SIZE : offset + size])
        except ValueError:
            break
        offset += size
    return offset


def iterate(path: str) -> Iterator[Tuple[np.ndarray, bytes]]:
    """Yield (header, body) for every valid prepare, deduplicated by
    checksum (crash-replay re-appends exact copies); stops at the first
    corrupt/torn entry (a torn tail is expected after a crash)."""
    with open(path, "rb") as f:
        blob = f.read()
    seen = set()
    offset = 0
    while offset + wire.HEADER_SIZE <= len(blob):
        try:
            h, command = wire.decode_header(
                blob[offset : offset + wire.HEADER_SIZE]
            )
        except ValueError:
            return
        if command != wire.Command.prepare:
            return
        size = int(h["size"])
        if offset + size > len(blob):
            return  # torn tail
        body = blob[offset + wire.HEADER_SIZE : offset + size]
        try:
            wire.verify_body(h, body)
        except ValueError:
            return
        checksum = wire.header_checksum(h)
        if checksum not in seen:
            seen.add(checksum)
            yield h, body
        offset += size
