"""Wire authentication: per-replica keys + header MACs (docs/fault_domains.md).

Checksums (vsr/vsr.zig checksum discipline) are error detection, not
authentication: any party that can compute AEGIS-128L can forge a frame
that verifies, so PR 6's ingress discipline only defends against Byzantine
*backups* whose transport identity pins them.  This module adds the missing cryptographic layer:

- every replica (and the sim/test harness) derives a per-origin key from a
  shared cluster secret — ``key(i) = BLAKE2b(secret || "replica" || i)`` —
  seeded deterministically so VOPR/tbmc schedules replay bit-identically;
- a frame's MAC is keyed BLAKE2b-128 over header bytes [16..256) with the
  MAC field itself zeroed (wire.MAC_OFFSET..MAC_END), computed under the
  key of the replica the header CLAIMS as its origin (``h["replica"]``) —
  so holding your own key lets you speak only as yourself;
- the MAC rides in the header bytes carved from ``reserved_frame``
  (wire.py): zero = unauthenticated, and the header checksum excludes the
  MAC bytes, so transports stamp egress frames in place.

The threat model is a Byzantine REPLICA (including the primary seat): the
cluster secret is deployment configuration shared by the operator with
every replica and client, exactly like the cluster id.  The model-checker
adversary (sim/mc.py "byzp" actions) holds only its OWN key — that
restriction is enforced by the action set, not by this module.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional

from . import wire

#: MAC width in bytes (the full reserved_frame carve).
MAC_BYTES = wire.MAC_END - wire.MAC_OFFSET


def derive_secret(cluster: int, seed: int = 0) -> bytes:
    """Deterministic cluster secret for sim/replay (production deployments
    would provision a random one out of band)."""
    tag = b"tb-auth-secret|%d|%d" % (cluster, seed)
    return hashlib.blake2b(tag, digest_size=32).digest()


class Keychain:
    """Per-origin MAC keys derived from one cluster secret.

    Keys are derived lazily and cached; any origin index (replicas and
    standbys alike) resolves to a stable key, so membership changes never
    re-key existing origins.
    """

    __slots__ = ("cluster", "secret", "_keys")

    def __init__(self, cluster: int, secret: Optional[bytes] = None,
                 seed: int = 0) -> None:
        self.cluster = int(cluster)
        self.secret = (
            secret if secret is not None else derive_secret(cluster, seed)
        )
        self._keys: Dict[int, bytes] = {}

    def key(self, origin: int) -> bytes:
        k = self._keys.get(origin)
        if k is None:
            k = hashlib.blake2b(
                self.secret + b"|replica|%d" % origin, digest_size=32
            ).digest()
            self._keys[origin] = k
        return k

    # -- MAC over the 256-byte header -----------------------------------------

    def mac(self, origin: int, header_bytes: bytes) -> int:
        """MAC of a header under ``origin``'s key: keyed BLAKE2b-128 over
        the checksum domain (bytes [16..256) with the MAC field zeroed).
        Never returns 0 — zero is the "unauthenticated" sentinel."""
        digest = hashlib.blake2b(
            wire.checksum_input(header_bytes),
            key=self.key(origin), digest_size=MAC_BYTES,
        ).digest()
        value = int.from_bytes(digest, "little")
        return value or 1

    def stamp(self, frame: bytes) -> bytes:
        """Stamp an encoded frame's MAC in place, under the key of the
        origin the header claims (byte 111) — egress transports call this
        only for frames they originated themselves."""
        origin = frame[111]
        return wire.stamp_mac(frame, self.mac(origin, frame))

    def verify(self, h) -> bool:
        """True iff the decoded header's MAC verifies under the CLAIMED
        origin's key.  A zero MAC never verifies (callers decide whether
        an unauthenticated frame is acceptable — mixed-version policy)."""
        claimed = wire.header_mac(h)
        if not claimed:
            return False
        return self.mac(int(h["replica"]), h.tobytes()) == claimed
