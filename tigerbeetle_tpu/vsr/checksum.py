"""vsr.checksum: AEGIS-128L in MAC mode, the universal 128-bit checksum.

Behavior contract (reference: src/vsr/checksum.zig — behavior only, clean
implementation): AEGIS-128L (draft-irtf-cfrg-aegis-aead) specialized to a
checksum — zero key, zero nonce, empty secret message, the input bytes as
associated data; the checksum is the 128-bit tag read as a little-endian
integer.  Used for: network message headers+bodies, WAL entries, superblock
copies, grid blocks, and prepare hash-chaining.

Primary implementation: native C++ w/ AES-NI (tigerbeetle_tpu/native/aegis.cpp)
via ctypes.  A pure-Python implementation below serves as fallback and as a
differential check in tests.  Test vectors from the reference's published
smoke-test vectors (checksum.zig "checksum test vectors").
"""

from __future__ import annotations

import struct
from typing import List, Optional

from .. import native

_C0 = bytes(
    [0x00, 0x01, 0x01, 0x02, 0x03, 0x05, 0x08, 0x0D,
     0x15, 0x22, 0x37, 0x59, 0x90, 0xE9, 0x79, 0x62]
)
_C1 = bytes(
    [0xDB, 0x3D, 0x18, 0x55, 0x6D, 0xC2, 0x2F, 0xF1,
     0x20, 0x11, 0x31, 0x42, 0x73, 0xB5, 0x28, 0xDD]
)

# --- AES round tables (generated, not copied) -------------------------------


def _make_tables():
    # AES S-box via GF(2^8) inverse + affine transform.
    sbox = [0] * 256
    p = q = 1
    sbox[0] = 0x63
    while True:
        p = p ^ ((p << 1) & 0xFF) ^ (0x1B if p & 0x80 else 0)
        q ^= (q << 1) & 0xFF
        q ^= (q << 2) & 0xFF
        q ^= (q << 4) & 0xFF
        if q & 0x80:
            q ^= 0x09
        rot = lambda x, r: ((x << r) | (x >> (8 - r))) & 0xFF
        sbox[p] = q ^ rot(q, 1) ^ rot(q, 2) ^ rot(q, 3) ^ rot(q, 4) ^ 0x63
        if p == 1:
            break
    t0 = [0] * 256
    for i in range(256):
        s = sbox[i]
        s2 = ((s << 1) ^ (0x1B if s & 0x80 else 0)) & 0xFF
        s3 = s2 ^ s
        t0[i] = s2 | (s << 8) | (s << 16) | (s3 << 24)
    t1 = [((x << 8) | (x >> 24)) & 0xFFFFFFFF for x in t0]
    t2 = [((x << 8) | (x >> 24)) & 0xFFFFFFFF for x in t1]
    t3 = [((x << 8) | (x >> 24)) & 0xFFFFFFFF for x in t2]
    return t0, t1, t2, t3


_T0, _T1, _T2, _T3 = _make_tables()


def _aesround(a: List[int], rk: List[int]) -> List[int]:
    """One AES round (SubBytes+ShiftRows+MixColumns+AddRoundKey) on 4 LE words."""
    a0, a1, a2, a3 = a
    return [
        _T0[a0 & 0xFF] ^ _T1[(a1 >> 8) & 0xFF] ^ _T2[(a2 >> 16) & 0xFF]
        ^ _T3[(a3 >> 24) & 0xFF] ^ rk[0],
        _T0[a1 & 0xFF] ^ _T1[(a2 >> 8) & 0xFF] ^ _T2[(a3 >> 16) & 0xFF]
        ^ _T3[(a0 >> 24) & 0xFF] ^ rk[1],
        _T0[a2 & 0xFF] ^ _T1[(a3 >> 8) & 0xFF] ^ _T2[(a0 >> 16) & 0xFF]
        ^ _T3[(a1 >> 24) & 0xFF] ^ rk[2],
        _T0[a3 & 0xFF] ^ _T1[(a0 >> 8) & 0xFF] ^ _T2[(a1 >> 16) & 0xFF]
        ^ _T3[(a2 >> 24) & 0xFF] ^ rk[3],
    ]


def _words(b: bytes) -> List[int]:
    return list(struct.unpack("<4I", b))


def _xor(a: List[int], b: List[int]) -> List[int]:
    return [x ^ y for x, y in zip(a, b)]


class _State:
    __slots__ = ("s",)

    def __init__(self) -> None:
        zero = [0, 0, 0, 0]
        c0, c1 = _words(_C0), _words(_C1)
        # init with key=0, nonce=0 (S0=K^N, S5=K^C0, S6=K^C1, S7=K^C0).
        self.s = [zero, c1, c0, list(c1), list(zero), list(c0), list(c1), list(c0)]
        for _ in range(10):
            self.update(zero, zero)

    def update(self, m0: List[int], m1: List[int]) -> None:
        # S'i = AESRound(S[i-1], S[i]); messages XOR into the key operand:
        # S'0 = AESRound(S7, S0 ^ M0), S'4 = AESRound(S3, S4 ^ M1).
        s = self.s
        t7 = s[7]
        s[7] = _aesround(s[6], s[7])
        s[6] = _aesround(s[5], s[6])
        s[5] = _aesround(s[4], s[5])
        s[4] = _aesround(s[3], _xor(s[4], m1))
        s[3] = _aesround(s[2], s[3])
        s[2] = _aesround(s[1], s[2])
        s[1] = _aesround(s[0], s[1])
        s[0] = _aesround(t7, _xor(s[0], m0))


def checksum_py(data: bytes) -> int:
    """Pure-Python AEGIS-128L MAC (fallback + differential check)."""
    st = _State()
    n = len(data)
    full = n // 32
    for i in range(full):
        st.update(_words(data[32 * i : 32 * i + 16]),
                  _words(data[32 * i + 16 : 32 * i + 32]))
    rem = n % 32
    if rem:
        pad = data[32 * full :] + b"\x00" * (32 - rem)
        st.update(_words(pad[:16]), _words(pad[16:]))
    # Finalize: tmp = S2 ^ (LE64(ad_len_bits) || LE64(0)); 7 updates; tag=S0^..^S6.
    tmp = _xor(st.s[2], _words(struct.pack("<QQ", 8 * n, 0)))
    for _ in range(7):
        st.update(tmp, tmp)
    tag = [0, 0, 0, 0]
    for i in range(7):
        tag = _xor(tag, st.s[i])
    return int.from_bytes(struct.pack("<4I", *tag), "little")


def checksum(data) -> int:
    """128-bit checksum of ``data`` (bytes-like), as an int."""
    lib = native.load()
    data = bytes(data)
    if lib is None:
        return checksum_py(data)
    import ctypes

    out = ctypes.create_string_buffer(16)
    lib.tb_checksum(data, len(data), out)
    return int.from_bytes(out.raw, "little")


CHECKSUM_EMPTY = None  # filled lazily below (avoids native build at import)


def checksum_empty() -> int:
    global CHECKSUM_EMPTY
    if CHECKSUM_EMPTY is None:
        CHECKSUM_EMPTY = checksum(b"")
    return CHECKSUM_EMPTY
