"""SuperBlock: the replica-local durable root, 4 checksummed copies.

Semantics from the reference (src/vsr/superblock.zig:1-29 invariants,
superblock_quorums.zig): the superblock stores the VSR state the replica must
never lose — view/log_view, commit numbers, and the current checkpoint
reference.  It is written as 4 sequential copies with fsync barriers between
pairs, so that a crash mid-update always leaves at least two intact copies of
either the old or the new state; open() reads all copies and picks the highest
sequence with a working quorum.

The checkpoint reference points at a snapshot file of the device ledger
(checkpoint.py) — the TPU analogue of the reference's grid/manifest refs.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from .checksum import checksum
from .storage import SUPERBLOCK_COPIES, SUPERBLOCK_COPY_SIZE, Storage

MAGIC = 0x7462_7470_7573_6201  # "tbtpusb\x01"
VERSION = 3  # v3: +primary_offset (committed reconfiguration, PR 20);
             # v2: +log_adopted_op amputation watermark (round 5)

# log_adopted_op sentinel written by VsrReplica.promote: a promoted data
# file opens log_suspect and can only be certified by installing a
# canonical start_view (repair cannot vouch for a REPLACED identity's
# history — the retired voter's journal, and the acks it held, are gone).
PROMOTION_SUSPECT_OP = 1 << 62

# Quorum for reading: with 4 copies, require 2 matching (superblock_quorums).
QUORUM_READ = 2

SUPERBLOCK_DTYPE = np.dtype(
    [
        ("checksum_lo", "<u8"), ("checksum_hi", "<u8"),
        ("copy", "u1"),
        ("_pad0", "V7"),
        ("magic", "<u8"),
        ("version", "<u4"),
        ("_pad1", "V4"),
        ("cluster_lo", "<u8"), ("cluster_hi", "<u8"),
        ("replica", "u1"),
        ("replica_count", "u1"),
        ("standby_count", "u1"),
        # Primary rotation offset: primary(view) = (view + primary_offset)
        # % replica_count.  A committed membership change (operation
        # reconfigure) picks the offset that keeps the CURRENT view's
        # primary fixed under the new modulus, so quorum flips never move
        # the primary without a view change (docs/reconfiguration.md).
        ("primary_offset", "u1"),
        ("_pad2", "V4"),
        ("sequence", "<u8"),
        # -- VSRState (superblock.zig CheckpointState analogue) --
        ("view", "<u4"),
        ("log_view", "<u4"),
        ("commit_min", "<u8"),           # == checkpoint op
        ("commit_max", "<u8"),
        # How far the canonical log of the durable log_view was KNOWN to
        # extend at adoption time (written only when log_view advances) —
        # the amputation-evidence watermark.  Distinct from commit_max,
        # which folds in heartbeat-learned cluster knowledge a lagging
        # backup's journal never held (ADVICE r4: using commit_max there
        # falsely marked intact lagging backups log_suspect).
        ("log_adopted_op", "<u8"),
        ("op_checkpoint", "<u8"),
        ("checkpoint_file_checksum_lo", "<u8"),
        ("checkpoint_file_checksum_hi", "<u8"),
        ("ledger_digest", "<u8"),        # state-machine parity digest
        ("prepare_timestamp", "<u8"),
        ("commit_timestamp", "<u8"),
        # LSM manifest reference (forest.py; manifest_log.zig's superblock
        # manifest refs).  Zero => legacy full-snapshot checkpoint.
        ("manifest_checksum_lo", "<u8"),
        ("manifest_checksum_hi", "<u8"),
        ("reserved", "V3928"),
    ]
)
assert SUPERBLOCK_DTYPE.itemsize == SUPERBLOCK_COPY_SIZE, SUPERBLOCK_DTYPE.itemsize


@dataclasses.dataclass
class SuperBlockState:
    cluster: int = 0
    replica: int = 0
    replica_count: int = 1
    # Non-voting members with indexes [replica_count, replica_count +
    # standby_count) — they consume the prepare stream but never ack or
    # vote (constants.zig:31-35).
    standby_count: int = 0
    primary_offset: int = 0
    sequence: int = 0
    view: int = 0
    log_view: int = 0
    commit_min: int = 0
    commit_max: int = 0
    log_adopted_op: int = 0
    op_checkpoint: int = 0
    checkpoint_file_checksum: int = 0
    ledger_digest: int = 0
    prepare_timestamp: int = 0
    commit_timestamp: int = 0
    manifest_checksum: int = 0


def _encode_copy(state: SuperBlockState, copy: int) -> bytes:
    rec = np.zeros((), dtype=SUPERBLOCK_DTYPE)
    rec["copy"] = copy
    rec["magic"] = MAGIC
    rec["version"] = VERSION
    rec["cluster_lo"] = state.cluster & 0xFFFF_FFFF_FFFF_FFFF
    rec["cluster_hi"] = state.cluster >> 64
    rec["replica"] = state.replica
    rec["replica_count"] = state.replica_count
    rec["standby_count"] = state.standby_count
    rec["primary_offset"] = state.primary_offset
    rec["sequence"] = state.sequence
    rec["view"] = state.view
    rec["log_view"] = state.log_view
    rec["commit_min"] = state.commit_min
    rec["commit_max"] = state.commit_max
    rec["log_adopted_op"] = state.log_adopted_op
    rec["op_checkpoint"] = state.op_checkpoint
    rec["checkpoint_file_checksum_lo"] = (
        state.checkpoint_file_checksum & 0xFFFF_FFFF_FFFF_FFFF
    )
    rec["checkpoint_file_checksum_hi"] = state.checkpoint_file_checksum >> 64
    rec["ledger_digest"] = state.ledger_digest
    rec["prepare_timestamp"] = state.prepare_timestamp
    rec["commit_timestamp"] = state.commit_timestamp
    rec["manifest_checksum_lo"] = state.manifest_checksum & 0xFFFF_FFFF_FFFF_FFFF
    rec["manifest_checksum_hi"] = state.manifest_checksum >> 64
    buf = bytearray(rec.tobytes())
    # checksum covers everything after the 16-byte checksum field, except the
    # copy byte (so all copies share one checksum; a misdirected copy write is
    # detected by the copy byte alone, like the reference's copy_index).
    c = _copy_checksum(bytes(buf))
    buf[0:8] = (c & 0xFFFF_FFFF_FFFF_FFFF).to_bytes(8, "little")
    buf[8:16] = (c >> 64).to_bytes(8, "little")
    return bytes(buf)


def _copy_checksum(buf: bytes) -> int:
    # zero out the copy byte for the checksum so copies are comparable.
    body = bytearray(buf[16:])
    body[0] = 0
    return checksum(bytes(body))


def _decode_copy(buf: bytes) -> Optional[Tuple[SuperBlockState, int]]:
    rec = np.frombuffer(buf, dtype=SUPERBLOCK_DTYPE)[0]
    stored = (int(rec["checksum_hi"]) << 64) | int(rec["checksum_lo"])
    if stored != _copy_checksum(buf):
        return None
    if int(rec["magic"]) != MAGIC or int(rec["version"]) != VERSION:
        return None
    state = SuperBlockState(
        cluster=(int(rec["cluster_hi"]) << 64) | int(rec["cluster_lo"]),
        replica=int(rec["replica"]),
        replica_count=int(rec["replica_count"]),
        standby_count=int(rec["standby_count"]),
        primary_offset=int(rec["primary_offset"]),
        sequence=int(rec["sequence"]),
        view=int(rec["view"]),
        log_view=int(rec["log_view"]),
        commit_min=int(rec["commit_min"]),
        commit_max=int(rec["commit_max"]),
        log_adopted_op=int(rec["log_adopted_op"]),
        op_checkpoint=int(rec["op_checkpoint"]),
        checkpoint_file_checksum=(
            (int(rec["checkpoint_file_checksum_hi"]) << 64)
            | int(rec["checkpoint_file_checksum_lo"])
        ),
        ledger_digest=int(rec["ledger_digest"]),
        prepare_timestamp=int(rec["prepare_timestamp"]),
        commit_timestamp=int(rec["commit_timestamp"]),
        manifest_checksum=(
            (int(rec["manifest_checksum_hi"]) << 64)
            | int(rec["manifest_checksum_lo"])
        ),
    )
    return state, int(rec["copy"])


# Cluster membership ceilings (constants.zig:31-35); also the u8 storage
# bound in SUPERBLOCK_DTYPE.
REPLICAS_MAX = 6
STANDBYS_MAX = 6


def validate_membership(replica: int, replica_count: int,
                        standby_count: int) -> None:
    """Operator-reachable validation (CLI format): real errors, not
    asserts (stripped under -O).  Called BEFORE any file is created so a
    rejected format leaves no debris."""
    if not 1 <= replica_count <= REPLICAS_MAX:
        raise ValueError(
            f"replica_count {replica_count} outside [1, {REPLICAS_MAX}]"
        )
    if not 0 <= standby_count <= STANDBYS_MAX:
        raise ValueError(
            f"standby_count {standby_count} outside [0, {STANDBYS_MAX}]"
        )
    if not 0 <= replica < replica_count + standby_count:
        raise ValueError(
            f"replica index {replica} outside [0, "
            f"{replica_count + standby_count}) "
            f"(replica_count={replica_count}, standby_count={standby_count})"
        )
    if replica_count == 1 and standby_count > 0:
        # The solo serving path has no consensus tick loop — a standby
        # would be silently starved of the prepare stream; reject rather
        # than format a node that can never catch up.
        raise ValueError(
            "standbys require a multi-replica cluster (replica_count >= 2)"
        )


class SuperBlock:
    def __init__(self, storage: Storage) -> None:
        self.storage = storage
        self.state = SuperBlockState()

    def format(self, cluster: int, replica: int, replica_count: int = 1,
               standby_count: int = 0) -> None:
        validate_membership(replica, replica_count, standby_count)
        self.state = SuperBlockState(
            cluster=cluster, replica=replica, replica_count=replica_count,
            standby_count=standby_count, sequence=1,
        )
        self._write_all()

    def checkpoint(self, state: SuperBlockState) -> None:
        """Durably install a new superblock state (sequence bumped)."""
        state.sequence = self.state.sequence + 1
        self.state = state
        self._write_all()

    def _write_all(self) -> None:
        off = self.storage.layout.superblock_offset
        for copy in range(SUPERBLOCK_COPIES):
            self.storage.write(
                off + copy * SUPERBLOCK_COPY_SIZE, _encode_copy(self.state, copy)
            )
            # fsync after each pair: a crash leaves >=2 copies of old or new.
            if copy % 2 == 1:
                self.storage.sync()
        self.storage.sync()

    def open(self) -> SuperBlockState:
        """Quorum-read the superblock (superblock_quorums.zig semantics)."""
        off = self.storage.layout.superblock_offset
        by_sequence: dict = {}
        for copy in range(SUPERBLOCK_COPIES):
            buf = self.storage.read(off + copy * SUPERBLOCK_COPY_SIZE,
                                    SUPERBLOCK_COPY_SIZE)
            decoded = _decode_copy(buf)
            if decoded is None:
                continue
            state, _stored_copy = decoded
            by_sequence.setdefault(state.sequence, []).append(state)
        if not by_sequence:
            raise RuntimeError("superblock: no valid copies (not formatted?)")
        for sequence in sorted(by_sequence, reverse=True):
            copies = by_sequence[sequence]
            if len(copies) >= QUORUM_READ:
                self.state = copies[0]
                return self.state
        # No sequence has a quorum: a torn first-ever write. Take the highest
        # valid copy (the previous quorum, if any, is older by construction).
        best = max(by_sequence)
        self.state = by_sequence[best][0]
        return self.state
