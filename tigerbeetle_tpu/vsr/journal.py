"""Journal (WAL): two on-disk rings — redundant headers + full prepares.

Semantics from the reference (src/vsr/journal.zig:17-46): prepares are written
to slot ``op % slot_count`` of the prepare ring; a redundant copy of each
256-byte prepare header goes to the header ring.  The write order (prepare
body first, fsync, then redundant header, fsync) plus dual checksums lets
recovery disentangle torn writes from true corruption (Protocol-Aware
Recovery):

- header-ring entry valid + prepare valid + checksums match  -> entry ok
- header-ring valid, prepare corrupt                          -> faulty slot
  (torn prepare write or bitrot; repairable from peers, or truncatable if
  the op was never acknowledged)
- header-ring corrupt, prepare valid                          -> torn header
  write; the prepare itself is authoritative, header is rewritten
- both corrupt                                                -> empty/corrupt

WAL entries are exactly wire-format prepare messages (header + body), so the
wire codec is the journal codec.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import wire
from .storage import Storage
from ..utils.tracer import tracer


@dataclasses.dataclass
class RecoveredEntry:
    op: int
    header: np.ndarray          # PREPARE_DTYPE record
    body: Optional[bytes]       # None => faulty (header known, body lost)


@dataclasses.dataclass
class Recovery:
    entries: Dict[int, RecoveredEntry]
    faulty_slots: List[int]
    repaired_headers: int


class Journal:
    def __init__(self, storage: Storage) -> None:
        self.storage = storage
        self.config = storage.config
        self.slot_count = self.config.journal_slot_count

    def slot(self, op: int) -> int:
        return op % self.slot_count

    # -- writes --------------------------------------------------------------

    def write_prepare(self, message: bytes, sync: bool = True) -> None:
        """Durably journal a prepare message (header+body wire bytes)."""
        with tracer.span("journal_write", size=len(message)):
            self._write_prepare(message, sync)

    def _write_prepare(self, message: bytes, sync: bool) -> None:
        h, command = wire.decode_header(message)
        assert command == wire.Command.prepare
        assert len(message) == int(h["size"]) <= self.config.message_size_max
        slot = self.slot(int(h["op"]))
        lay = self.storage.layout
        self.storage.write(
            lay.wal_prepares_offset + slot * self.config.message_size_max, message
        )
        if sync:
            self.storage.sync()
        self.storage.write(
            lay.wal_headers_offset + slot * self.config.header_size,
            message[: self.config.header_size],
        )
        if sync:
            self.storage.sync()

    def sync(self) -> None:
        self.storage.sync()

    # -- reads ---------------------------------------------------------------

    def _read_slot(
        self, slot: int, expect_op: Optional[int] = None
    ) -> Optional[Tuple[np.ndarray, bytes]]:
        """Read+verify whatever prepare the slot holds — embedded header
        first, then exactly the message's ``size`` bytes (a full-slot read
        would drag message_size_max (1 MiB default) through the page cache
        per call; this path runs once per committed op on backups).
        ``expect_op`` bails right after the header decode when the slot
        holds a different (wrapped) op — no body IO or checksum work."""
        lay = self.storage.layout
        base = lay.wal_prepares_offset + slot * self.config.message_size_max
        head = self.storage.read(base, self.config.header_size)
        try:
            h, command = wire.decode_header(head)
        except ValueError:
            return None
        if command != wire.Command.prepare:
            return None
        if expect_op is not None and int(h["op"]) != expect_op:
            return None
        size = int(h["size"])
        if size > self.config.message_size_max:
            return None
        body = (
            self.storage.read(base + wire.HEADER_SIZE, size - wire.HEADER_SIZE)
            if size > wire.HEADER_SIZE else b""
        )
        try:
            wire.verify_body(h, body)
        except ValueError:
            return None
        return h, body

    def read_prepare(self, op: int) -> Optional[Tuple[np.ndarray, bytes]]:
        """Read+verify the prepare at ``op``'s slot; None unless the slot
        currently holds exactly ``op``."""
        return self._read_slot(self.slot(op), expect_op=op)

    def never_had(self, op: int, checksum: int) -> bool:
        """True when this journal PROVABLY never held the prepare
        (op, checksum) — the safety condition for a view-change nack
        (vsr.zig nack protocol): an all-zero slot was never written, and a
        slot holding a DIFFERENT decodable prepare means the requested one
        was either never journaled here or provably superseded by a
        canonical-at-selection-time fork (which implies the requested op
        never committed).  Undecodable non-zero bytes could be a torn
        write OF the requested prepare — never nack those.

        BOTH rings must agree: a misdirected write can clobber the
        prepares slot with a different valid prepare, but the redundant
        headers ring (written last, after the body was durable) would
        still record that we once held (op, checksum) — that is exactly
        the disentanglement the dual-ring design exists for."""
        slot = self.slot(op)
        lay = self.storage.layout
        for offset, size in (
            (lay.wal_prepares_offset + slot * self.config.message_size_max,
             self.config.header_size),
            (lay.wal_headers_offset + slot * self.config.header_size,
             self.config.header_size),
        ):
            head = self.storage.read(offset, size)
            if not any(head):
                continue  # virgin ring slot: consistent with never-had
            try:
                h, command = wire.decode_header(head)
            except ValueError:
                return False  # torn/corrupt: might have been (op, checksum)
            if command != wire.Command.prepare:
                return False
            if int(h["op"]) == op and wire.u128(h, "checksum") == checksum:
                return False  # this ring remembers holding it
        return True

    def recover(self) -> Recovery:
        """Scan both rings, disentangle torn writes, return surviving entries."""
        lay = self.storage.layout
        headers_buf = self.storage.read(lay.wal_headers_offset, lay.wal_headers_size)
        entries: Dict[int, RecoveredEntry] = {}
        faulty: List[int] = []
        repaired = 0

        for slot in range(self.slot_count):
            ring_hdr = None
            hbuf = headers_buf[
                slot * self.config.header_size : (slot + 1) * self.config.header_size
            ]
            try:
                h, command = wire.decode_header(hbuf)
                if command == wire.Command.prepare:
                    ring_hdr = h
            except ValueError:
                ring_hdr = None

            # Sized read (embedded header first): scanning every slot at
            # its full message_size_max forces the whole prepares ring
            # (1 GiB at production config) through the page cache on every
            # open — ~12 s of replica startup for a mostly-virgin ring.
            prepare = self._read_slot(slot)

            if prepare is not None:
                ph, body = prepare
                op = int(ph["op"])
                if self.slot(op) == slot:
                    entries[op] = RecoveredEntry(op=op, header=ph, body=body)
                    if ring_hdr is None or wire.header_checksum(
                        ring_hdr
                    ) != wire.header_checksum(ph):
                        # Torn/stale header ring entry: prepare is authoritative.
                        self.storage.write(
                            lay.wal_headers_offset + slot * self.config.header_size,
                            ph.tobytes(),
                        )
                        repaired += 1
            elif ring_hdr is not None:
                # Header known but prepare lost: faulty (torn prepare write).
                op = int(ring_hdr["op"])
                if self.slot(op) == slot:
                    entries[op] = RecoveredEntry(op=op, header=ring_hdr, body=None)
                    faulty.append(slot)
            # else: empty slot.

        if repaired:
            self.storage.sync()
        return Recovery(entries=entries, faulty_slots=faulty, repaired_headers=repaired)
