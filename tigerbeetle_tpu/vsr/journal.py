"""Journal (WAL): two on-disk rings — redundant headers + full prepares.

Semantics from the reference (src/vsr/journal.zig:17-46): prepares are written
to slot ``op % slot_count`` of the prepare ring; a redundant copy of each
256-byte prepare header goes to the header ring.  The write order (prepare
body first, fsync, then redundant header, fsync) plus dual checksums lets
recovery disentangle torn writes from true corruption (Protocol-Aware
Recovery):

- header-ring entry valid + prepare valid + checksums match  -> entry ok
- header-ring valid, prepare corrupt                          -> faulty slot
  (torn prepare write or bitrot; repairable from peers, or truncatable if
  the op was never acknowledged)
- header-ring corrupt, prepare valid                          -> torn header
  write; the prepare itself is authoritative, header is rewritten
- both corrupt                                                -> empty/corrupt

WAL entries are exactly wire-format prepare messages (header + body), so the
wire codec is the journal codec.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import wire
from .storage import Storage
from ..utils.tracer import tracer


@dataclasses.dataclass
class RecoveredEntry:
    op: int
    header: np.ndarray          # PREPARE_DTYPE record
    body: Optional[bytes]       # None => faulty (header known, body lost)


class JournalWriteFailure(RuntimeError):
    """A WAL write failed read-back verification repeatedly (persistently
    misdirected/faulty medium).  Fail-stop for a real replica; the
    simulator models it as a replica crash."""


@dataclasses.dataclass
class Recovery:
    entries: Dict[int, RecoveredEntry]
    faulty_slots: List[int]
    repaired_headers: int
    # Slots holding a DECODABLE prepare whose op maps to a DIFFERENT slot:
    # impossible from legitimate writes, so it is PROOF of a misdirected
    # write — and the clobbered slot may have held an op this replica
    # ACKED.  A replica with foreign slots must not vouch for its log in a
    # view change until a start_view re-certifies it (storage-adversary
    # seed 31000: doing so let a VC quorum that excluded the op's other
    # holder truncate committed history).
    foreign_slots: List[int] = dataclasses.field(default_factory=list)
    # Slots whose content is NONZERO yet undecodable in BOTH rings: a
    # virgin slot is all-zero, so this is an inhabited slot destroyed by
    # corruption — the op that lived there may have been ACKED by this
    # replica, and nothing recoverable says which op it was.  Same
    # amputation-evidence class as foreign_slots (a replica must not vouch
    # for its log until repaired): without it, a read-faulted committed
    # slot recovers as "empty", the replica claims a clean-but-shorter log,
    # and a view-change quorum of such replicas truncates committed history
    # (VOPR seed 500285).
    corrupt_slots: List[int] = dataclasses.field(default_factory=list)


class Journal:
    def __init__(self, storage: Storage) -> None:
        self.storage = storage
        self.config = storage.config
        self.slot_count = self.config.journal_slot_count

    def slot(self, op: int) -> int:
        return op % self.slot_count

    # -- writes --------------------------------------------------------------

    def write_prepare(self, message: bytes, sync: bool = True) -> None:
        """Durably journal a prepare message (header+body wire bytes)."""
        with tracer.span("journal_write", size=len(message)):
            self._write_prepare(message, sync)

    # Write-verification retries: a misdirected write (disk firmware lying
    # about the LBA) lands the bytes elsewhere while the call "succeeds".
    WRITE_RETRIES = 3

    def _verify_meaningful(self) -> bool:
        """Read-back verification only means something when reads reach the
        medium: O_DIRECT, or the simulator's fault-injecting storage.  A
        buffered read is served by the page cache the write just populated
        and would match even if the platter write misdirected."""
        return getattr(self.storage, "direct_io", True)

    def _write_prepare(self, message: bytes, sync: bool) -> None:
        h, command = wire.decode_header(message)
        assert command == wire.Command.prepare
        assert len(message) == int(h["size"]) <= self.config.message_size_max
        slot = self.slot(int(h["op"]))
        lay = self.storage.layout
        head = message[: self.config.header_size]
        verify = self._verify_meaningful()
        # Verification reads bypass the simulator's read-fault injection
        # when the backend offers that (read_nofault): a fault injected on
        # the read-back would be healed by the immediate rewrite anyway,
        # but it would charge the fault atlas and shift every seed's dice.
        read = getattr(self.storage, "read_nofault", self.storage.read)
        targets = (
            (lay.wal_prepares_offset + slot * self.config.message_size_max,
             message),
            (lay.wal_headers_offset + slot * self.config.header_size,
             head),
        )
        for offset, payload in targets:
            for attempt in range(self.WRITE_RETRIES):
                self.storage.write(offset, payload)
                if sync:
                    self.storage.sync()
                # Read-back custody check: the prepare_ok this write
                # authorizes asserts "I hold this prepare" — and the nack
                # protocol later trusts never_had()'s ring inspection, so a
                # silently-misdirected write here could let a view change
                # truncate a COMMITTED op (VOPR storage-adversary find).
                if not verify or read(
                    offset, self.config.header_size
                ) == head:
                    break
            else:
                raise JournalWriteFailure(
                    f"journal write for op {int(h['op'])} failed "
                    f"verification {self.WRITE_RETRIES}x (misdirected IO?)"
                )

    def sync(self) -> None:
        self.storage.sync()

    # -- reads ---------------------------------------------------------------

    def _read_slot(
        self, slot: int, expect_op: Optional[int] = None,
        head_nonzero_out: Optional[list] = None,
    ) -> Optional[Tuple[np.ndarray, bytes]]:
        """Read+verify whatever prepare the slot holds — embedded header
        first, then exactly the message's ``size`` bytes (a full-slot read
        would drag message_size_max (1 MiB default) through the page cache
        per call; this path runs once per committed op on backups).
        ``expect_op`` bails right after the header decode when the slot
        holds a different (wrapped) op — no body IO or checksum work.
        ``head_nonzero_out``: recovery's corrupt-slot evidence needs "were
        the raw head bytes nonzero" without a second pread per slot; when a
        list is passed, the flag is appended to it (an out-param, NOT
        instance state — stale stashed state from an interleaved read would
        misclassify virgin slots as corrupt)."""
        lay = self.storage.layout
        base = lay.wal_prepares_offset + slot * self.config.message_size_max
        head = self.storage.read(base, self.config.header_size)
        if head_nonzero_out is not None:
            head_nonzero_out.append(any(head))
        try:
            h, command = wire.decode_header(head)
        except ValueError:
            return None
        if command != wire.Command.prepare:
            return None
        if expect_op is not None and int(h["op"]) != expect_op:
            return None
        size = int(h["size"])
        if size > self.config.message_size_max:
            return None
        body = (
            self.storage.read(base + wire.HEADER_SIZE, size - wire.HEADER_SIZE)
            if size > wire.HEADER_SIZE else b""
        )
        try:
            wire.verify_body(h, body)
        except ValueError:
            return None
        return h, body

    def read_prepare(self, op: int) -> Optional[Tuple[np.ndarray, bytes]]:
        """Read+verify the prepare at ``op``'s slot; None unless the slot
        currently holds exactly ``op``."""
        return self._read_slot(self.slot(op), expect_op=op)

    def never_had(self, op: int, checksum: int) -> bool:
        """True when this journal PROVABLY never held the prepare
        (op, checksum) — the safety condition for a view-change nack
        (vsr.zig nack protocol, ``prepare_inhabited``): ONLY a slot that is
        all-zero in BOTH rings qualifies.  Anything else — a torn write,
        corruption, or even a different valid prepare — could be the
        aftermath of once holding (and having ACKED) the requested one: a
        misdirected write of a LATER op can land different-but-valid bytes
        on a committed op's slot, so "holds something else" proves
        nothing.  (Found by the storage adversary, seed 31000: two such
        clobbers plus an offline replica truncated committed history.)"""
        slot = self.slot(op)
        lay = self.storage.layout
        for offset in (
            lay.wal_prepares_offset + slot * self.config.message_size_max,
            lay.wal_headers_offset + slot * self.config.header_size,
        ):
            head = self.storage.read(offset, self.config.header_size)
            if not any(head):
                continue  # virgin ring slot
            # Prior-lap content for THIS slot also proves never-had: a
            # legitimate write of the requested (newer) op would have
            # overwritten it, and nothing can write the OLDER op back.
            # Without this, a wrapped ring (every slot inhabited forever)
            # would permanently disable the nack protocol.
            try:
                h, command = wire.decode_header(head)
            except ValueError:
                return False  # torn/corrupt: might have been (op, checksum)
            if command != wire.Command.prepare:
                return False
            if self.slot(int(h["op"])) != slot or int(h["op"]) >= op:
                return False  # foreign (misdirect) or the op itself
        return True

    def recover(self) -> Recovery:
        """Scan both rings, disentangle torn writes, return surviving entries."""
        lay = self.storage.layout
        headers_buf = self.storage.read(lay.wal_headers_offset, lay.wal_headers_size)
        entries: Dict[int, RecoveredEntry] = {}
        faulty: List[int] = []
        foreign: List[int] = []
        corrupt: List[int] = []
        repaired = 0

        for slot in range(self.slot_count):
            ring_hdr = None
            hbuf = headers_buf[
                slot * self.config.header_size : (slot + 1) * self.config.header_size
            ]
            try:
                h, command = wire.decode_header(hbuf)
                if command == wire.Command.prepare:
                    if self.slot(int(h["op"])) != slot:
                        foreign.append(slot)  # misdirected-write evidence
                    else:
                        ring_hdr = h
            except ValueError:
                ring_hdr = None

            # Sized read (embedded header first): scanning every slot at
            # its full message_size_max forces the whole prepares ring
            # (1 GiB at production config) through the page cache on every
            # open — ~12 s of replica startup for a mostly-virgin ring.
            head_nonzero: list = []
            prepare = self._read_slot(slot, head_nonzero_out=head_nonzero)

            if prepare is not None and self.slot(int(prepare[0]["op"])) != slot:
                foreign.append(slot)  # misdirected-write evidence
                prepare = None
            if prepare is not None:
                ph, body = prepare
                op = int(ph["op"])
                if self.slot(op) == slot:
                    entries[op] = RecoveredEntry(op=op, header=ph, body=body)
                    if ring_hdr is None or wire.header_checksum(
                        ring_hdr
                    ) != wire.header_checksum(ph):
                        # Torn/stale header ring entry: prepare is authoritative.
                        self.storage.write(
                            lay.wal_headers_offset + slot * self.config.header_size,
                            ph.tobytes(),
                        )
                        repaired += 1
            elif ring_hdr is not None:
                # Header known but prepare lost: faulty (torn prepare write).
                op = int(ring_hdr["op"])
                if self.slot(op) == slot:
                    entries[op] = RecoveredEntry(op=op, header=ring_hdr, body=None)
                    faulty.append(slot)
            elif slot not in foreign:
                # Neither ring decodes.  All-zero = virgin; NONZERO bytes
                # mean an inhabited slot destroyed by corruption — possibly
                # an op this replica acked (see Recovery.corrupt_slots).
                # _read_slot(slot) above already read the prepare head;
                # its nonzero-ness rode back via the out-param (no second
                # pread, no hidden instance-state coupling).
                if any(hbuf) or (head_nonzero and head_nonzero[0]):
                    corrupt.append(slot)

        if repaired:
            self.storage.sync()
        return Recovery(
            entries=entries, faulty_slots=faulty, repaired_headers=repaired,
            foreign_slots=sorted(set(foreign)),
            corrupt_slots=corrupt,
        )
