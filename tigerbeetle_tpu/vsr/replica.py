"""Replica: the VSR participant owning journal, state machine, and sessions.

Mirrors the reference replica's lifecycle and commit pipeline
(src/vsr/replica.zig): requests become prepares (op assigned, batch timestamp
from the clock, parent hash-chained — :1308-1337), prepares are journaled to
the WAL before execution (:1364+), commit runs the state machine and builds a
checksummed reply (:3678-3836), replies are stored per client session for
retry idempotency (client_sessions.zig), and every ``vsr_checkpoint_interval``
ops the ledger snapshot + superblock are made durable (:3153-3169).

This module is transport-agnostic and synchronous: `on_request(header, body)`
returns the messages to send.  The TCP message bus (net/) and the consensus
message flow for multi-replica clusters layer on top; single-replica mode
commits immediately after journaling (quorum of 1).

Recovery (`open`): superblock quorum read -> checkpoint snapshot load ->
journal scan -> replay the hash-chained suffix of the WAL beyond the
checkpoint (§3.1 of SURVEY).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types
from ..config import ClusterConfig, LedgerConfig
from ..machine import DeviceStateUnrecoverable, TpuStateMachine
from ..obs.metrics import registry as _obs
from ..obs.txtrace import dump_blackboxes, txtrace
from ..utils.tracer import tracer
from . import checkpoint as checkpoint_mod
from . import overload as overload_mod
from . import wire
from .journal import Journal
from .storage import Storage
from .superblock import (
    PROMOTION_SUSPECT_OP, SuperBlock, SuperBlockState,
)

U64_MASK = 0xFFFF_FFFF_FFFF_FFFF


@dataclasses.dataclass
class Session:
    """One client's session (client_sessions.zig): session number is the
    commit number of its register op; the last reply is retained for retry
    idempotency."""

    client: int
    session: int           # commit number of the register prepare
    request: int           # most recent request number seen
    reply_bytes: bytes     # full wire reply (header+body) for that request
    slot: int = 0          # stable client_replies zone slot (0..clients_max-1)


class InvalidRequest(Exception):
    """Request rejected before journaling (malformed body / bad operation) —
    the reference drops such requests at header validation
    (message_header.zig Request.invalid_header)."""


class ForestDamage(RuntimeError):
    """Checkpoint files (manifest/base/runs/cold) are corrupt or missing.

    ``damage`` lists (kind, ident, expected_checksum) triples.  A solo
    replica treats this as fatal; a consensus replica repairs the files
    from peers via request_blocks/block (the reference's
    grid_blocks_missing.zig path) before falling back to full state sync.
    ``cold_paths`` maps a cold entry's expected checksum to its relative
    file name so the receiver knows where to install the fetched bytes
    (cold runs are addressed by checksum on the wire)."""

    def __init__(self, damage, cold_paths=None):
        super().__init__(f"checkpoint files damaged: {damage}")
        self.damage = damage
        self.cold_paths = cold_paths or {}


class Replica:
    def __init__(
        self,
        data_path: str,
        cluster_config: Optional[ClusterConfig] = None,
        ledger_config: Optional[LedgerConfig] = None,
        batch_lanes: int = 8192,
        # Production default; sim injects a seeded clock for replay.
        time_ns=time.time_ns,  # tblint: ignore[nondet]
        storage: Optional[Storage] = None,
        aof_path: Optional[str] = None,
        hash_log=None,
        hot_transfers_capacity_max: Optional[int] = None,
        process_config=None,
        host_engine: bool = False,
        scrub_interval: Optional[int] = None,
        merkle: Optional[bool] = None,
        machine_factory=None,
    ) -> None:
        self.data_path = data_path
        # Optional determinism oracle (utils/hash_log.OpHashLog): per-commit
        # ledger digests; wired by the VOPR cluster.
        self.hash_log = hash_log
        self.config = cluster_config or ClusterConfig()
        self.ledger_config = ledger_config or LedgerConfig()
        if batch_lanes < self.config.batch_max_create_transfers:
            # A wire-legal batch (bounded only by message_size_max) larger
            # than the kernel's lane count would assert inside the commit
            # path at runtime — the server would drop the connection, the
            # client would resend, forever.  Fail fast at startup instead.
            raise ValueError(
                f"batch_lanes={batch_lanes} < batch_max="
                f"{self.config.batch_max_create_transfers}: the commit "
                "kernel could not fit a maximum wire batch"
            )
        self.batch_lanes = batch_lanes
        self.time_ns = time_ns

        # Injectable storage lets the VOPR simulator substitute an in-memory
        # fault-injecting backend (testing/storage.zig's role).
        from ..config import PROCESS_DEFAULT

        self.process_config = process_config or PROCESS_DEFAULT
        self.storage = storage if storage is not None else Storage(
            data_path, self.config,
            direct_io=self.process_config.direct_io,
            direct_io_required=self.process_config.direct_io_required,
        )
        # LSM-equivalent durable layer: base snapshot + delta runs + manifest
        # (lsm/forest.py); full snapshots only at majors/capacity changes.
        from ..lsm.forest import Forest

        self.forest = Forest(data_path)
        # Optional append-only audit log of committed prepares (aof.zig).
        self.aof = None
        if aof_path:
            from .aof import AOF

            self.aof = AOF(aof_path)
        self.superblock = SuperBlock(self.storage)
        self.journal = Journal(self.storage)
        # ``machine_factory`` (default: the real TpuStateMachine) lets the
        # model checker (sim/mc.py) substitute its digest-chain stand-in —
        # the consensus/journal/session layers are what get explored, the
        # ledger folds to its digest (docs/tbmc.md).
        self.machine = (machine_factory or TpuStateMachine)(
            self.ledger_config, batch_lanes=batch_lanes,
            # Always derived from the data file (not from the CLI flag): a
            # restart WITHOUT --hot-transfers-log2-max must still be able to
            # reload a checkpoint whose cold_manifest references the spill.
            spill_dir=data_path + ".cold",
            hot_transfers_capacity_max=hot_transfers_capacity_max,
            # Native host data plane (host_engine.py): the solo-server OLTP
            # entry points opt in; sim/cluster replicas stay on the device
            # path (per-commit digests + tiering live there).
            host_engine=host_engine,
        )
        if scrub_interval is not None:
            # Device fault domain cadence (docs/fault_domains.md); the
            # mirror arms at the end of open(), once the restored state is
            # digest-verified and the WAL replayed.
            self.machine.scrub_interval = scrub_interval
        if merkle is not None:
            # Merkle commitment mode (docs/commitments.md): the scrub
            # substrate becomes the on-device incremental tree; the full
            # mirror survives only at the interval-1 paranoid cadence.
            self.machine.merkle_enabled = bool(merkle)

        self.cluster = 0
        self.replica = 0
        self.replica_count = 1
        self.standby_count = 0
        # Primary rotation offset (docs/reconfiguration.md): a committed
        # membership change keeps the current view's primary fixed under
        # the new modulus by adjusting this offset; persisted in the
        # superblock so restarts agree.
        self._primary_offset = 0
        # Membership this process OPENED with (refreshed from the
        # superblock on every open): read only by the tbmc
        # ``reconfig_stale_quorum`` knockout, which models a node sizing
        # its view-change quorum from the pre-reconfigure membership.
        self._boot_replica_count = 1
        # Wire authentication (vsr/auth.Keychain); None = zero-MAC legacy
        # wire.  The consensus layer (VsrReplica) adds the strict-mode
        # policy knobs; the base replica only needs the keychain to stamp
        # the replies it creates (_commit_prepare).
        self.auth = None
        # Optional commit observer (testing/auditor.py): called with every
        # committed op's (op, operation, timestamp, body, results, replay)
        # — the simulator's op-ordered reply auditor hooks in here.
        self.commit_observer = None
        # Optional flight recorder (obs/txtrace.Blackbox): attached by the
        # CLI server (TB_BLACKBOX), the simulator, and the consensus layer;
        # None = off (zero cost).  Dumped on device recovery, crash-path
        # exits, and on demand (dump_blackbox).
        self.blackbox = None
        # Overlapped checkpointing (single-replica TCP server only; see
        # checkpoint()).  _ckpt_thread holds the in-flight background write;
        # _ckpt_result its finished SuperBlockState until adopted.
        self.async_checkpoint = False
        self._last_group_fsync = None  # latest group-commit WAL barrier
        self._ckpt_thread = None
        # (SuperBlockState, cold_garbage) of a finished background write.
        self._ckpt_result = None
        self._ckpt_error: Optional[BaseException] = None
        # Captures taken at their aligned op while a write was still in
        # flight, awaiting their own background write (in order).
        self._ckpt_queue: List[tuple] = []
        # commit_min of the newest capture (see _checkpoint_due).
        self._ckpt_captured_op = 0
        # Cross-group commit pipeline (pipeline_depth >= 2, the TCP serving
        # engine; docs/commit_pipeline.md): at most ONE group's readbacks +
        # bookkeeping may be pending while the next group is admitted,
        # journaled, and dispatched.  Each in-flight entry is
        # (run, DeviceCommitHandle, its group's result_bodies dict).
        self._pipeline_inflight: List[tuple] = []
        self._pipeline_pending: Optional[dict] = None
        self.view = 0
        self.op = 0                 # latest journaled op
        self.commit_min = 0         # latest committed (executed) op
        self.op_checkpoint = 0
        self.parent_checksum = 0    # checksum of prepare at self.op
        self.sessions: Dict[int, Session] = {}
        self._sb_state: Optional[SuperBlockState] = None
        # Serializes superblock writers: the serving thread (_persist_view)
        # vs the background checkpoint thread.  See _superblock_install.
        import threading

        self._sb_lock = threading.Lock()

    # -- format / open -------------------------------------------------------

    @classmethod
    def format(
        cls,
        data_path: str,
        cluster: int,
        replica: int = 0,
        replica_count: int = 1,
        standby_count: int = 0,
        cluster_config: Optional[ClusterConfig] = None,
        storage: Optional[Storage] = None,
    ) -> None:
        """Create + initialize a data file (main.zig format path; the root
        prepare op=0 anchors the hash chain, message_header.zig Prepare.root)."""
        from .superblock import validate_membership

        config = cluster_config or ClusterConfig()
        validate_membership(replica, replica_count, standby_count)
        if storage is None:
            storage = Storage.format(data_path, config)
        try:
            superblock = SuperBlock(storage)
            superblock.format(cluster, replica, replica_count, standby_count)
            root = wire.new_header(
                wire.Command.prepare,
                cluster=cluster,
                op=0,
                operation=int(wire.Operation.root),
            )
            journal = Journal(storage)
            journal.write_prepare(wire.encode(root, b""))
        finally:
            storage.close()

    @classmethod
    def promote(cls, data_path: str, new_replica: int,
                cluster_config: Optional[ClusterConfig] = None) -> None:
        """Promote a STANDBY data file to voting index ``new_replica``.

        Rewrites the superblock identity in place, keeping the WAL and
        checkpoint the standby accumulated from the prepare stream — the
        promoted voter rejoins warm and repairs only the tail (the
        reference reserves standby promotion for operator reconfiguration,
        constants.zig:31-35; the operator must first retire any live
        replica that holds the target index).

        The promoted file opens LOG_SUSPECT (round-5 VOPR find, seed
        600919): the retired voter's journal — and the prepare_oks it
        contributed to commit quorums — is gone, so the promoted identity's
        (log_view, op) claim must not enter canonical selection until a
        view change carried by the REAL voters (whose quorum provably
        intersects every commit quorum) certifies its log via start_view.
        Without this, a view-change quorum of {other voter, promoted}
        could select a canonical log missing an op the retired voter had
        committed — the sweep caught exactly that as a double-commit
        divergence at the refilled op."""
        config = cluster_config or ClusterConfig()
        storage = Storage(data_path, config)
        try:
            superblock = SuperBlock(storage)
            state = superblock.open()
            if state.replica < state.replica_count:
                raise ValueError(
                    f"replica {state.replica} is already a voter"
                )
            if not (0 <= new_replica < state.replica_count):
                raise ValueError(
                    f"target index {new_replica} is not a voting slot "
                    f"(replica_count={state.replica_count})"
                )
            state.replica = new_replica
            state.log_adopted_op = PROMOTION_SUSPECT_OP
            superblock.checkpoint(state)
        finally:
            storage.close()

    def open(self) -> None:
        """Recover durable state: superblock -> checkpoint -> WAL replay."""
        recovery = self._open_durable_state()
        # Establish the head: the highest hash-chained op from the checkpoint.
        self._replay(recovery)
        # Arm the device fault domain from this VERIFIED state (checkpoint
        # digest checked + checksummed WAL replayed).  No-op at interval 0.
        self.machine.scrub_arm()

    def _open_durable_state(self):
        """Superblock quorum read + checkpoint snapshot load + journal scan
        (everything except WAL replay, which consensus defers until the
        replica knows how far the cluster committed)."""
        sb = self.superblock.open()
        self._sb_state = sb
        self.cluster = sb.cluster
        self.replica = sb.replica
        self.replica_count = sb.replica_count
        self.standby_count = sb.standby_count
        self._primary_offset = getattr(sb, "primary_offset", 0)
        self._boot_replica_count = self.replica_count
        self.view = sb.view
        self.op_checkpoint = sb.op_checkpoint
        self.commit_min = sb.op_checkpoint

        loaded = self._load_checkpoint_state(sb)
        if loaded is not None:
            ledger, meta = loaded
            self._install_checkpoint_ledger(ledger, meta, sb)
            self.sessions = {
                int(client_hex, 16): Session(
                    client=int(client_hex, 16),
                    session=s["session"],
                    request=s["request"],
                    reply_bytes=self._read_client_reply(s["slot"], s["reply_size"]),
                    slot=s["slot"],
                )
                for client_hex, s in meta.get("sessions", {}).items()
            }

        return self.journal.recover()

    def _load_checkpoint_state(self, sb) -> Optional[tuple]:
        """(ledger, meta) from the durable checkpoint, or None when no
        checkpoint exists (genesis).  Damage maps to ForestDamage (peer-
        repairable); shared by open() and recover_device_state()."""
        if sb is None or not (
            sb.op_checkpoint > 0 or sb.checkpoint_file_checksum != 0
        ):
            return None
        if sb.manifest_checksum:
            try:
                return self.forest.open(
                    sb.op_checkpoint, sb.manifest_checksum
                )
            except (OSError, RuntimeError, ValueError, KeyError) as err:
                # Only now pay for a full verify pass (the happy path
                # reads each file exactly once): enumerate what is
                # damaged so consensus can fetch it from peers.
                damage = self.forest.verify(
                    sb.op_checkpoint, sb.manifest_checksum
                )
                if damage:
                    raise ForestDamage(damage) from err
                raise
        # Legacy full-snapshot checkpoint (no manifest).
        ledger, meta = checkpoint_mod.load(
            self.data_path, sb.op_checkpoint, sb.checkpoint_file_checksum
        )
        # Seed the forest so state-sync can materialize this
        # checkpoint and the next checkpoint goes delta.
        self.forest.seed_base(
            ledger, sb.op_checkpoint, sb.checkpoint_file_checksum
        )
        return ledger, meta

    def _install_checkpoint_ledger(self, ledger, meta, sb) -> None:
        """Swap the checkpoint snapshot into the machine and verify its
        digest against the superblock anchor."""
        self.machine.ledger = ledger
        try:
            self.machine.restore_host_state(meta["machine"])
        except (OSError, RuntimeError, AssertionError) as err:
            # Cold-tier spill files are checkpoint state too: a restart
            # whose durable manifest references a missing/corrupt cold
            # run (crash between a sync install and its cold fetch, or
            # a damaged disk) must route to peer block repair like any
            # other checkpoint file — round-5 standby-sweep find: this
            # crashed the replica (and the whole sweep) instead.
            damage, cold_paths = self._verify_cold(meta)
            if damage:
                raise ForestDamage(damage, cold_paths=cold_paths) from err
            raise
        digest = self.machine.digest()
        if digest != sb.ledger_digest:
            raise RuntimeError(
                f"checkpoint digest mismatch: ledger {digest:#x} != "
                f"superblock {sb.ledger_digest:#x}"
            )
        want = meta.get("merkle_root")
        if want is not None:
            # Replay-free commitment verification: recompute the canonical
            # Merkle roots from the restored arrays (host numpy, no device
            # work) and compare against the captured commitment.
            from ..ops import merkle as merkle_mod

            got = merkle_mod.np_ledger_roots(ledger)
            exp = (
                int(want["accounts"]), int(want["transfers"]),
                int(want["posted"]),
            )
            if got != exp:
                raise RuntimeError(
                    "checkpoint merkle root mismatch: "
                    f"{[hex(g) for g in got]} != {[hex(e) for e in exp]}"
                )

    def _verify_cold(self, meta) -> tuple:
        """Enumerate damaged cold-tier run files referenced by a
        checkpoint's machine snapshot: (damage_triples, checksum->relpath).
        Wraps ColdStore.verify_manifest (one enumeration, incl. unsafe-path
        rejection); cold runs are requested from peers BY CHECKSUM (block
        kind 'cold'), so ident rides as 0 and the path map tells the
        receiver where to install the fetched bytes."""
        try:
            damaged = self.machine.cold.verify_manifest(
                meta.get("machine", {}).get("cold_manifest", [])
            )
        except ValueError:
            return [], {}  # hostile/unsafe manifest: not peer-repairable
        if any(not expect for _, expect in damaged):
            # A checksum-less entry cannot be addressed on the wire: no
            # peer-repair path — the caller re-raises toward state sync.
            return [], {}
        return (
            [("cold", 0, expect) for _, expect in damaged],
            {expect: name for name, expect in damaged},
        )

    def _restore_root(self):
        """Regenerate + rewrite the deterministic root prepare (op 0 is a
        pure function of the cluster id, replica.format): a latent fault on
        its WAL slot must not brick recovery."""
        root = wire.new_header(
            wire.Command.prepare,
            cluster=self.cluster,
            op=0,
            operation=int(wire.Operation.root),
        )
        raw = wire.encode(root, b"")
        self.journal.write_prepare(raw)
        h, _, _ = wire.decode(raw)
        entry = type("Entry", (), {})()
        entry.header = h
        entry.body = b""
        return entry

    def _replay(self, recovery) -> None:
        """Replay the contiguous, hash-chained WAL suffix beyond commit_min."""
        # Find the chain anchor: the entry at commit_min (or the root).
        anchor = recovery.entries.get(self.commit_min)
        if anchor is None and self.commit_min == 0:
            anchor = self._restore_root()
        if anchor is None:
            # The checkpoint op's slot was since overwritten by a newer op
            # (ring wrapped): it must chain from the checkpoint regardless —
            # the chain links below still verify each step.
            self.parent_checksum = 0
        else:
            self.parent_checksum = wire.header_checksum(anchor.header)
        self.op = self.commit_min

        op = self.commit_min + 1
        while op in recovery.entries:
            entry = recovery.entries[op]
            if entry.body is None:
                break  # faulty slot: torn write of an unacknowledged op
            parent = wire.u128(entry.header, "parent")
            if self.parent_checksum and parent != self.parent_checksum:
                break  # chain broken: stale entry from an older ring lap
            self._commit_prepare(entry.header, entry.body, replay=True)
            self.parent_checksum = wire.header_checksum(entry.header)
            self.op = op
            self.commit_min = op
            op += 1
            if self._checkpoint_due():
                # Keep checkpoint ops on the fixed op_checkpoint + interval
                # grid even through replay (see consensus._commit_journal).
                self.checkpoint()

    # -- request handling (the hot path, §3.2) -------------------------------

    def on_request(self, header: np.ndarray, body: bytes) -> List[bytes]:
        """Handle a verified client request; returns wire messages to send
        back (replica.zig on_request :1308-1337 + commit_op :3678-3836)."""
        self._settle_or_recover()  # strict op order vs any pipelined group
        self._scrub_poll()
        client = wire.u128(header, "client")
        try:
            operation = wire.Operation(int(header["operation"]))
            self._validate_request(operation, body)
        except (ValueError, InvalidRequest):
            # Malformed request: drop it *before* journaling — a journaled
            # prepare must always be executable, or replay would wedge.
            return []
        request_n = int(header["request"])

        session = self.sessions.get(client)
        if operation != wire.Operation.register:
            if session is None:
                # Unknown session: evict so the client re-registers.
                return [self._eviction(client, wire.EVICTION_NO_SESSION)]
            if int(header["session"]) != session.session:
                # MISMATCH echoes the offending session so a re-registered
                # client discards stale evictions about its OLD session
                # while a live duplicate-id client still surfaces the
                # violation (consensus.py keeps the same rule).
                return [self._eviction(
                    client, wire.EVICTION_SESSION_MISMATCH,
                    session=int(header["session"]),
                )]
            if request_n == session.request and session.reply_bytes:
                return [session.reply_bytes]  # duplicate: resend stored reply
            if request_n < session.request:
                return []  # stale: drop
        elif session is not None:
            # Duplicate register retry.
            if session.reply_bytes:
                return [session.reply_bytes]
            return []

        self._checkpoint_poll()
        if self.op + 1 > self.op_prepare_max:
            # WAL full until the in-flight checkpoint lands (op_prepare_max
            # backpressure): drop, the client retries.
            return []
        if self.async_checkpoint:
            # Server mode: overlap the WAL fsync with the device kernel
            # (the prefetch-stage role, SURVEY §2 #16 — the reference
            # overlaps LSM prefetch IO with compute the same way).  The
            # prepare is WRITTEN before execution; only its fsync runs
            # concurrently, and the reply is withheld until both the
            # execution AND the fsync finished — a crash in the window
            # loses an op no client was ever answered for.
            prepare_h, prepare_body = self._prepare(header, body, operation,
                                                    sync=False)
            fsync = self._io_pool_submit(self._journal_sync_staged)
            reply = self._commit_prepare(prepare_h, prepare_body, replay=False)
            fsync.result()
        else:
            prepare_h, prepare_body = self._prepare(header, body, operation)
            reply = self._commit_prepare(prepare_h, prepare_body, replay=False)
        assert reply is not None
        out = [reply]
        if self._checkpoint_due():
            self.checkpoint()
        return out

    def on_request_group(
        self, requests: List[Tuple[np.ndarray, bytes]]
    ) -> List[List[bytes]]:
        """Group commit: journal every admitted request, ONE fsync for the
        group (overlapped with execution), replies withheld until both land.
        Blocking variant of on_request_group_pipelined."""
        out, fsync = self.on_request_group_pipelined(requests)
        if fsync is not None:
            fsync.result()
        return out

    @property
    def pipeline_depth(self) -> int:
        """Commit-pipeline depth (machine.pipeline_depth: TB_PIPELINE env,
        default 2, CLI --pipeline-depth).  Depth 1 routes every group
        through the sequential engine — bit-for-bit the pre-pipeline
        serving path."""
        return self.machine.pipeline_depth

    @pipeline_depth.setter
    def pipeline_depth(self, value: int) -> None:
        self.machine.pipeline_depth = value

    def on_request_group_pipelined(self, requests, deferred_replies=False):
        """Group commit with the durability barrier EXPOSED: returns
        (replies, fsync_future_or_None).  Replies must not be released to
        clients until the future resolves — but the caller may start the
        next group immediately, so a slow fsync (shared-disk latency spikes)
        costs bandwidth, never pipeline stalls.

        The reference's single-threaded data plane has the same shape:
        io_uring submission batching (src/io/linux.zig:33-110) keeps N
        prepares in flight sharing barriers, with replies gated on
        completion (replica.zig commit pipeline).  Reply lists are
        index-aligned with the input (empty list = dropped, client
        retries).

        With pipeline_depth >= 2 the admitted group commits through the
        pipelined engine (docs/commit_pipeline.md): the leading device run
        dispatches BEFORE the WAL writes (fsync/compute overlap) and codes
        readbacks are deferred.  With ``deferred_replies`` additionally
        True, the returned replies may be a concurrent.futures.Future of
        the reply list — group N's readbacks + bookkeeping then overlap
        group N+1's admission/journaling/dispatch, and the caller must
        await the future exactly like the fsync barrier (and call
        pipeline_flush() when its queue idles, or the last group's replies
        never come due).  The reply barrier is unchanged either way: a
        reply is released only after BOTH the group fsync and the op's
        execution."""
        out: List[List[bytes]] = [[] for _ in requests]
        admitted: List[Tuple[int, wire.Operation, np.ndarray, bytes]] = []
        self._checkpoint_poll()
        self._scrub_poll()  # group boundary: the scrub cadence's home
        # Clients with an op in the still-pending group: their session
        # state (request number, stored reply) is not yet updated, so a
        # resend could double-commit — drop, the client retries (the
        # cross-group twin of the in-group duplicate guard below).
        busy = (
            {
                wire.u128(h, "client")
                for _i, h, _b in self._pipeline_pending["prepared"]
            }
            if self._pipeline_pending is not None else frozenset()
        )
        for i, (header, body) in enumerate(requests):
            client = wire.u128(header, "client")
            try:
                operation = wire.Operation(int(header["operation"]))
                self._validate_request(operation, body)
            except (ValueError, InvalidRequest):
                continue
            request_n = int(header["request"])
            session = self.sessions.get(client)
            if operation != wire.Operation.register:
                if session is None:
                    out[i] = [self._eviction(
                        client, wire.EVICTION_NO_SESSION
                    )]
                    continue
                if int(header["session"]) != session.session:
                    # Session-echoing MISMATCH (same rule as on_request
                    # and consensus.py).
                    out[i] = [self._eviction(
                        client, wire.EVICTION_SESSION_MISMATCH,
                        session=int(header["session"]),
                    )]
                    continue
                if client in busy:
                    continue
                if request_n == session.request and session.reply_bytes:
                    out[i] = [session.reply_bytes]
                    continue
                if request_n < session.request:
                    continue
                # A client pipelining into the same group twice (protocol
                # violation: one in-flight request per session) would race
                # its own session state; only the first is admitted.
                if any(
                    wire.u128(h, "client") == client
                    for _, _, h, _ in admitted
                ):
                    continue
            elif session is not None:
                if session.reply_bytes:
                    out[i] = [session.reply_bytes]
                continue
            # Each admitted request takes exactly one op; preparation is
            # deferred past admission, so count the queue, not just op+1.
            if self.op + len(admitted) + 1 > self.op_prepare_max:
                continue  # WAL full: drop, client retries
            admitted.append((i, operation, header, body))
        if not admitted:
            # No new commits — but duplicate-resend replies above may belong
            # to a group whose fsync is still in flight; gate them on the
            # latest barrier (>= their own group's, the IO pool is FIFO) so
            # a reconnecting client cannot observe a reply ahead of its
            # durability.
            last = self._last_group_fsync
            if last is not None and not last.done():
                return out, last
            return out, None
        if self.blackbox is not None:
            self.blackbox.record("group", n=len(admitted), op=self.op,
                                 depth=self.pipeline_depth)
        if self.pipeline_depth > 1 and self.hash_log is None:
            return self._commit_group_pipelined(admitted, out,
                                                deferred_replies)
        return self._commit_group_sequential(admitted, out)

    def _commit_group_sequential(self, admitted, out):
        """Depth-1 commit engine: journal every admitted request, ONE fsync
        for the group, then execute + reply strictly per op — the
        pre-pipeline serving path, preserved bit-for-bit (and the path the
        determinism oracle requires: per-op digests must capture per-op
        effects)."""
        self._pipeline_settle()  # a depth change mid-run must not reorder
        prepared = []
        for i, operation, header, body in admitted:
            prepare_h, prepare_body = self._prepare(
                header, body, operation, sync=False
            )
            prepared.append((i, prepare_h, prepare_body))
        fsync = self._io_pool_submit(self._journal_sync_staged)
        self._last_group_fsync = fsync
        runs = self._group_device_runs(prepared)
        precomputed: Dict[int, bytes] = {}
        for j, (i, prepare_h, prepare_body) in enumerate(prepared):
            run = runs.get(j)
            if run is not None:
                # The run's device dispatch executes HERE, at its position
                # in op order — never in a pre-pass: an interleaved
                # non-transfer op (a lookup, a create_accounts) must
                # observe exactly the ops before it, or replies diverge
                # from backups' and crash-replay's strict op-order
                # execution.
                if self.machine.fuse_batches and len(run) >= 2:
                    # TB_FUSE, depth-1 twin: fuse + dispatch + resolve at
                    # the run's own position (blocking); entries a
                    # mid-run refusal leaves out of ``precomputed`` fall
                    # through to per-op commits below.
                    self._commit_run_fused_blocking(run, precomputed)
                else:
                    res = self.machine.commit_group_fast(
                        [r[1] for r in run], [r[2] for r in run]
                    )
                    if res is not None:
                        for (jj, _b, _t), results in zip(run, res):
                            precomputed[jj] = _encode_results(results)
            reply = self._commit_prepare(
                prepare_h, prepare_body, replay=False,
                result_body=precomputed.get(j),
            )
            assert reply is not None
            out[i] = [reply]
        if self._checkpoint_due():
            self.checkpoint()
        return out, fsync

    def _commit_group_pipelined(self, admitted, out, deferred_replies):
        try:
            return self._commit_group_pipelined_inner(
                admitted, out, deferred_replies
            )
        except BaseException as err:
            # A failed group must not strand an earlier group's reply
            # promise (the bus flush task would await it forever).
            self._pipeline_abort(err)
            raise

    def _commit_group_pipelined_inner(self, admitted, out, deferred_replies):
        """Pipelined commit engine (depth >= 2): three overlaps, one reply
        barrier.

        1. fsync/compute overlap — ops and prepare headers are assigned
           first, the LEADING device runs dispatch (the whole prefix up
           to the first non-deferrable op), and only then are the
           group's WAL writes + fsync issued: the journal IO of group N
           runs while group N's device dispatches are in flight.  Safe: the
           device ledger is volatile (durable state only moves at
           checkpoints, which settle the pipeline first), and no reply is
           released before both the fsync and the execution — a crash in
           the window loses ops no client was ever answered for, exactly
           the pre-pipeline recovery semantics.
        2. deferred D2H readback — device runs return DeviceCommitHandles
           executing on the machine's dispatch lane; with
           ``deferred_replies`` the whole group's readbacks + bookkeeping
           stay PENDING past return (replies become a Future the caller
           awaits like the fsync barrier), so group N's readbacks and
           reply construction overlap group N+1's admission, journaling,
           and dispatch.  Handles resolve in dispatch order (commit
           timestamps and index appends are op-ordered).
        3. every op still EXECUTES at its position in op order: a
           non-deferrable op (lookup, create_accounts, a refused run)
           first drains the in-flight handles — its results must observe
           exactly the ops before it, and a query must see their index
           appends.

        Bookkeeping + reply construction (phase B) then run per op in
        order via _commit_prepare with the precomputed result bodies —
        either before return (blocking callers) or when the pending group
        comes due (next call / pipeline_flush)."""
        pending = self._pipeline_pending
        if pending is not None and (
            pending["last_op"]
            - max(self.op_checkpoint, self._ckpt_captured_op)
            >= self.config.vsr_checkpoint_interval
        ):
            # The pending group's bookkeeping crosses a checkpoint
            # boundary: settle + checkpoint BEFORE dispatching anything
            # new — the capture must see a ledger exactly at its
            # commit_min, never one with a newer group's effects applied.
            if _obs.enabled:
                _obs.counter("pipeline.stall.checkpoint").inc()
            self.pipeline_flush()
        messages: List[bytes] = []
        prepared = []
        inflight = self._pipeline_inflight
        result_bodies: Dict[int, bytes] = {}
        skip: set = set()
        runs: Dict[int, List[Tuple]] = {}
        # Overlap #1: the leading run's dispatch goes to the lane BEFORE
        # the WAL writes in the finally (and before the previous group's
        # bookkeeping).  The WHOLE header-assign + lead-dispatch section
        # rides the try: whatever fails, every op that advanced self.op
        # has its encoded message journaled — self.op and the WAL must
        # never disagree, or the next group's hash chain points at ops
        # recovery cannot find.
        try:
            for i, operation, header, body in admitted:
                prepare_h, prepare_body = self._prepare(
                    header, body, operation, sync=False,
                    defer_write=messages
                )
                prepared.append((i, prepare_h, prepare_body))
            runs = self._group_device_runs(prepared, single_ok=True)
            if _obs.enabled:
                _obs.gauge("pipeline.depth").set(self.pipeline_depth)
                _obs.counter("pipeline.groups").inc()
            # The LEADING PREFIX of device runs — every run up to the
            # first non-deferrable op — dispatches here, before the WAL
            # writes and before the previous group's readbacks come due:
            # while the serving thread sits in group N-1's resolves
            # (15 ms apiece through a remote tunnel), the lane executes
            # ALL of group N's prefix, not just its first run.  Op order
            # is preserved: only consecutive leading runs dispatch early
            # (a run past a non-deferrable op still dispatches at its own
            # position in phase A, after that op's barrier drain).
            j = 0
            while j in runs:
                run = runs[j]
                pairs, covered = self._dispatch_run_split(run, prepared)
                for subrun, handle in pairs:
                    self._pipeline_track(subrun, handle, result_bodies, skip)
                j += covered
                if covered < len(run):
                    break  # refused (whole run or a fused tail): those
                    # ops execute inline in phase A
        finally:
            for message in messages:
                self.journal.write_prepare(message, sync=False)
        fsync = self._io_pool_submit(self._journal_sync_staged)
        self._last_group_fsync = fsync

        def drain(reason: str) -> None:
            if inflight and _obs.enabled:
                _obs.counter(f"pipeline.stall.{reason}").inc()
                if self.machine.shards:
                    # Per-shard commit-lane stall twin: every shard's lane
                    # drains together (replicated dispatch), so one series
                    # covers the mesh (docs/observability.md).
                    _obs.counter(f"pipeline.shard.stall.{reason}").inc()
            while inflight:
                self._pipeline_retire()

        # The previous group comes due: its dispatches ran ahead of ours
        # on the FIFO lane, so its readbacks + bookkeeping + reply promise
        # land now — while OUR lead executes.
        self._pipeline_finish_pending()

        # Phase A: op-order execution; device runs defer their readbacks.
        for j, (i, prepare_h, prepare_body) in enumerate(prepared):
            if j in skip:
                continue
            run = runs.get(j)
            if run is not None and j != 0:
                pairs, covered = self._dispatch_run_split(run, prepared)
                for subrun, handle in pairs:
                    self._pipeline_track(subrun, handle, result_bodies, skip)
                if covered == len(run):
                    continue
                if _obs.enabled:
                    _obs.counter("pipeline.stall.refusal").inc()
                if covered:
                    # The covered prefix is tracked (this op included);
                    # the refused tail executes inline at its own
                    # positions below.
                    continue
                # Refused run (mid-run fast-path refusal, tiering, ...):
                # its ops fall through to per-op execution at their own
                # positions below.
            operation = wire.Operation(int(prepare_h["operation"]))
            if operation in (wire.Operation.register, wire.Operation.root):
                continue  # no state-machine execution; bookkeeping-only
            # Overlap #3 barrier: this op's results must observe every
            # prior op's effects AND index appends.
            drain("barrier")
            t0 = time.perf_counter_ns() if _obs.enabled else 0  # tblint: ignore[nondet] metrics
            with tracer.span("state_machine_commit",
                             op=int(prepare_h["op"]),
                             operation=operation.name):
                result_bodies[j] = self._execute(
                    operation, prepare_body, int(prepare_h["timestamp"])
                )
            if _obs.enabled:
                _obs.histogram("replica.commit_us", "us").observe(
                    (time.perf_counter_ns() - t0) / 1e3  # tblint: ignore[nondet] metrics
                )

        if deferred_replies and inflight:
            # Group N stays pending: readbacks + bookkeeping + replies
            # come due with group N+1 (or pipeline_flush when the queue
            # idles).  The reply barrier is unchanged — the caller awaits
            # the promise AND the fsync before releasing anything.
            import concurrent.futures

            promise: "concurrent.futures.Future" = (
                concurrent.futures.Future()
            )
            self._pipeline_pending = {
                "prepared": prepared,
                "out": out,
                "result_bodies": result_bodies,
                "promise": promise,
                "last_op": int(prepared[-1][1]["op"]),
            }
            return promise, fsync

        drain("flush")
        self._pipeline_phase_b(prepared, result_bodies, out)
        if self._checkpoint_due():
            self.checkpoint()
        return out, fsync

    # -- pipelined-engine plumbing (docs/commit_pipeline.md) ------------------

    @property
    def pipeline_pending(self) -> bool:
        """True while a commit group's readbacks/bookkeeping are deferred
        (the bus polls this to flush when its request queue idles)."""
        return self._pipeline_pending is not None or bool(
            self._pipeline_inflight
        )

    def pipeline_flush(self) -> None:
        """Drain the pipelined commit engine: resolve every in-flight
        device readback, run the pending group's bookkeeping + replies
        (fulfilling its reply promise), and take any checkpoint that came
        due.  No-op when nothing is pending.  Called by the bus when the
        request queue idles, by every blocking commit entry point, and by
        close()."""
        self._settle_or_recover()
        if self._checkpoint_due():
            self.checkpoint()

    def _settle_or_recover(self) -> None:
        """_pipeline_settle, routing a device-fault escalation raised while
        resolving deferred handles (mirror suspect / cold tier active —
        DeviceStateUnrecoverable) into the durable-state rebuild instead of
        crashing the serving path.  The failed group was already aborted by
        the settle (reply promises failed, clients retry); recovery
        restores the committed prefix and serving continues."""
        try:
            self._pipeline_settle()
        except DeviceStateUnrecoverable:
            self.recover_device_state()

    def _pipeline_settle(self) -> None:
        """Resolve all in-flight handles + pending bookkeeping WITHOUT the
        checkpoint-due check (checkpoint() itself calls this; the due
        check there would recurse)."""
        try:
            while self._pipeline_inflight:
                self._pipeline_retire()
            self._pipeline_finish_pending()
        except BaseException as err:
            self._pipeline_abort(err)
            raise

    def _pipeline_track(self, run, handle, result_bodies, skip) -> None:
        if _obs.enabled:
            _obs.counter("pipeline.dispatches").inc()
            _obs.histogram("pipeline.inflight", "handles").observe(
                len(self._pipeline_inflight) + 1
            )
        skip.update(jj for jj, _b, _t in run)
        self._pipeline_inflight.append((run, handle, result_bodies))

    def _pipeline_retire(self) -> None:
        """Resolve the OLDEST in-flight run (dispatch order == op order)
        into its group's result bodies.  The resolve IS the deferred ops'
        commit stage, so it carries the commit-stage series/span the
        blocking path records per op (one observation per run here)."""
        run, handle, result_bodies = self._pipeline_inflight.pop(0)
        t0 = time.perf_counter_ns() if _obs.enabled else 0  # tblint: ignore[nondet] metrics
        with tracer.span("state_machine_commit", deferred=True,
                         operation="create_transfers", batches=len(run)):
            results = handle.resolve()
        if _obs.enabled:
            # Queue wait (the join) is pipeline idle time, NOT commit
            # work: it rides pipeline.resolve_wait_us; commit_us must stay
            # comparable with the blocking path's execution-only series.
            _obs.histogram("replica.commit_us", "us").observe(max(
                (time.perf_counter_ns() - t0) / 1e3  # tblint: ignore[nondet] metrics
                - handle.join_wait_s * 1e6, 0.0,
            ))
        for (jj, _b, _t), res in zip(run, results):
            result_bodies[jj] = _encode_results(res)

    def _pipeline_finish_pending(self) -> None:
        """Run the pending group's remaining readbacks + phase B and
        fulfill its reply promise."""
        pending = self._pipeline_pending
        if pending is None:
            return
        # Its handles are the oldest in-flight entries (FIFO): resolve
        # exactly those — a newer group's may already be queued behind.
        while self._pipeline_inflight and (
            self._pipeline_inflight[0][2] is pending["result_bodies"]
        ):
            self._pipeline_retire()
        self._pipeline_pending = None
        try:
            self._pipeline_phase_b(
                pending["prepared"], pending["result_bodies"], pending["out"]
            )
        except BaseException as err:
            # The promise must ALWAYS resolve (the bus flush task awaits
            # it); _pipeline_abort can no longer see this group — pending
            # was just detached — so fail it here and re-raise.
            if not pending["promise"].done():
                pending["promise"].set_exception(
                    RuntimeError(f"pipelined group commit failed: {err!r}")
                )
            raise
        pending["promise"].set_result(pending["out"])

    def _pipeline_phase_b(self, prepared, result_bodies, out) -> None:
        """Phase B: bookkeeping + reply construction, strictly in op
        order.  The reply barrier is unchanged: the caller withholds these
        until the group fsync resolves."""
        for j, (i, prepare_h, prepare_body) in enumerate(prepared):
            reply = self._commit_prepare(
                prepare_h, prepare_body, replay=False,
                result_body=result_bodies.get(j),
            )
            assert reply is not None
            out[i] = [reply]

    def _pipeline_abort(self, err) -> None:
        """Engine failure: QUIESCE in-flight handles (join their lane
        dispatches — an orphaned closure would keep mutating the machine's
        ledger concurrently with the serving thread — and release their
        staging sets) and fail the pending reply promise so its flush task
        unblocks (the bus then drops those connections — clients retry,
        exactly the group-failure discipline)."""
        for _run, handle, _rb in self._pipeline_inflight:
            handle.discard()
        self._pipeline_inflight.clear()
        pending, self._pipeline_pending = self._pipeline_pending, None
        if pending is not None and not pending["promise"].done():
            pending["promise"].set_exception(
                RuntimeError(f"pipelined group commit failed: {err!r}")
            )

    def _dispatch_run(self, run, prepared=None):
        """Dispatch one device run deferred; returns a DeviceCommitHandle
        or None (not eligible — the engine executes the ops inline)."""
        machine = self.machine
        batches = [b for _jj, b, _t in run]
        timestamps = [t for _jj, _b, t in run]
        if len(run) == 1:
            handle = machine.commit_fast_deferred(batches[0], timestamps[0])
        else:
            handle = machine.commit_group_fast(
                batches, timestamps, deferred=True
            )
        if handle is not None and prepared is not None and txtrace.active:
            # Bind traced ops of this run into their causal chains at the
            # moment the run enters the FIFO dispatch lane — the deferred
            # engine's twin of the replica.execute span (docs/tracing.md).
            for jj, _b, _t in run:
                trace = int(prepared[jj][1]["trace"])
                if trace:
                    txtrace.hop(trace, "replica.dispatch_lane",
                                replica=self.replica,
                                op=int(prepared[jj][1]["op"]),
                                run_len=len(run))
        return handle

    def _plan_run_fusion(self, run):
        """Conflict-fusion plan for one device run (TB_FUSE): member
        batches with disjoint admission-time conflict signatures
        (vsr/overload.plan_fusion) coalesce into wider dispatched batches.
        Returns [(subrun, dispatch_batch, dispatch_timestamp), ...] in op
        order — a width-1 segment passes its batch through untouched, a
        wider one concatenates the members (host-side; the machine pads
        the result onto the same jit size classes a solo batch uses) and
        carries the LAST member's prepare timestamp, which per-lane
        timestamp math maps back to every member's solo values because
        plan_fusion requires timestamp contiguity."""
        batches = [b for _jj, b, _t in run]
        timestamps = [t for _jj, _b, t in run]
        segments, rejects = overload_mod.plan_fusion(
            batches, timestamps, self.machine.batch_lanes
        )
        if rejects and _obs.enabled:
            _obs.counter("fuse.conflict_rejects").inc(rejects)
        plan = []
        for s, e in segments:
            if e - s == 1:
                plan.append((run[s:e], batches[s], timestamps[s]))
            else:
                plan.append((
                    run[s:e],
                    np.concatenate(batches[s:e]),
                    timestamps[e - 1],
                ))
        return plan

    @staticmethod
    def _note_fused_dispatch(plan) -> None:
        if not _obs.enabled:
            return
        for subrun, _b, _t in plan:
            if len(subrun) > 1:
                _obs.counter("fuse.fused_runs").inc()
                _obs.histogram("fuse.fused_width", "batches").observe(
                    len(subrun)
                )

    def _dispatch_run_split(self, run, prepared=None):
        """Fusion-aware deferred dispatch of one device run: returns
        ``(pairs, covered)`` where pairs is ``[(subrun, handle), ...]``
        in op order and ``covered`` counts the leading run entries those
        handles own.  ``covered < len(run)`` means a fast-path refusal
        stopped the run mid-way — the uncovered tail executes inline at
        its own positions (phase A's per-op path drains the lane first,
        so op order is preserved exactly as with today's whole-run
        refusal).  With TB_FUSE off (or a too-short run) this is the
        plain single-handle dispatch."""
        machine = self.machine
        if not machine.fuse_batches or len(run) < 2:
            handle = self._dispatch_run(run, prepared)
            if handle is None:
                return [], 0
            return [(run, handle)], len(run)
        plan = self._plan_run_fusion(run)
        if machine.group_device_commit and len(plan) >= 2:
            # Grouped lane: ONE stacked dispatch over the fused segment
            # batches (each still <= batch_lanes rows, so the scan sees
            # the exact shapes it already compiled for).
            inner = machine.commit_group_fast(
                [b for _s, b, _t in plan],
                [t for _s, _b, t in plan],
                deferred=True,
            )
            if inner is None:
                return [], 0  # whole-run refusal: all ops execute inline
            handle = _FusedRunHandle(
                inner,
                [[len(b) for _jj, b, _t in subrun] for subrun, _b, _t in plan],
            )
            self._note_fused_dispatch(plan)
            self._trace_fused_dispatch(run, prepared, len(plan))
            return [(run, handle)], len(run)
        # Grouping off (or one segment): each segment dispatches through
        # the per-batch deferred fast kernel — a fused segment IS one
        # batch there, which is the whole win on hosts without the
        # grouped scan (fewer padded kernel bodies, fewer readbacks).
        pairs = []
        covered = 0
        for subrun, batch, timestamp in plan:
            inner = machine.commit_fast_deferred(batch, timestamp)
            if inner is None:
                break
            handle = (
                _FusedRunHandle(inner, [[len(b) for _jj, b, _t in subrun]])
                if len(subrun) > 1 else inner
            )
            self._note_fused_dispatch([(subrun, batch, timestamp)])
            self._trace_fused_dispatch(subrun, prepared, 1)
            pairs.append((subrun, handle))
            covered += len(subrun)
        return pairs, covered

    def _commit_run_fused_blocking(self, run, precomputed) -> bool:
        """Depth-1 fused commit: plan, dispatch, and RESOLVE the run's
        fused segments at its position in op order, landing per-member
        result bodies in ``precomputed``.  Returns True when every run
        entry resolved; False leaves the refused tail to the caller's
        per-op path (bit-identical inline execution)."""
        machine = self.machine
        plan = self._plan_run_fusion(run)
        if machine.group_device_commit and len(plan) >= 2:
            res = machine.commit_group_fast(
                [b for _s, b, _t in plan], [t for _s, _b, t in plan]
            )
            if res is None:
                return False
            self._note_fused_dispatch(plan)
            for (subrun, _b, _t), seg_res in zip(plan, res):
                members = _demux_compressed(
                    seg_res, [len(b) for _jj, b, _tt in subrun]
                )
                for (jj, _bb, _tt), member_res in zip(subrun, members):
                    precomputed[jj] = _encode_results(member_res)
            return True
        for subrun, batch, timestamp in plan:
            handle = machine.commit_fast_deferred(batch, timestamp)
            if handle is None:
                return False
            seg_res = handle.resolve()[0]
            self._note_fused_dispatch([(subrun, batch, timestamp)])
            members = _demux_compressed(
                seg_res, [len(b) for _jj, b, _tt in subrun]
            )
            for (jj, _bb, _tt), member_res in zip(subrun, members):
                precomputed[jj] = _encode_results(member_res)
        return True

    def _trace_fused_dispatch(self, run, prepared, segments: int) -> None:
        if prepared is None or not txtrace.active:
            return
        for jj, _b, _t in run:
            trace = int(prepared[jj][1]["trace"])
            if trace:
                txtrace.hop(trace, "replica.dispatch_lane",
                            replica=self.replica,
                            op=int(prepared[jj][1]["op"]),
                            run_len=len(run), fused_segments=segments)

    def _group_device_runs(
        self, admitted, single_ok: bool = False
    ) -> Dict[int, List[Tuple]]:
        """Identify runs of consecutive create_transfers prepares for the
        grouped device dispatch (machine.commit_group_fast): through a
        remote-TPU tunnel a dispatch costs ~60 ms, so per-op dispatch makes
        the device serving path RTT-bound — grouping amortizes it across
        the whole commit group.  Returns {first_admitted_index: run} where
        run = [(admitted_index, batch, timestamp), ...]; the commit loop
        dispatches each run when it REACHES it, preserving op order.
        Results are bit-identical to per-op commits (scan order == op
        order, per-op prepare timestamps ride along).

        ``single_ok`` (the pipelined engine): length-1 runs are emitted
        too — a lone create_transfers op dispatches DEFERRED through the
        per-batch fast kernel (machine.commit_fast_deferred), so the
        readback overlap works even where grouping is off (XLA-CPU, where
        an empty scan step pays table-sized temporaries).  When grouping
        is off entirely, every create_transfers op becomes its own run."""
        runs: Dict[int, List[Tuple]] = {}
        machine = self.machine
        grouping = bool(getattr(machine, "group_device_commit", False))
        # TB_FUSE widens run collection even where the grouped scan is
        # unavailable: the fusion planner (_dispatch_run_split) needs to
        # SEE consecutive create_transfers ops to coalesce them, and its
        # fused segments dispatch through the per-batch kernel there.
        fusing = bool(getattr(machine, "fuse_batches", False))
        if not grouping and not single_ok and not fusing:
            return runs
        if self.hash_log is not None:
            # The determinism oracle records a per-op ledger digest at
            # commit time; a grouped dispatch applies the whole run before
            # the per-op bookkeeping, so every digest but the run's last
            # would capture later ops' effects and false-alarm against
            # strict per-op replicas.  The oracle outranks the serving
            # optimization.
            return runs
        min_len = 1 if single_ok else 2
        max_len = machine.GROUP_K if (grouping or fusing) else 1
        run: List[Tuple[int, np.ndarray, int]] = []

        def flush() -> None:
            if len(run) >= min_len:
                runs[run[0][0]] = list(run)
            run.clear()

        for j, (_i, h, body) in enumerate(admitted):
            if (
                wire.Operation(int(h["operation"]))
                == wire.Operation.create_transfers
            ):
                if len(run) >= max_len:
                    flush()
                run.append((
                    j,
                    np.frombuffer(body, dtype=types.TRANSFER_DTYPE),
                    int(h["timestamp"]),
                ))
            else:
                flush()
        flush()
        return runs

    def _io_pool_submit(self, fn):
        if getattr(self, "_io_pool", None) is None:
            import concurrent.futures

            self._io_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tb-wal-fsync"
            )
        return self._io_pool.submit(fn)

    def _journal_sync_staged(self):
        """journal.sync under the ``wal_fsync`` attribution stage — the
        stage times the durability barrier itself (it runs on the IO pool
        thread), not the serving thread's wait for it."""
        with txtrace.stage("wal_fsync"):
            return self.journal.sync()

    def _prepare(
        self, request_h: np.ndarray, body: bytes, operation: wire.Operation,
        sync: bool = True, defer_write: Optional[List[bytes]] = None,
    ) -> Tuple[np.ndarray, bytes]:
        """Assign op + timestamp, hash-chain, and journal the prepare.

        ``defer_write``: collect the encoded message instead of writing it
        — the pipelined engine journals the whole group AFTER dispatching
        its leading device run, so the WAL IO overlaps device compute (the
        op/chain assignment here stays strictly ordered either way)."""
        # The pre-execution stage (the reference pipeline's prefetch slot:
        # everything between request admission and the state machine —
        # timestamp assignment, hash chain, WAL write).
        # Wall time feeds only the metrics registry, never replica state.
        t0 = time.perf_counter_ns() if _obs.enabled else 0  # tblint: ignore[nondet]
        op = self.op + 1
        count = self._event_count(operation, body)
        timestamp = self.machine.prepare(
            _OP_NAMES.get(operation, "other"), count, self.time_ns()
        )
        h = wire.new_header(
            wire.Command.prepare,
            cluster=self.cluster,
            view=self.view,
            parent=self.parent_checksum,
            request_checksum=wire.header_checksum(request_h),
            client=wire.u128(request_h, "client"),
            op=op,
            commit=self.commit_min,
            timestamp=timestamp,
            request=int(request_h["request"]),
            operation=int(operation),
        )
        h["replica"] = self.replica
        trace = int(request_h["trace"])
        if trace:
            # Sampled request: the trace id rides onto the prepare (and
            # from there onto the reply), inside the header-checksum
            # domain — one causal chain per request (obs/txtrace.py).
            h["trace"] = trace
            txtrace.hop(trace, "replica.prepare", replica=self.replica,
                        op=op)
        message = wire.encode(h, body)
        if defer_write is None:
            self.journal.write_prepare(message, sync=sync)
        else:
            defer_write.append(message)
        decoded, _ = wire.decode_header(message)
        self.op = op
        self.parent_checksum = wire.header_checksum(decoded)
        if _obs.enabled:
            _obs.histogram("replica.prefetch_us", "us").observe(
                (time.perf_counter_ns() - t0) / 1e3  # tblint: ignore[nondet] metrics
            )
        return decoded, body

    def _commit_prepare(
        self, header: np.ndarray, body: bytes, replay: bool,
        result_body: Optional[bytes] = None,
    ) -> Optional[bytes]:
        """Execute a journaled prepare; returns the reply message (stored in
        the session table either way).  ``result_body`` carries a result
        already produced by the grouped device dispatch
        (the grouped run dispatch in on_request_group_pipelined) — the state
        machine was applied there, so
        only the bookkeeping half (AOF, commit_min, session reply) runs
        here."""
        op = int(header["op"])
        operation = wire.Operation(int(header["operation"]))
        timestamp = int(header["timestamp"])
        client = wire.u128(header, "client")

        if operation == wire.Operation.root:
            return None
        if self.aof is not None:
            # Audit append BEFORE execution (replica.zig:3741-3746) — also
            # during replay, so a crash between journaling and appending
            # can't leave a committed op missing from the audit log.  The
            # resulting crash-replay duplicates are exact byte copies and
            # aof.iterate() dedupes them by checksum.
            self.aof.append(wire.encode(header, body))
        if operation == wire.Operation.register:
            result_body = b""
            self.commit_min = op
            session = Session(
                client=client, session=op, request=0, reply_bytes=b""
            )
            self._admit_session(session)
        elif operation == wire.Operation.reconfigure:
            result_body = self._apply_reconfigure(header, body)
            self.commit_min = op
            if _obs.enabled:
                _obs.counter("replica.commits").inc()
        else:
            if result_body is None:
                t0 = time.perf_counter_ns() if _obs.enabled else 0  # tblint: ignore[nondet] metrics
                with tracer.span("state_machine_commit", op=op,
                                 operation=operation.name):
                    # The kernel slice of a traced request's causal chain
                    # (docs/tracing.md): a real-duration span bound into
                    # the flow — the grouped/deferred engine's twin is the
                    # replica.dispatch_lane hop (_dispatch_run).
                    with txtrace.span(int(header["trace"]),
                                      "replica.execute",
                                      replica=self.replica, op=op):
                        result_body = self._execute(
                            operation, body, timestamp
                        )
                if _obs.enabled:
                    _obs.histogram("replica.commit_us", "us").observe(
                        (time.perf_counter_ns() - t0) / 1e3  # tblint: ignore[nondet] metrics
                    )
            self.commit_min = op
            if _obs.enabled:
                _obs.counter("replica.commits").inc()
                count = self._event_count(operation, body)
                if count:
                    _obs.histogram(
                        "replica.batch_events", "events"
                    ).observe(count)
            if self.hash_log is not None and operation in (
                wire.Operation.create_accounts,
                wire.Operation.create_transfers,
            ):
                # Determinism oracle (testing/hash_log.zig): per-op ledger
                # digests pinpoint the FIRST diverging commit across
                # replicas or across a crash-replay (sim/cluster.py).
                self.hash_log.record(op, int(self.machine.digest()))

        if self.commit_observer is not None:
            self.commit_observer(
                op, operation.name, timestamp, body, result_body, replay
            )

        reply_h = wire.new_header(
            wire.Command.reply,
            cluster=self.cluster,
            view=self.view,
            request_checksum=wire.u128(header, "request_checksum"),
            context=wire.header_checksum(header),
            client=client,
            op=op,
            commit=self.commit_min,
            timestamp=timestamp,
            request=int(header["request"]),
            operation=int(operation),
            # Continuous client-side auditing (docs/commitments.md): the
            # canonical accounts commitment root rides every reply —
            # carved from reserved padding, 0 when commitments are off,
            # so merkle-off serving stays bit-identical to pre-root wire.
            root=self.machine.commitment_root(),
        )
        reply_h["replica"] = self.replica
        trace = int(header["trace"])
        if trace:
            reply_h["trace"] = trace
            txtrace.hop(trace, "replica.reply", replica=self.replica, op=op)
        reply = wire.encode(reply_h, result_body)
        if self.auth is not None:
            # Stamp at creation, not egress: the MAC is keyed by the reply's
            # ORIGIN, so a stored reply re-served verbatim by any peer
            # (request_reply repair) still verifies under the creator's key.
            reply = self.auth.stamp(reply)

        session = self.sessions.get(client)
        if session is not None:
            if operation == wire.Operation.register:
                session.session = op
            session.request = int(header["request"])
            session.reply_bytes = reply
            self._store_client_reply(client, reply)
        return reply

    # -- state machine dispatch ----------------------------------------------

    def _execute(
        self, operation: wire.Operation, body: bytes, timestamp: int
    ) -> bytes:
        try:
            return self._execute_inner(operation, body, timestamp)
        except DeviceStateUnrecoverable:
            # The machine's in-process mirror recovery could not apply
            # (mirror suspect / cold tier active): rebuild from durable
            # state — the fault domain's last resort — and re-execute.
            self.recover_device_state()
            return self._execute_inner(operation, body, timestamp)

    def _execute_inner(
        self, operation: wire.Operation, body: bytes, timestamp: int
    ) -> bytes:
        if operation == wire.Operation.create_accounts:
            batch = np.frombuffer(body, dtype=types.ACCOUNT_DTYPE)
            results = self.machine.commit_batch("create_accounts", batch, timestamp)
            return _encode_results(results)
        if operation == wire.Operation.create_transfers:
            batch = np.frombuffer(body, dtype=types.TRANSFER_DTYPE)
            results = self.machine.commit_batch("create_transfers", batch, timestamp)
            return _encode_results(results)
        if operation == wire.Operation.lookup_accounts:
            ids = _decode_ids(body)
            return self.machine.lookup_accounts(ids).tobytes()
        if operation == wire.Operation.lookup_transfers:
            ids = _decode_ids(body)
            return self.machine.lookup_transfers(ids).tobytes()
        if operation == wire.Operation.get_proof:
            # Body: one u128 id (accounts, the PR 10 wire shape) or
            # id + a u64 kind selector (0 accounts / 1 transfers /
            # 2 posted) — validated in _validate_request.
            lanes = np.frombuffer(body, dtype="<u8")
            ident = int(lanes[0]) | (int(lanes[1]) << 64)
            kind = _PROOF_KIND_BY_CODE[int(lanes[2])] if len(lanes) > 2 \
                else "accounts"
            proof = self.machine.get_proof(ident, kind=kind)
            return proof if proof is not None else b""
        if operation in (
            wire.Operation.get_account_transfers,
            wire.Operation.get_account_history,
        ):
            filt = _decode_filter(body)
            rows = (
                self.machine.get_account_transfers(filt)
                if operation == wire.Operation.get_account_transfers
                else self.machine.get_account_history(filt)
            )
            # Reply rows are 128 B each; cap to one message body
            # (scan_buffer sizing, state_machine.zig:697-712).
            return rows[: self.config.message_body_size_max // 128].tobytes()
        raise ValueError(f"unimplemented operation {operation}")

    def _validate_request(self, operation: wire.Operation, body: bytes) -> None:
        """Reject anything that could not commit cleanly. Every prepare that
        reaches the WAL must be executable on replay."""
        max_body = self.config.message_body_size_max
        if len(body) > max_body:
            raise InvalidRequest("body exceeds message_body_size_max")
        if operation == wire.Operation.register:
            if body:
                raise InvalidRequest("register body must be empty")
            return
        if operation in (
            wire.Operation.create_accounts, wire.Operation.create_transfers
        ):
            if len(body) % 128 != 0:
                raise InvalidRequest("body not a multiple of event size")
            if len(body) // 128 > self.batch_lanes:
                raise InvalidRequest("batch exceeds configured lanes")
            return
        if operation in (
            wire.Operation.lookup_accounts, wire.Operation.lookup_transfers
        ):
            if len(body) % 16 != 0:
                raise InvalidRequest("body not a multiple of id size")
            # Replies are 128 B/row vs 16 B/id: cap so the reply always fits
            # in one message (state_machine.zig:70-75 batch_max semantics).
            if len(body) // 16 > max_body // 128:
                raise InvalidRequest("lookup batch exceeds reply capacity")
            return
        if operation in (
            wire.Operation.get_account_transfers,
            wire.Operation.get_account_history,
        ):
            # Any size is accepted: a body that is not exactly one
            # AccountFilter is treated as a zeroed (invalid) filter and
            # yields an empty reply (parse_filter_from_input,
            # state_machine.zig:810-820).
            return
        if operation == wire.Operation.reconfigure:
            # <u4 new_replica_count, <u4 new_standby_count, 8 B reserved.
            # Shape only — semantic checks happen at APPLY under the
            # membership current at that op (deterministic across replicas
            # and replay; an invalid transition commits a reject status).
            if len(body) != 16:
                raise InvalidRequest(
                    "reconfigure body must be 16 bytes "
                    "(u32 replica_count, u32 standby_count, 8 reserved)"
                )
            return
        if operation == wire.Operation.get_proof:
            # 16 B: one u128 id (accounts — PR 10 shape); 24 B: id + u64
            # kind selector.  Every journaled prepare must replay, so the
            # kind is validated HERE, not at execute.
            if len(body) not in (16, 24):
                raise InvalidRequest(
                    "get_proof body must be one u128 id (+ u64 kind)"
                )
            if len(body) == 24:
                kind = int(np.frombuffer(body[16:], "<u8")[0])
                if kind not in _PROOF_KIND_BY_CODE:
                    raise InvalidRequest(f"unknown proof kind {kind}")
            return
        raise InvalidRequest(f"operation {operation!r} not accepted")

    # -- membership reconfiguration (docs/reconfiguration.md) ----------------

    # Reply status codes (u64 LE result body) for operation reconfigure.
    RECONFIGURE_OK = 0
    RECONFIGURE_BAD_TRANSITION = 1   # not a single-step promote/demote
    RECONFIGURE_BOUNDS = 2           # outside REPLICAS_MAX/STANDBYS_MAX/solo
    RECONFIGURE_PRIMARY_DEMOTION = 3  # would demote the serving primary

    def _apply_reconfigure(self, header, body: bytes) -> bytes:
        """Execute a committed membership-change op.  Runs at the SAME op
        on every replica (and on WAL replay), so every input is taken from
        deterministic state: the membership current at this op and the
        prepare header's view — never the local wall clock or the
        replica's own (possibly lagging) view.  Idempotent: re-applying
        the current membership is a success no-op, which makes
        crash-replay safe without any dedup bookkeeping."""
        import numpy as np

        from .superblock import REPLICAS_MAX, STANDBYS_MAX

        lanes = np.frombuffer(body[:8], "<u4")
        new_rc, new_sc = int(lanes[0]), int(lanes[1])
        old_rc, old_sc = self.replica_count, self.standby_count
        status = self.RECONFIGURE_OK
        if (new_rc, new_sc) == (old_rc, old_sc):
            pass  # idempotent re-apply (crash replay)
        elif new_rc + new_sc != old_rc + old_sc or (
            abs(new_rc - old_rc) != 1
        ):
            # One step at a time, voters <-> standbys only: promotion
            # makes standby index old_rc a voter; demotion makes voter
            # index old_rc - 1 the first standby.  Indexes never move.
            status = self.RECONFIGURE_BAD_TRANSITION
        elif not (
            1 <= new_rc <= REPLICAS_MAX and 0 <= new_sc <= STANDBYS_MAX
        ) or (new_rc == 1 and new_sc > 0):
            status = self.RECONFIGURE_BOUNDS
        elif new_rc < old_rc and self._reconfigure_primary(
            int(header["view"]), old_rc
        ) == old_rc - 1:
            # Demoting the replica that is primary at this prepare's view
            # would drop the cluster's serving head without a view change.
            status = self.RECONFIGURE_PRIMARY_DEMOTION
        else:
            self.replica_count, self.standby_count = new_rc, new_sc
            self._membership_changed(old_rc, old_sc, int(header["view"]))
            if _obs.enabled:
                _obs.counter("reconfig.membership_ops").inc()
                _obs.gauge("reconfig.replica_count").set(new_rc)
                _obs.gauge("reconfig.standby_count").set(new_sc)
        if status != self.RECONFIGURE_OK and _obs.enabled:
            _obs.counter("reconfig.membership_rejected").inc()
        return int(status).to_bytes(8, "little")

    def _reconfigure_primary(self, view: int, replica_count: int) -> int:
        """Primary index at ``view`` under an explicit membership (the
        deterministic pre-transition mapping)."""
        return (view + self._primary_offset) % replica_count

    def _membership_changed(self, old_rc: int, old_sc: int,
                            view: int) -> None:
        """Post-transition hook.  The base replica only records the new
        shape (solo replicas can only no-op); VsrReplica overrides to fix
        the primary mapping, rebuild the clock quorum, and persist."""

    def _event_count(self, operation: wire.Operation, body: bytes) -> int:
        if operation in (
            wire.Operation.create_accounts, wire.Operation.create_transfers
        ):
            return len(body) // 128
        return 0

    # -- sessions ------------------------------------------------------------

    def _admit_session(self, session: Session) -> None:
        if len(self.sessions) >= self.config.clients_max and (
            session.client not in self.sessions
        ):
            # Evict the session with the lowest session number (oldest
            # register commit) — client_sessions.zig eviction policy.
            # Selection over SORTED items: session numbers are unique
            # (one commit op per registration), but the choice must be a
            # function of state, never of dict arrival order (tblint
            # nondet dict-selection rule; docs/tbmc.md determinism notes).
            victim = min(
                sorted(self.sessions.items()),
                key=lambda kv: kv[1].session,
            )[1]
            del self.sessions[victim.client]
        existing = self.sessions.get(session.client)
        if existing is not None:
            session.slot = existing.slot
        else:
            used = {s.slot for s in self.sessions.values()}
            session.slot = min(set(range(self.config.clients_max)) - used)
        self.sessions[session.client] = session

    def _eviction(
        self, client: int, reason: int = wire.EVICTION_NO_SESSION,
        session: int = 0,
    ) -> bytes:
        """Eviction carries WHY (wire.EVICTION_*): a capacity-evicted or
        unknown session is retryable (the client re-registers), a session-
        number mismatch is a protocol violation the client must surface.
        ``session`` echoes which session the eviction is about (0 = not
        session-specific) so clients can discard stale MISMATCHes for a
        session they already replaced."""
        h = wire.new_header(
            wire.Command.eviction,
            cluster=self.cluster, view=self.view, client=client,
            reason=reason, session=session,
        )
        h["replica"] = self.replica
        return wire.encode(h, b"")

    def _store_client_reply(self, client: int, reply: bytes) -> None:
        slot = self.sessions[client].slot
        # _validate_request guarantees replies fit one message slot.
        assert len(reply) <= self.config.message_size_max, len(reply)
        off = (
            self.storage.layout.client_replies_offset
            + slot * self.config.message_size_max
        )
        if self.async_checkpoint:
            # Server mode: reply slots are repair state, not commit state —
            # a torn write is re-served from a peer or retried by the client
            # (_read_client_reply tolerates corruption).  The reference
            # writes client_replies asynchronously for the same reason
            # (client_replies.zig); keeping a small O_DIRECT RMW off the
            # serving thread is worth ~0.5 ms/request.  The IO pool is one
            # FIFO worker, so writes for a session stay ordered.
            self._io_pool_submit(lambda: self.storage.write(off, reply))
            return
        self.storage.write(off, reply)

    def _read_client_reply(self, slot: int, size: int) -> bytes:
        if size == 0:
            return b""
        off = (
            self.storage.layout.client_replies_offset
            + slot * self.config.message_size_max
        )
        buf = self.storage.read(off, size)
        try:
            # Slice to the header's own size before verifying: the stored
            # slot may legitimately hold more bytes than this reply
            # (decode() itself rejects trailing bytes on ingress frames).
            h, _ = wire.decode_header(buf)
            raw = buf[: int(h["size"])]
            wire.verify_body(h, raw[wire.HEADER_SIZE:])
            return raw
        except ValueError:
            return b""  # corrupt stored reply: client will retry

    # -- checkpointing (replica.zig:3153-3169) --------------------------------

    @property
    def op_prepare_max(self) -> int:
        """Highest op this replica may journal (vsr.zig op_prepare_max).
        The WAL ring must always retain every op in (op_checkpoint, op] —
        commits replay from it and recovery anchors at the checkpoint — so
        the head may lead the checkpoint by at most the ring size.  A
        replica at this bound stalls until its next checkpoint; a lagging
        replica's head then falls behind the cluster's checkpoint, which is
        exactly the state-sync trigger."""
        return self.op_checkpoint + self.config.journal_slot_count - 1

    def _checkpoint_due(self) -> bool:
        # Measured from the last CAPTURE, not the last adopted checkpoint:
        # under async_checkpoint the adoption (op_checkpoint) lags the
        # in-flight write, and measuring from op_checkpoint would re-trigger
        # a capture on EVERY op after a boundary until adoption — misaligned
        # captures (breaking cross-replica forest determinism) and a
        # synchronous drain two ops later.
        return (
            self.commit_min
            - max(self.op_checkpoint, self._ckpt_captured_op)
            >= self.config.vsr_checkpoint_interval
        )

    def checkpoint(self) -> None:
        """Durably snapshot ledger + sessions + superblock at commit_min.

        With ``async_checkpoint`` on (both TCP servers — single-replica and
        cluster), the expensive half — forest delta + file writes + fsync +
        superblock — runs on a background thread while the replica keeps
        serving (replica.zig:3153-3169 overlaps checkpoint with the
        pipeline the same way); only the device→host snapshot is taken
        inline.  Cluster safety: every superblock write (this thread's
        _persist_view AND the background write) goes through the
        _superblock_install merge-point, which serializes them and merges
        monotonically.  The sim keeps checkpoints synchronous for
        determinism.

        Alignment: the CAPTURE always happens here, at the exact
        op_checkpoint+interval boundary the commit loop invokes us on —
        even when a previous write is still in flight (the capture is then
        queued and written after it).  Cross-replica forest determinism
        (peer block repair matches files by checksum) depends on every
        replica capturing at identical ops."""
        # A capture must never see a ledger ahead of commit_min: settle any
        # pipelined group first (no-op on the paths that already did).
        self._settle_or_recover()
        if self.machine.scrub_armed:
            # Checkpoint boundary: ALWAYS scrub (docs/fault_domains.md) —
            # a device-vs-mirror divergence here is a hard integrity
            # violation the capture must never bake into durable state.
            try:
                self.machine.scrub_check(boundary=True)
            except DeviceStateUnrecoverable:
                self.recover_device_state()
        if self.async_checkpoint:
            self._checkpoint_poll()
            if self._ckpt_thread is not None:
                if len(self._ckpt_queue) >= 1:
                    # Writes persistently slower than the checkpoint
                    # interval: block.  Backpressure must not skip the
                    # aligned capture — skipping would desynchronize this
                    # replica's forest files from its peers' — and the
                    # queue is bounded at one so peak host memory stays at
                    # two captures (in-flight + queued), not unbounded.
                    self._checkpoint_drain()
                self._ckpt_queue.append(self._checkpoint_capture())
                self._checkpoint_poll()  # start it if the write just landed
                return
            self._checkpoint_async_start()
            return
        t0 = time.perf_counter_ns() if _obs.enabled else 0  # tblint: ignore[nondet] metrics
        with tracer.span("checkpoint", op=self.commit_min):
            self._checkpoint_inner()
        if _obs.enabled:
            _obs.histogram("replica.checkpoint_ms", "ms").observe(
                (time.perf_counter_ns() - t0) / 1e6  # tblint: ignore[nondet] metrics
            )

    def _checkpoint_inner(self) -> None:
        arrays, meta, fields = self._checkpoint_capture()
        state = self._checkpoint_write(arrays, meta, fields)
        self._checkpoint_adopt(state, fields["cold_garbage"])

    def _checkpoint_capture(self):
        """The inline half of a checkpoint: everything that must be
        consistent with THIS commit_min — evictions, session snapshot,
        device→host ledger snapshot, digest, clocks."""
        # Tiering: spill the older half of the hot transfers window when it
        # is filling (deterministic: driven by the committed op stream; the
        # runs written here become durable with this checkpoint's manifest).
        m = self.machine
        m._maybe_evict_between_batches()
        self._ckpt_captured_op = self.commit_min
        meta = {
            "machine": m.host_state(),
            "sessions": {
                f"{client:032x}": {
                    "session": s.session,
                    "request": s.request,
                    "reply_size": len(s.reply_bytes),
                    "slot": s.slot,
                }
                for client, s in self.sessions.items()
            },
        }
        if m.merkle_armed:
            # Commitment root over the CANONICAL layout (shard-config
            # independent): restores — and any auditor holding the
            # checkpoint — verify the state against it WITHOUT replay
            # (docs/commitments.md; _install_checkpoint_ledger checks it).
            acc_root, tr_root, po_root = m.merkle_canonical_roots()
            meta["merkle_root"] = {
                "accounts": acc_root, "transfers": tr_root,
                "posted": po_root,
            }
        # checkpoint_ledger(): canonical single-device layout — under
        # TB_SHARDS the live ledger is owner-partitioned, and a checkpoint
        # must restore into ANY shard config (deterministic conversion, so
        # replica checkpoint file checksums stay cluster-comparable).
        arrays = checkpoint_mod.ledger_to_arrays(m.checkpoint_ledger())
        fields = dict(
            view=self.view,
            log_view=getattr(self, "log_view", self.view),
            commit_min=self.commit_min,
            commit_max=self.op,
            log_adopted_op=getattr(self, "_log_adopted_op", 0),
            ledger_digest=m.digest(),
            prepare_timestamp=m.prepare_timestamp,
            commit_timestamp=m.commit_timestamp,
            # Cold runs superseded as of THIS capture: the only ones whose
            # deletion this checkpoint's durability justifies.  Runs merged
            # AFTER capture (concurrent evictions under async_checkpoint)
            # are referenced by the captured cold_manifest and must survive
            # until the NEXT checkpoint lands.
            cold_garbage=list(m.cold.garbage),
        )
        return arrays, meta, fields

    def _checkpoint_write(self, arrays, meta, fields) -> SuperBlockState:
        """The expensive half (file writes + fsync + superblock): safe off
        the serving thread — it touches only the captured host snapshot,
        the forest files, and distinct storage zones."""
        # Session replies live in the client_replies zone; make them durable
        # before the superblock references their sizes.
        self.storage.sync()
        op = fields["commit_min"]
        file_checksum, manifest_checksum = self.forest.checkpoint_arrays(
            arrays, meta, op
        )
        state = SuperBlockState(
            cluster=self.cluster,
            replica=self.replica,
            replica_count=self.replica_count,
            # Membership metadata must ride EVERY superblock write: round-5
            # standby sweep find — omitting it here let the first
            # checkpoint erase standby_count, so restarted voters stopped
            # broadcasting to standbys forever.
            standby_count=self.standby_count,
            primary_offset=self._primary_offset,
            view=fields["view"],
            log_view=fields["log_view"],
            commit_min=op,
            commit_max=fields["commit_max"],
            log_adopted_op=fields["log_adopted_op"],
            op_checkpoint=op,
            checkpoint_file_checksum=file_checksum,
            ledger_digest=fields["ledger_digest"],
            prepare_timestamp=fields["prepare_timestamp"],
            commit_timestamp=fields["commit_timestamp"],
            manifest_checksum=manifest_checksum,
        )
        state = self._superblock_install(state)
        return state

    def _superblock_install(self, state: SuperBlockState) -> SuperBlockState:
        """The ONLY superblock write path: serializes the serving thread
        (_persist_view on view changes) against the background checkpoint
        thread and monotonically merges their fields so neither writer can
        regress the other's progress (the reference sequences superblock
        updates through a single-owner write queue, superblock.zig
        view_change/checkpoint staging):

        - view/log_view/commit bounds only move forward (a checkpoint
          captured before a view bump must not durably regress the view —
          a restarted replica could then ack in the old view: split brain).
        - The checkpoint anchor group (op_checkpoint + file checksums +
          digest + timestamps) moves forward as a UNIT: a view persist
          racing a landed background checkpoint must not revert the
          superblock to a manifest whose files the adopt step is about to
          GC — restart would anchor on deleted files."""
        with self._sb_lock:
            cur = self.superblock.state
            if state.op_checkpoint < cur.op_checkpoint:
                state = dataclasses.replace(
                    state,
                    op_checkpoint=cur.op_checkpoint,
                    checkpoint_file_checksum=cur.checkpoint_file_checksum,
                    manifest_checksum=cur.manifest_checksum,
                    ledger_digest=cur.ledger_digest,
                    prepare_timestamp=cur.prepare_timestamp,
                    commit_timestamp=cur.commit_timestamp,
                )
            # log_adopted_op travels WITH its writer's (log_view,
            # op_checkpoint): a later adoption may legitimately certify a
            # SHORTER canonical log (view-change truncation of an
            # uncommitted suffix), and a state sync legitimately LOWERS the
            # watermark to the synced checkpoint op at the same log_view —
            # so the lexicographically newer writer wins; max() would let a
            # pre-sync SV target_op survive the sync durably and wedge
            # every post-sync restart log_suspect.
            skey = (state.log_view, state.op_checkpoint)
            ckey = (cur.log_view, cur.op_checkpoint)
            if skey > ckey:
                adopted = state.log_adopted_op
            elif skey < ckey:
                adopted = cur.log_adopted_op
            else:
                a, b = state.log_adopted_op, cur.log_adopted_op
                if (a >= PROMOTION_SUSPECT_OP) != (b >= PROMOTION_SUSPECT_OP):
                    # Certification replaces the promotion sentinel at the
                    # same key: on_start_view's persisted target_op must
                    # actually land, or every later crash re-opens the
                    # promoted replica suspect forever.  (A stale
                    # checkpoint still carrying the sentinel must equally
                    # not resurrect it over a landed certification.)
                    adopted = min(a, b)
                else:
                    adopted = max(a, b)
            state = dataclasses.replace(
                state,
                view=max(state.view, cur.view),
                log_view=max(state.log_view, cur.log_view),
                commit_min=max(state.commit_min, cur.commit_min),
                commit_max=max(state.commit_max, cur.commit_max),
                log_adopted_op=adopted,
            )
            self.superblock.checkpoint(state)
            return state

    def _checkpoint_adopt(self, state: SuperBlockState, cold_garbage) -> None:
        # The background write merged in the view as of ITS write moment; a
        # view change since then is already durable via _persist_view —
        # fold it into the serving thread's view of the superblock too.
        state = dataclasses.replace(
            state,
            view=max(state.view, self.view),
            log_view=max(state.log_view, getattr(self, "log_view", self.view)),
        )
        self._sb_state = state
        self.op_checkpoint = state.op_checkpoint
        # The state-sync responder pack (canonical arrays + trees for the
        # PREVIOUS checkpoint, vsr/consensus.py) is dead weight the moment
        # the checkpoint moves: release it rather than holding a full
        # state copy until the next sync request happens to replace it.
        self._sync_pack_cache = None
        if _obs.enabled:
            _obs.counter("replica.checkpoints").inc()
            _obs.gauge("replica.op_checkpoint").set(self.op_checkpoint)
        # GC only after the superblock referencing the new manifest is
        # durable (crash before this point must find the old files intact).
        self.forest.gc()
        # Same discipline for cold runs — restricted to the files that were
        # already superseded AT CAPTURE (see _checkpoint_capture).
        self.machine.cold.gc(cold_garbage)

    # -- overlapped checkpoint (async_checkpoint; replica.zig:3153-3169) ------

    def _checkpoint_async_start(self) -> None:
        # Wall time feeds ONLY the slow-capture diagnostic below, never
        # replica state — replay stays seed-stable.
        t0 = time.monotonic()  # tblint: ignore[nondet]
        arrays, meta, fields = self._checkpoint_capture()
        dt = time.monotonic() - t0  # tblint: ignore[nondet]
        if _obs.enabled:
            _obs.histogram("replica.checkpoint_capture_ms", "ms").observe(
                dt * 1e3
            )
        if dt > 0.05:
            dbg = getattr(self, "_debug", None)
            if dbg is not None:
                dbg("slow_ckpt_capture", ms=round(dt * 1e3, 1),
                    op=self.commit_min)
        self._checkpoint_write_start(arrays, meta, fields)

    def _checkpoint_write_start(self, arrays, meta, fields) -> None:
        import threading

        self._ckpt_error = None

        def work():
            # Handoff protocol: the serving thread reads _ckpt_result/
            # _ckpt_error only in _checkpoint_poll, strictly AFTER
            # t.is_alive() goes False — thread termination is the
            # happens-before edge, so these two writes need no lock.
            try:
                state = self._checkpoint_write(arrays, meta, fields)
                garbage = fields["cold_garbage"]
                self._ckpt_result = (state, garbage)  # tblint: ignore[lane-race] is_alive gate
            except Exception as err:  # noqa: BLE001 — surfaced at poll
                self._ckpt_error = err  # tblint: ignore[lane-race] is_alive gate

        t = threading.Thread(
            target=work, name="tb-checkpoint", daemon=True
        )
        self._ckpt_thread = t
        with tracer.span("checkpoint_async_start", op=fields["commit_min"]):
            t.start()

    def _checkpoint_poll(self) -> None:
        """Adopt a finished background checkpoint and start the next queued
        write, if any (serving thread only)."""
        t = self._ckpt_thread
        if t is not None and t.is_alive():
            return
        if t is not None:
            self._ckpt_thread = None
            if self._ckpt_error is not None:
                err, self._ckpt_error = self._ckpt_error, None
                # Retry path: re-arm the due trigger at the next commit
                # (measured-from-capture would otherwise suppress the next
                # checkpoint until commit_min reaches captured_op+interval —
                # with the production config that is beyond the WAL cap, so
                # one transient EIO would wedge the replica at WAL-full
                # forever).  Queued captures are discarded with it: the
                # fresh capture supersedes them.
                self._ckpt_captured_op = self.op_checkpoint
                self._ckpt_queue.clear()
                raise RuntimeError("background checkpoint failed") from err
            (state, cold_garbage), self._ckpt_result = self._ckpt_result, None
            if state.op_checkpoint >= self.op_checkpoint:
                self._checkpoint_adopt(state, cold_garbage)
            else:
                # Superseded while in flight (state sync adopted a newer
                # anchor) — adopting would regress op_checkpoint.  Still
                # GC the capture's cold garbage (gc() intersects with the
                # CURRENT garbage list, so anything the new state tracks
                # or references survives) or those files leak until
                # restart.
                self.machine.cold.gc(cold_garbage)
        if self._ckpt_thread is None and self._ckpt_queue:
            self._checkpoint_write_start(*self._ckpt_queue.pop(0))

    def _checkpoint_drain(self) -> None:
        while self._ckpt_thread is not None:
            self._ckpt_thread.join()
            self._checkpoint_poll()  # adopts; starts the next queued write

    # -- device fault domain (docs/fault_domains.md) --------------------------

    def _scrub_poll(self) -> None:
        """Run a due scrub check at a commit-group boundary (the cadence
        knob: machine.scrub_interval / --scrub-interval).  Settles the
        pipelined commit engine first — the fold must see a quiesced
        ledger — and escalates an unrecoverable mismatch to the durable-
        state rebuild."""
        m = self.machine
        if not m.scrub_armed or not m.scrub_due:
            return
        self._settle_or_recover()
        try:
            m.scrub_check()
        except DeviceStateUnrecoverable:
            self.recover_device_state()

    def dump_blackbox(self, reason: str = "on_demand") -> Optional[str]:
        """Write the flight recorder's retained history next to the data
        file (postmortem artifact, docs/tracing.md); no-op when no
        recorder is attached.  Best-effort: a dump must never raise over
        the failure that triggered it.  Returns the path or None."""
        box = self.blackbox
        if box is None:
            return None
        box.record("dump", reason=reason, op=self.op,
                   commit_min=self.commit_min)
        directory = os.path.dirname(self.data_path) or "."
        paths = dump_blackboxes([box], directory)
        return paths[0] if paths else None

    def recover_device_state(self) -> None:
        """Last-resort device-state recovery: rebuild the machine from the
        durable checkpoint + WAL replay — the restart recovery path, run
        in process (the fault domain's fallback when the mirror itself is
        suspect or cannot re-materialize, e.g. under the cold tier).

        Sessions, the WAL, and all host-side replica state are intact (the
        fault domain covers only device-resident state); only the machine's
        ledger and derived state are rebuilt.  The prepare clock is
        preserved: already-journaled prepares above commit_min keep their
        timestamps monotone."""
        m = self.machine
        if _obs.enabled:
            _obs.counter("device_recovery.wal_replays").inc()
        # The flight recorder's reason to exist: dump the retained protocol
        # history BEFORE the rebuild mutates anything further.
        self.dump_blackbox("device_recovery")
        prepare_timestamp = m.prepare_timestamp
        m.scrub_disarm()
        m.quarantine()
        sb = self._sb_state
        loaded = self._load_checkpoint_state(sb)
        if loaded is not None:
            ledger, meta = loaded
            self._install_checkpoint_ledger(ledger, meta, sb)
            floor = sb.op_checkpoint
        else:
            m.reset_device_state()
            floor = 0
        recovery = self.journal.recover()
        for op in range(floor + 1, self.commit_min + 1):
            entry = recovery.entries.get(op)
            if entry is None or entry.body is None:
                raise RuntimeError(
                    f"device-state recovery: committed op {op} unreadable "
                    "from the WAL"
                )
            operation = wire.Operation(int(entry.header["operation"]))
            name = _OP_NAMES.get(operation)
            if name is None:
                continue  # register/lookup/query ops: no machine state
            dtype = (
                types.ACCOUNT_DTYPE if name == "create_accounts"
                else types.TRANSFER_DTYPE
            )
            m.commit_batch(
                name, np.frombuffer(entry.body, dtype=dtype),
                int(entry.header["timestamp"]),
            )
        m.prepare_timestamp = max(m.prepare_timestamp, prepare_timestamp)
        m.device_recoveries += 1
        m.scrub_arm()  # re-arm from the freshly verified state

    def close(self) -> None:
        self._pipeline_settle()
        self._checkpoint_drain()
        pool = getattr(self, "_io_pool", None)
        if pool is not None:
            pool.shutdown(wait=True)
        lane = getattr(self.machine, "_lane", None)
        if lane is not None:
            lane.shutdown(wait=True)
            self.machine._lane = None
        if self.aof is not None:
            self.aof.close()
        dbg = getattr(self, "_debug_file", None)
        if dbg is not None:
            dbg.close()
            self._debug_file = None
        self.storage.close()


class _FusedRunHandle:
    """Per-client demux over a fused dispatch (TB_FUSE; docs/
    commit_pipeline.md fusion section): the inner DeviceCommitHandle
    resolved per DISPATCHED batch — one or more of which are
    concatenations of member client batches — and this wrapper reslices
    each dispatched batch's compressed (lane, code) results back to
    per-member results by row offset, preserving the engine's
    one-result-list-per-run-entry retire contract.  Lane timestamps need
    no translation: plan_fusion only fuses timestamp-contiguous members,
    which makes every fused row's device timestamp equal its solo
    dispatch value (docs/commitments.md).

    ``member_counts`` is one list per dispatched batch of the member row
    counts, in member order; resolve() returns the flattened per-member
    result lists."""

    def __init__(self, inner, member_counts: List[List[int]]):
        self._inner = inner
        self._member_counts = member_counts

    @property
    def join_wait_s(self) -> float:
        return self._inner.join_wait_s

    def discard(self) -> None:
        self._inner.discard()

    def resolve(self) -> List[List[Tuple[int, int]]]:
        results = self._inner.resolve()
        out: List[List[Tuple[int, int]]] = []
        for res, counts in zip(results, self._member_counts):
            out.extend(_demux_compressed(res, counts))
        return out


def _demux_compressed(
    res: List[Tuple[int, int]], counts: List[int]
) -> List[List[Tuple[int, int]]]:
    """Slice one dispatched batch's compressed (lane, error_code) pairs
    (ascending lanes; machine._compress) into per-member result lists by
    row offset, rebasing each member's lanes to its own numbering."""
    out: List[List[Tuple[int, int]]] = []
    offset = 0
    for c in counts:
        out.append([
            (lane - offset, code)
            for lane, code in res
            if offset <= lane < offset + c
        ])
        offset += c
    return out


_OP_NAMES = {
    wire.Operation.create_accounts: "create_accounts",
    wire.Operation.create_transfers: "create_transfers",
}

# Wire kind selector for get_proof (ops/merkle.py PROOF_KINDS).
_PROOF_KIND_BY_CODE = {0: "accounts", 1: "transfers", 2: "posted"}


def _encode_results(results: List[Tuple[int, int]]) -> bytes:
    arr = np.zeros(len(results), dtype=types.EVENT_RESULT_DTYPE)
    for i, (index, result) in enumerate(results):
        arr[i]["index"] = index
        arr[i]["result"] = result
    return arr.tobytes()


def _decode_filter(body: bytes) -> np.void:
    """AccountFilter from a request body; wrong-size bodies become a zeroed
    (hence invalid -> empty-reply) filter (state_machine.zig:810-820)."""
    if len(body) == types.ACCOUNT_FILTER_DTYPE.itemsize:
        return np.frombuffer(body, dtype=types.ACCOUNT_FILTER_DTYPE)[0]
    return np.zeros(1, dtype=types.ACCOUNT_FILTER_DTYPE)[0]


def _decode_ids(body: bytes) -> List[int]:
    lanes = np.frombuffer(body, dtype="<u8")
    return [
        int(lanes[2 * i]) | (int(lanes[2 * i + 1]) << 64)
        for i in range(len(lanes) // 2)
    ]
